"""Resilience plane (paddle_tpu/resilience/ + trainer/checkpoint/
task-queue wiring): deterministic chaos injection, numeric guards with
skip/rollback policies, retry/backoff with reconnect, preemption-safe
training, and the crash-consistency torn-write matrix.

Every chaos test is seeded: the fault schedule is a pure function of
(chaos_spec, chaos_seed), so a passing run passes forever and a failure
reproduces exactly from the two flag values.
"""
import os
import signal
import zlib

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.distributed import TaskMaster, TaskMasterClient, \
    serve_master
from paddle_tpu.incubate import checkpoint as ckpt
from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience import chaos, guard, retry


# ---------------------------------------------------------------- helpers

def _batches(n, bs=4, seed=0):
    rng = np.random.RandomState(seed)
    return [[(rng.randn(4).astype("float32"),
              rng.randn(1).astype("float32")) for _ in range(bs)]
            for _ in range(n)]


def _trainer(ckdir=None, step_interval=2, max_keep=50, epoch_interval=1):
    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1, bias_attr=False, name="fc")
        return layers.mean(layers.square_error_cost(pred, y))

    cfg = None
    if ckdir is not None:
        cfg = pt.CheckpointConfig(ckdir, max_num_checkpoints=max_keep,
                                  epoch_interval=epoch_interval,
                                  step_interval=step_interval)
    return pt.Trainer(train_func,
                      lambda: pt.optimizer.SGD(learning_rate=0.05),
                      place=pt.CPUPlace(), checkpoint_config=cfg)


def _fire(seed, site, n, prob):
    """Mirror of chaos._decide: does invocation n of `site` fire?"""
    return zlib.crc32(f"{seed}:{site}:{n}".encode()) / 0xFFFFFFFF < prob


def _seed_where(site, prob, skip_first, fire_within):
    """A seed whose schedule skips invocation 0 (so recovery machinery
    exists before the first fault) but fires within the next
    `fire_within` invocations."""
    for s in range(1000):
        if not any(_fire(s, site, i, prob) for i in range(skip_first)) \
                and any(_fire(s, site, i, prob)
                        for i in range(skip_first, fire_within)):
            return s
    raise AssertionError("no seed found (prob too small?)")


# ------------------------------------------------------------ chaos core

def test_chaos_spec_grammar():
    spec = chaos.parse_spec(
        "trainer.step=nan:0.25; task_queue.rpc=raise:0.5 ;"
        "executor.run=delay:1.0:0.02;checkpoint.shard_write=truncate")
    assert spec["trainer.step"].kind == "nan"
    assert spec["trainer.step"].prob == 0.25
    assert spec["task_queue.rpc"].kind == "raise"
    assert spec["executor.run"].arg == 0.02
    assert spec["checkpoint.shard_write"].prob == 1.0
    for bad in ("siteonly", "a=unknownkind", "a=nan:2.0", "a=raise:x"):
        with pytest.raises(ValueError):
            chaos.parse_spec(bad)


def test_chaos_off_is_noop():
    flags.set_flag("chaos_spec", "")
    with chaos.fault_point("trainer.step"):
        pass
    v = [np.ones(3)]
    assert chaos.poison("trainer.step", v) is v
    assert chaos.schedule() == []


@pytest.mark.chaos
def test_chaos_schedule_replays_exactly():
    flags.set_flag("chaos_seed", 7)
    flags.set_flag("chaos_spec", "site.a=raise:0.3;site.b=nan:0.6")

    def one_run():
        chaos.reset()
        flags.set_flag("chaos_spec", "site.a=raise:0.3;site.b=nan:0.6")
        for _ in range(40):
            try:
                chaos.trigger("site.a")
            except chaos.InjectedFault:
                pass
            chaos.poison("site.b", np.zeros(2))
        return chaos.schedule()

    s1, s2 = one_run(), one_run()
    assert s1 == s2 and len(s1) > 0
    # a different seed produces a different schedule
    flags.set_flag("chaos_seed", 8)
    assert one_run() != s1
    flags.set_flag("chaos_seed", 0)


@pytest.mark.chaos
def test_fault_point_raise_decorator_and_poison():
    flags.set_flag("chaos_seed", 0)
    flags.set_flag("chaos_spec", "x=raise:1.0")

    @chaos.fault_point("x", exc=ConnectionError)
    def f():
        return 1

    with pytest.raises(ConnectionError, match="chaos: injected"):
        f()
    flags.set_flag("chaos_spec", "y=inf:1.0")
    out = chaos.poison("y", [np.float32(0.5), np.ones(2)])
    assert np.isinf(out[0]).all()
    np.testing.assert_array_equal(out[1], np.ones(2))  # only the loss
    flags.set_flag("chaos_spec", "z=nan:1.0")
    assert np.isnan(chaos.poison("z", 1.25)).all()


@pytest.mark.chaos
def test_corrupt_file_truncates(tmp_path):
    p = str(tmp_path / "f.bin")
    open(p, "wb").write(b"x" * 1000)
    flags.set_flag("chaos_spec", "w=truncate:1.0:0.5")
    chaos.corrupt_file("w", p)
    assert os.path.getsize(p) == 500


# ------------------------------------------------------------ flags plane

def test_malformed_env_flag_names_the_flag(monkeypatch):
    monkeypatch.setenv("PTPU_RESILIENCE_TEST_FLAG", "not-an-int")
    with pytest.raises(ValueError, match=r"resilience_test_flag.*"
                       r"PTPU_RESILIENCE_TEST_FLAG.*not-an-int"):
        flags.define_flag("resilience_test_flag", 3)


def test_resilience_flags_registered():
    for name in ("chaos_spec", "chaos_seed", "nan_policy",
                 "bad_step_limit", "retry_max_attempts"):
        assert name in flags.all_flags()


# ------------------------------------------------------------ retry plane

def test_retry_backoff_reconnect_and_metrics():
    calls = {"n": 0, "reconnects": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    pol = retry.RetryPolicy(name="t_retry", max_attempts=5,
                            base_delay=0.001, max_delay=0.01)
    before = obs.REGISTRY.get("retry_attempts_total").total()
    out = retry.call_with_retry(
        flaky, pol,
        on_retry=lambda e: calls.__setitem__(
            "reconnects", calls["reconnects"] + 1))
    assert out == "ok" and calls["n"] == 3 and calls["reconnects"] == 2
    after = obs.REGISTRY.get("retry_attempts_total").total()
    assert after - before == 2


def test_retry_exhausted_reraises_original():
    pol = retry.RetryPolicy(name="t_exhaust", max_attempts=3,
                            base_delay=0.001, retry_on=(OSError,))

    def always():
        raise OSError("disk sneezed")

    with pytest.raises(OSError, match="disk sneezed"):
        retry.call_with_retry(always, pol)
    # non-retryable errors pass straight through without burning budget
    with pytest.raises(KeyError):
        retry.call_with_retry(lambda: (_ for _ in ()).throw(KeyError("x")),
                              pol)


def test_retry_delay_is_deterministic_and_bounded():
    pol = retry.RetryPolicy(name="t_delay", base_delay=0.05, max_delay=0.4,
                            jitter=0.5)
    d = [pol.delay(a) for a in (1, 2, 3, 4, 5)]
    assert d == [pol.delay(a) for a in (1, 2, 3, 4, 5)]
    assert all(x <= 0.4 * 1.5 for x in d)
    assert d[1] > d[0]          # exponential growth under the cap


# ------------------------------------------------------------ guard plane

def test_guard_nan_spike_and_breaker():
    g = guard.NumericGuard(policy="skip_step", bad_step_limit=3,
                           spike_factor=10.0, warmup_steps=2)
    assert g.observe(1.0) == guard.OK
    assert g.observe(float("nan")) == guard.NAN
    assert g.observe(float("inf")) == guard.NAN
    assert g.observe(1.0) == guard.OK       # recovery resets the streak
    assert g.observe(1.0) == guard.OK
    assert g.observe(50.0) == guard.SPIKE   # 50 > 10 * ema(~1.0)
    assert g.observe(float("nan")) == guard.NAN
    with pytest.raises(guard.CircuitBreakerOpen):
        g.observe(float("nan"))             # 3rd consecutive bad


def test_guard_spike_disabled_and_warmup():
    g = guard.NumericGuard(policy="skip_step", bad_step_limit=0,
                           spike_factor=0.0)
    assert g.observe(1.0) == guard.OK
    assert g.observe(1e9) == guard.OK       # spike detection off
    g2 = guard.NumericGuard(policy="skip_step", bad_step_limit=0,
                            spike_factor=10.0, warmup_steps=5)
    assert g2.observe(1.0) == guard.OK
    assert g2.observe(100.0) == guard.OK    # still warming up
    with pytest.raises(ValueError, match="nan_policy"):
        guard.NumericGuard(policy="explode")


# -------------------------------------------- checkpoint crash consistency

def test_torn_write_matrix_falls_back(tmp_path):
    """Truncated shard / deleted manifest / flipped byte each invalidate
    exactly their serial; latest_checkpoint falls back past all three."""
    root = str(tmp_path)
    for i in range(4):
        ckpt.save_checkpoint(root, {"x": np.full((4,), i, "float32")},
                             meta={"i": i}, max_keep=10)
    d = lambda s: os.path.join(root, f"checkpoint_{s}")
    shard = lambda s: os.path.join(
        d(s), [n for n in os.listdir(d(s)) if n.startswith("shard_")][0])
    # serial 3: truncate the shard (torn write)
    with open(shard(3), "r+b") as f:
        f.truncate(os.path.getsize(shard(3)) // 2)
    # serial 2: crash before the manifest commit
    os.remove(os.path.join(d(2), ckpt.MANIFEST))
    # serial 1: single flipped byte (bit rot)
    raw = bytearray(open(shard(1), "rb").read())
    raw[len(raw) // 2] ^= 0x01
    open(shard(1), "wb").write(bytes(raw))
    for s, ok in ((3, False), (2, False), (1, False), (0, True)):
        assert ckpt.is_valid(d(s)) == ok
    assert ckpt.latest_checkpoint(root) == 0
    state, meta, serial = ckpt.load_checkpoint(root)
    assert serial == 0 and meta["i"] == 0
    np.testing.assert_array_equal(state["x"], np.zeros(4, "float32"))


def test_sidecars_deleted_after_commit(tmp_path):
    d = str(tmp_path / "c0")
    ckpt.save_state(d, {"w": np.ones((2, 2), "float32")})
    assert ckpt.is_valid(d)
    assert not [n for n in os.listdir(d) if n.startswith(".side_")]


def test_stale_sidecar_is_not_merged(tmp_path, monkeypatch):
    """A leftover sidecar from a previous save (its shard has been
    rewritten since) must not satisfy the merge barrier."""
    d = str(tmp_path / "c0")
    os.makedirs(d)
    stale = {"entries": {"w": {"shape": [1], "dtype": "float32",
                               "pieces": [{"key": "w@0", "index": [[0, 1]],
                                           "shard":
                                           "shard_00001-of-00002.npz"}]}},
             "crc": {"shard_00001-of-00002.npz": 123}}
    import json
    import time
    side1 = os.path.join(d, ".side_00001.json")
    json.dump(stale, open(side1, "w"))
    # process 1's shard rewritten AFTER the sidecar => sidecar is stale
    shard1 = os.path.join(d, "shard_00001-of-00002.npz")
    open(shard1, "wb").write(b"new bytes")
    old = time.time() - 120
    os.utime(side1, (old, old))
    monkeypatch.setattr(ckpt, "SIDECAR_TIMEOUT", 0.3)
    with pytest.raises(ckpt.CheckpointCorrupt, match="missing or stale"):
        ckpt.save_state(d, {"w": np.ones((1,), "float32")},
                        process_index=0, num_processes=2)
    # layout mismatch (sidecar from an n=4 run) is equally rejected
    json.dump({"entries": {}, "crc": {"shard_00001-of-00004.npz": 1}},
              open(side1, "w"))
    with pytest.raises(ckpt.CheckpointCorrupt, match="missing or stale"):
        ckpt.save_state(d, {"w": np.ones((1,), "float32")},
                        process_index=0, num_processes=2)


def test_two_process_save_merges_fresh_sidecars(tmp_path):
    """The happy multi-process path still works: p1 saves its shard,
    then p0 merges both and commits; reassembly sees both pieces."""
    d = str(tmp_path / "c0")
    a = np.arange(4, dtype="float32")
    b = np.arange(4, 8).astype("float32")
    ckpt.save_state(d, {"pa": a}, process_index=1, num_processes=2)
    ckpt.save_state(d, {"pb": b}, process_index=0, num_processes=2)
    assert ckpt.is_valid(d)
    out, _ = ckpt.load_state(d)
    np.testing.assert_array_equal(out["pa"], a)
    np.testing.assert_array_equal(out["pb"], b)
    assert not [n for n in os.listdir(d) if n.startswith(".side_")]


@pytest.mark.chaos
def test_chaos_mid_save_tear_trainer_resumes(tmp_path):
    """Torn-write chaos during Trainer.train: every torn serial is
    skipped at resume, the newest intact one loads."""
    site, prob = "checkpoint.shard_write", 0.5
    seed = _seed_where(site, prob, skip_first=1, fire_within=6)
    flags.set_flag("chaos_seed", seed)
    flags.set_flag("chaos_spec", f"{site}=truncate:{prob}")
    root = str(tmp_path / "ck")
    t1 = _trainer(root, step_interval=1, epoch_interval=10)
    data = _batches(6)
    t1.train(num_epochs=1, event_handler=lambda e: None,
             reader=lambda: iter(data), feed_order=["x", "y"])
    torn = {n for s, n, k in chaos.schedule() if s == site}
    assert torn, "seed must tear at least one save"
    # serial k <-> the k-th shard write; torn ones fail CRC validation
    for k in range(6):
        assert ckpt.is_valid(
            os.path.join(root, f"checkpoint_{k}")) == (k not in torn)
    newest_valid = max(k for k in range(6) if k not in torn)
    assert ckpt.latest_checkpoint(root) == newest_valid
    flags.set_flag("chaos_spec", "")
    t2 = _trainer(root, step_interval=1, epoch_interval=10)
    # meta of serial k records k+1 completed steps (step_interval=1)
    assert t2.step_offset == newest_valid + 1
    w, = [n for n in t2.scope.var_names() if n.endswith(".w_0")]
    assert np.isfinite(np.asarray(t2.scope.find_var(w))).all()


# -------------------------------------------------- trainer guard policies

@pytest.mark.chaos
def test_nan_policy_skip_step(tmp_path):
    site, prob = "trainer.step", 0.4
    seed = _seed_where(site, prob, skip_first=1, fire_within=10)
    flags.set_flag("chaos_seed", seed)
    flags.set_flag("chaos_spec", f"{site}=nan:{prob}")
    flags.set_flag("nan_policy", "skip_step")
    flags.set_flag("bad_step_limit", 50)
    skipped0 = obs.REGISTRY.get("trainer_skipped_steps_total").value
    bad0 = obs.REGISTRY.get("trainer_bad_steps_total").total()
    seen = {"empty": 0, "full": 0}

    def handler(e):
        if isinstance(e, pt.EndStepEvent):
            seen["empty" if not e.metrics else "full"] += 1

    try:
        t = _trainer()
        t.train(num_epochs=1, event_handler=handler,
                reader=lambda: iter(_batches(10)), feed_order=["x", "y"])
    finally:
        flags.set_flag("nan_policy", "raise")
        flags.set_flag("bad_step_limit", 5)
    n_poisoned = len([1 for s, n, k in chaos.schedule() if s == site])
    assert n_poisoned > 0
    assert seen["empty"] == n_poisoned and seen["full"] == 10 - n_poisoned
    assert obs.REGISTRY.get("trainer_skipped_steps_total").value \
        - skipped0 == n_poisoned
    assert obs.REGISTRY.get("trainer_bad_steps_total").total() \
        - bad0 == n_poisoned


@pytest.mark.chaos
def test_nan_policy_rollback(tmp_path):
    site, prob = "trainer.step", 0.3
    # first fault must come after the first checkpoint exists (step 0)
    seed = _seed_where(site, prob, skip_first=2, fire_within=12)
    flags.set_flag("chaos_seed", seed)
    flags.set_flag("chaos_spec", f"{site}=nan:{prob}")
    flags.set_flag("nan_policy", "rollback")
    flags.set_flag("bad_step_limit", 50)
    rb0 = obs.REGISTRY.get("trainer_rollbacks_total").value
    try:
        t = _trainer(str(tmp_path / "ck"), step_interval=1)
        t.train(num_epochs=1, event_handler=lambda e: None,
                reader=lambda: iter(_batches(12)), feed_order=["x", "y"])
    finally:
        flags.set_flag("nan_policy", "raise")
        flags.set_flag("bad_step_limit", 5)
    n_bad = len(chaos.schedule())
    assert n_bad > 0
    assert obs.REGISTRY.get("trainer_rollbacks_total").value - rb0 == n_bad
    w, = [n for n in t.scope.var_names() if n.endswith(".w_0")]
    assert np.isfinite(np.asarray(t.scope.find_var(w))).all()


@pytest.mark.chaos
def test_nan_policy_raise_and_circuit_breaker():
    flags.set_flag("chaos_seed", 0)
    flags.set_flag("chaos_spec", "trainer.step=nan:1.0")
    t = _trainer()
    with pytest.raises(guard.BadStepError):
        t.train(num_epochs=1, event_handler=lambda e: None,
                reader=lambda: iter(_batches(4)), feed_order=["x", "y"])
    # skip_step cannot out-skip the breaker
    flags.set_flag("nan_policy", "skip_step")
    flags.set_flag("bad_step_limit", 3)
    try:
        t2 = _trainer()
        with pytest.raises(guard.CircuitBreakerOpen, match="3 consecutive"):
            t2.train(num_epochs=1, event_handler=lambda e: None,
                     reader=lambda: iter(_batches(8)),
                     feed_order=["x", "y"])
    finally:
        flags.set_flag("nan_policy", "raise")
        flags.set_flag("bad_step_limit", 5)


# ------------------------------------------------------------- preemption

def test_sigterm_checkpoints_and_resumes_at_step(tmp_path):
    root = str(tmp_path / "ck")
    steps_seen = []

    def handler(e):
        if isinstance(e, pt.EndStepEvent):
            steps_seen.append(e.step)
            if e.step == 2:          # preemption notice mid-epoch
                signal.raise_signal(signal.SIGTERM)

    t1 = _trainer(root, step_interval=100, epoch_interval=100)
    t1.train(num_epochs=2, event_handler=handler,
             reader=lambda: iter(_batches(6)), feed_order=["x", "y"])
    assert t1.preempted and steps_seen == [0, 1, 2]
    assert ckpt.latest_checkpoint(root) == 0   # the emergency serial

    # resume: fast-forward past the 3 completed steps, no replay
    t2 = _trainer(root, step_interval=100, epoch_interval=100)
    assert t2.epoch_offset == 0 and t2.step_offset == 3
    resumed = []

    def handler2(e):
        if isinstance(e, pt.BeginStepEvent):
            resumed.append((e.epoch, e.step))

    t2.train(num_epochs=1, event_handler=handler2,
             reader=lambda: iter(_batches(6)), feed_order=["x", "y"])
    assert resumed == [(0, 3), (0, 4), (0, 5)]
    assert not t2.preempted


def test_preemption_metric_and_handler_restoration(tmp_path):
    old = signal.getsignal(signal.SIGTERM)
    pre0 = obs.REGISTRY.get("trainer_preemptions_total").value
    t = _trainer(str(tmp_path / "ck"))

    def handler(e):
        if isinstance(e, pt.EndStepEvent):
            signal.raise_signal(signal.SIGTERM)

    t.train(num_epochs=1, event_handler=handler,
            reader=lambda: iter(_batches(4)), feed_order=["x", "y"])
    assert obs.REGISTRY.get("trainer_preemptions_total").value == pre0 + 1
    assert signal.getsignal(signal.SIGTERM) == old


# --------------------------------------------------------- task-queue plane

def test_client_context_manager_and_auto_task_failed():
    m = TaskMaster()
    m.set_dataset([f"s{i}" for i in range(3)])
    srv, (host, port) = serve_master(m)
    try:
        with TaskMasterClient(host, port) as c:
            t = c.get_task()
            with pytest.raises(RuntimeError, match="boom"):
                with c.processing(t):
                    raise RuntimeError("boom")
            # the lease came straight back (no 60s timeout wait)
            s = m.stats()
            assert s["pending"] == 0 and s["todo"] == 3
            t2 = c.get_task()
            with c.processing(t2):
                pass
            assert m.stats()["done"] == 1
        assert c._sock is None      # context exit closed the socket
    finally:
        srv.shutdown()


@pytest.mark.chaos
def test_client_retries_through_socket_drop_chaos():
    site, prob = "task_queue.rpc", 0.35
    seed = _seed_where(site, prob, skip_first=1, fire_within=8)
    # no 3-in-a-row fire anywhere in the window we use, so the default
    # retry budget always wins
    for s in range(seed, 1000):
        ok = not any(all(_fire(s, site, i + j, prob) for j in range(3))
                     for i in range(40))
        if ok and not _fire(s, site, 0, prob) \
                and any(_fire(s, site, i, prob) for i in range(1, 8)):
            seed = s
            break
    flags.set_flag("chaos_seed", seed)
    flags.set_flag("chaos_spec", f"{site}=raise:{prob}")
    att0 = obs.REGISTRY.get("retry_attempts_total").total()
    m = TaskMaster()
    m.set_dataset([f"s{i}" for i in range(6)])
    srv, (host, port) = serve_master(m)
    try:
        with TaskMasterClient(host, port) as c:
            done = 0
            while True:
                t = c.get_task()
                if t is None or t.epoch > 0:
                    break
                c.task_finished(t.task_id)
                done += 1
        assert done == 6
    finally:
        srv.shutdown()
    injected = len([1 for s_, n, k in chaos.schedule() if s_ == site])
    assert injected > 0
    assert obs.REGISTRY.get("retry_attempts_total").total() \
        - att0 >= injected


def test_client_reconnects_after_real_socket_close():
    m = TaskMaster()
    m.set_dataset(["a", "b"])
    srv, (host, port) = serve_master(m)
    try:
        c = TaskMasterClient(host, port)
        t = c.get_task()
        assert t is not None
        c._sock.close()             # yank the wire mid-session
        assert c.stats()["pending"] == 1    # re-dialed transparently
        c.close()
    finally:
        srv.shutdown()


# -------------------------------------------------- acceptance + soak

def _chaos_spec_for_acceptance(nan_p, tear_p, drop_p):
    return (f"trainer.step=nan:{nan_p};"
            f"checkpoint.shard_write=truncate:{tear_p};"
            f"task_queue.rpc=raise:{drop_p}")


def _acceptance_seed(nan_p, tear_p, drop_p):
    """One seed that (a) leaves the first two steps clean so a valid
    checkpoint exists before the first NaN, (b) never fires the first
    shard write (one intact serial), (c) fires every fault kind at
    least once in 50 steps, (d) never drops the socket 3x in a row."""
    for s in range(2000):
        if _fire(s, "trainer.step", 0, nan_p) or \
                _fire(s, "trainer.step", 1, nan_p):
            continue
        if _fire(s, "checkpoint.shard_write", 0, tear_p):
            continue
        if not any(_fire(s, "trainer.step", i, nan_p) for i in range(50)):
            continue
        if not any(_fire(s, "checkpoint.shard_write", i, tear_p)
                   for i in range(25)):
            continue
        if not any(_fire(s, "task_queue.rpc", i, drop_p)
                   for i in range(60)):
            continue
        if any(all(_fire(s, "task_queue.rpc", i + j, drop_p)
                   for j in range(3)) for i in range(120)):
            continue
        return s
    raise AssertionError("no acceptance seed found")


def _run_chaos_training(root, seed, spec, n_steps=50, epochs=5):
    """One fully-armed run: NaN poison on the step, torn checkpoint
    shards, dropped task-queue sockets — reader leases every batch
    through the master.  Returns (trainer, chaos schedule)."""
    chaos.reset()
    flags.set_flag("chaos_seed", seed)
    flags.set_flag("chaos_spec", spec)
    flags.set_flag("nan_policy", "rollback")
    flags.set_flag("bad_step_limit", 25)
    per_epoch = n_steps // epochs
    data = _batches(per_epoch)
    m = TaskMaster(lease_timeout=30.0)
    m.set_dataset([str(i) for i in range(per_epoch)])
    srv, (host, port) = serve_master(m)
    try:
        client = TaskMasterClient(host, port)

        def reader():
            first = None
            while True:
                t = client.get_task()
                if t is None:
                    return
                if first is None:
                    first = t.epoch
                if t.epoch != first:    # next pass: hand the lease back
                    client.task_failed(t.task_id)
                    return
                with client.processing(t):
                    for sh in t.shards:
                        yield data[int(sh)]

        t = _trainer(root, step_interval=2, epoch_interval=1)
        steps = {"n": 0}

        def handler(e):
            if isinstance(e, pt.EndStepEvent):
                steps["n"] += 1

        t.train(num_epochs=epochs, event_handler=handler, reader=reader,
                feed_order=["x", "y"])
        client.close()
        assert steps["n"] == n_steps
        return t, chaos.schedule()
    finally:
        srv.shutdown()
        flags.set_flag("chaos_spec", "")
        flags.set_flag("nan_policy", "raise")
        flags.set_flag("bad_step_limit", 5)


@pytest.mark.chaos
def test_acceptance_50_step_armed_run_completes_and_replays(tmp_path):
    """ISSUE 2 acceptance: NaN-poison + torn-write + socket-drop armed
    at a fixed seed, a 50-step train completes with no operator in the
    loop, and the same seed replays the identical fault schedule."""
    nan_p, tear_p, drop_p = 0.12, 0.3, 0.15
    seed = _acceptance_seed(nan_p, tear_p, drop_p)
    spec = _chaos_spec_for_acceptance(nan_p, tear_p, drop_p)
    rb0 = obs.REGISTRY.get("trainer_rollbacks_total").value
    t1, sched1 = _run_chaos_training(str(tmp_path / "a"), seed, spec)
    by_site = {}
    for s, n, k in sched1:
        by_site.setdefault(s, []).append(n)
    assert set(by_site) == {"trainer.step", "checkpoint.shard_write",
                            "task_queue.rpc"}
    assert obs.REGISTRY.get("trainer_rollbacks_total").value > rb0
    # torn serials were skipped: the newest VALID checkpoint loads
    root = str(tmp_path / "a")
    assert ckpt.latest_checkpoint(root) >= 0
    raw = ckpt.latest_checkpoint(root, require_valid=False)
    torn_alive = [s for s in range(raw + 1)
                  if os.path.isdir(os.path.join(root, f"checkpoint_{s}"))
                  and not ckpt.is_valid(os.path.join(root,
                                                     f"checkpoint_{s}"))]
    state, meta, serial = ckpt.load_checkpoint(root)
    assert serial not in torn_alive
    w, = [n for n in t1.scope.var_names() if n.endswith(".w_0")]
    assert np.isfinite(np.asarray(t1.scope.find_var(w))).all()
    # exact replay: a second armed run fires the identical schedule
    _, sched2 = _run_chaos_training(str(tmp_path / "b"), seed, spec)
    assert sched2 == sched1


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_soak_deterministic(tmp_path):
    """Longer mixed-fault soak (excluded from tier-1 by the slow mark):
    200 steps under the full fault mix, training completes and every
    recovery counter moved."""
    nan_p, tear_p, drop_p = 0.12, 0.3, 0.15
    seed = _acceptance_seed(nan_p, tear_p, drop_p)
    spec = _chaos_spec_for_acceptance(nan_p, tear_p, drop_p)
    rb0 = obs.REGISTRY.get("trainer_rollbacks_total").value
    inj0 = obs.REGISTRY.get("resilience_faults_injected_total").total()
    t, sched = _run_chaos_training(str(tmp_path / "soak"), seed, spec,
                                   n_steps=200, epochs=10)
    assert obs.REGISTRY.get("trainer_rollbacks_total").value > rb0
    assert obs.REGISTRY.get(
        "resilience_faults_injected_total").total() > inj0
    assert len(sched) >= 10


# ------------------------------------------------------- executor site

@pytest.mark.chaos
def test_executor_run_fault_site():
    flags.set_flag("chaos_seed", 0)
    flags.set_flag("chaos_spec", "executor.run=raise:1.0")
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [2], dtype="float32")
        y = layers.mean(x)
    exe = pt.Executor(pt.CPUPlace())
    with pytest.raises(chaos.InjectedFault):
        exe.run(main, feed={"x": np.ones((2, 2), "float32")},
                fetch_list=[y])
    flags.set_flag("chaos_spec", "")
    out, = exe.run(main, feed={"x": np.ones((2, 2), "float32")},
                   fetch_list=[y])
    assert np.isclose(float(out), 1.0)
