"""Fuse-block transpiler: whole-transformer-block pattern matching.

InferenceTranspiler-style program rewrite (same family as the conv+BN
fold): scan the global block for the pre-norm transformer-block op
sequence that models/transformer.py's encoder_layer(fused=True) emits —

    layer_norm -> fused_mha -> elementwise_add (residual)
    -> layer_norm -> mul -> elementwise_add (bias) -> relu
    -> mul -> elementwise_add (bias) -> elementwise_add (residual)

— and collapse each match into ONE ``fused_transformer_block`` op
(ops/fused_ops.py), which lowers to the VMEM-resident Pallas block
kernel (kernels/fused_block.py) on TPU.  Gated by FLAGS_fuse_block via
``maybe_fuse``; matching is conservative — any dataflow mismatch, an
externally-consumed intermediate, dropout in the block, or non-standard
layer_norm axes leaves the ops unfused (degrade to the composition,
never to wrong results).
"""
from __future__ import annotations

from typing import Optional

from ..core import flags
from ..framework.program import Operator, Program

# op-type skeleton of one pre-norm block, in program order
_PATTERN = ("layer_norm", "fused_mha", "elementwise_add", "layer_norm",
            "mul", "elementwise_add", "relu", "mul", "elementwise_add",
            "elementwise_add")


class FuseBlockTranspiler:
    def transpile(self, program: Optional[Program] = None) -> int:
        """Rewrite in place; returns the number of blocks fused."""
        from ..framework.program import default_main_program
        program = program or default_main_program()
        block = program.global_block()
        ops = block.ops

        # consumers per var across ALL blocks: an intermediate read
        # outside the fused window must keep the unfused ops
        consumers: dict = {}
        for b in program.blocks:
            for op in b.ops:
                for n in op.input_names():
                    consumers[n] = consumers.get(n, 0) + 1

        new_ops = []
        i = 0
        fused = 0
        while i < len(ops):
            repl, width = self._try_match(block, ops, i, consumers)
            if repl is not None:
                new_ops.append(repl)
                i += width
                fused += 1
            else:
                new_ops.append(ops[i])
                i += 1
        if fused:
            block.ops = new_ops
            program._bump()
            # post-condition (ISSUE 10): a fusion that severed dataflow
            # (wrong consumer count, half-collapsed window) re-verifies
            # here as a named finding instead of a silent miscompile
            from .. import analysis
            analysis.maybe_check_transpiled(program,
                                            "FuseBlockTranspiler")
        return fused

    def _try_match(self, block, ops, i, consumers):
        n = len(_PATTERN)
        if i + n > len(ops):
            return None, 0
        win = ops[i:i + n]
        if tuple(op.type for op in win) != _PATTERN:
            return None, 0
        (ln1, mha, res1, ln2, mul1, badd1, relu, mul2, badd2,
         res2) = win

        def out0(op, slot):
            return op.outputs.get(slot, [None])[0]

        def in0(op, slot):
            return op.inputs.get(slot, [None])[0]

        x = in0(ln1, "X")
        # dataflow: each stage consumes the previous stage's output,
        # residuals reference x and the first residual sum
        chain = (
            in0(mha, "X") == out0(ln1, "Y")
            and not mha.inputs.get("XKV")
            and in0(res1, "X") == out0(mha, "Out")
            and in0(res1, "Y") == x
            and in0(ln2, "X") == out0(res1, "Out")
            and in0(mul1, "X") == out0(ln2, "Y")
            and in0(badd1, "X") == out0(mul1, "Out")
            and in0(relu, "X") == out0(badd1, "Out")
            and in0(mul2, "X") == out0(relu, "Out")
            and in0(badd2, "X") == out0(mul2, "Out")
            and in0(res2, "X") == out0(badd2, "Out")
            and in0(res2, "Y") == out0(res1, "Out"))
        if not chain:
            return None, 0
        # both layer_norms: affine, over the last axis of a rank-3
        # activation (the kernel normalizes dim -1)
        for ln in (ln1, ln2):
            if not (ln.inputs.get("Scale") and ln.inputs.get("Bias")
                    and int(ln.attrs.get("begin_norm_axis", 1)) == 2):
                return None, 0
        # MLP matmuls must be the fc flattening the kernel assumes
        if int(mul1.attrs.get("x_num_col_dims", 1)) != 2 or \
                int(mul2.attrs.get("x_num_col_dims", 1)) != 2:
            return None, 0
        # shapes: square block (wo: [E, D], w1: [D, F], w2: [F, D])
        try:
            D = int(block.var(x).shape[-1])
            wq = block.var(in0(mha, "Wq"))
            wo = block.var(in0(mha, "Wo"))
            w1 = block.var(in0(mul1, "Y"))
            w2 = block.var(in0(mul2, "Y"))
            if (wq.shape[0] != D or wo.shape[1] != D
                    or w1.shape[0] != D or w2.shape[1] != D
                    or w1.shape[1] != w2.shape[0]):
                return None, 0
        except Exception:
            return None, 0
        # every intermediate must be internal to the window (res1 is
        # read twice inside; everything else once)
        internal = {out0(ln1, "Y"): 1, out0(mha, "Out"): 1,
                    out0(res1, "Out"): 2, out0(ln2, "Y"): 1,
                    out0(mul1, "Out"): 1, out0(badd1, "Out"): 1,
                    out0(relu, "Out"): 1, out0(mul2, "Out"): 1,
                    out0(badd2, "Out"): 1}
        for name, want in internal.items():
            if consumers.get(name, 0) != want:
                return None, 0
            if block.has_var(name) and block.var(name).persistable:
                return None, 0
        repl = Operator(
            block, "fused_transformer_block",
            {"X": [x],
             "Ln1Scale": [in0(ln1, "Scale")],
             "Ln1Bias": [in0(ln1, "Bias")],
             "Wq": [in0(mha, "Wq")], "Wk": [in0(mha, "Wk")],
             "Wv": [in0(mha, "Wv")], "Wo": [in0(mha, "Wo")],
             "Ln2Scale": [in0(ln2, "Scale")],
             "Ln2Bias": [in0(ln2, "Bias")],
             "W1": [in0(mul1, "Y")], "B1": [in0(badd1, "Y")],
             "W2": [in0(mul2, "Y")], "B2": [in0(badd2, "Y")]},
            {"Out": [out0(res2, "Out")]},
            {"n_head": int(mha.attrs["n_head"]),
             "causal": bool(mha.attrs.get("causal", False)),
             "eps1": float(ln1.attrs.get("epsilon", 1e-5)),
             "eps2": float(ln2.attrs.get("epsilon", 1e-5))})
        return repl, len(_PATTERN)


def maybe_fuse(program: Optional[Program] = None) -> int:
    """Apply FuseBlockTranspiler when FLAGS_fuse_block is on; returns
    the number of blocks fused (0 when off or nothing matched)."""
    if not flags.get_flag("fuse_block"):
        return 0
    return FuseBlockTranspiler().transpile(program)
