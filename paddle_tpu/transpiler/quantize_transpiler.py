"""QAT quantize transpiler.

Capability parity with /root/reference/python/paddle/fluid/contrib/quantize/
quantize_transpiler.py:81 (QuantizeTranspiler): rewrites a training program
so every quantizable op (mul/matmul/conv2d) reads fake-quantized inputs and
weights — abs_max or moving_average_abs_max activation quantization,
channel-wise abs_max weight quantization — training stays fp with
straight-through gradients, export folds to int8 scales.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..framework.program import Program
from ..framework import unique_name

QUANTIZABLE_OPS = ("mul", "matmul", "conv2d", "conv2d_transpose")


class QuantizeTranspiler:
    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "abs_max",
                 weight_quantize_type: str = "channel_wise_abs_max",
                 moving_rate: float = 0.9):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.moving_rate = moving_rate

    def training_transpile(self, program: Optional[Program] = None,
                           startup_program: Optional[Program] = None):
        from ..framework.program import (default_main_program,
                                         default_startup_program)
        program = program or default_main_program()
        self._startup = startup_program or default_startup_program()
        block = program.global_block()
        params = {p.name for p in program.all_parameters()}
        # cache key: (var name, weight quant_axis for this consumer kind) —
        # a weight feeding both a conv2d (axis 0) and a mul/matmul (axis 1)
        # must get two channel-wise quantizations, not reuse the first
        quantized = {}

        new_ops: list = []
        for op in list(block.ops):
            if op.type in QUANTIZABLE_OPS:
                self._consumer_type = op.type
                axis_kind = 0 if op.type == "conv2d" else 1
                for slot, names in op.inputs.items():
                    new_names = []
                    for n in names:
                        key = (n, axis_kind if n in params else None)
                        if key not in quantized:
                            quantized[key] = self._insert_quant(
                                block, new_ops, n, n in params)
                        new_names.append(quantized[key])
                    op.inputs[slot] = new_names
            new_ops.append(op)
        block.ops = new_ops
        program._bump()
        return program

    def _insert_quant(self, block, new_ops, name: str, is_weight: bool):
        var = block.var(name)
        qname = unique_name.generate(name + ".quantized")
        out = block.create_var(qname, shape=var.shape, dtype=var.dtype)
        scale = block.create_var(unique_name.generate(name + ".scale"),
                                 dtype="float32")
        if is_weight:
            if self.weight_quantize_type == "channel_wise_abs_max":
                op_type = "fake_channel_wise_quantize_abs_max"
            else:
                op_type = "fake_quantize_abs_max"
            attrs = {"bit_length": self.weight_bits}
            inputs = {"X": [name]}
        else:
            if self.activation_quantize_type == "moving_average_abs_max":
                op_type = "fake_quantize_moving_average_abs_max"
                # moving scale is persistable state, initialised to 1.0 in
                # the startup program (ref quantize_transpiler scale state)
                in_scale = block.create_var(
                    unique_name.generate(name + ".in_scale"),
                    shape=[], dtype="float32", persistable=True)
                sb = self._startup.global_block()
                sb.create_var(in_scale.name, shape=[], dtype="float32",
                              persistable=True)
                sb.append_op("fill_constant", {},
                             {"Out": [in_scale.name]},
                             {"shape": [], "dtype": "float32",
                              "value": 1.0})
                inputs = {"X": [name], "InScale": [in_scale.name]}
                attrs = {"bit_length": self.activation_bits,
                         "moving_rate": self.moving_rate, "is_test": False}
                # OutScale writes back the persistable InScale var, so the
                # moving average actually advances across steps (executor
                # persists state by var name)
                scale = in_scale
            else:
                op_type = "fake_quantize_abs_max"
                attrs = {"bit_length": self.activation_bits}
                inputs = {"X": [name]}
        if is_weight and op_type == "fake_channel_wise_quantize_abs_max":
            # ref quantization_pass: quant_axis 1 for mul/matmul ([in,out])
            # and conv2d_transpose (IOHW), 0 for conv2d (OIHW)
            attrs["quant_axis"] = 0 if self._consumer_type == "conv2d" else 1
        from ..framework.program import Operator
        op = Operator(block, op_type, inputs,
                      {"Out": [qname], "OutScale": [scale.name]}, attrs)
        new_ops.append(op)
        return qname

    def freeze_program(self, program: Program):
        """Export-time: flip moving-average quant ops to is_test (scales
        frozen) — the int8 kernel swap is XLA's int8 matmul when targeted."""
        for b in program.blocks:
            for op in b.ops:
                if op.type == "fake_quantize_moving_average_abs_max":
                    op.attrs["is_test"] = True
        program._bump()
        return program
