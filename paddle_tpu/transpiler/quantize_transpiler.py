"""QAT quantize transpiler.

Capability parity with /root/reference/python/paddle/fluid/contrib/quantize/
quantize_transpiler.py:81 (QuantizeTranspiler): rewrites a training program
so every quantizable op (mul/matmul/conv2d) reads fake-quantized inputs and
weights — abs_max or moving_average_abs_max activation quantization,
channel-wise abs_max weight quantization — training stays fp with
straight-through gradients, export folds to int8 scales.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..framework.program import Parameter, Program
from ..framework import unique_name

QUANTIZABLE_OPS = ("mul", "matmul", "conv2d", "conv2d_transpose")


class QuantizeTranspiler:
    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "abs_max",
                 weight_quantize_type: str = "channel_wise_abs_max",
                 moving_rate: float = 0.9):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.moving_rate = moving_rate

    def training_transpile(self, program: Optional[Program] = None,
                           startup_program: Optional[Program] = None):
        from ..framework.program import (default_main_program,
                                         default_startup_program)
        program = program or default_main_program()
        self._startup = startup_program or default_startup_program()
        block = program.global_block()
        params = {p.name for p in program.all_parameters()}
        # cache key: (var name, weight quant_axis for this consumer kind) —
        # a weight feeding both a conv2d (axis 0) and a mul/matmul (axis 1)
        # must get two channel-wise quantizations, not reuse the first
        quantized = {}

        new_ops: list = []
        for op in list(block.ops):
            if op.type in QUANTIZABLE_OPS:
                self._consumer_type = op.type
                axis_kind = 0 if op.type == "conv2d" else 1
                for slot, names in op.inputs.items():
                    new_names = []
                    for n in names:
                        key = (n, axis_kind if n in params else None)
                        if key not in quantized:
                            quantized[key] = self._insert_quant(
                                block, new_ops, n, n in params)
                        new_names.append(quantized[key])
                    op.inputs[slot] = new_names
            new_ops.append(op)
        block.ops = new_ops
        program._bump()
        # post-condition (ISSUE 10): the fake-quant splice must
        # re-verify clean (every rewired consumer reads a produced var)
        from .. import analysis
        analysis.maybe_check_transpiled(
            program, "QuantizeTranspiler.training_transpile")
        return program

    def _insert_quant(self, block, new_ops, name: str, is_weight: bool):
        var = block.var(name)
        qname = unique_name.generate(name + ".quantized")
        out = block.create_var(qname, shape=var.shape, dtype=var.dtype)
        scale = block.create_var(unique_name.generate(name + ".scale"),
                                 dtype="float32")
        if is_weight:
            if self.weight_quantize_type == "channel_wise_abs_max":
                op_type = "fake_channel_wise_quantize_abs_max"
            else:
                op_type = "fake_quantize_abs_max"
            attrs = {"bit_length": self.weight_bits}
            inputs = {"X": [name]}
        else:
            if self.activation_quantize_type == "moving_average_abs_max":
                op_type = "fake_quantize_moving_average_abs_max"
                # moving scale is persistable state, initialised to 1.0 in
                # the startup program (ref quantize_transpiler scale state).
                # The name is DETERMINISTIC (no unique suffix): transpiling
                # a train program and its for_test clone must yield the
                # SAME scale var, so the scales trained by one are seen by
                # the other through the scope — the reference's standard
                # QAT flow (train, then freeze the test program)
                scale_name = name + ".quant_in_scale"
                in_scale = (block.var(scale_name)
                            if block.has_var(scale_name) else
                            block.create_var(scale_name, shape=[],
                                             dtype="float32",
                                             persistable=True))
                sb = self._startup.global_block()
                if not sb.has_var(scale_name):
                    sb.create_var(scale_name, shape=[], dtype="float32",
                                  persistable=True)
                    sb.append_op("fill_constant", {},
                                 {"Out": [scale_name]},
                                 {"shape": [], "dtype": "float32",
                                  "value": 1.0})
                inputs = {"X": [name], "InScale": [in_scale.name]}
                attrs = {"bit_length": self.activation_bits,
                         "moving_rate": self.moving_rate, "is_test": False}
                # OutScale writes back the persistable InScale var, so the
                # moving average actually advances across steps (executor
                # persists state by var name)
                scale = in_scale
            else:
                op_type = "fake_quantize_abs_max"
                attrs = {"bit_length": self.activation_bits}
                inputs = {"X": [name]}
        if is_weight and op_type == "fake_channel_wise_quantize_abs_max":
            # ref quantization_pass: quant_axis 1 for mul/matmul ([in,out])
            # and conv2d_transpose (IOHW), 0 for conv2d (OIHW)
            attrs["quant_axis"] = 0 if self._consumer_type == "conv2d" else 1
        from ..framework.program import Operator
        op = Operator(block, op_type, inputs,
                      {"Out": [qname], "OutScale": [scale.name]}, attrs)
        new_ops.append(op)
        return qname

    def freeze_program(self, program: Program, scope=None,
                       quantize_dtype: str = "int8"):
        """Export-time freeze with REAL quantized execution.

        The reference's freeze only folds scales and hopes a downstream
        engine has an int8 kernel (contrib quantize_transpiler.py:114 —
        "the quantized ops ... are only supported in int8 kernels").
        Here the rewrite emits genuinely quantized programs:

          * weights are quantized ONCE (per-channel absmax along the
            recorded quant_axis) into int8/fp8 arrays stored in the
            scope, with f32 scale vectors beside them;
          * each quantizable consumer (fc's ``mul``, plain ``matmul``,
            ``conv2d``) becomes a ``quantized_matmul`` /
            ``quantized_conv2d`` op reading the RAW activation: the op
            quantizes it on the fly against the TRAINED moving-average
            scale (wired in as InScale) or dynamically (abs_max), and
            contracts on the low-precision units
            (int8 x int8 -> int32 via preferred_element_type);
          * fake-quantize ops whose outputs became dead are dropped;
            surviving moving-average ops flip to is_test.

        Programs whose quant ops were never trained are REJECTED: a
        missing weight/scale in the scope (startup or training never
        ran), or a moving-average scale still at its 1.0 initializer,
        raises instead of silently folding garbage scales.
        """
        import jax.numpy as jnp
        import numpy as np

        from ..core.enforce import EnforceNotMet
        from ..framework.program import Operator
        from ..ops.quantize_ops import channel_scales, qspec, quantize_array
        if scope is None:
            from ..framework.executor import global_scope
            scope = global_scope()
        qspec(quantize_dtype)           # validate the spelling up front
        block = program.global_block()
        params = {p.name for p in program.all_parameters()}

        # quantized-var name -> (source var, fake op) for every fake
        # quantize op in the program
        fake_quant = ("fake_quantize_abs_max",
                      "fake_quantize_moving_average_abs_max",
                      "fake_quantize_range_abs_max",
                      "fake_channel_wise_quantize_abs_max")
        produced: dict = {}
        for op in block.ops:
            if op.type in fake_quant:
                produced[op.outputs["Out"][0]] = (op.inputs["X"][0], op)

        def _need(name, what):
            v = scope.find_var(name)
            if v is None:
                raise EnforceNotMet(
                    f"freeze_program: no recorded value for {what} "
                    f"{name!r} in the scope — run the startup program "
                    f"and train (or load trained params) before "
                    f"freezing; freezing an untrained program would "
                    f"fold garbage scales")
            return np.asarray(v)

        def _act_scale_input(src, fop):
            """InScale wiring for one quantized activation input: the
            trained moving-average scale var, or None (dynamic)."""
            if fop.type != "fake_quantize_moving_average_abs_max":
                return None
            scale_name = fop.inputs["InScale"][0]
            val = _need(scale_name, "moving-average activation scale")
            if float(np.asarray(val).reshape(())) == 1.0:
                raise EnforceNotMet(
                    f"freeze_program: activation scale {scale_name!r} "
                    f"is still at its 1.0 initializer — the quant op "
                    f"was never trained (no recorded scales); run "
                    f"training steps before freezing")
            return scale_name

        qweights: dict = {}     # (param, axis) -> (qname, scale_name)

        def _quantize_weight(wname, axis, kind):
            key = (wname, axis)
            if key in qweights:
                return qweights[key]
            W = _need(wname, "weight")
            if kind == "fake_channel_wise_quantize_abs_max":
                scales = channel_scales(W, axis)
            else:
                scales = np.maximum(np.abs(W).max(), 1e-8).astype(
                    "float32").reshape(())
            shape = [1] * W.ndim
            if scales.ndim:
                shape[axis] = -1
            wq = quantize_array(jnp.asarray(W),
                                jnp.asarray(scales).reshape(shape),
                                quantize_dtype)
            dt = "int8" if quantize_dtype == "int8" else \
                str(jnp.dtype(wq.dtype).name)
            qname = unique_name.generate(wname + ".quantized_w")
            sname = unique_name.generate(wname + ".w_scale")
            block.create_var(qname, shape=W.shape, dtype=dt,
                             persistable=True, stop_gradient=True)
            block.create_var(sname, shape=scales.shape, dtype="float32",
                             persistable=True, stop_gradient=True)
            scope.set_var(qname, wq)
            scope.set_var(sname, jnp.asarray(scales))
            qweights[key] = (qname, sname)
            return qweights[key]

        def _rewrite(op):
            """One consumer op -> its quantized twin, or None (keep)."""
            if op.type == "mul":
                w_slot, x_slot = "Y", "X"
            elif op.type == "matmul":
                if op.attrs.get("transpose_X") or \
                        op.attrs.get("transpose_Y") or \
                        float(op.attrs.get("alpha", 1.0)) != 1.0:
                    return None
                w_slot, x_slot = "Y", "X"
            elif op.type == "conv2d":
                w_slot, x_slot = "Filter", "Input"
            else:
                return None
            wq_name = op.inputs.get(w_slot, [None])[0]
            xq_name = op.inputs.get(x_slot, [None])[0]
            if wq_name not in produced or xq_name not in produced:
                return None
            w_src, w_fop = produced[wq_name]
            x_src, x_fop = produced[xq_name]
            if w_src not in params:
                return None     # weight input is not a parameter
            axis = int(w_fop.attrs.get("quant_axis",
                                       0 if op.type == "conv2d" else 1))
            qname, sname = _quantize_weight(w_src, axis, w_fop.type)
            in_scale = _act_scale_input(x_src, x_fop)
            if op.type == "conv2d":
                inputs = {"Input": [x_src], "Filter": [qname],
                          "FilterScale": [sname]}
                if in_scale:
                    inputs["InScale"] = [in_scale]
                return Operator(
                    block, "quantized_conv2d", inputs,
                    {"Output": [op.outputs["Output"][0]]},
                    {"quantize_dtype": quantize_dtype,
                     "strides": op.attrs.get("strides", [1, 1]),
                     "paddings": op.attrs.get("paddings", [0, 0]),
                     "dilations": op.attrs.get("dilations", [1, 1]),
                     "groups": op.attrs.get("groups", 1)})
            inputs = {"X": [x_src], "W": [qname], "WScale": [sname]}
            if in_scale:
                inputs["InScale"] = [in_scale]
            return Operator(
                block, "quantized_matmul", inputs,
                {"Out": [op.outputs["Out"][0]]},
                {"quantize_dtype": quantize_dtype,
                 "x_num_col_dims": op.attrs.get("x_num_col_dims", 1)})

        new_ops = []
        for op in block.ops:
            repl = _rewrite(op) if op.type in QUANTIZABLE_OPS else None
            new_ops.append(repl if repl is not None else op)

        # drop fake-quantize ops whose quantized outputs no longer feed
        # anything (the rewritten consumers read the raw sources)
        still_read = {n for op in new_ops if op.type not in fake_quant
                      for ns in op.inputs.values() for n in ns}
        kept = []
        for op in new_ops:
            if (op.type in fake_quant
                    and op.outputs["Out"][0] not in still_read):
                continue
            if op.type == "fake_quantize_moving_average_abs_max":
                op.attrs["is_test"] = True
            kept.append(op)
        block.ops = kept

        # drop ORPHANED fp32 weight Parameters: their consumers now
        # read the int8/fp8 twins, so leaving them declared would (a)
        # stage dead fp32 buffers from the scope every run and (b)
        # trip the verifier's orphan_param lint on every frozen program.
        # "used" walks EVERY block (like the orphan lint itself) — a
        # param read only inside a while/cond sub-block is not orphaned
        used = {n for b in program.blocks for op in b.ops
                for ns in list(op.inputs.values())
                + list(op.outputs.values()) for n in ns}
        for name in [n for n, v in block.vars.items()
                     if isinstance(v, Parameter) and n not in used]:
            del block.vars[name]
        program._bump()
        # post-condition (ISSUE 10): the frozen program must re-verify
        # clean — a half-rewritten consumer or a dangling fake-quant op
        # is a named diagnostic, not a silent miscompile
        from .. import analysis
        analysis.maybe_check_transpiled(
            program, "QuantizeTranspiler.freeze_program")
        return program
