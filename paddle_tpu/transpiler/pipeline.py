"""PipelineTranspiler — GPipe pipeline parallelism as a *program
transformation* on the Program IR.

The 2018 reference has NO pipeline parallelism (SURVEY §2.2 parallelism
table); its distributed modes are program rewrites
(distribute_transpiler.py:268), and this transpiler keeps that
discipline for the TPU-native capability: after transpile, the SAME
Program a user built for one device trains GPipe-style over a mesh
"pipe" axis —

  * the user marks stage cuts with ``layers.pipeline_boundary(x)`` —
    x may be a LIST of activations (pytree payload, e.g. hidden +
    residual); identity ops in un-transpiled programs (the later
    reference generations play this role with device_guard
    annotations);
  * the executor's shard_map plane partitions the forward op list at
    the markers into pp_degree stage sub-programs and runs the GPipe
    schedule: M microbatches stream through a ``lax.scan`` of ticks,
    each device runs its own stage (``lax.switch`` on the pipe axis
    index) and hands its activation to the next stage with
    ``lax.ppermute``; bubble ticks are masked out of the loss;
  * the backward schedule comes from differentiating the scan —
    jax.vjp reverses the ticks and the ppermutes, so each device
    computes gradients exactly for its own stage's parameters;
  * per-gradient ``c_allreduce_sum`` over the pipe axis is inserted
    after the backward (stage gradients are disjoint, so a plain sum —
    no 1/N — replicates the full gradient on every pipe rank), exactly
    like the data-parallel rewrite's mechanics.

Composes with DistributeTranspiler (data parallelism): transpile the
program with both and run with ``Executor(place, mesh=Mesh(devices.
reshape(dp, pp), ("data", "pipe")))``.  Under the pipeline plane only
the loss (and persistable state) is fetchable — per-layer activations
live inside the scan (the executor validates fetches up front).

Schedules: ``schedule="gpipe"`` (default) differentiates the forward
scan — the backward is the time-reversed pipeline, and the scan vjp
saves the M-tick boundary-payload carry history.  ``schedule="1f1b"``
(non-interleaved 1F1B / PipeDream-Flush) runs an EXPLICIT per-tick
backward: microbatch m's backward at stage s fires at tick 2P-1-s+m —
one tick behind its forward on the last stage — recomputing the stage
under jax.vjp from a ring buffer of boundary INPUTS bounded at 2P
slots (stages rematerialize anyway, so inputs are the only residuals).
Both schedules share the bubble fraction (P-1)/(M+P-1); 1F1B bounds
the in-flight buffer by P instead of M, and because its vjp lives
INSIDE each stage branch it also supports RNG ops (dropout) in stages,
which jax's cond partial-eval cannot differentiate across branches on
the gpipe plane.  Parity + dropout-determinism tests:
tests/test_pipeline_parallel.py.
"""
from __future__ import annotations

from typing import Optional

from ..core.enforce import check_arg
from ..framework.program import Program


class PipelineTranspiler:
    def __init__(self, axis_name: str = "pipe"):
        self.axis_name = axis_name

    def transpile(self, program: Program, pp_degree: int,
                  n_microbatches: Optional[int] = None,
                  schedule: str = "gpipe") -> None:
        """Rewrite `program` for pp_degree-way pipelining.

        The program must contain exactly pp_degree - 1
        ``pipeline_boundary`` marker ops (layers.pipeline_boundary) at
        payload-homogeneous activation cuts, and a training section
        (autodiff + optimizer ops from Optimizer.minimize).
        n_microbatches defaults to pp_degree; the batch dim of every
        feed must divide by it.  schedule: "gpipe" (scan + its vjp) or
        "1f1b" (explicit per-tick backward; bounds the in-flight
        boundary buffer to ~2*pp_degree microbatches instead of the
        scan carry's n_microbatches — same math, same bubble)."""
        check_arg(pp_degree >= 1,
                  f"pp_degree must be >= 1, got {pp_degree}")
        check_arg(schedule in ("gpipe", "1f1b"),
                  f"unknown pipeline schedule {schedule!r} "
                  f"(expected 'gpipe' or '1f1b')")
        if pp_degree == 1:
            return                      # degenerate: leave untouched
        check_arg(
            getattr(program, "_dist_pp_axis", None) is None,
            "program is already pipeline-transpiled; a second pass "
            "would stack duplicate gradient allreduces (P x grads)")
        block = program.global_block()
        markers = [op for op in block.ops
                   if op.type == "pipeline_boundary"]
        check_arg(
            len(markers) == pp_degree - 1,
            f"pp_degree={pp_degree} needs exactly {pp_degree - 1} "
            f"pipeline_boundary markers in the program, found "
            f"{len(markers)} (insert layers.pipeline_boundary at the "
            f"stage cuts)")
        # boundary payloads (pytrees of activations) ride the ppermute
        # ring as the scan carry: every marker must carry the same
        # TUPLE of shapes/dtypes
        shapes = set()
        for op in markers:
            sig = tuple(
                (tuple(block.var(n).shape or ()), str(block.var(n).dtype))
                for n in op.outputs["Out"])
            shapes.add(sig)
        check_arg(
            len(shapes) <= 1,
            f"pipeline_boundary payloads must share one tuple of "
            f"shapes/dtypes (the ppermute ring payload); found "
            f"{sorted(shapes)}")
        ad_idx = [i for i, op in enumerate(block.ops)
                  if op.type == "autodiff"]
        check_arg(ad_idx, "pipeline transpile needs a training program "
                          "(call Optimizer.minimize first)")
        idx = ad_idx[0]
        check_arg(all(block.ops.index(m) < idx for m in markers),
                  "pipeline_boundary markers must be in the forward "
                  "section (before the backward)")
        M = int(n_microbatches or pp_degree)
        # stage gradients are disjoint: sum over the pipe axis
        # replicates the full gradient (no 1/N — cf. the dp rewrite,
        # distribute_transpiler.py _insert_grad_allreduce)
        grads = list(block.ops[idx].attrs.get("grads", []))
        insert_at = idx + 1
        for g in grads:
            ar = g + "@PP_ALLREDUCE"
            if not block.has_var(ar):
                block.create_var(name=ar, dtype="float32")
            block.append_op("c_allreduce_sum", {"X": [g]}, {"Out": [ar]},
                            {"axis_name": self.axis_name},
                            index=insert_at)
            block.append_op("assign", {"X": [ar]}, {"Out": [g]}, {},
                            index=insert_at + 1)
            insert_at += 2
        program._dist_pp_axis = self.axis_name
        program._pp_degree = int(pp_degree)
        program._pp_microbatches = M
        program._pp_schedule = schedule
        # post-condition (ISSUE 10): the spliced allreduce/assign chain
        # must re-verify clean
        from .. import analysis
        analysis.maybe_check_transpiled(program, "PipelineTranspiler")
