"""Program transpilers (ref python/paddle/fluid/transpiler/).

What remains a program transformation on TPU:
  * QuantizeTranspiler — QAT rewrite (contrib/quantize/quantize_transpiler.py)
  * InferenceTranspiler — conv+BN fold (inference_transpiler.py:24); the
    rest of its fusions are XLA's job
  * memory_optimize/release_memory — no-ops kept for API parity: XLA's
    liveness analysis + buffer donation replace the liveness transpiler
    (memory_optimization_transpiler.py:491)
  * DistributeTranspiler — API-compatible shim mapping the pserver-era
    contract onto the mesh/sharding plane (distribute_transpiler.py:148)
  * TensorParallelTranspiler — Megatron-style tp as a layout rewrite on
    the Program (no 2018-reference analogue; the mode the reference
    lacked), executed by the mesh plane's GSPMD path
"""
from .quantize_transpiler import QuantizeTranspiler
from .inference_transpiler import InferenceTranspiler
from .fused_block import FuseBlockTranspiler
from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig)
from .tensor_parallel import TensorParallelTranspiler
from .context_parallel import ContextParallelTranspiler
from .expert_parallel import ExpertParallelTranspiler
from .pipeline import PipelineTranspiler


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """ref memory_optimization_transpiler.py:491.  The executor compiles
    the whole program with XLA, whose buffer liveness + donation subsumes
    the var-reuse rewrite; kept so user scripts run unchanged."""
    return input_program


def release_memory(input_program, skip_opt_set=None):
    """ref memory_optimization_transpiler.py:547 — same story as above."""
    return input_program
