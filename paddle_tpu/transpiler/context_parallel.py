"""ContextParallelTranspiler — ring-attention context parallelism as a
*program transformation* on the Program IR.

The reference has no long-context strategy at all (SURVEY §5: 2018-era
LoD + DynamicRNN); its distributed modes are program rewrites
(distribute_transpiler.py:268).  This transpiler keeps that discipline
for the TPU-native capability: after transpile, the SAME Program a user
built for one device trains with its sequence dimension sharded over a
mesh axis —

  * data feeds shard along dim 1 (the sequence), not the batch
    (`_dist_feed_shard_dim` marker, honored by the Executor's shard_map
    plane);
  * `fused_attention` ops lower to parallel/ring_attention.py inside the
    shard_map — K/V blocks rotate around the axis via ppermute with
    exact cross-chunk causal masking (`_dist_cp_axis` marker read from
    the LowerContext);
  * position-indexed parameters (e.g. the [T, D] sinusoid table —
    anything whose leading dim equals the sequence length) get a
    `(axis, None)` sharding so each device holds the slice matching its
    global positions;
  * per-gradient (c_allreduce_sum, 1/N scale) pairs are inserted after
    the backward, exactly like the data-parallel rewrite — shard losses
    are means over local tokens, so summed-and-scaled gradients equal
    the global-batch gradient.

Run with ``Executor(place, mesh=Mesh(devices, ("cp",)))``.  Composes
with the fused attention path only (the unfused path would need its
[T, T] bias sharded too — use fused_attention=True models for long
context, which is the point of the exercise).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from ..core.enforce import check_arg
from ..framework.program import Parameter, Program
from .distribute_transpiler import DistributeTranspiler


class ContextParallelTranspiler:
    def __init__(self, axis_name: str = "cp"):
        self.axis_name = axis_name

    def transpile(self, program: Program, cp_degree: int,
                  seq_len: Optional[int] = None,
                  seq_params: Optional[Sequence[str]] = None
                  ) -> Dict[str, tuple]:
        """Rewrite `program` for cp_degree-way sequence sharding.

        seq_len: the global sequence length (defaults to dim 1 of the
        first data var).  seq_params: names of position-indexed params
        to shard; defaults to every Parameter whose leading dim ==
        seq_len (the sinusoid-table pattern)."""
        axis = self.axis_name
        block = program.global_block()
        check_arg(cp_degree >= 1, f"cp_degree must be >= 1, got "
                                  f"{cp_degree}")
        if cp_degree == 1:
            return {}        # degenerate: leave the program untouched
        # only the fused path is cp-aware; the unfused matmul+softmax
        # attention would silently compute block-diagonal attention on
        # each local chunk
        check_arg(
            any(op.type in ("fused_attention", "fused_mha")
                for op in block.ops),
            "context-parallel transpile requires fused_attention/"
            "fused_mha ops (build the model with fused_attention=True); "
            "the unfused attention path cannot shard the sequence")
        if seq_len is None:
            data_vars = [v for v in block.vars.values() if v.is_data]
            check_arg(data_vars, "program has no data vars")
            cands = [v for v in data_vars
                     if v.shape and len(v.shape) >= 2]
            check_arg(cands, "cannot infer seq_len: pass it explicitly")
            seq_len = int(cands[0].shape[1])
        check_arg(seq_len % cp_degree == 0,
                  f"sequence length {seq_len} not divisible by "
                  f"cp degree {cp_degree}")

        if seq_params is None:
            # position tables are non-trainable [T, ...] constants; the
            # trainable filter keeps coincidentally-T-sized weights
            # (e.g. a bias of width == seq_len) replicated — pass
            # seq_params explicitly for exotic position-indexed params
            seq_params = [v.name for v in block.vars.values()
                          if isinstance(v, Parameter) and v.shape
                          and len(v.shape) >= 2
                          and int(v.shape[0]) == seq_len
                          and not getattr(v, "trainable", True)]
        assigned: Dict[str, tuple] = {}
        for name in seq_params:
            v = block.var(name)
            spec = (axis,) + (None,) * (len(v.shape) - 1)
            v.sharding = spec
            assigned[name] = spec

        # the (c_allreduce_sum, 1/N) pairs + the shard_map markers —
        # identical mechanics to the data-parallel rewrite
        DistributeTranspiler().transpile(
            trainer_id=0, program=program, trainers=cp_degree,
            axis_name=axis)
        program._dist_feed_shard_dim = 1
        program._dist_cp_axis = axis
        return assigned
