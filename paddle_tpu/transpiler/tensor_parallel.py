"""TensorParallelTranspiler — tensor parallelism as a *program
transformation* on the Program IR.

The reference's distributed modes are all program rewrites
(/root/reference/python/paddle/fluid/transpiler/distribute_transpiler.py:268
rewrites a local program into trainer/pserver programs); this transpiler
keeps that discipline for a parallelism mode the 2018 reference did not
have: Megatron-style tensor parallelism (+ vocab-parallel embeddings).

TPU-first design: the transpiler annotates each Parameter with a
`jax.sharding.PartitionSpec`-shaped tuple and the executor's mesh plane
(framework/executor.py in_shardings path) hands those to XLA — GSPMD
inserts the all-reduces/all-gathers that Megatron's fused layers issue by
hand (and the reference's pserver/NCCL machinery would have carried).
That is the whole point of building on XLA: a *layout* transformation is
sufficient; no communication ops need to be spliced into the program, so
the same Program runs unchanged on 1 device or an N-way mesh.  (The
hand-written shard_map pipeline in parallel/hybrid.py remains the
explicit-collective reference implementation of the same math; the
DistributeTranspiler covers the explicit-collective data-parallel plane.)

Annotation recipe (the Megatron alternation), decided by a small forward
dataflow pass over the global block:

  * `lookup_table` tables  -> (axis, None)   vocab(row)-parallel
  * `mul`/`matmul` weights -> (None, axis)   column-parallel when the
    activation feeding them is unsharded; (axis, None) row-parallel when
    the activation's feature dim is already sharded (the matching
    all-reduce is GSPMD's job)
  * bias of a column-parallel fc -> (axis,)
  * everything else (layer_norm scales, pos tables) stays replicated.

Sharded-ness of activations is tracked as a boolean "feature dim is
model-sharded" through shape/elementwise ops — enough to reproduce the
qkv->out_proj / ffn1->ffn2 column->row pairing on transformer blocks.
The annotations are *advisory* for XLA: any consistent assignment is
correct; pairing only controls where the collectives land.

Fused-attention note: GSPMD cannot partition through the fused_mha /
fused_attention pallas_call, so those ops (and their weights) run
REPLICATED under this transpiler — numerically identical, with tp
speedup only on the FFN/embedding side
(tests/test_tensor_parallel.py::test_tp_with_fused_mha_...).  Fully
tensor-parallel attention is served by the unfused path (plain
mul/matmul ops shard normally) or the explicit shard_map plane
(parallel/hybrid.py tp+sp attention).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..core.enforce import check_arg
from ..framework.program import Parameter, Program

# ops through which "my feature dim is sharded" propagates from any input
# to all outputs
_PROPAGATE = {
    "reshape", "transpose", "scale", "dropout", "softmax", "cast",
    "relu", "gelu", "tanh", "sigmoid", "elementwise_add",
    "elementwise_sub", "elementwise_mul", "elementwise_div", "sum",
    "stack", "concat", "split", "unsqueeze", "squeeze",
    "layer_norm",
}  # matmul/mul have their own branch below (incl. the param-less case)


class TensorParallelTranspiler:
    """Annotate a Program's parameters for N-way tensor parallelism over
    a named mesh axis; run the result with
    ``Executor(place, mesh=make_mesh((dp, tp), ("data", axis_name)))``."""

    def __init__(self, axis_name: str = "model"):
        self.axis_name = axis_name

    # -----------------------------------------------------------------
    def transpile(self, program: Program,
                  num_partitions: Optional[int] = None) -> Dict[str, tuple]:
        """Walk the program, assign Parameter.sharding specs, and return
        {param_name: spec} for inspection/tests.  `num_partitions` (the
        tp degree) is used only to validate divisibility of the dims it
        shards — the mesh supplies the actual factor at run time."""
        axis = self.axis_name
        block = program.global_block()
        sharded: Dict[str, bool] = {}
        assigned: Dict[str, tuple] = {}

        def is_param(name: str) -> bool:
            return block.has_var(name) and isinstance(block.var(name),
                                                      Parameter)

        def check_div(name: str, dim: int):
            if num_partitions:
                size = block.var(name).shape[dim]
                check_arg(
                    size % num_partitions == 0,
                    f"tensor-parallel transpile: {name} dim {dim} "
                    f"({size}) not divisible by {num_partitions}")

        def assign(name: str, spec: tuple):
            var = block.var(name)
            if getattr(var, "sharding", None) is None:
                var.sharding = spec
                assigned[name] = spec

        for op in block.ops:
            ins: List[str] = [n for names in op.inputs.values()
                              for n in names]
            outs: List[str] = [n for names in op.outputs.values()
                               for n in names if n]
            if op.type in ("lookup_table", "lookup_table_v2"):
                for w in op.inputs.get("W", []):
                    if is_param(w):
                        check_div(w, 0)
                        assign(w, (axis, None))
                # gathered rows come out replicated
                for o in outs:
                    sharded[o] = False
            elif op.type in ("mul", "matmul"):
                ps = [n for n in ins if is_param(n)
                      and len(block.var(n).shape) == 2]
                if len(ps) == 1:
                    w = ps[0]
                    acts = [n for n in ins if n != w]
                    feeding_sharded = any(sharded.get(a) for a in acts)
                    # which weight dim is the contraction vs the output:
                    # matmul's transpose_X/transpose_Y flip them (mul has
                    # no transpose attrs)
                    w_is_y = w in op.inputs.get("Y", [])
                    transposed = bool(op.attrs.get(
                        "transpose_Y" if w_is_y else "transpose_X", False))
                    if w_is_y:
                        contract_dim, out_dim = ((1, 0) if transposed
                                                 else (0, 1))
                    else:   # weight on the left: X [k, m] (or [m, k]^T)
                        contract_dim, out_dim = ((0, 1) if transposed
                                                 else (1, 0))
                    if feeding_sharded:
                        check_div(w, contract_dim)
                        spec = [None, None]
                        spec[contract_dim] = axis   # row-parallel
                        assign(w, tuple(spec))
                        out_sharded = False         # after GSPMD's psum
                    else:
                        check_div(w, out_dim)
                        spec = [None, None]
                        spec[out_dim] = axis        # column-parallel
                        assign(w, tuple(spec))
                        out_sharded = True
                    for o in outs:
                        sharded[o] = out_sharded
                else:
                    for o in outs:
                        sharded[o] = any(sharded.get(n) for n in ins)
            elif op.type == "elementwise_add" and any(
                    is_param(n) and len(block.var(n).shape) == 1
                    for n in ins):
                # bias add: shard the bias like the activation it joins
                act_sharded = any(sharded.get(n) for n in ins
                                  if not is_param(n))
                for n in ins:
                    if is_param(n) and len(block.var(n).shape) == 1 \
                            and act_sharded:
                        check_div(n, 0)
                        assign(n, (axis,))
                for o in outs:
                    sharded[o] = act_sharded
            elif op.type in _PROPAGATE:
                val = any(sharded.get(n) for n in ins)
                for o in outs:
                    sharded[o] = val
            else:
                # conservative: sharded-ness does not cross unknown ops
                for o in outs:
                    sharded[o] = False
        program._tp_axis = axis
        # post-condition (ISSUE 10): annotations must leave the program
        # verifying clean (a bad spec shows up as a shape finding)
        from .. import analysis
        analysis.maybe_check_transpiled(program,
                                        "TensorParallelTranspiler")
        return assigned
