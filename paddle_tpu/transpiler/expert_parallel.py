"""ExpertParallelTranspiler — switch-MoE expert parallelism as a
*program transformation* on the Program IR.

The 2018 reference has no MoE at all; its distributed modes are program
rewrites (distribute_transpiler.py:268), and this transpiler keeps that
discipline for the TPU-native capability (the last parallelism mode to
join the Program plane — dp/tp/cp/pp landed in rounds 3-4):

  * every `moe_ffn` op's expert stacks (W1 [E, D, F], W2 [E, F, D])
    get an `("expert", None, None)` sharding — the executor's shard_map
    plane splits them so each rank holds E/ep experts;
  * the op lowering reads `_dist_ep_axis` from the LowerContext and
    dispatches/combines tokens via all_to_all over the axis
    (parallel/moe.py switch_moe);
  * data feeds shard along the batch (each rank routes its own tokens);
  * replicated-parameter gradients get the (c_allreduce_sum, 1/N)
    pairs, while the SHARDED expert gradients get only the 1/N — the
    all_to_all vjp already routed every rank's cotangents to the
    owning expert slice (distribute_transpiler.py skip logic).

Run with ``Executor(place, mesh=Mesh(devices, ("expert",)))``.
"""
from __future__ import annotations

from typing import Dict

from ..core.enforce import check_arg
from ..framework.program import Program
from .distribute_transpiler import DistributeTranspiler


class ExpertParallelTranspiler:
    def __init__(self, axis_name: str = "expert"):
        self.axis_name = axis_name

    def transpile(self, program: Program, ep_degree: int
                  ) -> Dict[str, tuple]:
        """Rewrite `program` for ep_degree-way expert sharding; returns
        {param_name: sharding} for the expert stacks."""
        axis = self.axis_name
        block = program.global_block()
        check_arg(ep_degree >= 1,
                  f"ep_degree must be >= 1, got {ep_degree}")
        if ep_degree == 1:
            return {}
        moe_ops = [op for op in block.ops if op.type == "moe_ffn"]
        check_arg(moe_ops,
                  "expert-parallel transpile requires moe_ffn ops "
                  "(build the model with layers.moe)")
        assigned: Dict[str, tuple] = {}
        for op in moe_ops:
            gate = block.var(op.inputs["Gate"][0])
            E = int(gate.shape[-1])
            check_arg(
                E % ep_degree == 0,
                f"num_experts {E} not divisible by ep degree "
                f"{ep_degree}")
            for slot in ("W1", "W2"):
                v = block.var(op.inputs[slot][0])
                spec = (axis,) + (None,) * (len(v.shape) - 1)
                v.sharding = spec
                assigned[v.name] = spec

        # (c_allreduce_sum, 1/N) for replicated grads, 1/N only for the
        # sharded expert grads + the shard_map markers — the same
        # mechanics as the data-parallel rewrite
        DistributeTranspiler().transpile(
            trainer_id=0, program=program, trainers=ep_degree,
            axis_name=axis)      # post-condition runs inside transpile
        program._dist_ep_axis = axis
        return assigned
