"""Inference transpiler: fold batch_norm into the preceding conv2d.

Capability parity with /root/reference/python/paddle/fluid/transpiler/
inference_transpiler.py:24 (_fuse_batch_norm).  Unlike the reference this
is an *optional* arithmetic simplification — XLA already fuses the BN
elementwise math into the conv epilogue — but folding removes the BN
parameters entirely from the exported model, which shrinks the program
and the checkpoint, so the capability is kept as a real transformation.

Fold: conv W' = W * gamma/sqrt(var+eps) (per out-channel),
      b' = (b - mean) * gamma/sqrt(var+eps) + beta.
"""
from __future__ import annotations

import numpy as np

from ..framework.executor import Executor, Scope
from ..framework.program import Program


class InferenceTranspiler:
    def transpile(self, program: Program, place=None, scope: Scope = None):
        from ..framework.executor import global_scope
        scope = scope or global_scope()
        block = program.global_block()
        ops = block.ops
        # consumer count per var across ALL blocks: fold only when the conv
        # output feeds the BN exclusively (a skip connection or sub-block
        # reading the pre-BN activation must keep the unfused conv)
        consumers: dict = {}
        for b in program.blocks:
            for bop in b.ops:
                for n in bop.input_names():
                    consumers[n] = consumers.get(n, 0) + 1
        from ..framework.program import Operator
        new_ops = []
        i = 0
        while i < len(ops):
            op = ops[i]
            nxt = ops[i + 1] if i + 1 < len(ops) else None
            if (op.type == "conv2d" and nxt is not None
                    and nxt.type == "batch_norm"
                    and op.outputs.get("Output", [None])[0]
                    == nxt.inputs.get("X", [None])[0]
                    and consumers.get(op.outputs["Output"][0], 0) == 1):
                self._fold(scope, op, nxt)
                # conv keeps its own output var (a fetch of it stays legal —
                # it now holds the post-BN value, which is the only value
                # that exists after folding); alias the BN output onto it
                new_ops.append(op)
                new_ops.append(Operator(
                    block, "assign", {"X": [op.outputs["Output"][0]]},
                    {"Out": [nxt.outputs["Y"][0]]}, {}))
                i += 2
                continue
            new_ops.append(op)
            i += 1
        block.ops = new_ops
        program._bump()
        return program

    def _fold(self, scope, conv_op, bn_op):
        w_name = conv_op.inputs["Filter"][0]
        W = np.asarray(scope.find_var(w_name))
        scale = np.asarray(scope.find_var(bn_op.inputs["Scale"][0]))
        bias = np.asarray(scope.find_var(bn_op.inputs["Bias"][0]))
        mean = np.asarray(scope.find_var(bn_op.inputs["Mean"][0]))
        var = np.asarray(scope.find_var(bn_op.inputs["Variance"][0]))
        eps = float(bn_op.attrs.get("epsilon", 1e-5))
        alpha = scale / np.sqrt(var + eps)             # [C_out]
        scope.set_var(w_name, (W * alpha[:, None, None, None]).astype(
            W.dtype))
        # conv bias: reuse if present, else the BN bias var becomes it
        if conv_op.inputs.get("Bias"):
            b_name = conv_op.inputs["Bias"][0]
            b = np.asarray(scope.find_var(b_name))
            new_b = (b - mean) * alpha + bias
        else:
            b_name = bn_op.inputs["Bias"][0]
            conv_op.inputs["Bias"] = [b_name]
            new_b = -mean * alpha + bias
        scope.set_var(b_name, new_b.astype(W.dtype))
