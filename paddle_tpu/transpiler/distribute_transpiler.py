"""DistributeTranspiler: the pserver-era contract mapped to the mesh plane.

Capability statement (see SURVEY.md §2.2 and hard part (e)): the reference
rewrites one program into trainer programs (grads -> send/barrier/recv) and
pserver programs (listen_and_serv around per-param optimize blocks) —
/root/reference/python/paddle/fluid/transpiler/distribute_transpiler.py:148,
268, 646.  On TPU the *capability* (scale training beyond one process,
shard huge params) is delivered by collectives over ICI/DCN:

  pserver sync loop            -> gradient psum under pjit/shard_map
                                  (parallel/hybrid.py, ParallelExecutor)
  param block-splitting (:1049)-> Parameter.sharding PartitionSpecs
  distributed lookup table     -> row-sharded embedding + all_to_all
    (:1010,1274)                  (parallel/hybrid.py MoE dispatch shows
                                  the pattern; deepfm sparse_shard_axis)
  gen_nccl_id handshake (:213) -> jax.distributed.initialize rendezvous
                                  (parallel/env.py)
  async pserver / DC-ASGD      -> not reproduced: sync collectives are
                                  strictly faster on ICI; documented gap

This class keeps the reference's API so multi-role scripts run: transpile()
validates the role layout, get_trainer_program() returns the (unchanged)
program annotated with a data-parallel mesh hint, and get_pserver_program()
raises with migration guidance — there are no parameter servers to run.
"""
from __future__ import annotations

from typing import List, Optional

from ..framework.program import Program, default_main_program


class DistributeTranspilerConfig:
    """ref distribute_transpiler.py:126 — kept fields that still steer
    sharding decisions."""

    def __init__(self):
        self.slice_var_up = True       # -> shard params over the mesh
        self.min_block_size = 8192
        self.split_method = "RoundRobin"


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self, trainer_id: int, program: Optional[Program] = None,
                  pservers: str = "", trainers: int = 1,
                  sync_mode: bool = True, startup_program=None,
                  current_endpoint: str = ""):
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.program = program or default_main_program()
        self.sync_mode = sync_mode
        if not sync_mode:
            import warnings
            warnings.warn(
                "async pserver mode has no TPU equivalent; proceeding with "
                "synchronous collective data parallelism (strictly faster "
                "over ICI)")
        self._transpiled = True
        return self

    def get_trainer_program(self, wait_port: bool = True) -> Program:
        assert self._transpiled, "call transpile() first"
        # data parallelism is a sharding, not a program rewrite: run this
        # program with ParallelExecutor(mesh=...) or Executor(mesh=...)
        return self.program

    def get_pserver_program(self, endpoint: str) -> Program:
        raise NotImplementedError(
            "There are no parameter servers on TPU: gradients aggregate "
            "via psum over ICI (use ParallelExecutor with a mesh spanning "
            "your slice; multi-host rendezvous via "
            "paddle_tpu.parallel.env.init_distributed_env). Sharded huge "
            "tables: give the Parameter a `sharding` PartitionSpec.")

    def get_pserver_programs(self, endpoint: str):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint: str = "",
                            pserver_program=None) -> Program:
        raise NotImplementedError(
            "No pserver startup program on TPU — see get_pserver_program.")
