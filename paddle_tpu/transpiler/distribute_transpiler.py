"""DistributeTranspiler: the pserver-era contract mapped to the mesh plane.

Capability statement (see SURVEY.md §2.2 and hard part (e)): the reference
rewrites one program into trainer programs (grads -> send/barrier/recv) and
pserver programs (listen_and_serv around per-param optimize blocks) —
/root/reference/python/paddle/fluid/transpiler/distribute_transpiler.py:148,
268, 646.  On TPU the *capability* (scale training beyond one process,
shard huge params) is delivered by collectives over ICI/DCN:

  pserver sync loop            -> gradient psum under pjit/shard_map
                                  (parallel/hybrid.py, ParallelExecutor)
  param block-splitting (:1049)-> Parameter.sharding PartitionSpecs
  distributed lookup table     -> row-sharded embedding + all_to_all
    (:1010,1274)                  (parallel/hybrid.py MoE dispatch shows
                                  the pattern; deepfm sparse_shard_axis)
  gen_nccl_id handshake (:213) -> jax.distributed.initialize rendezvous
                                  (parallel/env.py)
  async pserver / DC-ASGD      -> distributed/async_update.py: host-plane
                                  AsyncParameterServer (stale-grad pushes,
                                  DC-ASGD compensation); the DEVICE plane
                                  stays sync — collectives over ICI beat
                                  any RPC hop

This class keeps the reference's API and performs the nccl2-mode program
transformation for real: transpile(trainers=N) inserts a
(c_allreduce_sum, scale 1/N) pair per gradient after the backward —
the reference's InsertAllReduceOp + CreateScaleLossGradOp — and marks
the program so the Executor runs it under shard_map with the mesh axis
in scope.  get_pserver_program() raises with migration guidance — there
are no parameter servers to run.  Tested for op-structure and for loss
parity vs single-device training in tests/test_dist_transpiler.py.
"""
from __future__ import annotations

from typing import List, Optional

from ..core.enforce import check_arg
from ..framework.program import Program, default_main_program


class DistributeTranspilerConfig:
    """ref distribute_transpiler.py:126 — kept fields that still steer
    sharding decisions."""

    def __init__(self):
        self.slice_var_up = True       # -> shard params over the mesh
        self.min_block_size = 8192
        self.split_method = "RoundRobin"


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self, trainer_id: int, program: Optional[Program] = None,
                  pservers: str = "", trainers: int = 1,
                  sync_mode: bool = True, startup_program=None,
                  current_endpoint: str = "", axis_name: str = "data"):
        """Rewrite the program for collective data parallelism — the
        nccl2-mode transformation (ref distribute_transpiler.py:213 +
        multi_devices_graph_pass.cc InsertAllReduceOp:572 /
        CreateScaleLossGradOp:663): after the backward, every gradient
        is allreduce-summed over the mesh axis and scaled by 1/trainers,
        in place (the optimizer ops downstream read the same var names).
        The program is marked so the Executor runs it under shard_map
        with the axis in scope."""
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.program = program or default_main_program()
        self.sync_mode = sync_mode
        if not sync_mode:
            import warnings
            warnings.warn(
                "async pserver mode is a host-plane capability here "
                "(paddle_tpu.distributed.AsyncParameterServer); the device "
                "data plane proceeds with synchronous collectives "
                "(strictly faster over ICI)")
        if trainers > 1:
            self._insert_grad_allreduce(axis_name)
            # post-condition (ISSUE 10): the rewritten program must
            # re-verify clean — a malformed allreduce splice becomes a
            # named diagnostic here, not a mid-jit trace.  Covers the
            # context/expert-parallel transpilers too (they delegate
            # their collective rewrite to this pass).
            from .. import analysis
            analysis.maybe_check_transpiled(
                self.program, "DistributeTranspiler")
        self._transpiled = True
        return self

    def _insert_grad_allreduce(self, axis_name: str = "data"):
        prev = getattr(self.program, "_dist_spmd_axis", None)
        check_arg(
            prev is None,
            f"program already carries collective rewrites over axis "
            f"{prev!r} (DistributeTranspiler, or a transpiler that "
            f"delegates to it such as ContextParallelTranspiler); "
            f"stacking another pass would duplicate the gradient "
            f"allreduces")
        block = self.program.global_block()
        ad_idx = [i for i, op in enumerate(block.ops)
                  if op.type == "autodiff"]
        if not ad_idx:
            return                      # inference program: nothing to do
        idx = ad_idx[0]
        grads = list(block.ops[idx].attrs.get("grads", []))
        params = list(block.ops[idx].attrs.get("params", []))
        p_of_g = dict(zip(grads, params))
        insert_at = idx + 1
        for g in grads:
            # a parameter SHARDED over this axis (expert stacks) gets a
            # complete local-slice gradient already — the collective
            # vjps (all_to_all) routed every rank's cotangents to the
            # owning shard.  Allreducing would mix unrelated expert
            # slices; only the 1/N (loss is a local mean) applies.
            pvar = (block.var(p_of_g[g])
                    if p_of_g.get(g) and block.has_var(p_of_g[g])
                    else None)
            sharded = (pvar is not None and
                       axis_name in (getattr(pvar, "sharding", None)
                                     or ()))
            if sharded:
                block.append_op("scale", {"X": [g]}, {"Out": [g]},
                                {"scale": 1.0 / self.trainer_num},
                                index=insert_at)
                insert_at += 1
                continue
            ar = g + "@ALLREDUCE"
            if not block.has_var(ar):
                block.create_var(name=ar, dtype="float32")
            # sum over the data axis, then 1/N — writes BACK to the grad
            # var so the optimizer ops need no rewiring
            block.append_op("c_allreduce_sum", {"X": [g]}, {"Out": [ar]},
                            {"axis_name": axis_name}, index=insert_at)
            block.append_op("scale", {"X": [ar]}, {"Out": [g]},
                            {"scale": 1.0 / self.trainer_num},
                            index=insert_at + 1)
            insert_at += 2
        self.program._dist_spmd_axis = axis_name
        self.program._dist_trainers = self.trainer_num

    def get_trainer_program(self, wait_port: bool = True) -> Program:
        assert self._transpiled, "call transpile() first"
        # run with Executor(mesh=...) — the _dist_spmd_axis marker makes
        # the compiled step execute under shard_map so the inserted
        # collectives have their axis in scope
        return self.program

    def get_pserver_program(self, endpoint: str) -> Program:
        raise NotImplementedError(
            "There are no parameter servers on TPU: gradients aggregate "
            "via psum over ICI (use ParallelExecutor with a mesh spanning "
            "your slice; multi-host rendezvous via "
            "paddle_tpu.parallel.env.init_distributed_env). Sharded huge "
            "tables: give the Parameter a `sharding` PartitionSpec.")

    def get_pserver_programs(self, endpoint: str):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint: str = "",
                            pserver_program=None) -> Program:
        raise NotImplementedError(
            "No pserver startup program on TPU — see get_pserver_program.")
