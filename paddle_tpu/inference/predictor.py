"""Predictor: AOT-compiled inference over a saved program.

Capability parity with the reference's deployment ABI
(/root/reference/paddle/fluid/inference/api/paddle_api.h:134
`PaddlePredictor`, analysis_predictor.h:42 `AnalysisPredictor`,
paddle_analysis_config.h:37 `AnalysisConfig`, CreatePaddlePredictor
:217):

  reference                                   here
  ---------                                   ----
  NativePaddlePredictor (NaiveExecutor loop)  jit-compiled program fn
  AnalysisPredictor IR fuse pass pipeline     XLA fusion (the pass list
    (fc_fuse, conv_bn, tensorrt subgraph...)   collapses into the compiler)
  ir_params_sync_among_devices                device_put of the param state
  ZeroCopyTensor                              dlpack/jax.Array in, numpy out
  Clone() per-thread predictors               Predictor.clone() sharing the
                                              compiled executable + state

AOT: the first call per input signature traces + compiles; `prepare()`
compiles ahead of time for a given batch shape (jax .lower().compile()),
so serving never pays compile latency on the request path.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from .. import io as pio
from ..core.enforce import check_arg
from ..core.place import CPUPlace, Place, TPUPlace, default_place
from ..framework.executor import LowerContext, Scope, run_ops_in_env
from ..framework.program import Program
from ..observability import tracectx as obs_tracectx


class NativeConfig:
    """ref paddle_api.h:176."""

    def __init__(self, model_dir: Optional[str] = None,
                 use_tpu: bool = True, device: int = 0):
        self.model_dir = model_dir
        self.use_tpu = use_tpu
        self.device = device


class AnalysisConfig(NativeConfig):
    """ref paddle_analysis_config.h:37 — optimisation switches that still
    mean something on TPU are honoured; graph-fusion toggles are XLA's
    business and accepted as no-ops for API compatibility."""

    def __init__(self, model_dir: Optional[str] = None, use_tpu: bool = True,
                 device: int = 0):
        super().__init__(model_dir, use_tpu, device)
        self.ir_optim = True           # accepted; XLA always fuses
        self.enable_memory_optim_ = True
        self._donate_inputs = False

    def enable_memory_optim(self):
        self.enable_memory_optim_ = True

    def switch_ir_optim(self, flag: bool):
        self.ir_optim = flag


class Predictor:
    """exe-free inference runner over a pruned program."""

    def __init__(self, config: NativeConfig, _shared=None):
        self.config = config
        if _shared is not None:
            (self.program, self.feed_names, self.fetch_names,
             self.state, self._device) = _shared
            self._compiled: Dict = {}
            return
        check_arg(config.model_dir is not None
                  and os.path.isdir(config.model_dir),
                  f"model_dir {config.model_dir!r} does not exist")
        place = TPUPlace(config.device) if config.use_tpu else CPUPlace()
        self._device = place.jax_device()   # raises if absent: a config
        # asking for a TPU must not silently serve on CPU
        scope = Scope()
        from ..framework.executor import Executor
        exe = Executor(place, scope=scope)
        self.program, self.feed_names, self.fetch_names = \
            pio.load_inference_model(config.model_dir, exe)
        persist = {v.name for v in self.program.list_vars() if v.persistable}
        self.state = {n: jax.device_put(scope.find_var(n), self._device)
                      for n in persist if scope.find_var(n) is not None}
        self._compiled = {}

    # -- compile ------------------------------------------------------------
    def _fn(self):
        program = self.program
        fetch_names = self.fetch_names

        def run(state, feeds):
            env = dict(state)
            env.update(feeds)
            ctx = LowerContext(jax.random.PRNGKey(0))
            ctx.program = program
            ctx.env = env
            env = run_ops_in_env(ctx, env, [
                op for op in program.global_block().ops
                if op.type not in ("feed", "fetch", "data")])
            return [env[n] for n in fetch_names]
        return run

    def _sig(self, feeds: Dict[str, np.ndarray]):
        return tuple(sorted((n, tuple(a.shape), str(a.dtype))
                            for n, a in feeds.items()))

    def _check_feed_names(self, feeds: Dict[str, np.ndarray]):
        """Both directions: a missing feed can't run, and an UNKNOWN
        feed silently changes ``_sig`` — every request with a stray
        key would compile a fresh executable (a request-path recompile
        storm wearing an innocent face)."""
        missing = set(self.feed_names) - set(feeds)
        check_arg(not missing, f"missing feeds: {sorted(missing)}")
        unknown = set(feeds) - set(self.feed_names)
        check_arg(
            not unknown,
            f"unknown feed names {sorted(unknown)}: this predictor "
            f"feeds {sorted(self.feed_names)} — extra names would "
            f"change the compile signature and force a fresh "
            f"executable per request")

    def _persist_components(self, sig) -> Dict[str, object]:
        """Stable key components of one AOT signature: program
        topology + persistable-state signature + feed signature +
        fetch names + the lowering-affecting numerics flags — the
        executor's KeyParts vocabulary, predictor-shaped."""
        from ..framework import jit_cache as pjit_cache
        return {
            "program": pjit_cache.program_fingerprint(self.program),
            "state": sorted((n, tuple(np.shape(a)),
                             str(jax.numpy.result_type(a)))
                            for n, a in self.state.items()),
            "feeds": list(sig),
            "fetch": list(self.fetch_names),
            "flags": pjit_cache.numerics_flags(),
        }

    def prepare(self, example_feeds: Dict[str, np.ndarray]):
        """AOT-compile for this input signature (lowered+compiled now, so
        the request path never traces).  With ``jit_cache_dir`` set the
        executable round-trips the persistent cache: a warm replica
        deserializes its whole grid instead of compiling it (the
        reference's save_inference_model tier never persisted compiled
        artifacts at all)."""
        from ..framework import jit_cache as pjit_cache
        feeds = {n: np.asarray(v) for n, v in example_feeds.items()}
        self._check_feed_names(feeds)
        sig = self._sig(feeds)
        if sig not in self._compiled:
            comps = khash = None
            if pjit_cache.enabled():
                comps = self._persist_components(sig)
                khash = pjit_cache.entry_key("predictor", comps)
                loaded = pjit_cache.load("predictor", khash, comps)
                if loaded is not None:
                    self._compiled[sig] = loaded
                    return loaded
            # X-ray: a request whose signature missed the AOT grid
            # compiles HERE — the span lands in that request's own
            # timeline, naming the signature that forced it
            with obs_tracectx.span("predictor.compile", kind="compile",
                                   signature=str(sig)[:200]):
                lowered = jax.jit(self._fn()).lower(self.state, feeds)
                self._compiled[sig] = lowered.compile()
            if khash is not None and pjit_cache.program_verified(
                    self.program, set(feeds), self.fetch_names,
                    feed_shapes={n: tuple(a.shape)
                                 for n, a in feeds.items()}):
                pjit_cache.store("predictor", khash, comps,
                                 self._compiled[sig])
        return self._compiled[sig]

    def prepare_buckets(self, example_feeds: Dict[str, np.ndarray],
                        batch_sizes: Sequence[int],
                        seq_lens: Optional[Sequence[int]] = None) -> dict:
        """AOT-compile the full serving bucket grid up front: every
        (batch, seq) combination of `batch_sizes` x `seq_lens` gets an
        executable NOW, so serving startup cost is this one call
        instead of N hand-written prepare()s — and the request path
        never compiles.

        `example_feeds` supplies dtypes and trailing feature shapes;
        axis 0 is resized to each batch size and (for feeds with >= 2
        dims) axis 1 to each sequence length.  Logs the total compile
        time; returns {"(batch, seq)": compile_seconds} + totals."""
        import time as _time
        feeds0 = {n: np.asarray(v) for n, v in example_feeds.items()}
        self._check_feed_names(feeds0)
        report: Dict[str, float] = {}
        t0 = _time.perf_counter()
        n_before = len(self._compiled)
        for bs in batch_sizes:
            for sl in (seq_lens if seq_lens else [None]):
                feeds = {}
                for n, a in feeds0.items():
                    shape = list(a.shape)
                    if shape:
                        shape[0] = int(bs)
                    if sl is not None and a.ndim >= 2:
                        shape[1] = int(sl)
                    feeds[n] = np.zeros(shape, a.dtype)
                tb = _time.perf_counter()
                self.prepare(feeds)
                report[f"({bs}, {sl})"] = round(
                    _time.perf_counter() - tb, 3)
        total = _time.perf_counter() - t0
        compiled = len(self._compiled) - n_before
        report["total_seconds"] = round(total, 3)
        report["executables"] = compiled
        print(f"[predictor] prepared bucket grid: {compiled} "
              f"executable(s) over batch={list(batch_sizes)} x "
              f"seq={list(seq_lens) if seq_lens else ['-']} "
              f"in {total:.2f}s")
        return report

    # -- run ----------------------------------------------------------------
    def run(self, feeds: Dict[str, np.ndarray],
            return_numpy: bool = True) -> List[np.ndarray]:
        # device-resident feeds pass through untouched: np.asarray on a
        # jax.Array is a full device->host readback (then the call
        # re-uploads), which on a tunneled chip costs more than the
        # inference itself
        feeds = {n: v if isinstance(v, jax.Array) else np.asarray(v)
                 for n, v in feeds.items()}
        self._check_feed_names(feeds)
        compiled = self._compiled.get(self._sig(feeds))
        if compiled is None:
            compiled = self.prepare(feeds)
        with obs_tracectx.span("predictor.run", kind="dispatch"):
            outs = compiled(self.state, feeds)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return list(outs)

    __call__ = run

    def clone(self) -> "Predictor":
        """Share program/state/compiled executables (ref
        PaddlePredictor::Clone for multi-thread serving — here the jax
        runtime is thread-safe and buffers are immutable, so sharing is
        free)."""
        p = Predictor(self.config, _shared=(
            self.program, self.feed_names, self.fetch_names, self.state,
            self._device))
        p._compiled = self._compiled
        return p

    def get_input_names(self) -> List[str]:
        return list(self.feed_names)

    def get_output_names(self) -> List[str]:
        return list(self.fetch_names)


def create_predictor(config: NativeConfig) -> Predictor:
    """ref CreatePaddlePredictor (paddle_api.h:217)."""
    return Predictor(config)
