"""Inference/deployment layer (ref /root/reference/paddle/fluid/inference/)."""
from .predictor import (AnalysisConfig, NativeConfig, Predictor,
                        create_predictor)
