"""Python side of the C inference ABI (native/predictor_capi.cc).

The C library (libpaddle_tpu_capi.so) embeds CPython and calls the three
functions below with plain ints/strs/bytes — no custom types cross the
boundary, so the C side stays small.  Counterpart of the reference's
C++-native predictor ABI (paddle_api.h:134 PaddlePredictor /
CreatePaddlePredictor:217) serving non-Python applications.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Sequence, Tuple

import numpy as np

_DTYPES = {0: np.float32, 1: np.int64, 2: np.int32}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int64): 1,
                np.dtype(np.int32): 2}

_predictors: Dict[int, object] = {}
_next_id = itertools.count(1)


def create(model_dir: str, device: str = "cpu") -> int:
    """Load a saved inference model; returns an opaque handle id."""
    from ..core.place import CPUPlace, TPUPlace
    from .predictor import AnalysisConfig, create_predictor
    cfg = AnalysisConfig(model_dir=model_dir, use_tpu=(device == "tpu"))
    pid = next(_next_id)
    _predictors[pid] = create_predictor(cfg)
    return pid


def run(pid: int, names: Sequence[str], dtypes: Sequence[int],
        shapes: Sequence[Sequence[int]], buffers: Sequence[bytes]
        ) -> List[Tuple[str, int, Tuple[int, ...], bytes]]:
    """One inference call.  Inputs as raw little-endian buffers; outputs
    the same way: [(name, dtype_code, shape, bytes), ...]."""
    pred = _predictors[pid]
    feeds = {}
    for name, dt, shape, buf in zip(names, dtypes, shapes, buffers):
        arr = np.frombuffer(buf, dtype=_DTYPES[int(dt)]).reshape(
            [int(s) for s in shape])
        feeds[name] = arr
    outs = pred.run(feeds)
    result = []
    for name, arr in zip(pred.fetch_names, outs):
        arr = np.ascontiguousarray(arr)
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:            # normalize exotic dtypes for the ABI
            arr = arr.astype(np.float32)
            code = 0
        result.append((str(name), code, tuple(arr.shape), arr.tobytes()))
    return result


def destroy(pid: int) -> None:
    _predictors.pop(pid, None)
