"""Python side of the C inference ABI (native/predictor_capi.cc).

The C library (libpaddle_tpu_capi.so) embeds CPython and calls the three
functions below with plain ints/strs/bytes — no custom types cross the
boundary, so the C side stays small.  Counterpart of the reference's
C++-native predictor ABI (paddle_api.h:134 PaddlePredictor /
CreatePaddlePredictor:217) serving non-Python applications.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Sequence, Tuple

import numpy as np

_DTYPES = {0: np.float32, 1: np.int64, 2: np.int32}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int64): 1,
                np.dtype(np.int32): 2}

_predictors: Dict[int, object] = {}
_next_id = itertools.count(1)


def create(model_dir: str, device: str = "cpu") -> int:
    """Load a saved inference model; returns an opaque handle id."""
    from ..core.place import CPUPlace, TPUPlace
    from .predictor import AnalysisConfig, create_predictor
    cfg = AnalysisConfig(model_dir=model_dir, use_tpu=(device == "tpu"))
    pid = next(_next_id)
    _predictors[pid] = create_predictor(cfg)
    return pid


def _decode_feeds(names, dtypes, shapes, buffers):
    """Raw little-endian C buffers -> named numpy feeds (shared by the
    inference and training entries so both parse the ABI identically)."""
    feeds = {}
    for name, dt, shape, buf in zip(names, dtypes, shapes, buffers):
        feeds[name] = np.frombuffer(buf, dtype=_DTYPES[int(dt)]).reshape(
            [int(s) for s in shape])
    return feeds


def run(pid: int, names: Sequence[str], dtypes: Sequence[int],
        shapes: Sequence[Sequence[int]], buffers: Sequence[bytes]
        ) -> List[Tuple[str, int, Tuple[int, ...], bytes]]:
    """One inference call.  Inputs as raw little-endian buffers; outputs
    the same way: [(name, dtype_code, shape, bytes), ...]."""
    pred = _predictors[pid]
    outs = pred.run(_decode_feeds(names, dtypes, shapes, buffers))
    result = []
    for name, arr in zip(pred.fetch_names, outs):
        arr = np.ascontiguousarray(arr)
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:            # normalize exotic dtypes for the ABI
            arr = arr.astype(np.float32)
            code = 0
        result.append((str(name), code, tuple(arr.shape), arr.tobytes()))
    return result


def destroy(pid: int) -> None:
    _predictors.pop(pid, None)


# ---------------------------------------------------------------------------
# C TRAINING ABI (native train entry — the reference can train from pure
# C++ via a saved program: train/demo/demo_trainer.cc:1 loads
# startup/main ProgramDescs and steps the Executor.  Same capability
# here over the Program JSON serde.)
# ---------------------------------------------------------------------------

_trainers: Dict[int, tuple] = {}


def create_trainer(model_dir: str, device: str = "cpu") -> int:
    """Load `<dir>/startup_program.json` + `<dir>/main_program.json`
    (io.save_train_program), locate the loss like the reference demo
    (first `mean` op's output), run the startup program; returns a
    handle id."""
    import json
    import os

    import paddle_tpu as pt
    with open(os.path.join(model_dir, "startup_program.json")) as f:
        startup = pt.Program.from_dict(json.load(f))
    with open(os.path.join(model_dir, "main_program.json")) as f:
        main = pt.Program.from_dict(json.load(f))
    loss_name = None
    for op in main.global_block().ops:
        if op.type == "mean":
            loss_name = op.outputs["Out"][0]
            break
    if loss_name is None:
        raise ValueError("cannot locate the loss: no `mean` op in the "
                         "main program (demo_trainer.cc contract)")
    place = pt.TPUPlace(0) if device == "tpu" else pt.CPUPlace()
    exe = pt.Executor(place, scope=pt.Scope())
    exe.run(startup)
    pid = next(_next_id)
    _trainers[pid] = (exe, main, loss_name)
    return pid


def train_run(pid: int, names: Sequence[str], dtypes: Sequence[int],
              shapes: Sequence[Sequence[int]], buffers: Sequence[bytes]
              ) -> List[Tuple[str, int, Tuple[int, ...], bytes]]:
    """One training step: feed the batch, run forward+backward+update,
    return [(loss_name, dtype, shape, bytes)]."""
    exe, main, loss_name = _trainers[pid]
    feeds = _decode_feeds(names, dtypes, shapes, buffers)
    out, = exe.run(main, feed=feeds, fetch_list=[loss_name])
    arr = np.ascontiguousarray(np.asarray(out, dtype=np.float32))
    return [(loss_name, 0, tuple(arr.shape), arr.tobytes())]


def destroy_trainer(pid: int) -> None:
    _trainers.pop(pid, None)
