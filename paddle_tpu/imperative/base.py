"""guard / enabled / to_variable (ref python/paddle/fluid/imperative/
base.py:28)."""
from __future__ import annotations

import contextlib

import numpy as np

from .varbase import Tape, VarBase, _active_tape, pop_tape, push_tape


def enabled() -> bool:
    return _active_tape() is not None


@contextlib.contextmanager
def guard(seed: int = 0):
    """Enter imperative mode: ops recorded on a fresh tape."""
    push_tape(Tape(seed=seed))
    try:
        yield
    finally:
        pop_tape()


def to_variable(value, stop_gradient: bool = False) -> VarBase:
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), stop_gradient=stop_gradient)
