"""VarBase + tape autograd (ref imperative/layer.h:30, engine.h:25).

The reference's Tracer appends grad-OpDescs while forward ops run and
RunBackward walks them; here the tape stores the forward lowering
closure itself and backward uses jax.vjp per entry — exact gradients
for every differentiable registered op, no per-op grad maker needed.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.enforce import EnforceNotMet
from ..framework.registry import LowerContext, get_op_def


class VarBase:
    """Eager tensor (ref imperative/layer.h:30)."""

    _next_id = 0

    def __init__(self, value, stop_gradient: bool = False,
                 name: Optional[str] = None):
        self.value = jnp.asarray(value)
        self.stop_gradient = bool(stop_gradient)
        self.grad: Optional[jnp.ndarray] = None
        VarBase._next_id += 1
        self.name = name or f"eager_{VarBase._next_id}"

    # -- introspection ----------------------------------------------------
    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return str(self.value.dtype)

    def numpy(self) -> np.ndarray:
        return np.asarray(self.value)

    def __repr__(self):
        return (f"VarBase(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, stop_gradient={self.stop_gradient})")

    # -- autograd ---------------------------------------------------------
    def backward(self):
        """ref layer.h VarBase::RunBackward: seed d(self)=1 and walk the
        tape in reverse."""
        tape = _active_tape()
        if tape is None:
            raise EnforceNotMet(
                "backward() outside imperative.guard(): no tape")
        tape.backward(self)

    def clear_gradient(self):
        self.grad = None

    # arithmetic sugar via traced ops
    def __add__(self, other):
        return trace_op("elementwise_add",
                        {"X": [self], "Y": [_as_var(other)]}, {})[0]

    def __mul__(self, other):
        return trace_op("elementwise_mul",
                        {"X": [self], "Y": [_as_var(other)]}, {})[0]

    def __sub__(self, other):
        return trace_op("elementwise_sub",
                        {"X": [self], "Y": [_as_var(other)]}, {})[0]


def _as_var(v) -> VarBase:
    return v if isinstance(v, VarBase) else VarBase(v, stop_gradient=True)


class _TapeEntry:
    __slots__ = ("fn", "in_vars", "out_vars")

    def __init__(self, fn, in_vars: List[VarBase], out_vars: List[VarBase]):
        self.fn = fn                  # flat jnp values -> flat jnp values
        self.in_vars = in_vars
        self.out_vars = out_vars


class Tape:
    """Forward-op recorder + reverse-replay engine (ref engine.h:25)."""

    def __init__(self, seed: int = 0):
        self.entries: List[_TapeEntry] = []
        self._ctx = LowerContext(jax.random.PRNGKey(seed))
        self._consumed = False

    def ctx(self) -> LowerContext:
        return self._ctx

    def record(self, fn, in_vars, out_vars):
        self._consumed = False
        self.entries.append(_TapeEntry(fn, in_vars, out_vars))

    def backward(self, root: VarBase):
        if self._consumed:
            raise EnforceNotMet(
                "tape already consumed by a previous backward(); trace "
                "the forward again inside the guard before another "
                "backward (the tape is single-use, like the reference's "
                "grad-op chain)")
        # replaying entry closures rewinds the shared RNG counter; save
        # and restore it so ops traced after backward() draw fresh keys
        counter_after_forward = self._ctx._counter
        grads: Dict[int, jnp.ndarray] = {
            id(root): jnp.ones_like(root.value)}
        for entry in reversed(self.entries):
            out_cts = [grads.get(id(o)) for o in entry.out_vars]
            if all(c is None for c in out_cts):
                continue
            cts = tuple(
                jnp.zeros_like(o.value) if c is None else c
                for o, c in zip(entry.out_vars, out_cts))
            in_vals = tuple(v.value for v in entry.in_vars)
            _, vjp_fn = jax.vjp(entry.fn, *in_vals)
            in_cts = vjp_fn(cts)
            for v, ct in zip(entry.in_vars, in_cts):
                if v.stop_gradient or ct is None:
                    continue
                prev = grads.get(id(v))
                grads[id(v)] = ct if prev is None else prev + ct
        # publish .grad once per distinct var that received one
        seen: Dict[int, VarBase] = {}
        for entry in self.entries:
            for v in entry.in_vars:
                seen.setdefault(id(v), v)
        for vid, v in seen.items():
            g = grads.get(vid)
            if g is not None and not v.stop_gradient:
                v.grad = (g if v.grad is None else v.grad + g)
        self._ctx._counter = counter_after_forward
        # the tape is single-use (like the reference's grad-op chain):
        # free intermediates so a training loop inside one guard() stays
        # O(step) in time and memory
        self.entries.clear()
        self._consumed = True


_tape_stack: List[Tape] = []


def _active_tape() -> Optional[Tape]:
    return _tape_stack[-1] if _tape_stack else None


def push_tape(tape: Tape):
    _tape_stack.append(tape)


def pop_tape():
    _tape_stack.pop()


_MAIN_SLOTS = ("Out", "Y", "Output", "Loss", "Cost", "Hidden")


def _default_slot_order(outs):
    """Main slot first (Out/Y/Output/...), then the rest sorted — so
    trace_op(...)[0] is the principal output, not an aux like Mask."""
    main = [s for s in _MAIN_SLOTS if s in outs]
    rest = sorted(s for s in outs if s not in _MAIN_SLOTS)
    return main + rest


def trace_op(op_type: str, ins: Dict[str, Sequence[VarBase]],
             attrs: Dict[str, Any], out_slots: Optional[List[str]] = None
             ) -> List[VarBase]:
    """Run one registered op eagerly (ref tracer.h:44 Tracer::Trace):
    lower with concrete values, wrap outputs in VarBase, record on the
    tape.  Returns outputs of `out_slots` (default: all slots, sorted,
    main slot 'Out'-style first) flattened in order."""
    tape = _active_tape()
    if tape is None:
        raise EnforceNotMet(
            f"imperative op {op_type!r} outside imperative.guard()")
    opdef = get_op_def(op_type)
    ctx = tape.ctx()

    in_items = [(slot, i, v) for slot, vs in sorted(ins.items())
                for i, v in enumerate(vs)]
    in_vars = [v for (_, _, v) in in_items]
    # pin the RNG counter so the vjp re-execution draws the SAME keys as
    # the forward run (dropout etc. must replay identically)
    rng_start = ctx._counter

    def fn(*flat_vals):
        rebuilt: Dict[str, List[Any]] = {}
        for (slot, i, _), val in zip(in_items, flat_vals):
            rebuilt.setdefault(slot, []).append(val)
        ctx._counter = rng_start
        outs = opdef.lower(ctx, rebuilt, attrs)
        slots = out_slots or _default_slot_order(outs)
        return tuple(o for s in slots for o in outs[s])

    flat_in = tuple(v.value for v in in_vars)
    flat_out = fn(*flat_in)
    sg = opdef.stop_gradient or all(v.stop_gradient for v in in_vars)
    out_vars = [VarBase(o, stop_gradient=sg) for o in flat_out]
    if not sg:
        tape.record(fn, in_vars, out_vars)
    return out_vars
