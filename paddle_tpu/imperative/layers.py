"""Layer / PyLayer (ref imperative/layer.h:89 Layer, python layers.py:26
PyLayer)."""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from .base import to_variable
from .varbase import VarBase, trace_op

# Eager-parameter init stream: one process-wide RandomState so stacked
# same-shape layers draw DIFFERENT weights (symmetry breaking), while
# `seed_parameters(n)` restores reproducibility on demand.
_param_rng = np.random.RandomState(0)


def seed_parameters(seed: int) -> None:
    """Reset the eager-mode parameter-init stream (call before building a
    model to reproduce its initial weights)."""
    global _param_rng
    _param_rng = np.random.RandomState(seed)


class Layer:
    """Eager module: owns parameters, `forward` defines compute
    (ref imperative/layer.h:89)."""

    def __init__(self):
        self._parameters: Dict[str, VarBase] = {}
        self._sublayers: Dict[str, "Layer"] = {}
        self._built = False

    def create_parameter(self, name: str, shape, dtype="float32",
                         initializer=None) -> VarBase:
        if initializer is None:
            fan_in = int(np.prod(shape[:-1])) or 1
            init = _param_rng.uniform(
                -np.sqrt(6.0 / fan_in), np.sqrt(6.0 / fan_in),
                shape).astype(dtype)
        else:
            init = np.asarray(initializer, dtype=dtype).reshape(shape)
        p = VarBase(init, stop_gradient=False, name=name)
        self._parameters[name] = p
        return p

    def parameters(self) -> List[VarBase]:
        ps = list(self._parameters.values())
        for sub in self._sublayers.values():
            ps.extend(sub.parameters())
        return ps

    def __setattr__(self, name, value):
        if isinstance(value, Layer):
            self.__dict__.setdefault("_sublayers", {})[name] = value
        object.__setattr__(self, name, value)

    def _build_once(self, *inputs):
        pass

    def __call__(self, *inputs):
        inputs = tuple(to_variable(x) for x in inputs)
        if not self._built:
            self._build_once(*inputs)
            self._built = True
        return self.forward(*inputs)

    def forward(self, *inputs):
        raise NotImplementedError


class FC(Layer):
    """Eager fully-connected layer — the canonical Layer example
    (parallels the graph-mode layers.fc)."""

    def __init__(self, size: int, act: str = None):
        super().__init__()
        self.size = size
        self.act = act

    def _build_once(self, x):
        d = int(x.shape[-1])
        self.w = self.create_parameter("w", [d, self.size], x.dtype)
        self.b = self.create_parameter("b", [self.size], x.dtype,
                                       initializer=np.zeros(self.size))

    def forward(self, x):
        out = trace_op("mul", {"X": [x], "Y": [self.w]},
                       {"x_num_col_dims": 1, "y_num_col_dims": 1})[0]
        out = out + self.b
        if self.act:
            out = trace_op(self.act, {"X": [out]}, {})[0]
        return out


class PyLayer(Layer):
    """User-defined eager layer (ref python layers.py:26): subclass and
    implement forward over VarBases."""

    def forward(self, *inputs):
        raise NotImplementedError
