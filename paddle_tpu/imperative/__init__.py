"""Imperative (dygraph) mode — eager per-op execution with a grad tape.

Capability parity with the reference's embryonic imperative package
(/root/reference/paddle/fluid/imperative/: layer.h:30 VarBase, tracer.h:44
Tracer::Trace, engine.h:25; python/paddle/fluid/imperative/base.py:28
guard/to_variable, layers.py:26 PyLayer).

TPU-first redesign: JAX is already eager outside jit, so there is no
separate eager kernel path — imperative ops call the SAME registry
lowering functions the Executor traces (one op library, two drivers,
mirroring how the reference shares OpKernels between Executor and
Tracer).  The Tracer's grad-op chain (VarBase::RunBackward walking
pre-built grad ops) becomes a tape of (lower_fn, inputs, outputs)
entries; backward() replays the tape in reverse through jax.vjp, so
every registered differentiable op works imperatively with no extra
grad registry.
"""
from .base import enabled, guard, to_variable
from .layers import FC, Layer, PyLayer, seed_parameters
from .varbase import VarBase, trace_op

__all__ = ["enabled", "guard", "to_variable", "FC", "Layer", "PyLayer",
           "VarBase", "trace_op", "seed_parameters"]
