"""Weight regularizers (ref python/paddle/fluid/regularizer.py).

Applied by the Optimizer as grad-side program ops: grad += coeff * param
(L2) — identical contract to the reference's append_regularization_ops.
"""
from __future__ import annotations


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad_name, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.coeff = float(regularization_coeff)

    def append_regularization_op(self, param, grad_name, block):
        tmp = f"{grad_name}.l2decay"
        block.create_var(name=tmp, shape=param.shape, dtype=param.dtype,
                         stop_gradient=True)
        block.append_op("scale", {"X": [param.name]}, {"Out": [tmp]},
                        {"scale": self.coeff})
        block.append_op("sum", {"X": [grad_name, tmp]},
                        {"Out": [grad_name]}, {})


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.coeff = float(regularization_coeff)

    def append_regularization_op(self, param, grad_name, block):
        sgn = f"{grad_name}.l1sign"
        tmp = f"{grad_name}.l1decay"
        for n in (sgn, tmp):
            block.create_var(name=n, shape=param.shape, dtype=param.dtype,
                             stop_gradient=True)
        block.append_op("sign", {"X": [param.name]}, {"Out": [sgn]}, {})
        block.append_op("scale", {"X": [sgn]}, {"Out": [tmp]},
                        {"scale": self.coeff})
        block.append_op("sum", {"X": [grad_name, tmp]},
                        {"Out": [grad_name]}, {})


L2Decay = L2DecayRegularizer
L1Decay = L1DecayRegularizer
