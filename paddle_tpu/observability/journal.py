"""Fleet event journal: one append-only JSONL timeline of lifecycle
events across the whole fleet.

The metrics registry answers "how much/how often"; the runlog answers
"what did THIS run's scalars do"; neither answers the incident
question: *what happened, in what order, across the fleet, around
14:32?*  This module is that record — structured lifecycle events
(supervisor spawn/restart/park/revive, master generation bumps,
resize requested/applied, lease fences, guard trips with their
first-bad-var, chaos injections, checkpoint/reshard commits, serving
drains) appended as strict-JSON lines, each stamped with the paired
(``time_unix``, ``perf_counter``) clock sample of PR 4's fleet
protocol and the ambient X-ray trace id, so events correlate across
hosts and against request waterfalls.

Write discipline is the runlog idiom: writes NEVER raise (a full disk
must not take training down — failures land in
``journal_write_failures_total``), every line is strict JSON
(non-finite floats stringified), and rotation is atomic
(``os.replace`` to ``<path>.1``).  Unlike the per-run runlog, the
journal APPENDS across process restarts — a respawned incarnation
continues the same timeline — and rotates only when the file outgrows
``journal_rotate_bytes``.

Fleet assembly: every event also lands in a bounded in-memory ring
with an absolute-cursor read (:func:`events_since`, the
``trace.events_since`` contract) so the FleetReporter ships new events
to the coordinator over the existing ``report_events`` transport; the
FleetAggregator normalizes their clocks onto the master timeline
(``perf_counter + offset``, the PR 11 X-ray idiom — robust to
restarted perf epochs and skewed hosts) and appends them to the
coordinator's own journal file, producing ONE durable merged fleet
timeline.  ``python -m paddle_tpu.observability.incident`` reads it
back.

Enable via the ``journal_path`` flag (``PTPU_JOURNAL_PATH``); empty =
every :func:`emit` is a cheap no-op and no file or ring state exists
(the PR 7/10/11 flag-off invariance idiom, regression-tested).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence

from ..core import flags
from . import metrics as obs_metrics

SCHEMA = "paddle_tpu.journal.v1"

flags.define_flag("journal_path", "",
                  "Append-only JSONL fleet event journal "
                  "(observability/journal.py, schema "
                  "paddle_tpu.journal.v1): structured lifecycle events "
                  "— supervisor spawn/restart/park/revive, master "
                  "generation bumps, resizes, lease fences, guard "
                  "trips, chaos injections, checkpoint commits, "
                  "serving drains — stamped with the fleet clock pair "
                  "and the ambient trace id.  Empty disables (no file, "
                  "no ring, zero overhead).")
flags.define_flag("journal_rotate_bytes", 64_000_000,
                  "Rotate the journal to <path>.1 (atomic os.replace) "
                  "when a writer opens a file already larger than this "
                  "many bytes.  Unlike the per-run runlog the journal "
                  "appends across restarts; rotation only bounds "
                  "growth.  0 = never rotate.")

_m_events = obs_metrics.counter(
    "journal_events_total",
    "Events appended to the fleet event journal, by kind.", ("kind",))
_m_failures = obs_metrics.counter(
    "journal_write_failures_total",
    "Journal appends that failed (disk full / permission) and were "
    "absorbed — the journal must never take the fleet down.")
_m_collisions = obs_metrics.counter(
    "journal_field_collisions_total",
    "emit() instrumentation fields DROPPED because they collide with "
    "a reserved record name (rank/pid/seq/...).  Non-zero means a "
    "call site is silently losing data — rename the field (the PR 15 "
    "'worker=' gotcha, caught at the source).", ("field",))

# the envelope emit() owns; an instrumentation field by one of these
# names would either be dropped (old behavior, silently) or corrupt
# the dedupe/merge keys if honored — so it is dropped LOUDLY instead
_RESERVED_FIELDS = ("schema", "kind", "event", "time_unix",
                    "perf_counter", "rank", "pid", "seq", "trace_id")
_warned_collisions: set = set()

_RING_MAX = 4096

_lock = threading.Lock()
_writer_f = None                 # open file handle (lazy)
_writer_path: Optional[str] = None
_ring: List[dict] = []
_ring_base = 0                   # absolute index of _ring[0]
_generation = 0                  # bumped by reset(): cursor consumers resync
_seq = 0                         # per-process monotonic id (dedupe key)
_rank = 0


def enabled() -> bool:
    return bool(str(flags.get_flag("journal_path") or ""))


def set_rank(rank: int):
    """Fleet identity stamped on every event this process emits (the
    supervisor's elastic workers call this; 0 is the single-process
    default)."""
    global _rank
    _rank = int(rank)


def _strict(v: Any):
    """JSON-safe copy: non-finite floats stringified (every line must
    be strict JSON — a NaN loss is exactly what gets journaled),
    numpy scalars coerced, unknown objects repr-bounded.  A local twin
    of runlog's helper: the runlog module doubles as a CLI and must
    stay OUT of the package import graph (the runpy gotcha), so the
    journal cannot import it."""
    if isinstance(v, float):
        return v if math.isfinite(v) else repr(v)
    if isinstance(v, (int, bool, str)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k)[:80]: _strict(x) for k, x in list(v.items())[:32]}
    if isinstance(v, (list, tuple)):
        return [_strict(x) for x in list(v)[:32]]
    try:
        import operator
        return int(operator.index(v))     # integral numpy scalar
    except TypeError:
        pass
    try:
        return _strict(float(v))          # numpy scalar / 0-d array
    except (TypeError, ValueError):
        return repr(v)[:300]


def _open_writer(path: str):
    """Open (or reopen after a flag change) the journal file, rotating
    an oversized predecessor aside first.  Never raises."""
    global _writer_f, _writer_path
    cap = int(flags.get_flag("journal_rotate_bytes"))
    try:
        if cap > 0 and os.path.getsize(path) > cap:
            os.replace(path, path + ".1")
    except FileNotFoundError:
        pass
    except OSError as e:
        _m_failures.inc()
        warnings.warn(
            f"journal could not rotate {path!r} aside ({e}); "
            f"appending to the oversized file", RuntimeWarning,
            stacklevel=4)
    try:
        _writer_f = open(path, "a", encoding="utf-8")
        _writer_path = path
    except OSError as e:
        _writer_f, _writer_path = None, path
        _m_failures.inc()
        warnings.warn(f"journal not opened ({path}): {e}",
                      RuntimeWarning, stacklevel=4)


def emit(kind: str, event: str, **fields) -> Optional[dict]:
    """Append one lifecycle event: journal file + shipping ring.
    No-op (one flag read) when the journal is off; never raises.
    Returns the record, or None when disabled/failed."""
    path = str(flags.get_flag("journal_path") or "")
    if not path:
        return None
    global _seq
    from . import tracectx as obs_tracectx
    with _lock:
        _seq += 1
        rec: Dict[str, Any] = {
            "schema": SCHEMA, "kind": str(kind), "event": str(event),
            "time_unix": time.time(),
            "perf_counter": time.perf_counter(),
            "rank": _rank, "pid": os.getpid(), "seq": _seq,
        }
        tid = obs_tracectx.current_trace_id()
        if tid is not None:
            rec["trace_id"] = tid
        for k, v in fields.items():
            if k in _RESERVED_FIELDS:
                # LOUD drop: a collision with the envelope loses the
                # caller's data either way — say so (warn once per
                # site, count always) instead of eating it
                _m_collisions.labels(field=k).inc()
                site = (rec["kind"], rec["event"], k)
                if site not in _warned_collisions:
                    _warned_collisions.add(site)
                    warnings.warn(
                        f"journal.emit({rec['kind']}/{rec['event']}): "
                        f"field {k!r} collides with a reserved record "
                        f"name and was DROPPED — rename it (reserved: "
                        f"{_RESERVED_FIELDS})", RuntimeWarning,
                        stacklevel=2)
            elif k not in rec:
                rec[k] = _strict(v)
        _ring.append(rec)
        if len(_ring) > _RING_MAX:
            global _ring_base
            cut = len(_ring) // 2
            _ring_base += cut
            del _ring[:cut]
        if _writer_f is None or _writer_path != path:
            if _writer_f is not None:
                try:
                    _writer_f.close()
                except OSError:
                    pass
            _open_writer(path)
        _write_locked(rec)
    _m_events.labels(kind=str(kind)).inc()
    return rec


def _write_locked(rec: dict):
    global _writer_f
    if _writer_f is None:
        _m_failures.inc()
        return
    try:
        _writer_f.write(json.dumps(rec, allow_nan=False,
                                   separators=(",", ":")) + "\n")
        _writer_f.flush()
    except (OSError, ValueError):
        _m_failures.inc()


def append_raw(rec: dict):
    """Write a pre-built (already clock-normalized) record to THIS
    process's journal file — the coordinator's FleetAggregator appends
    worker-shipped events here so one file holds the merged durable
    fleet timeline.  Raw records bypass the shipping ring (they were
    shipped TO us) and, like every journal write, never raise."""
    path = str(flags.get_flag("journal_path") or "")
    if not path or not isinstance(rec, dict):
        return
    rec = dict(rec)
    rec.setdefault("schema", SCHEMA)
    with _lock:
        if _writer_f is None or _writer_path != path:
            if _writer_f is not None:
                try:
                    _writer_f.close()
                except OSError:
                    pass
            _open_writer(path)
        _write_locked({k: _strict(v) for k, v in rec.items()})


def events_since(cursor: int, gen: Optional[int] = None):
    """Atomic (generation, absolute length, tail) read for the
    FleetReporter — the trace.events_since contract: a generation
    mismatch means reset() wiped the ring, so the whole buffer
    returns; cursors are ABSOLUTE append positions (the ring trims
    from the front; ``_ring_base`` keeps them stable across trims)."""
    with _lock:
        g = _generation
        start_abs = cursor if gen == g else 0
        idx = max(0, min(start_abs - _ring_base, len(_ring)))
        return g, _ring_base + len(_ring), list(_ring[idx:])


def generation() -> int:
    return _generation


def tail(n: int = 100) -> List[dict]:
    """The newest `n` locally-emitted events (the /journal route's
    local half)."""
    with _lock:
        return list(_ring[-max(0, int(n)):])


def reset():
    """Test hook (conftest): close the writer, wipe the ring, bump the
    generation so cursor consumers resync, and zero the rank."""
    global _writer_f, _writer_path, _ring_base, _generation, _seq, _rank
    with _lock:
        if _writer_f is not None:
            try:
                _writer_f.close()
            except OSError:
                pass
        _writer_f, _writer_path = None, None
        _ring.clear()
        _ring_base = 0
        _generation += 1
        _seq = 0
        _rank = 0
        _warned_collisions.clear()
    _m_collisions.clear()


# -- reading / merging ------------------------------------------------------

def read_events(path: str) -> List[dict]:
    """Parse a journal file back into records.  Strict: every non-blank
    line must be a JSON object carrying this module's schema — the
    round-trip contract the incident CLI (and tests) rely on."""
    out: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{i}: not JSON ({e})") from e
            if not isinstance(rec, dict) or rec.get("schema") != SCHEMA:
                raise ValueError(
                    f"{path}:{i}: schema "
                    f"{rec.get('schema') if isinstance(rec, dict) else rec!r}"
                    f" != {SCHEMA}")
            out.append(rec)
    return out


def _dedupe_key(rec: dict):
    """Stable identity of one emission: (rank, pid, seq).  The same
    event can reach the incident CLI twice — once from the emitting
    rank's own file and once through the coordinator's merged file
    (shipped over report_events) — and must appear ONCE in the
    timeline.  Records without the triple (foreign/synthetic) are
    never deduped."""
    if all(k in rec for k in ("rank", "pid", "seq")):
        return (rec["rank"], rec["pid"], rec["seq"])
    return None


def merge_events(streams: Sequence[Sequence[dict]]) -> List[dict]:
    """Merge event streams into one timeline: dedupe by emission
    identity, order by ``time_unix`` (already master-normalized for
    aggregator-shipped events; the emitter's own wall clock
    otherwise)."""
    seen = set()
    out: List[dict] = []
    for stream in streams:
        for rec in stream or []:
            if not isinstance(rec, dict):
                continue
            key = _dedupe_key(rec)
            if key is not None:
                if key in seen:
                    continue
                seen.add(key)
            out.append(rec)
    out.sort(key=lambda r: (float(r.get("time_unix", 0.0) or 0.0),
                            r.get("seq", 0)))
    return out
