"""XLA cost model: per-compiled-program FLOPs / bytes / peak-HBM.

The compiler-facing half of the observability plane (ISSUE 3).  PR 1
counts *how often* the executor compiles and how long steps take; this
module says *how well* the hardware is used: every compiled program
(``_CompiledProgram``'s jitted step, ``run_steps``' ``_multi_cache``
device loops, the parallel executor's pjit programs — they all funnel
through the same ``jax.jit`` objects) can be lowered ahead-of-time and
asked for XLA's own accounting::

    lowered = jitted.lower(*abstract_args)
    compiled = lowered.compile()
    compiled.cost_analysis()      # {'flops': ..., 'bytes accessed': ...}
    compiled.memory_analysis()    # argument/output/temp/alias bytes

which is the JAX equivalent of the reference's per-op profiler +
memory-usage analysis (platform/profiler.h, contrib/memory_usage_calc),
and the accounting PaLM-style MFU reporting standardized.

When the XLA path is unavailable (backend without cost analysis, a
lowering that cannot be re-traced abstractly), a jaxpr-walking
*analytical* fallback estimates FLOPs (dot_general / conv counted
exactly from shapes, everything else as one flop per output element)
and bytes (operand + result footprints).  Reports carry a ``source``
field ("xla" | "analytic") so dashboards know which accounting they are
reading.

Analysis is LAZY and cached per compiled program: the first request
(``Executor.explain``, the trainer's MFU gauge, ``bench.py``,
``forensics.cache_report``) pays one extra AOT trace+compile; steady
state pays nothing.  The ``cost_model`` flag gates the whole plane.
"""
from __future__ import annotations

import threading
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from ..core import flags
from . import metrics as obs_metrics

# --- registry metrics: one gauge family per cost dimension ---------------
_m_flops = obs_metrics.gauge(
    "program_cost_flops",
    "XLA/analytic FLOPs of one execution of a compiled program.",
    ("program",))
_m_bytes = obs_metrics.gauge(
    "program_cost_bytes_accessed",
    "Bytes accessed (HBM traffic) of one execution of a compiled "
    "program.", ("program",))
_m_peak = obs_metrics.gauge(
    "program_cost_peak_hbm_bytes",
    "Peak device-memory footprint of a compiled program "
    "(arguments + outputs + XLA temps - aliased/donated).", ("program",))
_m_mem = obs_metrics.gauge(
    "program_cost_memory_bytes",
    "Memory footprint of a compiled program by component "
    "(argument/output/temp/alias).", ("program", "component"))

# v5e bf16 peak — the bar bench.py has always used for TPU MFU.
_TPU_PEAK_FLOPS = 197e12


def enabled() -> bool:
    return bool(flags.get_flag("cost_model"))


def device_peak_flops() -> float:
    """Per-device peak FLOP/s for MFU: the ``device_peak_flops`` flag
    when set, else a per-platform table (TPU only).  0.0 = unknown."""
    v = float(flags.get_flag("device_peak_flops"))
    if v > 0:
        return v
    import jax
    try:
        if jax.devices()[0].platform == "tpu":
            return _TPU_PEAK_FLOPS
    except Exception:
        pass
    return 0.0


@dataclass
class ProgramCost:
    """One compiled program's cost/memory accounting."""

    label: str
    flops: float = 0.0
    bytes_accessed: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0
    generated_code_bytes: int = 0
    source: str = "xla"          # "xla" | "analytic"
    raw: Dict[str, float] = field(default_factory=dict)

    @property
    def peak_hbm_bytes(self) -> int:
        return max(0, self.argument_bytes + self.output_bytes
                   + self.temp_bytes - self.alias_bytes)

    def to_dict(self) -> dict:
        return {
            "label": self.label,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "source": self.source,
        }


# computed costs by label — the flight recorder's per-program summary
_lock = threading.Lock()
_COSTS: Dict[str, ProgramCost] = {}


def summaries() -> Dict[str, dict]:
    """Every cost computed so far, keyed by program label (flight.py
    folds this into the diagnostic bundle)."""
    with _lock:
        return {k: v.to_dict() for k, v in _COSTS.items()}


def reset():
    with _lock:
        _COSTS.clear()


def _publish(cost: ProgramCost):
    with _lock:
        _COSTS[cost.label] = cost
    _m_flops.labels(program=cost.label).set(cost.flops)
    _m_bytes.labels(program=cost.label).set(cost.bytes_accessed)
    _m_peak.labels(program=cost.label).set(cost.peak_hbm_bytes)
    for comp, v in (("argument", cost.argument_bytes),
                    ("output", cost.output_bytes),
                    ("temp", cost.temp_bytes),
                    ("alias", cost.alias_bytes)):
        _m_mem.labels(program=cost.label, component=comp).set(v)


def abstractify(tree):
    """Shape/dtype skeleton of an argument pytree — what ``lower()``
    needs, without pinning the (possibly donated) device buffers."""
    import jax
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def args_label(uid: int, version: int, abs_args, kind: str = "step") -> str:
    """Stable short label for a compiled variant: program uid.version
    plus a hash of the abstract argument signature (distinguishes e.g.
    two batch sizes of the same program)."""
    import jax
    sig = ",".join(
        f"{a.shape}{a.dtype}" for a in jax.tree.leaves(abs_args))
    h = zlib.crc32(sig.encode()) & 0xFFFF
    return f"p{uid}.v{version}.{h:04x}.{kind}"


def analyze_jitted(jitted, abs_args: Tuple, label: str,
                   prefer_analytic: bool = False) -> Optional[ProgramCost]:
    """Cost/memory analysis of a ``jax.jit`` object against abstract
    args: XLA's own analysis when the backend provides it, the jaxpr
    walker otherwise.  ``prefer_analytic=True`` skips the XLA path (one
    abstract trace instead of a full AOT compile — what the trainer's
    per-step MFU gauge uses; matmul/conv FLOPs are exact either way).
    Returns None when the plane is off or both paths fail.  Results are
    published to the registry."""
    if not enabled():
        return None
    cost = None if prefer_analytic else _xla_analyze(jitted, abs_args,
                                                     label)
    if cost is None:
        cost = _jaxpr_analyze(jitted, abs_args, label)
    if cost is not None:
        _publish(cost)
    return cost


def _xla_analyze(jitted, abs_args, label) -> Optional[ProgramCost]:
    try:
        compiled = jitted.lower(*abs_args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = dict(ca or {})
        flops = float(ca.get("flops", 0.0) or 0.0)
        if flops <= 0:
            return None             # backend has no real cost model
        ma = compiled.memory_analysis()
        return ProgramCost(
            label=label, flops=flops,
            bytes_accessed=float(ca.get("bytes accessed", 0.0) or 0.0),
            argument_bytes=int(getattr(ma, "argument_size_in_bytes", 0)),
            output_bytes=int(getattr(ma, "output_size_in_bytes", 0)),
            temp_bytes=int(getattr(ma, "temp_size_in_bytes", 0)),
            alias_bytes=int(getattr(ma, "alias_size_in_bytes", 0)),
            generated_code_bytes=int(
                getattr(ma, "generated_code_size_in_bytes", 0)),
            source="xla",
            raw={k: v for k, v in ca.items()
                 if isinstance(v, (int, float)) and "{" not in k})
    except Exception:
        return None


def _jaxpr_analyze(fn, abs_args, label) -> Optional[ProgramCost]:
    """Analytical fallback: trace to a jaxpr and walk it."""
    import jax
    try:
        closed = jax.make_jaxpr(fn)(*abs_args)
        flops, traffic = _walk_jaxpr(closed.jaxpr)
        arg_bytes = sum(_aval_bytes(a) for a in closed.in_avals)
        out_bytes = sum(_aval_bytes(a) for a in closed.out_avals)
        return ProgramCost(
            label=label, flops=float(flops),
            bytes_accessed=float(traffic),
            argument_bytes=int(arg_bytes), output_bytes=int(out_bytes),
            temp_bytes=0, alias_bytes=0, source="analytic")
    except Exception:
        return None


# --- the jaxpr walker ----------------------------------------------------

def _aval_bytes(aval) -> int:
    import numpy as np
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    try:
        item = np.dtype(dtype).itemsize
    except TypeError:
        item = 4                    # extended dtypes (PRNG keys)
    n = 1
    for d in shape:
        n *= int(d)
    return n * item


def _aval_size(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def _dot_flops(eqn) -> float:
    (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    k = 1
    for d in lhs_c:
        k *= int(lhs.shape[d])
    out = _aval_size(eqn.outvars[0].aval)
    return 2.0 * k * out


def _conv_flops(eqn) -> float:
    dn = eqn.params["dimension_numbers"]
    rhs = eqn.invars[1].aval
    out = _aval_size(eqn.outvars[0].aval)
    rhs_total = _aval_size(rhs)
    out_feat = int(rhs.shape[dn.rhs_spec[0]])
    # per output element: 2 * (in_c / groups) * prod(kernel_spatial)
    return 2.0 * out * (rhs_total / max(1, out_feat))


def _walk_jaxpr(jaxpr) -> Tuple[float, float]:
    """(flops, bytes_moved) of one jaxpr, recursing into sub-jaxprs
    (pjit/scan/cond/while/custom_* closures).  scan multiplies its body
    by the trip count; while counts cond+body once (trip count is data-
    dependent — a lower bound, stated as such by source='analytic')."""
    flops = 0.0
    traffic = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "cond":
            # data-dependent branch: charge the most expensive one
            # (walk each branch ONCE — re-walking the winner would go
            # exponential on nested conds)
            costs = [_walk_jaxpr(b) for b in
                     (_as_jaxpr(x) for x in eqn.params.get(
                         "branches", ()))
                     if b is not None]
            if costs:
                f, b = max(costs, key=lambda c: c[0])
                flops += f
                traffic += b
                continue
        subs = _sub_jaxprs(eqn)
        if subs:
            for sub, mult in subs:
                f, b = _walk_jaxpr(sub)
                flops += f * mult
                traffic += b * mult
            continue
        if name == "dot_general":
            flops += _dot_flops(eqn)
        elif name == "conv_general_dilated":
            flops += _conv_flops(eqn)
        else:
            # elementwise estimate: one flop per output element
            flops += max((_aval_size(v.aval) for v in eqn.outvars),
                         default=0)
        traffic += sum(_aval_bytes(v.aval) for v in eqn.invars
                       if hasattr(v, "aval"))
        traffic += sum(_aval_bytes(v.aval) for v in eqn.outvars)
    return flops, traffic


def _as_jaxpr(obj):
    jaxpr = getattr(obj, "jaxpr", None)
    return jaxpr if jaxpr is not None and hasattr(jaxpr, "eqns") else (
        obj if hasattr(obj, "eqns") else None)


def _sub_jaxprs(eqn):
    """[(sub_jaxpr, multiplier), ...] for call-like primitives; [] for
    leaf primitives."""
    name = eqn.primitive.name
    params = eqn.params
    out = []
    if name == "scan":
        sub = _as_jaxpr(params.get("jaxpr"))
        if sub is not None:
            return [(sub, int(params.get("length", 1)))]
    if name == "while":
        for k in ("cond_jaxpr", "body_jaxpr"):
            sub = _as_jaxpr(params.get(k))
            if sub is not None:
                out.append((sub, 1))
        return out
    for v in params.values():
        sub = _as_jaxpr(v)
        if sub is not None:
            out.append((sub, 1))
    return out
