"""Perfscope: roofline attribution, collective-bubble accounting, and a
perf-regression watch.

The bench trajectory says *what* throughput is (MFU flat at 0.56 since
BENCH_r05); this module says *why*.  It joins the signals the stack
already has — the cost model's FLOPs / bytes_accessed (costmodel.py),
the measured step anatomy (trainer data-wait/host/device split,
executor dispatch histograms, serving prefill/decode timings) and the
``collective:*`` named scopes in parallel/hybrid.py — into one roofline
verdict per program and per trainer/serving phase:

  achieved FLOP/s vs device peak, arithmetic intensity vs the ridge
  point, and a bound classification {compute|memory|comms|input|host}
  with a recommended knob per verdict (the docs/PERF.md anatomy->knob
  table, machine-executed).

Collective accounting — how, honestly: ``jax.named_scope`` blocks run
at TRACE time, so the host cannot time individual collectives per
execution.  Instead perfscope traces the jitted step to a jaxpr
(``jax.make_jaxpr`` — an abstract trace, NOT an XLA compile; the
forensics compile log stays silent) and walks it like costmodel's
analytic walker, attributing each collective equation's output bytes to
the ``collective:<label>`` name found on its source-info name stack
(scan bodies multiply by trip count; gradient transposes keep the scope
as a substring).  Byte counts over per-platform link bandwidth give a
deterministic comm-time model; the MEASURED device step time anchors
the absolute seconds:

  perf_comm_exposed_seconds  = device_s x (comm model share)
  perf_bubble_fraction{collective} = that collective's share of the
                                     modeled step time

Device parameters: TPU uses the costmodel peak-FLOPs table plus ~819
GB/s HBM / ~45 GB/s ICI; other backends fall back to DOCUMENTED priors
(1 TFLOP/s peak, 100 GB/s HBM, 10 GB/s ICI) so classification is
deterministic in CPU tier-1 runs.  ``perf_hbm_gbps`` /
``perf_ici_gbps`` / ``device_peak_flops`` override all three.

Regression watch: per phase, the FIRST ``perf_baseline_window`` step
times freeze as the baseline; the rolling median of the newest window
is compared against it and published as ``perf_regression_ratio{phase}``
— the gauge the built-in ``perf_regression`` Watchtower rule
(alerts.py) thresholds at ``perf_regression_factor``, with this
module's :func:`alert_context` supplying the offending phase and an
exemplar trace id.

Everything is gated on the ``perfscope`` flag: off means byte-identical
outputs, compile keys and explain() reports, and zero gauges published.
On adds NO compiles on any step/request path either — the comm model
and the analytic cost are both jaxpr traces.
"""
from __future__ import annotations

import argparse
import json
import re
import sys
import threading
from collections import deque
from statistics import median
from typing import Any, Dict, List, Optional

from ..core import flags
from . import costmodel as obs_cost
from . import metrics as obs_metrics

# --- registry metrics ------------------------------------------------------
_m_ratio = obs_metrics.gauge(
    "perf_regression_ratio",
    "Rolling step-time median / frozen baseline median per phase "
    "(perfscope regression watch; the built-in perf_regression alert "
    "thresholds this at perf_regression_factor).", ("phase",))
_m_exposed = obs_metrics.gauge(
    "perf_comm_exposed_seconds",
    "Exposed (non-overlapped) collective seconds of the last "
    "collective-bearing step: measured device time x the comm model's "
    "share of modeled step time.")
_m_bubble = obs_metrics.gauge(
    "perf_bubble_fraction",
    "Per-collective bubble: that collective's share of the modeled "
    "step time (named from the collective:* scopes in "
    "parallel/hybrid.py via the jaxpr name stack).", ("collective",))
_m_mfu = obs_metrics.gauge(
    "perf_mfu",
    "Achieved FLOP/s / device peak per perfscope phase.", ("phase",))
_m_achieved = obs_metrics.gauge(
    "perf_achieved_flops",
    "Achieved FLOP/s (model FLOPs / measured device seconds) per "
    "perfscope phase.", ("phase",))
_m_intensity = obs_metrics.gauge(
    "perf_arith_intensity",
    "Arithmetic intensity (FLOPs / bytes accessed) per perfscope "
    "phase; compare against the device ridge point.", ("phase",))
_m_bound = obs_metrics.gauge(
    "perf_bound",
    "1 on the series matching a phase's CURRENT bound classification "
    "(compute|memory|comms|input|host), 0 on its previous one.",
    ("phase", "bound"))

BOUNDS = ("compute", "memory", "comms", "input", "host")

# Documented CPU-fallback priors — arbitrary but FIXED, so tier-1
# classification is deterministic without real hardware counters.
_CPU_PEAK_FLOPS = 1e12
_CPU_HBM_BPS = 100e9
_CPU_ICI_BPS = 10e9
# v5e figures (HBM from the spec sheet, ICI per link); peak FLOPs come
# from costmodel's table / the device_peak_flops flag.
_TPU_HBM_BPS = 819e9
_TPU_ICI_BPS = 45e9

# classification thresholds (fractions of wall / modeled step time)
_INPUT_FRACTION = 0.5
_HOST_FRACTION = 0.5
_COMM_SHARE = 1.0 / 3.0         # comms = plurality of the modeled time

RECOMMEND = {
    "compute": "raise MXU throughput: fuse_block, amp_bf16, or "
               "quantize_dtype (int8/fp8 matmuls)",
    "memory": "cut HBM traffic: fuse_block (VMEM-resident blocks), "
              "less remat, larger fused steps (run_steps)",
    "comms": "overlap collectives with compute (ROADMAP item 5) or "
             "grow the per-device batch to amortize the psum",
    "input": "raise prefetch_depth (double-buffered feeds) or speed "
             "up the reader",
    "host": "batch device work with run_steps (one dispatch per N "
            "steps) and trim per-step host work",
}

_COLLECTIVE_RE = re.compile(r"collective:([A-Za-z0-9_.\-]+)")
# fallback labels for collectives outside any collective:* scope
_COLLECTIVE_PRIMS = frozenset((
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "all_gather_invariant",
))

_lock = threading.RLock()
_phases: Dict[str, dict] = {}        # phase -> record (see _phase_rec)
_programs: Dict[str, dict] = {}      # program label -> sink record
_models: Dict[str, Optional[dict]] = {}   # label -> cached program model
_collectives: Dict[str, dict] = {}   # collective label -> last accounting
_last_regression: Optional[dict] = None


def enabled() -> bool:
    return bool(flags.get_flag("perfscope"))


def device_params() -> dict:
    """Roofline parameters for THIS process's backend, with documented
    CPU-fallback priors so verdicts stay deterministic off-TPU."""
    platform = "unknown"
    try:
        import jax
        platform = jax.devices()[0].platform
    except Exception:
        pass
    peak = obs_cost.device_peak_flops()
    if peak <= 0:
        peak = _CPU_PEAK_FLOPS
    hbm = float(flags.get_flag("perf_hbm_gbps")) * 1e9
    if hbm <= 0:
        hbm = _TPU_HBM_BPS if platform == "tpu" else _CPU_HBM_BPS
    ici = float(flags.get_flag("perf_ici_gbps")) * 1e9
    if ici <= 0:
        ici = _TPU_ICI_BPS if platform == "tpu" else _CPU_ICI_BPS
    return {"platform": platform, "peak_flops": peak, "hbm_bps": hbm,
            "ici_bps": ici, "ridge_intensity": peak / hbm}


# --- the comm model (jaxpr walk keyed by collective:* name scopes) ---------

def comm_model(fn, abs_args) -> Dict[str, float]:
    """Bytes moved per collective label in one execution of ``fn`` —
    an abstract jaxpr trace (NO XLA compile), walking sub-jaxprs with
    scan-trip multipliers exactly like costmodel's analytic walker.
    Labels come from the ``collective:<name>`` scopes on each
    equation's name stack (substring match, so gradient transposes
    keep their attribution); un-scoped collective primitives fall back
    to the primitive name.  {} when the trace fails or the program has
    no collectives."""
    try:
        import jax
        closed = jax.make_jaxpr(fn)(*abs_args)
    except Exception:
        return {}
    out: Dict[str, float] = {}
    _walk_comm(closed.jaxpr, 1.0, out)
    return out


def _walk_comm(jaxpr, mult: float, out: Dict[str, float]):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "cond":
            # charge the branch with the most collective bytes (the
            # costmodel max-branch idiom)
            best: Dict[str, float] = {}
            for br in eqn.params.get("branches", ()):
                sub = obs_cost._as_jaxpr(br)
                if sub is None:
                    continue
                acc: Dict[str, float] = {}
                _walk_comm(sub, mult, acc)
                if sum(acc.values()) > sum(best.values()):
                    best = acc
            for k, v in best.items():
                out[k] = out.get(k, 0.0) + v
            continue
        subs = obs_cost._sub_jaxprs(eqn)
        if subs:
            for sub, m in subs:
                _walk_comm(sub, mult * m, out)
            continue
        stack = str(getattr(eqn.source_info, "name_stack", "") or "")
        m_ = _COLLECTIVE_RE.search(stack)
        if m_:
            label = m_.group(1)
        elif name in _COLLECTIVE_PRIMS:
            label = name
        else:
            continue
        nbytes = sum(obs_cost._aval_bytes(v.aval) for v in eqn.outvars)
        out[label] = out.get(label, 0.0) + nbytes * mult


def program_model(label: str, fn, args) -> Optional[dict]:
    """Cached {flops, bytes_accessed, comm:{label: bytes}} model of a
    jitted step — built ONCE per label from abstract shapes (call
    before dispatch: donated buffers must still be valid).  Uses the
    cost model when its flag is on (publishing program_cost_* gauges),
    the raw jaxpr walker otherwise; None only when both traces fail."""
    with _lock:
        if label in _models:
            return _models[label]
    abs_args = obs_cost.abstractify(args)
    comm = comm_model(fn, abs_args)
    cost = obs_cost.analyze_jitted(fn, abs_args, label,
                                   prefer_analytic=True)
    if cost is None:                     # cost_model flag off
        cost = obs_cost._jaxpr_analyze(fn, abs_args, label)
    model = None
    if cost is not None or comm:
        model = {"flops": float(cost.flops) if cost else 0.0,
                 "bytes_accessed":
                     float(cost.bytes_accessed) if cost else 0.0,
                 "comm": comm}
    with _lock:
        _models[label] = model
    return model


# --- classification --------------------------------------------------------

def classify(flops: float, bytes_accessed: float,
             comm_bytes: float = 0.0, *, device_s: float = 0.0,
             data_wait_s: float = 0.0, host_s: float = 0.0,
             wall_s: float = 0.0, params: Optional[dict] = None) -> dict:
    """Roofline verdict for one step/program.  Pure and deterministic:
    measured seconds feed the achieved-FLOP/s and input/host checks;
    the compute-vs-memory-vs-comms split is the cost MODEL (flops/peak,
    bytes/hbm_bw, comm_bytes/ici_bw), so CPU tier-1 classification
    does not depend on wall-clock noise.  bound is None when there is
    nothing to classify (no cost model and no anatomy)."""
    p = params or device_params()
    compute_s = flops / p["peak_flops"]
    mem_s = bytes_accessed / p["hbm_bps"]
    comm_s = comm_bytes / p["ici_bps"]
    model_s = compute_s + mem_s + comm_s
    wall = wall_s or (data_wait_s + host_s + device_s)
    intensity = flops / bytes_accessed if bytes_accessed > 0 else 0.0
    achieved = flops / device_s if device_s > 0 and flops > 0 else 0.0
    bound = None
    if wall > 0 and data_wait_s / wall >= _INPUT_FRACTION:
        bound = "input"
    elif wall > 0 and host_s / wall >= _HOST_FRACTION \
            and host_s > device_s:
        bound = "host"
    elif model_s > 0:
        if comm_s >= _COMM_SHARE * model_s:
            bound = "comms"
        elif intensity >= p["ridge_intensity"]:
            bound = "compute"
        else:
            bound = "memory"
    comm_share = comm_s / model_s if model_s > 0 else 0.0
    return {
        "bound": bound,
        "recommend": RECOMMEND.get(bound),
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "comm_bytes": comm_bytes,
        "arith_intensity": intensity,
        "ridge_intensity": p["ridge_intensity"],
        "achieved_flops": achieved,
        "mfu": achieved / p["peak_flops"] if achieved > 0 else 0.0,
        "comm_share": comm_share,
        "exposed_comm_seconds":
            device_s * comm_share if device_s > 0 else comm_s,
        "model_seconds": {"compute": compute_s, "memory": mem_s,
                          "comms": comm_s},
    }


# --- phase / program recording --------------------------------------------

def _phase_rec(phase: str) -> dict:
    rec = _phases.get(phase)
    if rec is None:
        window = max(1, int(flags.get_flag("perf_baseline_window")))
        rec = {"count": 0, "total_s": 0.0, "last_s": 0.0,
               "baseline": deque(maxlen=window),
               "recent": deque(maxlen=window),
               "ratio": 1.0, "regressed": False,
               "last_trace_id": None, "verdict": None}
        _phases[phase] = rec
    return rec


def _watch(phase: str, rec: dict, seconds: float,
           trace_id: Optional[str]):
    """One regression-watch sample (call under _lock): the first
    window freezes as baseline, the rolling median of the newest
    window is the ratio numerator."""
    global _last_regression
    if trace_id:
        rec["last_trace_id"] = trace_id
    if len(rec["baseline"]) < rec["baseline"].maxlen:
        rec["baseline"].append(seconds)
    else:
        rec["recent"].append(seconds)
    ratio = 1.0
    if rec["recent"] and rec["baseline"]:
        base = median(rec["baseline"])
        ratio = median(rec["recent"]) / max(base, 1e-12)
    rec["ratio"] = ratio
    factor = float(flags.get_flag("perf_regression_factor"))
    rec["regressed"] = factor > 1.0 and ratio >= factor
    _m_ratio.labels(phase=phase).set(ratio)
    if rec["regressed"]:
        _last_regression = {
            "phase": phase, "ratio": ratio,
            "baseline_s": median(rec["baseline"]),
            "recent_s": median(rec["recent"]),
            "trace_id": rec["last_trace_id"]}


def _publish_bound(phase: str, rec: dict, bound: Optional[str]):
    prev = (rec.get("verdict") or {}).get("bound")
    if prev and prev != bound:
        _m_bound.labels(phase=phase, bound=prev).set(0)
    if bound:
        _m_bound.labels(phase=phase, bound=bound).set(1)


def note_step(phase: str, device_s: float = 0.0,
              data_wait_s: float = 0.0, host_s: float = 0.0,
              wall_s: float = 0.0, cost: Any = None,
              model: Optional[dict] = None,
              trace_id: Optional[str] = None):
    """Record one measured step of ``phase``: roofline verdict (from
    ``model`` — a :func:`program_model` dict — or a costmodel
    ProgramCost), comm-exposure gauges when the model names
    collectives, and a regression-watch sample.  No-op when the
    perfscope flag is off."""
    if not enabled():
        return
    flops = bytes_acc = 0.0
    comm: Dict[str, float] = {}
    if model:
        flops = float(model.get("flops", 0.0))
        bytes_acc = float(model.get("bytes_accessed", 0.0))
        comm = dict(model.get("comm") or {})
    elif cost is not None:
        flops = float(getattr(cost, "flops", 0.0))
        bytes_acc = float(getattr(cost, "bytes_accessed", 0.0))
    params = device_params()
    verdict = classify(flops, bytes_acc, sum(comm.values()),
                       device_s=device_s, data_wait_s=data_wait_s,
                       host_s=host_s, wall_s=wall_s, params=params)
    with _lock:
        rec = _phase_rec(phase)
        rec["count"] += 1
        seconds = wall_s or (data_wait_s + host_s + device_s)
        rec["total_s"] += seconds
        rec["last_s"] = seconds
        _publish_bound(phase, rec, verdict["bound"])
        rec["verdict"] = verdict
        if verdict["achieved_flops"] > 0:
            _m_achieved.labels(phase=phase).set(
                verdict["achieved_flops"])
            _m_mfu.labels(phase=phase).set(verdict["mfu"])
        if verdict["arith_intensity"] > 0:
            _m_intensity.labels(phase=phase).set(
                verdict["arith_intensity"])
        if comm:
            _m_exposed.set(verdict["exposed_comm_seconds"])
            model_total = sum(verdict["model_seconds"].values())
            for label, nbytes in comm.items():
                frac = (nbytes / params["ici_bps"]) / model_total \
                    if model_total > 0 else 0.0
                _m_bubble.labels(collective=label).set(frac)
                _collectives[label] = {
                    "bytes": nbytes,
                    "model_seconds": nbytes / params["ici_bps"],
                    "bubble_fraction": frac}
        _watch(phase, rec, seconds, trace_id)


def note_phase(phase: str, seconds: float,
               trace_id: Optional[str] = None):
    """Timing-only sample (serving prefill/decode): regression watch
    and time-sink accounting, no roofline (no cost model attached)."""
    note_step(phase, device_s=seconds, trace_id=trace_id)


def note_dispatch(label: str, seconds: float, cost: Any = None):
    """One executor dispatch of a compiled program: per-PROGRAM sink
    accounting for the top-N report and explain(perf=True).  Programs
    are not phases — no regression watch (label cardinality follows
    compiled variants, not pipeline stages)."""
    if not enabled():
        return
    flops = float(getattr(cost, "flops", 0.0) or 0.0)
    bytes_acc = float(getattr(cost, "bytes_accessed", 0.0) or 0.0)
    verdict = classify(flops, bytes_acc, device_s=seconds)
    with _lock:
        rec = _programs.setdefault(
            label, {"count": 0, "total_s": 0.0, "last_s": 0.0})
        rec["count"] += 1
        rec["total_s"] += seconds
        rec["last_s"] = seconds
        rec["verdict"] = verdict


# --- alert context (the perf_regression built-in rule) ---------------------

def alert_context(labels: Optional[Dict[str, str]] = None) -> dict:
    """Context for a firing perf_regression alert: the offending phase
    (from the breaching series' labels, else the last regressed
    phase), its ratio/baseline, and an exemplar trace id of a slow
    step.  Wired as the rule's ``context_fn`` in alerts.py — gauges
    carry no exemplars, so the engine cannot find these itself."""
    with _lock:
        phase = (labels or {}).get("phase")
        rec = _phases.get(phase) if phase else None
        if rec is None and _last_regression is not None:
            phase = _last_regression["phase"]
            rec = _phases.get(phase)
        if rec is None:
            return {}
        ctx: Dict[str, Any] = {
            "phase": phase, "regression_ratio": rec["ratio"]}
        if rec["baseline"]:
            ctx["baseline_seconds"] = median(rec["baseline"])
        if rec["recent"]:
            ctx["recent_seconds"] = median(rec["recent"])
        if rec["last_trace_id"]:
            ctx["exemplar_trace_ids"] = [rec["last_trace_id"]]
        return ctx


# --- reporting -------------------------------------------------------------

def _phase_doc(rec: dict) -> dict:
    d = {"count": rec["count"], "total_s": rec["total_s"],
         "last_s": rec["last_s"], "regression_ratio": rec["ratio"],
         "regressed": rec["regressed"],
         "last_trace_id": rec["last_trace_id"]}
    if rec["baseline"]:
        d["baseline_s"] = median(rec["baseline"])
    v = rec.get("verdict")
    if v:
        d.update({k: v[k] for k in
                  ("bound", "recommend", "mfu", "achieved_flops",
                   "arith_intensity", "comm_share",
                   "exposed_comm_seconds")})
    return d


def status_doc() -> dict:
    """The full perfscope view — GET /perf (local half), the CLI, and
    Executor.explain(perf=True) all render from this one document."""
    with _lock:
        phases = {name: _phase_doc(rec)
                  for name, rec in sorted(_phases.items())}
        programs = {
            label: {"count": rec["count"], "total_s": rec["total_s"],
                    "last_s": rec["last_s"],
                    **{k: rec["verdict"][k] for k in
                       ("bound", "recommend", "mfu", "achieved_flops",
                        "arith_intensity")
                       if rec.get("verdict")}}
            for label, rec in sorted(_programs.items())}
        collectives = {k: dict(v)
                       for k, v in sorted(_collectives.items())}
        last_reg = dict(_last_regression) if _last_regression else None
    return {
        "schema": "paddle_tpu.perf.v1",
        "enabled": enabled(),
        "device": device_params(),
        "regression": {
            "factor": float(flags.get_flag("perf_regression_factor")),
            "window": int(flags.get_flag("perf_baseline_window")),
            "last": last_reg},
        "phases": phases,
        "programs": programs,
        "collectives": collectives,
    }


def report(top: int = 5) -> List[str]:
    """Top-N time sinks (phases + programs by cumulative seconds),
    one line each: verdict + the recommended knob."""
    doc = status_doc()
    sinks = [("phase", name, d) for name, d in doc["phases"].items()]
    sinks += [("program", label, d)
              for label, d in doc["programs"].items()]
    sinks.sort(key=lambda s: -s[2].get("total_s", 0.0))
    dev = doc["device"]
    lines = [f"perfscope: platform={dev['platform']} "
             f"peak={dev['peak_flops']:.3g} FLOP/s "
             f"hbm={dev['hbm_bps']:.3g} B/s ici={dev['ici_bps']:.3g} "
             f"B/s ridge={dev['ridge_intensity']:.1f} flops/byte"]
    for kind, name, d in sinks[:max(0, top)]:
        bound = d.get("bound") or "unmeasured"
        line = (f"  {kind} {name}: {d['total_s'] * 1e3:.1f} ms over "
                f"{d['count']} runs -> {bound}-bound")
        if d.get("mfu"):
            line += f" (mfu {d['mfu']:.3f})"
        if d.get("regression_ratio", 1.0) and \
                d.get("regressed"):
            line += f" REGRESSED x{d['regression_ratio']:.2f}"
        lines.append(line)
        if d.get("recommend"):
            lines.append(f"      knob: {d['recommend']}")
    for label, c in doc["collectives"].items():
        lines.append(f"  collective {label}: {c['bytes']:.3g} B/step, "
                     f"bubble {c['bubble_fraction']:.1%}")
    if not sinks:
        lines.append("  (no samples recorded)")
    return lines


def explain_section(cost: Any, seconds: float = 0.0) -> dict:
    """Roofline verdict for one compiled program's cost — the
    Executor.explain(perf=True) section body."""
    flops = float(getattr(cost, "flops", 0.0) or 0.0)
    bytes_acc = float(getattr(cost, "bytes_accessed", 0.0) or 0.0)
    v = classify(flops, bytes_acc, device_s=seconds)
    return {"device": device_params(),
            "bound": v["bound"], "recommend": v["recommend"],
            "arith_intensity": v["arith_intensity"],
            "ridge_intensity": v["ridge_intensity"],
            "achieved_flops": v["achieved_flops"], "mfu": v["mfu"]}


def rows_from_metrics_doc(doc: Optional[dict]) -> dict:
    """Reconstruct per-phase roofline rows from a metrics DOCUMENT
    (this process's registry or a fleet worker's shipped snapshot) —
    what fleet.perf_rows() builds the per-rank merged view from."""
    fams = (doc or {}).get("metrics") or {}

    def series(name):
        return (fams.get(name) or {}).get("series") or []

    phases: Dict[str, dict] = {}

    def row_for(labels):
        return phases.setdefault(str((labels or {}).get("phase")), {})

    for metric, key in (("perf_regression_ratio", "regression_ratio"),
                        ("perf_mfu", "mfu"),
                        ("perf_achieved_flops", "achieved_flops"),
                        ("perf_arith_intensity", "arith_intensity")):
        for row in series(metric):
            row_for(row.get("labels"))[key] = row.get("value", 0.0)
    for row in series("perf_bound"):
        if row.get("value"):
            labels = row.get("labels") or {}
            row_for(labels)["bound"] = labels.get("bound")
    exposed = 0.0
    for row in series("perf_comm_exposed_seconds"):
        exposed = float(row.get("value", 0.0))
    bubbles = {
        (row.get("labels") or {}).get("collective"):
            float(row.get("value", 0.0))
        for row in series("perf_bubble_fraction")}
    return {"phases": phases, "comm_exposed_seconds": exposed,
            "bubble_fraction": bubbles}


def reset():
    """Drop baselines, sinks, cached models and every perf_* gauge
    series (conftest: one test's rooflines/regressions must not leak
    into the next)."""
    global _last_regression
    with _lock:
        _phases.clear()
        _programs.clear()
        _models.clear()
        _collectives.clear()
        _last_regression = None
    for m in (_m_ratio, _m_exposed, _m_bubble, _m_mfu, _m_achieved,
              _m_intensity, _m_bound):
        m.clear()


# --- CLI -------------------------------------------------------------------

def _self_test() -> int:
    """Hermetic fixture smoke (the xray/incident CLI idiom): synthetic
    verdicts + a synthetic regression exercised against TEMPORARY flag
    state; prints one PERFSCOPE_SELF_TEST json line, exit 0 on pass."""
    saved = {k: flags.get_flag(k) for k in
             ("perfscope", "perf_baseline_window",
              "perf_regression_factor")}
    flags.set_flag("perfscope", True)
    flags.set_flag("perf_baseline_window", 4)
    flags.set_flag("perf_regression_factor", 2.0)
    reset()
    try:
        p = device_params()
        checks = {}
        # 512^3 matmul: intensity ~85 flops/byte >> any ridge point
        v = classify(2 * 512.0 ** 3, 3 * 512.0 * 512 * 4,
                     device_s=1e-3, params=p)
        checks["compute_bound"] = v["bound"] == "compute"
        # tiny compute, 1 GB over the interconnect
        v = classify(1e8, 1e7, comm_bytes=1e9, device_s=1e-3, params=p)
        checks["comms_bound"] = v["bound"] == "comms"
        checks["exposed_positive"] = v["exposed_comm_seconds"] > 0
        # reader starvation: 90% of the wall is data wait
        v = classify(1e8, 1e8, data_wait_s=0.9, device_s=0.1, params=p)
        checks["input_bound"] = v["bound"] == "input"
        # regression watch: 4 fast samples freeze the baseline, then
        # 4 slow ones trip the x5 ratio past the x2 factor
        for _ in range(4):
            note_phase("selftest.phase", 0.010, trace_id="t-fast")
        for _ in range(4):
            note_phase("selftest.phase", 0.050, trace_id="t-slow")
        doc = status_doc()
        rec = doc["phases"]["selftest.phase"]
        checks["regression_fires"] = bool(rec["regressed"])
        ctx = alert_context({"phase": "selftest.phase"})
        checks["regression_context"] = \
            ctx.get("phase") == "selftest.phase" and \
            ctx.get("exemplar_trace_ids") == ["t-slow"]
        checks["ratio_gauge"] = \
            _m_ratio.labels(phase="selftest.phase").value >= 2.0
        ok = all(checks.values())
        print("PERFSCOPE_SELF_TEST " + json.dumps(
            {"ok": ok, "checks": checks,
             "ratio": rec["regression_ratio"]}, sort_keys=True))
        return 0 if ok else 1
    finally:
        reset()
        for k, v in saved.items():
            flags.set_flag(k, v)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.perfscope",
        description="Perfscope: roofline attribution + regression "
                    "watch over the live registry.")
    ap.add_argument("--doc", action="store_true",
                    help="print the full perf status document as JSON")
    ap.add_argument("--top", type=int, default=5,
                    help="time sinks to print (default 5)")
    ap.add_argument("--self-test", action="store_true",
                    help="hermetic fixture smoke; exit 0 on pass")
    args = ap.parse_args(argv)
    if args.self_test:
        return _self_test()
    if not enabled():
        print("perfscope: disabled (set the perfscope flag / "
              "PTPU_PERFSCOPE=1)", file=sys.stderr)
        return 2
    if args.doc:
        print(json.dumps(status_doc(), indent=2, sort_keys=True))
        return 0
    for line in report(args.top):
        print(line)
    return 0


if __name__ == "__main__":          # pragma: no cover
    sys.exit(main())
