"""X-ray waterfall CLI: render a ``paddle_tpu.xray.v1`` trace document
as an ASCII waterfall (plus raw JSON passthrough).

Usage::

    # a dumped waterfall document
    python -m paddle_tpu.observability.xray trace.json

    # straight off a live endpoint (GET /trace/<id>)
    python -m paddle_tpu.observability.xray --url http://host:port \
        --trace-id 4bf92f3577b34da6a3ce929d0e0e4736

    # tier-1 smoke: parse + render a bundled fixture
    python -m paddle_tpu.observability.xray --self-test

Exit codes: 0 rendered, 1 trace not found / malformed, 2 bad usage —
the ``analysis.lint`` CLI contract.
"""
from __future__ import annotations

import json
import sys
from typing import List, Optional

from . import tracectx

_BAR_WIDTH = 40

# A miniature but structurally complete serving trace: root request,
# queue wait, bucketed prefill with a compile inside it (the
# request-triggered-recompile shape), decode chunks, retire marker —
# what --self-test parses and renders without any live process.
_SELF_TEST_DOC = {
    "schema": tracectx.SCHEMA,
    "trace_id": "4bf92f3577b34da6a3ce929d0e0e4736",
    "span_count": 6,
    "duration_s": 0.1,
    "start_unix": 1700000000.0,
    "spans": [
        {"name": "serving.request", "span_id": "00f067aa0ba902b7",
         "parent_id": None, "kind": "request", "rank": 0,
         "offset_s": 0.0, "start_unix": 1700000000.0, "dur": 0.1,
         "orphan": False, "attrs": {"prompt_len": 9}},
        {"name": "serving.queue_wait", "span_id": "00f067aa0ba902b8",
         "parent_id": "00f067aa0ba902b7", "kind": "queue", "rank": 0,
         "offset_s": 0.0, "start_unix": 1700000000.0, "dur": 0.01,
         "orphan": False},
        {"name": "serving.prefill", "span_id": "00f067aa0ba902b9",
         "parent_id": "00f067aa0ba902b7", "kind": "prefill", "rank": 1,
         "offset_s": 0.01, "start_unix": 1700000000.01, "dur": 0.05,
         "orphan": False, "attrs": {"bucket": 16}},
        {"name": "serving.compile_bucket", "span_id": "00f067aa0ba902ba",
         "parent_id": "00f067aa0ba902b9", "kind": "compile", "rank": 1,
         "offset_s": 0.011, "start_unix": 1700000000.011, "dur": 0.04,
         "orphan": False, "attrs": {"bucket": 16}},
        {"name": "serving.decode", "span_id": "00f067aa0ba902bb",
         "parent_id": "00f067aa0ba902b7", "kind": "decode", "rank": 1,
         "offset_s": 0.06, "start_unix": 1700000000.06, "dur": 0.039,
         "orphan": False, "attrs": {"tokens": 8}},
        {"name": "serving.retire", "span_id": "00f067aa0ba902bc",
         "parent_id": "00f067aa0ba902b7", "kind": "marker", "rank": 1,
         "offset_s": 0.099, "start_unix": 1700000000.099, "dur": 0.0,
         "orphan": False},
    ],
}


def _fmt_dur(s: float) -> str:
    if s >= 1.0:
        return f"{s:.3f}s"
    if s >= 1e-3:
        return f"{s * 1e3:.2f}ms"
    return f"{s * 1e6:.0f}us"


def render_waterfall(doc: dict) -> str:
    """ASCII waterfall of one xray document: one line per span, a bar
    positioned/scaled on the trace's time axis, parent-indented, rank
    and slowest-span marked."""
    if doc.get("schema") != tracectx.SCHEMA:
        raise ValueError(
            f"not a {tracectx.SCHEMA} document "
            f"(schema={doc.get('schema')!r})")
    spans = list(doc.get("spans") or [])
    total = float(doc.get("duration_s") or 0.0) or max(
        (float(s.get("offset_s", 0.0)) + float(s.get("dur", 0.0))
         for s in spans), default=0.0)
    lines: List[str] = [
        f"trace {doc.get('trace_id')}  "
        f"({len(spans)} span(s), {_fmt_dur(total)})"]
    if doc.get("capture"):
        cap = doc["capture"]
        lines.append(f"  !! captured: {cap.get('reason')} "
                     f"{cap.get('detail') or ''}")
    by_id = {s.get("span_id"): s for s in spans}

    def depth(s, seen=()):
        p = s.get("parent_id")
        if not p or p not in by_id or s.get("span_id") in seen:
            return 0
        return 1 + depth(by_id[p], seen + (s.get("span_id"),))

    slowest = max((s for s in spans if s.get("dur")),
                  key=lambda s: s["dur"], default=None)
    for s in spans:
        off = float(s.get("offset_s", 0.0))
        dur = float(s.get("dur", 0.0))
        if total > 0:
            start = int(round(_BAR_WIDTH * off / total))
            width = max(1, int(round(_BAR_WIDTH * dur / total))) \
                if dur > 0 else 0
        else:
            start, width = 0, 0
        start = min(start, _BAR_WIDTH - 1)
        width = min(width, _BAR_WIDTH - start)
        bar = " " * start + ("#" * width if width else "|")
        bar = bar.ljust(_BAR_WIDTH)
        name = "  " * depth(s) + str(s.get("name"))
        mark = " <-- slowest" if s is slowest else ""
        orphan = " (orphan)" if s.get("orphan") else ""
        attrs = s.get("attrs")
        attr_s = (" " + ",".join(f"{k}={v}"
                                 for k, v in sorted(attrs.items()))
                  if attrs else "")
        lines.append(f"  [{bar}] {name:<32} {_fmt_dur(dur):>9} "
                     f"r{s.get('rank', 0)}{attr_s}{orphan}{mark}")
    return "\n".join(lines)


def _fetch_url(url: str, trace_id: str) -> dict:
    import urllib.request
    endpoint = url.rstrip("/") + f"/trace/{trace_id}"
    with urllib.request.urlopen(endpoint, timeout=10) as r:
        return json.loads(r.read().decode())


def _self_test() -> int:
    doc = json.loads(json.dumps(_SELF_TEST_DOC))   # exercise the wire
    text = render_waterfall(doc)
    needed = ["trace 4bf92f3577b34da6a3ce929d0e0e4736",
              "serving.prefill", "serving.compile_bucket",
              "bucket=16", "<-- slowest"]
    missing = [n for n in needed if n not in text]
    if missing:
        print(f"xray --self-test FAILED: missing {missing}\n{text}",
              file=sys.stderr)
        return 1
    # round-trip through build_waterfall too: raw spans -> document
    spans = [{**s, "trace_id": doc["trace_id"],
              "start_unix": s["start_unix"], "dur": s["dur"]}
             for s in doc["spans"]]
    rebuilt = tracectx.build_waterfall(doc["trace_id"], spans)
    if rebuilt["span_count"] != doc["span_count"]:
        print("xray --self-test FAILED: rebuild span count "
              f"{rebuilt['span_count']} != {doc['span_count']}",
              file=sys.stderr)
        return 1
    render_waterfall(rebuilt)
    print("xray --self-test OK")
    return 0


def _main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.xray",
        description="Render a request X-ray trace as an ASCII "
                    "waterfall.")
    ap.add_argument("trace", nargs="?",
                    help="path to a paddle_tpu.xray.v1 JSON document "
                         "('-' = stdin)")
    ap.add_argument("--url", help="live endpoint root; fetches "
                                  "GET /trace/<id>")
    ap.add_argument("--trace-id", help="trace id for --url")
    ap.add_argument("--json", action="store_true",
                    help="print the raw document instead of rendering")
    ap.add_argument("--self-test", action="store_true",
                    help="parse + render the bundled fixture and exit")
    args = ap.parse_args(argv)
    if args.self_test:
        return _self_test()
    try:
        if args.url:
            if not args.trace_id:
                ap.error("--url needs --trace-id")
            doc = _fetch_url(args.url, args.trace_id)
        elif args.trace == "-":
            doc = json.load(sys.stdin)
        elif args.trace:
            with open(args.trace) as f:
                doc = json.load(f)
        else:
            ap.error("give a trace file, '-', or --url/--trace-id")
            return 2
    except OSError as e:
        print(f"xray: cannot load trace: {e}", file=sys.stderr)
        return 1
    except ValueError as e:
        print(f"xray: malformed JSON: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0
    try:
        print(render_waterfall(doc))
    except ValueError as e:
        print(f"xray: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
