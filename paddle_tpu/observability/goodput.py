"""Timecard: fleet chip-time accounting and goodput/badput attribution.

Every other observability plane measures instantaneous rates or single
events; this one integrates over time.  A per-rank wall-clock **state
machine** partitions the rank's lifetime into monotonic, non-overlapping
segments drawn from a closed state catalog:

  compute | input_wait | compile | checkpoint_save | checkpoint_restore
  | resize_barrier | restart_gap | drain | idle

and answers the production question "what fraction of paid chip-seconds
did useful training?" — goodput, the fleet-level complement of MFU.

Feeding discipline (the tentpole constraint): NO new timers in hot
loops.  Every segment transition happens at a boundary the stack
already times —

* the trainer's per-step anatomy split (data-wait / host / device,
  PR 4) feeds ``input_wait`` and ``compute`` via :func:`note_step`;
* the executor's explicit AOT compile spans feed ``compile`` via
  :func:`note_span`;
* checkpoint save/restore in the trainer and the elastic worker feed
  ``checkpoint_save`` / ``checkpoint_restore``;
* the elastic worker's existing wait/retire boundaries feed ``idle``
  and ``resize_barrier`` via :func:`note_wait`;
* the serving batcher's drain_begin/drain_complete boundary feeds
  ``drain``;
* restart gaps (death -> respawn) and park gaps exist only OUTSIDE a
  process lifetime, so the live plane never records them — the offline
  reconstructor derives them from supervisor journal pairs.

Conservation invariant (asserted in the tier-1 elastic soak): the
accounting clock ``_last_perf`` only moves forward and every charge
advances it, so per-rank segments are non-overlapping BY CONSTRUCTION
and their sum equals the rank's tracked wall time exactly.  A span
reported with a duration that overlaps already-charged time is clipped
(never double-booked), and :func:`note_step` scales its anatomy parts
to the unclaimed remainder when a compile span already ate into the
step's wall.

Surfaces:

* live: ``chip_seconds_total{state}`` + ``goodput_fraction`` on the
  registry (local and fleet-merged /metrics), ``GET /goodput`` with
  per-rank rows via fleet.goodput_rows();
* offline: ``python -m paddle_tpu.observability.goodput <journal...>``
  replays the fleet journal (+ optional runlog) into the same per-rank
  timeline — a badput breakdown table and an ASCII timeline, with
  ``--compare`` across two runs and the runlog CLI exit-code contract
  (0 ok / 1 goodput regression / 2 bad input);
* alerting: the built-in ``goodput_collapse`` Watchtower rule
  (alerts.default_rules) fires when ``badput_fraction`` (the published
  complement — 0.0 until any chip-time is tracked, so an idle fresh
  rank can never false-fire) holds at or above
  ``1 - goodput_collapse_fraction``, with this module's
  :func:`alert_context` naming the dominant badput state.

Everything is gated on the ``goodput`` flag: off means byte-identical
outputs and compile keys and zero step-path work (one flag read per
already-existing boundary).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..core import flags
from . import metrics as obs_metrics

# the closed state catalog; compute is the only goodput state
STATES = ("compute", "input_wait", "compile", "checkpoint_save",
          "checkpoint_restore", "resize_barrier", "restart_gap",
          "drain", "idle")
GOOD_STATE = "compute"
BADPUT_STATES = tuple(s for s in STATES if s != GOOD_STATE)

SCHEMA = "paddle_tpu.goodput.v1"

# --- registry metrics ------------------------------------------------------
_m_chip = obs_metrics.counter(
    "chip_seconds_total",
    "Accounted chip-seconds per Timecard state (compute|input_wait|"
    "compile|checkpoint_save|checkpoint_restore|resize_barrier|"
    "restart_gap|drain|idle).  Per-rank segments are non-overlapping "
    "and sum to the rank's tracked wall time (the conservation "
    "invariant).", ("state",))
_m_fraction = obs_metrics.gauge(
    "goodput_fraction",
    "compute chip-seconds / total tracked chip-seconds of this rank.")
# the alerting series: a labelless gauge always exposes a 0.0 default
# series, so a low-goodput rule thresholding goodput_fraction directly
# would false-fire on a rank that has not tracked ANY chip-time yet.
# The complement starts at the safe end: 0.0 badput until data exists,
# and goodput_collapse fires on badput_fraction >= 1 - collapse_fraction
_m_badput = obs_metrics.gauge(
    "badput_fraction",
    "1 - goodput_fraction once chip-time is tracked (0 before): the "
    "built-in goodput_collapse alert fires when this holds at or "
    "above 1 - goodput_collapse_fraction.")

# durations below this are noise, not segments (clock granularity)
_EPS = 1e-9
# timeline ring bound: merging makes transitions rare, but a pathological
# alternation must not grow without bound
_SEG_MAX = 4096

_lock = threading.RLock()
_t0_unix: Optional[float] = None
_t0_perf: Optional[float] = None
_last_perf: Optional[float] = None
_accum: Dict[str, float] = {}
_segments: List[dict] = []          # closed segments (merged)
_cur: Optional[dict] = None         # open segment {state, start_unix, dur}
_drain_start: Optional[float] = None


def enabled() -> bool:
    return bool(flags.get_flag("goodput"))


# --- the state machine -----------------------------------------------------

def _ensure_started_locked(now_perf: float):
    global _t0_unix, _t0_perf, _last_perf
    if _t0_perf is None:
        _t0_perf = _last_perf = now_perf
        _t0_unix = time.time() - (time.perf_counter() - now_perf)


def _close_cur_locked():
    """Close the open segment: move it to the ring and (journal on)
    emit it — the offline reconstructor's per-rank timeline source."""
    global _cur
    if _cur is None or _cur["dur"] <= _EPS:
        _cur = None
        return
    seg = {"state": _cur["state"],
           "start_unix": round(_cur["start_unix"], 6),
           "dur": round(_cur["dur"], 6)}
    _segments.append(seg)
    if len(_segments) > _SEG_MAX:
        del _segments[:_SEG_MAX // 2]
    _cur = None
    from . import journal as obs_journal
    obs_journal.emit("goodput", "segment", state=seg["state"],
                     seg_start_unix=seg["start_unix"], dur=seg["dur"])


def _charge_locked(state: str, start_perf: float, dur: float):
    """Book ``dur`` seconds of ``state`` starting at ``start_perf``.
    Callers guarantee start_perf >= _last_perf (monotonic)."""
    global _cur, _last_perf
    if dur <= _EPS:
        return
    _accum[state] = _accum.get(state, 0.0) + dur
    _m_chip.labels(state=state).inc(dur)
    start_unix = _t0_unix + (start_perf - _t0_perf)
    if _cur is not None and _cur["state"] == state:
        _cur["dur"] = (start_perf + dur) - _cur["_start_perf"]
    else:
        _close_cur_locked()
        _cur = {"state": state, "start_unix": start_unix,
                "_start_perf": start_perf, "dur": dur}
    _last_perf = max(_last_perf, start_perf + dur)
    total = sum(_accum.values())
    if total > _EPS:
        frac = _accum.get(GOOD_STATE, 0.0) / total
        _m_fraction.set(frac)
        _m_badput.set(1.0 - frac)


def note_wait(state: str):
    """Charge everything since the last accounted boundary to
    ``state`` — the elastic worker's idle/resize spin and RPC waits
    (the sleep/return IS the boundary; no new timer)."""
    if not enabled():
        return
    now = time.perf_counter()
    with _lock:
        _ensure_started_locked(now)
        _charge_locked(state, _last_perf, now - _last_perf)


def note_span(state: str, seconds: float):
    """Charge a span that just ENDED with a caller-measured duration
    (compile spans, checkpoint save/restore).  The span is clipped to
    the unclaimed interval — time already booked is never re-booked —
    and any gap between the last boundary and the span start is idle."""
    if not enabled():
        return
    now = time.perf_counter()
    seconds = max(0.0, float(seconds))
    with _lock:
        # first-ever charge: start the clock at the span START so the
        # span itself is inside the tracked window
        _ensure_started_locked(now - seconds)
        start = max(_last_perf, now - seconds)
        gap = start - _last_perf
        if gap > _EPS:
            _charge_locked("idle", _last_perf, gap)
        _charge_locked(state, start, now - start)


def note_step(data_wait_s: float, host_s: float, device_s: float,
              wall_s: float):
    """The trainer anatomy seam (PR 4 splits, measured already): one
    training step's wall partitions into input_wait (reader next +
    feed build), compute (dispatch + device), and idle residual.  When
    part of the step's wall was already claimed (an executor compile
    span fired mid-step), the anatomy is scaled down proportionally to
    the unclaimed remainder so the conservation invariant holds."""
    if not enabled():
        return
    now = time.perf_counter()
    wall = max(0.0, float(wall_s))
    with _lock:
        # first-ever charge: start the clock at the step START so the
        # step itself is inside the tracked window
        _ensure_started_locked(now - wall)
        avail = now - _last_perf
        if avail <= _EPS:
            return
        if avail > wall:
            # inter-step gap (event handlers, between-epoch work)
            _charge_locked("idle", _last_perf, avail - wall)
            avail = wall
        parts = [("input_wait", max(0.0, float(data_wait_s))),
                 ("compute", max(0.0, float(host_s))
                  + max(0.0, float(device_s)))]
        part_sum = sum(p for _, p in parts)
        parts.append(("idle", max(0.0, wall - part_sum)))
        total = max(part_sum, wall)
        scale = avail / total if total > _EPS else 0.0
        cursor = _last_perf
        for state, dur in parts:
            d = dur * scale
            if d <= _EPS:
                continue
            _charge_locked(state, cursor, d)
            cursor += d


def note_drain_begin():
    """Serving drain started (batcher.begin_drain — already journals
    here); the matching note_drain_end charges the span."""
    if not enabled():
        return
    global _drain_start
    with _lock:
        _drain_start = time.perf_counter()


def note_drain_end():
    if not enabled():
        return
    global _drain_start
    with _lock:
        if _drain_start is None:
            return
        dur = time.perf_counter() - _drain_start
        _drain_start = None
    note_span("drain", dur)


def flush():
    """Close the open segment (journal emit) — call before reading a
    final snapshot or exiting, so the timeline is complete."""
    if not enabled():
        return
    with _lock:
        _close_cur_locked()


def emit_final():
    """Journal this rank's final per-state totals — the offline
    reconstructor's per-rank breakdown source (segments give the
    timeline; the final gives totals that survive ring bounds)."""
    if not enabled():
        return
    with _lock:
        _close_cur_locked()
        snap = _snapshot_locked()
    from . import journal as obs_journal
    obs_journal.emit("goodput", "final",
                     states={k: round(v, 6)
                             for k, v in snap["states"].items()},
                     wall_s=round(snap["wall_s"], 6),
                     fraction=snap["goodput_fraction"])


# --- reading ---------------------------------------------------------------

def _snapshot_locked() -> dict:
    tracked = sum(_accum.values())
    wall = 0.0 if _t0_perf is None else (_last_perf - _t0_perf)
    frac = (_accum.get(GOOD_STATE, 0.0) / tracked) if tracked > _EPS \
        else 0.0
    segs = list(_segments)
    if _cur is not None and _cur["dur"] > _EPS:
        segs.append({"state": _cur["state"],
                     "start_unix": round(_cur["start_unix"], 6),
                     "dur": round(_cur["dur"], 6)})
    return {"states": {k: round(v, 6) for k, v in sorted(_accum.items())},
            "wall_s": round(wall, 6),
            "tracked_s": round(tracked, 6),
            "goodput_fraction": round(frac, 6),
            "started_unix": _t0_unix,
            "segments": segs}


def snapshot() -> dict:
    """This rank's accounting: per-state seconds, tracked wall, the
    fraction, and the (bounded) segment timeline."""
    with _lock:
        return _snapshot_locked()


def fraction() -> float:
    with _lock:
        tracked = sum(_accum.values())
        return (_accum.get(GOOD_STATE, 0.0) / tracked) \
            if tracked > _EPS else 0.0


def dominant_badput() -> Tuple[Optional[str], float]:
    """(state, seconds) of the largest non-compute accumulator — the
    goodput_collapse alert's context headline."""
    with _lock:
        bad = [(s, _accum.get(s, 0.0)) for s in BADPUT_STATES
               if _accum.get(s, 0.0) > _EPS]
    if not bad:
        return None, 0.0
    return max(bad, key=lambda kv: kv[1])


def status_doc() -> dict:
    """One document behind GET /goodput and the live CLI."""
    doc = snapshot()
    doc["schema"] = SCHEMA
    doc["enabled"] = enabled()
    doc["states_catalog"] = list(STATES)
    state, secs = dominant_badput()
    doc["dominant_badput"] = state
    doc["dominant_badput_s"] = round(secs, 6)
    return doc


def rows_from_metrics_doc(doc: Optional[dict]) -> dict:
    """Reconstruct the per-rank breakdown from a metrics DOCUMENT
    (this process's registry or a fleet worker's shipped snapshot) —
    what fleet.goodput_rows() builds the per-rank merged view from."""
    fams = (doc or {}).get("metrics") or {}

    def series(name):
        return (fams.get(name) or {}).get("series") or []

    states: Dict[str, float] = {}
    for row in series("chip_seconds_total"):
        state = (row.get("labels") or {}).get("state")
        if state:
            states[state] = float(row.get("value", 0.0))
    # derive the fraction from the chip-second counters, never from the
    # gauge: a labelless gauge exposes a 0.0 default series even on a
    # rank that tracked nothing, and "no data" must read as None here
    total = sum(states.values())
    frac = states.get(GOOD_STATE, 0.0) / total if total > _EPS else None
    return {"states": states, "goodput_fraction": frac,
            "chip_seconds": round(total, 6)}


def alert_context(labels: Dict[str, str]) -> dict:
    """Context for the built-in goodput_collapse rule: the fraction
    plus the dominant badput state the operator should chase."""
    state, secs = dominant_badput()
    with _lock:
        states = {k: round(v, 6) for k, v in sorted(_accum.items())}
    return {"goodput_fraction": round(fraction(), 6),
            "dominant_badput": state,
            "dominant_badput_s": round(secs, 6),
            "chip_seconds": states}


def reset():
    """Drop the accounting clock, accumulators, timeline and both
    metric families (conftest: one test's chip-time must not leak
    into the next)."""
    global _t0_unix, _t0_perf, _last_perf, _cur, _drain_start
    with _lock:
        _t0_unix = _t0_perf = _last_perf = None
        _accum.clear()
        _segments.clear()
        _cur = None
        _drain_start = None
    _m_chip.clear()
    _m_fraction.clear()
    _m_badput.clear()


# --- offline reconstructor -------------------------------------------------

def reconstruct_events(events: Sequence[dict],
                       runlog_records: Optional[Sequence[dict]] = None
                       ) -> dict:
    """Replay a merged journal stream (+ optional runlog) into the
    same per-rank timeline the live plane publishes.

    * ``goodput/segment`` events give each rank's timeline and
      ``goodput/final`` events its live per-state totals (summed over
      incarnations, so a retired-then-revived rank accumulates);
    * restart gaps come from supervisor ``restart -> spawn`` pairs and
      park gaps (a shrink parking the rank until a later grow) from
      ``park -> spawn`` pairs — chip-time no process could account for
      itself, kept under offline-only keys per rank;
    * ``master/resize_applied`` events become the fleet resize log;
    * runlog step records back-fill compute/input_wait for a rank that
      journaled but never ran the live plane (goodput off, journal on).
    """
    ranks: Dict[int, dict] = {}

    def rank_rec(r) -> dict:
        return ranks.setdefault(int(r), {
            "states": {}, "offline_states": {}, "segments": [],
            "finals": 0})

    pending: Dict[int, Tuple[str, float]] = {}   # worker -> (why, t)
    restart_gaps: List[dict] = []
    resizes: List[dict] = []
    for e in events:
        kind, ev = e.get("kind"), e.get("event")
        t = float(e.get("time_unix", 0.0))
        if kind == "goodput" and ev == "segment":
            rec = rank_rec(e.get("rank", 0))
            rec["segments"].append(
                {"state": e.get("state"),
                 "start_unix": float(e.get("seg_start_unix", t)),
                 "dur": float(e.get("dur", 0.0))})
        elif kind == "goodput" and ev == "final":
            rec = rank_rec(e.get("rank", 0))
            rec["finals"] += 1
            for s, v in (e.get("states") or {}).items():
                rec["states"][s] = rec["states"].get(s, 0.0) + float(v)
        elif kind == "supervisor" and ev in ("restart", "park"):
            w = e.get("worker")
            if w is not None:
                pending[int(w)] = (ev, t)
        elif kind == "supervisor" and ev == "spawn":
            w = e.get("worker")
            if w is None or int(w) not in pending:
                continue
            why, t_dead = pending.pop(int(w))
            gap = max(0.0, t - t_dead)
            state = "restart_gap" if why == "restart" \
                else "resize_barrier"
            rec = rank_rec(w)
            rec["offline_states"][state] = \
                rec["offline_states"].get(state, 0.0) + gap
            rec["segments"].append({"state": state, "start_unix": t_dead,
                                    "dur": gap, "offline": True})
            restart_gaps.append({"rank": int(w), "why": why,
                                 "start_unix": t_dead,
                                 "dur": round(gap, 6)})
        elif kind == "master" and ev == "resize_applied":
            resizes.append({"old": e.get("old_world"),
                            "new": e.get("new_world"),
                            "epoch": e.get("epoch"), "time_unix": t})
    # a rank that never closed a final still has segments: derive its
    # live totals from them so a chaos-killed incarnation's chip-time
    # is not dropped from the fleet sum
    for rec in ranks.values():
        if rec["finals"] == 0 and rec["segments"]:
            for seg in rec["segments"]:
                if seg.get("offline"):
                    continue
                rec["states"][seg["state"]] = \
                    rec["states"].get(seg["state"], 0.0) + seg["dur"]
    # runlog back-fill: only for the emitting rank 0 timeline when no
    # goodput events exist at all (journal-only runs)
    if runlog_records and not any(r["states"] or r["segments"]
                                  for r in ranks.values()):
        rec = rank_rec(0)
        for r in runlog_records:
            if r.get("kind") not in ("step", "bench"):
                continue
            dt = float(r.get("step_seconds", r.get("seconds", 0.0))
                       or 0.0)
            dw = float(r.get("data_wait_seconds", 0.0) or 0.0)
            if dt <= 0.0:
                continue
            rec["states"]["input_wait"] = \
                rec["states"].get("input_wait", 0.0) + min(dw, dt)
            rec["states"][GOOD_STATE] = \
                rec["states"].get(GOOD_STATE, 0.0) + max(0.0, dt - dw)
    fleet: Dict[str, float] = {}
    out_ranks: Dict[str, dict] = {}
    for r in sorted(ranks):
        rec = ranks[r]
        full = dict(rec["states"])
        for s, v in rec["offline_states"].items():
            full[s] = full.get(s, 0.0) + v
        for s, v in full.items():
            fleet[s] = fleet.get(s, 0.0) + v
        tracked = sum(full.values())
        segs = sorted(rec["segments"],
                      key=lambda seg: seg["start_unix"])
        out_ranks[str(r)] = {
            "states": {k: round(v, 6)
                       for k, v in sorted(rec["states"].items())},
            "offline_states": {k: round(v, 6) for k, v in
                               sorted(rec["offline_states"].items())},
            "states_full": {k: round(v, 6)
                            for k, v in sorted(full.items())},
            "chip_seconds": round(tracked, 6),
            "goodput_fraction": round(
                full.get(GOOD_STATE, 0.0) / tracked, 6)
            if tracked > _EPS else 0.0,
            "segments": segs}
    total = sum(fleet.values())
    return {"schema": SCHEMA, "source": "journal",
            "ranks": out_ranks,
            "fleet": {"states": {k: round(v, 6)
                                 for k, v in sorted(fleet.items())},
                      "chip_seconds": round(total, 6),
                      "goodput_fraction": round(
                          fleet.get(GOOD_STATE, 0.0) / total, 6)
                      if total > _EPS else 0.0},
            "restart_gaps": restart_gaps, "resizes": resizes}


def reconstruct(journal_paths: Sequence[str],
                runlog_path: Optional[str] = None) -> dict:
    """File wrapper over :func:`reconstruct_events`.  Raises OSError /
    ValueError on unreadable or wrong-schema inputs (CLI exit 2)."""
    from . import journal as obs_journal
    streams = [obs_journal.read_events(p) for p in journal_paths]
    events = obs_journal.merge_events(streams)
    records = None
    if runlog_path:
        from . import runlog as obs_runlog
        records = obs_runlog.read_records(runlog_path)
    return reconstruct_events(events, runlog_records=records)


# --- rendering -------------------------------------------------------------

_TL_CHARS = {"compute": "#", "input_wait": "i", "compile": "c",
             "checkpoint_save": "s", "checkpoint_restore": "r",
             "resize_barrier": "b", "restart_gap": "x", "drain": "d",
             "idle": "."}


def badput_table(doc: dict) -> List[str]:
    """The breakdown table: one row per state, fleet-summed, plus a
    per-rank goodput column block."""
    fleet = doc.get("fleet") or {}
    states = fleet.get("states") or {}
    total = fleet.get("chip_seconds") or sum(states.values()) or 0.0
    lines = ["goodput breakdown "
             f"(fleet chip-seconds {total:.2f}, goodput "
             f"{100.0 * (fleet.get('goodput_fraction') or 0.0):.1f}%)",
             f"  {'state':<20} {'seconds':>10} {'share':>7}"]
    for state in STATES:
        v = states.get(state, 0.0)
        if v <= _EPS:
            continue
        share = v / total if total > _EPS else 0.0
        tag = " (goodput)" if state == GOOD_STATE else ""
        lines.append(f"  {state:<20} {v:>10.2f} {share:>6.1%}{tag}")
    lines.append("  per-rank goodput:")
    for r, rec in sorted((doc.get("ranks") or {}).items(),
                         key=lambda kv: int(kv[0])):
        lines.append(
            f"    rank {r}: {100.0 * rec['goodput_fraction']:.1f}% of "
            f"{rec['chip_seconds']:.2f}s")
    return lines


def timeline_lines(doc: dict, width: int = 64) -> List[str]:
    """ASCII per-rank timeline: one row per rank, one column per time
    bucket, the bucket's dominant state as its glyph."""
    ranks = doc.get("ranks") or {}
    segs = [s for rec in ranks.values() for s in rec.get("segments", [])
            if s.get("dur", 0.0) > _EPS]
    if not segs:
        return ["(no segments to draw)"]
    t0 = min(s["start_unix"] for s in segs)
    t1 = max(s["start_unix"] + s["dur"] for s in segs)
    span = max(t1 - t0, _EPS)
    lines = [f"timeline ({span:.1f}s, {width} cols; "
             + " ".join(f"{c}={s}" for s, c in _TL_CHARS.items()) + ")"]
    for r, rec in sorted(ranks.items(), key=lambda kv: int(kv[0])):
        buckets: List[Dict[str, float]] = [{} for _ in range(width)]
        for seg in rec.get("segments", []):
            lo = (seg["start_unix"] - t0) / span * width
            hi = (seg["start_unix"] + seg["dur"] - t0) / span * width
            for i in range(max(0, int(lo)),
                           min(width, int(hi) + 1)):
                ov = min(hi, i + 1) - max(lo, i)
                if ov > 0:
                    b = buckets[i]
                    b[seg["state"]] = b.get(seg["state"], 0.0) + ov
        row = "".join(
            _TL_CHARS.get(max(b, key=b.get), "?") if b else " "
            for b in buckets)
        lines.append(f"  rank {r:>2} |{row}|")
    return lines


def compare_docs(a: dict, b: dict, tolerance: float = 0.1
                 ) -> Tuple[List[str], bool]:
    """Side-by-side fleet breakdown of two reconstructed runs; the
    second run regresses when its fleet goodput_fraction drops more
    than ``tolerance`` (absolute) below the first's."""
    fa, fb = a.get("fleet") or {}, b.get("fleet") or {}
    ga = fa.get("goodput_fraction") or 0.0
    gb = fb.get("goodput_fraction") or 0.0
    lines = [f"  {'state':<20} {'run A (s)':>10} {'run B (s)':>10}"]
    sa, sb = fa.get("states") or {}, fb.get("states") or {}
    for state in STATES:
        va, vb = sa.get(state, 0.0), sb.get(state, 0.0)
        if va <= _EPS and vb <= _EPS:
            continue
        lines.append(f"  {state:<20} {va:>10.2f} {vb:>10.2f}")
    lines.append(f"  {'goodput_fraction':<20} {ga:>10.3f} {gb:>10.3f}")
    regressed = (ga - gb) > tolerance
    if regressed:
        lines.append(f"  REGRESSION: goodput dropped "
                     f"{ga - gb:.3f} (> tolerance {tolerance})")
    return lines, regressed


def incident_section(events: Sequence[dict],
                     min_spike_s: float = 0.25, top: int = 8) -> dict:
    """The incident --goodput join: the largest badput segments in the
    window, each annotated with the alert fires / controller decisions
    within +-5s — "the fleet idled HERE, while THIS was firing"."""
    doc = reconstruct_events(events)
    spikes = []
    for r, rec in (doc.get("ranks") or {}).items():
        for seg in rec.get("segments", []):
            if seg["state"] == GOOD_STATE or seg["dur"] < min_spike_s:
                continue
            spikes.append({"rank": int(r), "state": seg["state"],
                           "start_unix": seg["start_unix"],
                           "dur": round(seg["dur"], 3)})
    spikes.sort(key=lambda s: -s["dur"])
    spikes = spikes[:top]
    for sp in spikes:
        lo, hi = sp["start_unix"] - 5.0, \
            sp["start_unix"] + sp["dur"] + 5.0
        near = []
        for e in events:
            if e.get("kind") not in ("alert", "controller"):
                continue
            t = float(e.get("time_unix", 0.0))
            if lo <= t <= hi:
                near.append(f"{e.get('kind')}/{e.get('event')} "
                            f"{e.get('rule') or e.get('action') or ''}"
                            .strip())
        sp["nearby"] = near[:6]
    return {"fleet": doc.get("fleet"), "spikes": spikes,
            "restart_gaps": doc.get("restart_gaps"),
            "resizes": doc.get("resizes")}


# --- CLI -------------------------------------------------------------------

def _self_test() -> int:
    """Hermetic fixture smoke (the perfscope/memscope CLI idiom):
    synthetic charges + a synthetic journal replay against TEMPORARY
    flag state; prints one GOODPUT_SELF_TEST json line, exit 0 on
    pass."""
    saved = {k: flags.get_flag(k) for k in
             ("goodput", "goodput_collapse_fraction")}
    flags.set_flag("goodput", True)
    reset()
    try:
        checks = {}
        # a synthetic rank lifetime: wait, a step, a compile span, a
        # checkpoint — conservation must hold exactly
        note_wait("idle")
        note_span("compile", 0.0)      # zero-length span: no-op
        t_before = time.perf_counter()
        while time.perf_counter() - t_before < 0.002:
            pass
        note_step(data_wait_s=0.001, host_s=0.001, device_s=0.0,
                  wall_s=0.002)
        note_span("checkpoint_save", 0.0005)
        note_wait("resize_barrier")
        snap = snapshot()
        tracked, wall = snap["tracked_s"], snap["wall_s"]
        checks["conservation"] = abs(tracked - wall) <= 0.05 * max(
            wall, 1e-6)
        checks["has_compute"] = snap["states"].get("compute", 0.0) > 0
        checks["has_input_wait"] = \
            snap["states"].get("input_wait", 0.0) > 0
        segs = snap["segments"]
        checks["segments_sorted"] = all(
            a["start_unix"] + a["dur"] <= b["start_unix"] + 1e-6
            for a, b in zip(segs, segs[1:]))
        state, _secs = dominant_badput()
        checks["dominant_badput"] = state in BADPUT_STATES
        ctx = alert_context({})
        checks["alert_context"] = ctx["dominant_badput"] == state
        # offline replay: synthetic journal with a goodput final, a
        # segment, and a supervisor restart->spawn pair
        base = 1000.0
        events = [
            {"kind": "supervisor", "event": "spawn", "worker": 1,
             "time_unix": base, "rank": 0, "pid": 1, "seq": 1},
            {"kind": "goodput", "event": "segment", "rank": 1,
             "state": "compute", "seg_start_unix": base + 1.0,
             "dur": 3.0, "time_unix": base + 4.0, "pid": 2, "seq": 1},
            {"kind": "goodput", "event": "final", "rank": 1,
             "states": {"compute": 3.0, "idle": 1.0},
             "wall_s": 4.0, "fraction": 0.75,
             "time_unix": base + 5.0, "pid": 2, "seq": 2},
            {"kind": "supervisor", "event": "restart", "worker": 1,
             "rc": 1, "time_unix": base + 5.5, "rank": 0, "pid": 1,
             "seq": 2},
            {"kind": "supervisor", "event": "spawn", "worker": 1,
             "time_unix": base + 7.5, "rank": 0, "pid": 1, "seq": 3},
            {"kind": "master", "event": "resize_applied",
             "old_world": 2, "new_world": 4, "epoch": 1,
             "time_unix": base + 8.0, "rank": 0, "pid": 3, "seq": 1},
        ]
        doc = reconstruct_events(events)
        r1 = doc["ranks"]["1"]
        checks["replay_states"] = r1["states"].get("compute") == 3.0
        checks["replay_restart_gap"] = abs(
            r1["offline_states"].get("restart_gap", 0.0) - 2.0) < 1e-6
        checks["replay_resizes"] = doc["resizes"][0]["new"] == 4
        checks["table_renders"] = len(badput_table(doc)) >= 3
        checks["timeline_renders"] = any(
            "#" in ln for ln in timeline_lines(doc, width=24))
        _lines, regressed = compare_docs(doc, doc, tolerance=0.1)
        checks["self_compare_clean"] = not regressed
        ok = all(checks.values())
        print("GOODPUT_SELF_TEST " + json.dumps(
            {"ok": ok, "checks": checks}, sort_keys=True))
        return 0 if ok else 1
    finally:
        reset()
        for k, v in saved.items():
            flags.set_flag(k, v)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.goodput",
        description="Timecard: fleet chip-time accounting — live "
                    "report, or offline journal replay into a per-rank "
                    "goodput/badput timeline.")
    ap.add_argument("journal", nargs="*",
                    help="fleet journal JSONL path(s) to replay "
                         "(none: report the LIVE accounting)")
    ap.add_argument("--runlog", default=None,
                    help="runlog JSONL to back-fill compute/input_wait "
                         "for journal-only runs")
    ap.add_argument("--compare", nargs="+", metavar="JOURNAL",
                    help="second run's journal path(s); exit 1 when "
                         "its goodput_fraction regresses past "
                         "--tolerance")
    ap.add_argument("--tolerance", type=float, default=0.1,
                    help="absolute goodput_fraction drop tolerated by "
                         "--compare (default 0.1)")
    ap.add_argument("--doc", action="store_true",
                    help="print the full document as JSON")
    ap.add_argument("--width", type=int, default=64,
                    help="ASCII timeline width (default 64)")
    ap.add_argument("--self-test", action="store_true",
                    help="hermetic fixture smoke; exit 0 on pass")
    args = ap.parse_args(argv)
    if args.self_test:
        return _self_test()
    if not args.journal:
        if args.compare:
            print("goodput: --compare needs a baseline journal",
                  file=sys.stderr)
            return 2
        if not enabled():
            print("goodput: disabled (set the goodput flag / "
                  "PTPU_GOODPUT=1)", file=sys.stderr)
            return 2
        doc = status_doc()
        if args.doc:
            print(json.dumps(doc, indent=2, sort_keys=True))
            return 0
        live = {"schema": SCHEMA, "fleet": {
            "states": doc["states"],
            "chip_seconds": doc["tracked_s"],
            "goodput_fraction": doc["goodput_fraction"]},
            "ranks": {"0": {"states": doc["states"],
                            "chip_seconds": doc["tracked_s"],
                            "goodput_fraction": doc["goodput_fraction"],
                            "segments": doc["segments"]}}}
        for line in badput_table(live):
            print(line)
        return 0
    try:
        doc = reconstruct(args.journal, runlog_path=args.runlog)
    except (OSError, ValueError) as e:
        print(f"goodput: bad input: {e}", file=sys.stderr)
        return 2
    if args.doc:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for line in badput_table(doc):
            print(line)
        for line in timeline_lines(doc, width=args.width):
            print(line)
    if args.compare:
        try:
            other = reconstruct(args.compare,
                                runlog_path=None)
        except (OSError, ValueError) as e:
            print(f"goodput: bad --compare input: {e}",
                  file=sys.stderr)
            return 2
        lines, regressed = compare_docs(doc, other,
                                        tolerance=args.tolerance)
        print("compare (A = positional run, B = --compare run):")
        for line in lines:
            print(line)
        return 1 if regressed else 0
    return 0


if __name__ == "__main__":          # pragma: no cover
    sys.exit(main())
