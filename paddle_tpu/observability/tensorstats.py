"""In-graph tensor statistics: model-health telemetry computed INSIDE
the compiled train step.

The systems planes (PRs 1, 3, 4) can say a step was slow or a program
recompiled; they cannot say WHICH layer produced the first NaN, or how
the gradient norm trended before the guard tripped.  This module closes
that gap the TPU way: statistics are fused reductions traced into the
step executable itself — min/max/mean/rms, NaN/Inf counts, gradient
norms and weight-update ratios for every floating-point variable — and
fetched as ONE packed ``[n_vars, 8]`` float32 array every
``tensor_stats_interval`` steps.  A host-side loop over fetched tensors
would destroy the MFU the perf PRs bought; a handful of fused
reductions riding the existing dispatch costs one extra executable and
nothing else:

* ``tensor_stats`` **off** (default): the executor's compile key,
  ``explain()`` report and step outputs are byte-identical to the
  stats-less executor — zero overhead, zero extra compiles.
* **on**: sampled steps run a second executable (compile key gains a
  ``tensor_stats`` flags entry, so forensics diagnoses the flip as
  ``flags`` drift — never a storm); non-sampled steps reuse the
  ORIGINAL executable bit-for-bit.

Variables are ordered by their FINAL write position in the program
(feeds, then never-written state, then op outputs in op order), so the
"earliest variable whose NaN/Inf count went nonzero" is the first bad
producer in dataflow order — :func:`first_bad` / :func:`attribution`
are what ``NumericGuard`` asks when it trips, and the flight bundle
embeds the full last snapshot (:func:`snapshot_doc`).

Consumers:

* gauges ``model_grad_norm`` / ``model_update_ratio`` /
  ``model_nan_vars`` with a bounded ``var`` label set (top-K per sample
  + an ``__all__`` aggregate row — the families are CLEARED and
  re-published each sample so cardinality never creeps);
* ``FleetReporter`` ships :func:`fleet_row` so the coordinator's
  ``/metrics`` shows per-rank grad norms and the aggregator warns on
  cross-rank divergence (``grad_divergence_factor`` flag);
* the observability HTTP endpoint serves the snapshot at ``/model``;
* the Trainer's runlog records the sampled summary per step.

The reference's analogue is its ``debugger``/graph-viz plane plus the
``Print`` op (fetch-and-inspect a tensor mid-program); this module is
the compiled-era version — no host round-trip per tensor, statistics
land in the same registry/fleet/flight machinery as everything else.
"""
from __future__ import annotations

import math
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import flags
from . import metrics as obs_metrics

SCHEMA = "paddle_tpu.tensorstats.v1"

# reserved fetch name the stats variant appends to its compiled fetch
# list; Executor.run pops it back off before returning to the caller
FETCH_NAME = "__tensor_stats__"

# packed-array column order (one row per variable)
COLUMNS = ("min", "max", "mean", "rms", "nan_count", "inf_count",
           "numel", "delta_rms")
_NAN, _INF, _RMS, _NUMEL, _DELTA = 4, 5, 3, 6, 7

GRAD_SUFFIX = "@GRAD"

_m_grad_norm = obs_metrics.gauge(
    "model_grad_norm",
    "Per-variable gradient L2 norm from the last tensorstats sample "
    "(top-K by norm + the '__all__' global norm; bounded cardinality — "
    "the family is re-published per sample).", ("var",))
_m_update_ratio = obs_metrics.gauge(
    "model_update_ratio",
    "Per-parameter weight-update ratio ||delta|| / ||theta|| of the "
    "last sampled step (top-K + '__all__'; ~1e-3 is healthy SGD, ~1 "
    "means the step is rewriting the weights).", ("var",))
_m_nan_vars = obs_metrics.gauge(
    "model_nan_vars",
    "NaN/Inf element counts per variable in the last tensorstats "
    "sample (top-K offenders; '__all__' = number of bad variables).",
    ("var",))
_m_samples = obs_metrics.counter(
    "model_stats_samples_total",
    "Tensorstats samples fetched from the compiled step.")

_lock = threading.Lock()
_state: Dict[str, Any] = {"counter": 0, "snapshot": None, "samples": 0,
                          "position": None, "mesh_warned": False}


def enabled() -> bool:
    return bool(flags.get_flag("tensor_stats"))


def reset():
    """Test hook: zero the sampling counter and drop the snapshot (and
    the per-var gauge series, which are re-published per sample)."""
    with _lock:
        _state["counter"] = 0
        _state["snapshot"] = None
        _state["samples"] = 0
        _state["position"] = None
        _state["mesh_warned"] = False
    for m in (_m_grad_norm, _m_update_ratio, _m_nan_vars):
        m.clear()


def note_position(epoch: int, step: int):
    """Trainer hook, called before each dispatch: stamps the RESUMABLE
    (epoch, step-in-epoch) position onto the next sample.  The fallback
    dispatch counter restarts at 0 when an elastic worker is respawned,
    so cross-rank row alignment (the fleet divergence check) must key
    on the trainer's checkpoint-resumed position, not process age."""
    with _lock:
        _state["position"] = (int(epoch), int(step))


def _is_train_program(program) -> bool:
    """True when the program contains an autodiff op (a train step) —
    cached per program version so the per-run check is O(1)."""
    ver = getattr(program, "_ts_ad_version", None)
    if ver != program._version:
        program._ts_ad_version = program._version
        program._ts_has_ad = any(
            op.type == "autodiff" for op in program.global_block().ops)
    return bool(program._ts_has_ad)


def want_sample(program) -> bool:
    """Called by Executor.run once per dispatch of `program`: advances
    the sampling counter (train programs only, flag on only) and says
    whether THIS step should run the stats variant."""
    if not enabled() or not _is_train_program(program):
        return False
    interval = max(1, int(flags.get_flag("tensor_stats_interval")))
    with _lock:
        n = _state["counter"]
        _state["counter"] = n + 1
    return n % interval == 0


def note_mesh_skipped(program):
    """Executor hook for the mesh path: in-graph sampling only augments
    the single-device jitted step — under a mesh the feeds/fetches are
    sharded and the stats fetch is not wired through pjit.  When the
    flag is on anyway, warn ONCE so the operator learns the flag is
    inert in this configuration (and how to get per-rank stats) instead
    of silently missing samples, divergence checks and attribution."""
    if not enabled() or not _is_train_program(program):
        return
    with _lock:
        if _state["mesh_warned"]:
            return
        _state["mesh_warned"] = True
    warnings.warn(
        "tensor_stats=True but this Executor drives a sharded (mesh) "
        "program — in-graph tensor statistics are single-device only "
        "and NO samples will be collected on this path (grad-divergence "
        "checks and NaN attribution stay dark).  Run each data-parallel "
        "rank with a mesh-less per-process executor to sample per-rank "
        "stats, or disable tensor_stats under this mesh.",
        RuntimeWarning, stacklevel=3)


def sample_count() -> int:
    return int(_state["samples"])


# -- trace-time: inside the compiled step -----------------------------------

def stats_order(ops, feed_names: Sequence[str],
                state_names: Sequence[str]) -> List[str]:
    """Variable names ordered by FINAL write position: feeds first,
    then state vars no op rewrites, then every op output at the index
    of its last producing op.  A NaN scan in this order finds the first
    bad PRODUCER, not an updated-parameter casualty of a bad gradient
    (optimizer writes land last)."""
    pos: Dict[str, Tuple[int, str]] = {}
    for n in sorted(feed_names):
        pos.setdefault(n, (-2, n))
    for n in state_names:
        pos.setdefault(n, (-1, n))
    for i, op in enumerate(ops):
        for names in op.outputs.values():
            for n in names:
                if n:
                    pos[n] = (i, n)
    return [n for n, _ in sorted(pos.items(), key=lambda kv: kv[1])]


def pack(order: Sequence[str], env: Dict[str, Any],
         state: Dict[str, Any]) -> Tuple[List[str], Any]:
    """Trace-time: build the packed ``[n_vars, 8]`` float32 stats array
    from the step environment.  Only floating/complex-free inexact
    tensors are covered; ``delta_rms`` is nonzero only for state vars
    an op actually rewrote (identity check against the input state —
    an untouched var is the SAME traced value)."""
    import jax.numpy as jnp

    names: List[str] = []
    rows = []
    for name in order:
        v = env.get(name)
        if v is None or isinstance(v, (bool, int, float, str, bytes)):
            continue
        dtype = getattr(v, "dtype", None)
        shape = getattr(v, "shape", None)
        if dtype is None or shape is None:
            continue
        try:
            if not jnp.issubdtype(dtype, jnp.floating):
                continue
        except TypeError:
            continue
        numel = int(np.prod(shape)) if len(shape) else 1
        if numel == 0:
            continue
        x = jnp.asarray(v).astype(jnp.float32).reshape(-1)
        old = state.get(name)
        if (old is not None and old is not v
                and getattr(old, "shape", None) == shape
                and jnp.issubdtype(getattr(old, "dtype", np.int32),
                                   jnp.floating)):
            d = x - jnp.asarray(old).astype(jnp.float32).reshape(-1)
            delta_rms = jnp.sqrt(jnp.mean(d * d))
        else:
            delta_rms = jnp.float32(0.0)
        rows.append(jnp.stack([
            jnp.min(x), jnp.max(x), jnp.mean(x),
            jnp.sqrt(jnp.mean(x * x)),
            jnp.isnan(x).sum().astype(jnp.float32),
            jnp.isinf(x).sum().astype(jnp.float32),
            jnp.float32(numel), delta_rms]))
        names.append(name)
    packed = jnp.stack(rows) if rows else jnp.zeros((0, len(COLUMNS)),
                                                    jnp.float32)
    return names, packed


# -- host-side: sample ingestion --------------------------------------------

def _norm(row) -> float:
    """L2 norm from a stats row: rms * sqrt(numel)."""
    return float(row[_RMS] * math.sqrt(max(row[_NUMEL], 0.0)))


def note_sample(program, names: List[str], packed) -> Optional[dict]:
    """Ingest one fetched stats array: store the snapshot (what the
    guard/flight/fleet read) and re-publish the bounded model_* gauge
    families.  Never raises — telemetry must not take training down."""
    try:
        arr = np.asarray(packed, dtype=np.float64)
        if arr.ndim != 2 or arr.shape[0] != len(names):
            return None
        snap = _build_snapshot(program, list(names), arr)
        with _lock:
            _state["snapshot"] = snap
            _state["samples"] += 1
            snap["sample"] = _state["samples"]
        _m_samples.inc()
        _publish_gauges(snap)
        return snap
    except Exception:
        return None


def _build_snapshot(program, names: List[str], arr: np.ndarray) -> dict:
    bad = arr[:, _NAN] + arr[:, _INF]
    bad_idx = np.nonzero(bad > 0)[0]
    grad_sq = upd_sq = theta_sq = 0.0
    for i, n in enumerate(names):
        sq = float(arr[i, _RMS]) ** 2 * float(arr[i, _NUMEL])
        if n.endswith(GRAD_SUFFIX) and math.isfinite(sq):
            grad_sq += sq
        if arr[i, _DELTA] > 0:
            d = float(arr[i, _DELTA]) ** 2 * float(arr[i, _NUMEL])
            if math.isfinite(d):
                upd_sq += d
            if math.isfinite(sq):
                theta_sq += sq
    pos = _state["position"]
    return {
        "schema": SCHEMA,
        "time_unix": time.time(),
        "program": getattr(program, "_uid", None),
        "epoch": pos[0] if pos is not None else None,
        "step": (pos[1] if pos is not None
                 else max(0, int(_state["counter"]) - 1)),
        "columns": list(COLUMNS),
        "names": names,
        "stats": arr,
        "grad_norm": math.sqrt(grad_sq),
        "update_ratio": (math.sqrt(upd_sq / theta_sq)
                         if theta_sq > 0 else 0.0),
        "nan_vars": int(len(bad_idx)),
        "first_bad": names[int(bad_idx[0])] if len(bad_idx) else None,
    }


def _publish_gauges(snap: dict):
    names, arr = snap["names"], snap["stats"]
    k = max(1, int(flags.get_flag("tensor_stats_topk")))
    for m in (_m_grad_norm, _m_update_ratio, _m_nan_vars):
        m.clear()

    grads = [(n, _norm(arr[i])) for i, n in enumerate(names)
             if n.endswith(GRAD_SUFFIX)]
    for n, v in sorted(grads, key=lambda kv: -_finite_or_inf(kv[1]))[:k]:
        _m_grad_norm.labels(var=n).set(v)
    _m_grad_norm.labels(var="__all__").set(snap["grad_norm"])

    ratios = [(n, float(arr[i, _DELTA]) / (float(arr[i, _RMS]) + 1e-12))
              for i, n in enumerate(names) if arr[i, _DELTA] > 0]
    for n, v in sorted(ratios, key=lambda kv: -_finite_or_inf(kv[1]))[:k]:
        _m_update_ratio.labels(var=n).set(v)
    _m_update_ratio.labels(var="__all__").set(snap["update_ratio"])

    bad = [(n, float(arr[i, _NAN] + arr[i, _INF]))
           for i, n in enumerate(names) if arr[i, _NAN] + arr[i, _INF] > 0]
    for n, v in sorted(bad, key=lambda kv: -kv[1])[:k]:
        _m_nan_vars.labels(var=n).set(v)
    _m_nan_vars.labels(var="__all__").set(float(snap["nan_vars"]))


def _finite_or_inf(v: float) -> float:
    # NaN norms (a var that IS all-NaN) sort as +inf: the most broken
    # variable belongs at the top of the top-K, not dropped by a NaN
    # comparison quirk
    return v if not math.isnan(v) else float("inf")


# -- consumers: guard attribution, flight, fleet, /model --------------------

def snapshot() -> Optional[dict]:
    """The raw last sample (stats as an ndarray), or None."""
    return _state["snapshot"]


def first_bad() -> Optional[Tuple[str, float, int]]:
    """(name, bad_element_count, sample_step) of the EARLIEST variable
    in final-write order whose NaN/Inf count is nonzero in the last
    sample — the first bad producer, not just any NaN var."""
    snap = _state["snapshot"]
    if not snap or snap["first_bad"] is None:
        return None
    arr, names = snap["stats"], snap["names"]
    i = names.index(snap["first_bad"])
    return snap["first_bad"], float(arr[i, _NAN] + arr[i, _INF]), \
        snap["step"]


def attribution() -> Tuple[str, str]:
    """(label, detail) naming the first bad variable for guard log
    lines and the bounded ``first_var`` metric label.  Always answers:
    falls back to an 'unattributed' label explaining what to enable."""
    if not enabled():
        return "unattributed", "unattributed(enable tensor_stats)"
    snap = _state["snapshot"]
    if snap is None:
        return "unattributed", \
            "unattributed(tensor_stats on but no sample landed yet)"
    fb = first_bad()
    if fb is None:
        return "unattributed", (
            f"unattributed(last tensorstats sample @step {snap['step']} "
            f"was clean; lower tensor_stats_interval to catch the bad "
            f"step)")
    name, count, step = fb
    return name, (f"first bad var {name!r} ({int(count)} NaN/Inf "
                  f"elements, tensorstats sample @step {step})")


def snapshot_doc() -> Optional[dict]:
    """The last sample as a JSON-ready document (flight bundle embed,
    the /model HTTP route, tests)."""
    snap = _state["snapshot"]
    if snap is None:
        return None
    doc = {k: v for k, v in snap.items() if k != "stats"}
    doc["stats"] = [[_jsonable(x) for x in row]
                    for row in np.asarray(snap["stats"]).tolist()]
    return doc


def _jsonable(v: float):
    return v if math.isfinite(v) else repr(float(v))


def fleet_row() -> Optional[dict]:
    """The compact per-rank summary FleetReporter ships: enough for the
    coordinator's divergence check and per-rank /metrics without moving
    the whole snapshot every interval."""
    snap = _state["snapshot"]
    if snap is None:
        return None
    return {"step": snap["step"], "epoch": snap.get("epoch"),
            "sample": snap.get("sample", 0),
            "time_unix": snap["time_unix"],
            "grad_norm": _jsonable(snap["grad_norm"]),
            "update_ratio": _jsonable(snap["update_ratio"]),
            "nan_vars": snap["nan_vars"],
            "first_bad": snap["first_bad"]}
