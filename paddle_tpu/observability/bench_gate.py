"""Bench regression gate: compare a bench_metrics.json against a
committed BENCH_r*.json baseline and exit nonzero on regression.

    python -m paddle_tpu.observability.bench_gate \
        --baseline BENCH_r05.json --candidate bench_metrics.json \
        --tolerance 0.15

Accepted input formats (both sides, auto-detected):

* driver records — ``{"parsed": {"summary": {metric: {"value": v}}}}``
  (the committed BENCH_r*.json files);
* registry dumps — ``{"schema": "paddle_tpu.metrics.v1", ...}`` with
  ``bench_value{metric=...}`` series (what bench.py writes to
  ``PTPU_BENCH_METRICS_PATH``);
* plain ``{metric: value}`` maps (synthetic/test inputs).

Direction is inferred from the metric name: ``*_ms_per_batch`` rows are
lower-is-better, everything else (tokens/s, img/s) higher-is-better.  A
candidate more than ``tolerance`` (fractional) WORSE than baseline is a
regression; a baseline metric missing from the candidate is a failure
unless ``--allow-missing``.  Candidate-only metrics are reported as
``new`` and never fail the gate.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional


def load_metric_values(doc: dict) -> Dict[str, float]:
    """Extract {metric: value} from any accepted input format."""
    if not isinstance(doc, dict):
        raise ValueError(
            f"expected a JSON object, got {type(doc).__name__}")
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    if "summary" in doc and isinstance(doc["summary"], dict):
        out = {}
        for m, row in doc["summary"].items():
            out[m] = float(row["value"]) if isinstance(row, dict) \
                else float(row)
        return out
    if str(doc.get("schema", "")).startswith("paddle_tpu.metrics"):
        out = {}
        fam = doc.get("metrics", {}).get("bench_value", {})
        for row in fam.get("series", []):
            m = row.get("labels", {}).get("metric")
            if m is not None:
                out[m] = float(row["value"])
        return out
    return {m: float(v) for m, v in doc.items()
            if isinstance(v, (int, float))}


def lower_is_better(metric: str) -> bool:
    return metric.endswith("_ms_per_batch") or metric.endswith("_seconds")


def compare(baseline: Dict[str, float], candidate: Dict[str, float],
            tolerance: float) -> List[dict]:
    """Per-metric verdict rows: status ok | regression | missing | new."""
    rows = []
    for metric in sorted(set(baseline) | set(candidate)):
        base = baseline.get(metric)
        cand = candidate.get(metric)
        if base is None:
            rows.append({"metric": metric, "candidate": cand,
                         "status": "new"})
            continue
        if cand is None:
            rows.append({"metric": metric, "baseline": base,
                         "status": "missing"})
            continue
        if base == 0:
            ratio = float("inf") if cand else 1.0
        else:
            ratio = cand / base
        if lower_is_better(metric):
            regressed = cand > base * (1.0 + tolerance)
        else:
            regressed = cand < base * (1.0 - tolerance)
        rows.append({"metric": metric, "baseline": base,
                     "candidate": cand, "ratio": round(ratio, 4),
                     "status": "regression" if regressed else "ok"})
    return rows


def gate(baseline: Dict[str, float], candidate: Dict[str, float],
         tolerance: float = 0.15, allow_missing: bool = False) -> dict:
    rows = compare(baseline, candidate, tolerance)
    bad = [r for r in rows if r["status"] == "regression"
           or (r["status"] == "missing" and not allow_missing)]
    return {"schema": "paddle_tpu.bench_gate.v1",
            "tolerance": tolerance, "rows": rows,
            "regressions": [r["metric"] for r in bad], "ok": not bad}


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.bench_gate",
        description="Compare bench metrics against a committed baseline; "
                    "exit 1 on regression.")
    p.add_argument("--baseline", default="BENCH_r05.json")
    p.add_argument("--candidate", default="bench_metrics.json")
    p.add_argument("--tolerance", type=float, default=0.15,
                   help="fractional slowdown tolerated (default 0.15)")
    p.add_argument("--allow-missing", action="store_true",
                   help="baseline metrics absent from the candidate "
                        "do not fail the gate")
    args = p.parse_args(argv)
    try:
        with open(args.baseline) as f:
            base = load_metric_values(json.load(f))
        with open(args.candidate) as f:
            cand = load_metric_values(json.load(f))
    except (OSError, ValueError, KeyError) as e:
        print(f"bench_gate: cannot load inputs: {e!r}", file=sys.stderr)
        return 2
    if not base:
        print(f"bench_gate: no metrics found in baseline "
              f"{args.baseline}", file=sys.stderr)
        return 2
    result = gate(base, cand, args.tolerance, args.allow_missing)
    for r in result["rows"]:
        mark = {"ok": "  ok", "new": " new",
                "missing": "MISS", "regression": "FAIL"}[r["status"]]
        ratio = f" ({r['ratio']:.3f}x)" if "ratio" in r else ""
        print(f"[{mark}] {r['metric']}{ratio}")
    print(json.dumps({k: result[k] for k in
                      ("tolerance", "regressions", "ok")}))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
