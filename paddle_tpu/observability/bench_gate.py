"""Bench regression gate: compare a bench_metrics.json against a
committed BENCH_r*.json baseline and exit nonzero on regression.

    python -m paddle_tpu.observability.bench_gate \
        --baseline BENCH_r05.json --candidate bench_metrics.json \
        --tolerance 0.15

Accepted input formats (both sides, auto-detected):

* driver records — ``{"parsed": {"summary": {metric: {"value": v}}}}``
  (the committed BENCH_r*.json files);
* registry dumps — ``{"schema": "paddle_tpu.metrics.v1", ...}`` with
  ``bench_value{metric=...}`` series (what bench.py writes to
  ``PTPU_BENCH_METRICS_PATH``);
* plain ``{metric: value}`` maps (synthetic/test inputs).

Direction is inferred from the metric name: ``*_ms_per_batch`` rows are
lower-is-better, everything else (tokens/s, img/s) higher-is-better.  A
candidate more than ``tolerance`` (fractional) WORSE than baseline is a
regression; a baseline metric missing from the candidate is a failure
unless ``--allow-missing``.  Candidate-only metrics are reported as
``new`` and never fail the gate.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional


def _unwrap_parsed(doc: dict) -> Optional[dict]:
    """Driver wrapper (n/cmd/rc/tail/parsed): only the parsed payload
    is metric data.  Returns None for a failed parse (``parsed: null``)
    — falling through would scrape the wrapper's own numeric
    bookkeeping fields (n, rc) as bogus metric series."""
    if not isinstance(doc, dict):
        raise ValueError(
            f"expected a JSON object, got {type(doc).__name__}")
    if "parsed" in doc:
        doc = doc["parsed"]
        if not isinstance(doc, dict):
            return None
    return doc


def load_metric_values(doc: dict) -> Dict[str, float]:
    """Extract {metric: value} from any accepted input format."""
    doc = _unwrap_parsed(doc)
    if doc is None:
        return {}
    if "summary" in doc and isinstance(doc["summary"], dict):
        out = {}
        for m, row in doc["summary"].items():
            out[m] = float(row["value"]) if isinstance(row, dict) \
                else float(row)
        return out
    if str(doc.get("schema", "")).startswith("paddle_tpu.metrics"):
        out = {}
        fam = doc.get("metrics", {}).get("bench_value", {})
        for row in fam.get("series", []):
            m = row.get("labels", {}).get("metric")
            if m is not None:
                out[m] = float(row["value"])
        return out
    return {m: float(v) for m, v in doc.items()
            if isinstance(v, (int, float))}


def lower_is_better(metric: str) -> bool:
    # *_bytes: memory footprints (bench_peak_hbm_bytes and friends) —
    # a new release using MORE HBM is a regression, not an improvement
    return (metric.endswith("_ms_per_batch")
            or metric.endswith("_seconds")
            or metric.endswith("_bytes"))


def load_trend_record(doc: dict) -> Dict[str, dict]:
    """Extract ``{metric: {"value": v, "mfu": m?, "bound": b?}}`` from
    one release record — driver BENCH_r*.json files (with or without
    the compact ``summary``), registry dumps, or plain maps.  Unlike
    :func:`load_metric_values` this keeps the per-metric MFU and the
    perfscope roofline bound classification, so the trend view tracks
    efficiency and perf character next to throughput.  Records written
    before bench.py attached bounds simply carry ``bound: None``."""
    doc = _unwrap_parsed(doc)
    if doc is None:
        return {}
    if "summary" in doc and isinstance(doc["summary"], dict):
        out = {}
        for m, row in doc["summary"].items():
            if isinstance(row, dict):
                out[m] = {"value": float(row["value"]),
                          "mfu": row.get("mfu"),
                          "bound": row.get("bound")}
                # pre-Memscope summaries carry no peak and pre-Timecard
                # ones no goodput: keep their loaded shape unchanged,
                # keys present only when dumped
                if row.get("peak_hbm_bytes") is not None:
                    out[m]["peak_hbm_bytes"] = row["peak_hbm_bytes"]
                if row.get("goodput_fraction") is not None:
                    out[m]["goodput_fraction"] = row["goodput_fraction"]
            else:
                out[m] = {"value": float(row), "mfu": None,
                          "bound": None, "peak_hbm_bytes": None}
        return out
    if "metric" in doc and "value" in doc:
        # pre-summary driver records (BENCH_r01): one row at top level
        return {str(doc["metric"]): {
            "value": float(doc["value"]), "mfu": doc.get("mfu"),
            "bound": doc.get("bound"),
            "peak_hbm_bytes": doc.get("peak_hbm_bytes"),
            "goodput_fraction": doc.get("goodput_fraction")}}
    return {m: {"value": v, "mfu": None, "bound": None,
                "peak_hbm_bytes": None, "goodput_fraction": None}
            for m, v in load_metric_values(doc).items()}


def trend(records: List, tolerance: float = 0.15,
          allow_missing: bool = False) -> dict:
    """Cross-release trajectory over ``[(name, {metric: {value, mfu}}),
    ...]`` (oldest -> newest): per metric, the full series, the
    best-ever release, and whether the NEWEST record regresses that
    best by more than `tolerance` (direction-aware; per-metric MFU is
    tracked as its own higher-is-better series).  Metrics present in
    any prior record but absent from the newest are flagged
    ``missing`` and fail the gate unless ``allow_missing``.

    Records carrying a perfscope ``bound`` classification get a
    ``{metric}.bound`` row; when the newest record's bound differs
    from the last known one (e.g. compute -> comms) the row is a
    named regression — the workload's perf character changed, so the
    roofline knobs tuned against the old bound no longer apply, even
    if raw throughput squeaked under the tolerance."""
    if len(records) < 2:
        raise ValueError(
            f"trend needs >= 2 release records, got {len(records)}")
    newest_name, newest = records[-1]
    rows = []

    def row_for(metric, series, lower, unit):
        vals = [(n, v) for n, v in series if v is not None]
        best_name, best = (min if lower else max)(
            vals, key=lambda kv: kv[1])
        cur = vals[-1][1] if vals[-1][0] == newest_name else None
        regressed = (cur is not None
                     and (cur > best * (1.0 + tolerance) if lower
                          else cur < best * (1.0 - tolerance)))
        return {"metric": metric, "unit": unit,
                "series": [{"release": n, "value": v}
                           for n, v in series],
                "best": best, "best_release": best_name,
                "newest": cur,
                "status": "regression" if regressed else "ok"}

    # union across ALL records, not just the newest: a workload that
    # errored out of the newest bench run must surface as "missing",
    # not silently drop out of the gate
    all_metrics = sorted({m for _, rec in records for m in rec})
    for metric in all_metrics:
        series = [(name, (rec.get(metric) or {}).get("value"))
                  for name, rec in records]
        row = row_for(metric, series, lower_is_better(metric), "value")
        if metric not in newest:
            row["status"] = "missing"
        rows.append(row)
        if any((rec.get(metric) or {}).get("mfu") is not None
               for _, rec in records):
            mseries = [(name, (rec.get(metric) or {}).get("mfu"))
                       for name, rec in records]
            mrow = row_for(f"{metric}.mfu", mseries, False, "mfu")
            if (newest.get(metric) or {}).get("mfu") is None:
                mrow["status"] = "missing"
            rows.append(mrow)
        if any((rec.get(metric) or {}).get("goodput_fraction")
               is not None for _, rec in records):
            # Timecard subseries (ISSUE 19): goodput is higher-is-
            # better like MFU — a release whose rows spend more
            # chip-time outside compute regresses by name
            gseries = [(name,
                        (rec.get(metric) or {}).get("goodput_fraction"))
                       for name, rec in records]
            grow = row_for(f"{metric}.goodput_fraction", gseries,
                           False, "fraction")
            if (newest.get(metric) or {}).get("goodput_fraction") \
                    is None:
                grow["status"] = "missing"
            rows.append(grow)
        if any((rec.get(metric) or {}).get("peak_hbm_bytes") is not None
               for _, rec in records):
            # memory subseries: the "_bytes" suffix routes through the
            # lower-is-better rule, so a fatter peak is a named
            # regression exactly like a slower step
            hseries = [(name,
                        (rec.get(metric) or {}).get("peak_hbm_bytes"))
                       for name, rec in records]
            hrow = row_for(f"{metric}.peak_hbm_bytes", hseries,
                           lower_is_better("peak_hbm_bytes"), "bytes")
            if (newest.get(metric) or {}).get("peak_hbm_bytes") is None:
                hrow["status"] = "missing"
            rows.append(hrow)
        bounds = [(name, (rec.get(metric) or {}).get("bound"))
                  for name, rec in records]
        known = [(n, b) for n, b in bounds if b]
        if known:
            cur = (newest.get(metric) or {}).get("bound")
            prior = [b for n, b in known if n != newest_name]
            brow = {"metric": f"{metric}.bound", "unit": "bound",
                    "series": [{"release": n, "value": b}
                               for n, b in bounds],
                    "best": None, "best_release": None,
                    "newest": cur, "status": "ok"}
            if cur is not None and prior and prior[-1] != cur:
                brow["status"] = "regression"
                brow["flip"] = f"{prior[-1]}->{cur}"
            rows.append(brow)
    bad = [r["metric"] for r in rows if r["status"] == "regression"]
    missing = [r["metric"] for r in rows if r["status"] == "missing"]
    return {"schema": "paddle_tpu.bench_trend.v1",
            "tolerance": tolerance, "newest": newest_name,
            "rows": rows, "regressions": bad, "missing": missing,
            "ok": not bad and (allow_missing or not missing)}


def compare(baseline: Dict[str, float], candidate: Dict[str, float],
            tolerance: float) -> List[dict]:
    """Per-metric verdict rows: status ok | regression | missing | new."""
    rows = []
    for metric in sorted(set(baseline) | set(candidate)):
        base = baseline.get(metric)
        cand = candidate.get(metric)
        if base is None:
            rows.append({"metric": metric, "candidate": cand,
                         "status": "new"})
            continue
        if cand is None:
            rows.append({"metric": metric, "baseline": base,
                         "status": "missing"})
            continue
        if base == 0:
            ratio = float("inf") if cand else 1.0
        else:
            ratio = cand / base
        if lower_is_better(metric):
            regressed = cand > base * (1.0 + tolerance)
        else:
            regressed = cand < base * (1.0 - tolerance)
        rows.append({"metric": metric, "baseline": base,
                     "candidate": cand, "ratio": round(ratio, 4),
                     "status": "regression" if regressed else "ok"})
    return rows


def gate(baseline: Dict[str, float], candidate: Dict[str, float],
         tolerance: float = 0.15, allow_missing: bool = False) -> dict:
    rows = compare(baseline, candidate, tolerance)
    bad = [r for r in rows if r["status"] == "regression"
           or (r["status"] == "missing" and not allow_missing)]
    return {"schema": "paddle_tpu.bench_gate.v1",
            "tolerance": tolerance, "rows": rows,
            "regressions": [r["metric"] for r in bad], "ok": not bad}


def smoke() -> int:
    """Fast perf-path sanity lane (tiny shapes, any backend; tier-1
    runs it on CPU): asserts the gate plumbing end to end AND that the
    quantized-execution path still compiles and matches its fake-quant
    reference — so a broken int8/fuse path fails tests the same day,
    not the nightly bench."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import flags as ptflags
    from paddle_tpu.transpiler import QuantizeTranspiler

    failures: List[str] = []

    def check(name, ok, detail=""):
        print(f"[{'  ok' if ok else 'FAIL'}] smoke:{name}"
              f"{' ' + detail if detail else ''}")
        if not ok:
            failures.append(name)

    # 1. gate plumbing: ok / regression / missing / new verdicts and
    #    the lower-is-better direction
    r = gate({"a_tokens_per_sec": 100.0, "b_ms_per_batch": 10.0,
              "gone": 1.0},
             {"a_tokens_per_sec": 95.0, "b_ms_per_batch": 20.0,
              "fresh": 2.0}, tolerance=0.15, allow_missing=True)
    by = {row["metric"]: row["status"] for row in r["rows"]}
    check("gate_verdicts",
          by == {"a_tokens_per_sec": "ok", "b_ms_per_batch": "regression",
                 "gone": "missing", "fresh": "new"}
          and not r["ok"], str(by))

    # 2. QAT -> freeze -> REAL int8 program compiles and matches the
    #    fake-quant reference
    def build():
        x = layers.data("x", [8], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        return layers.fc(h, size=4)

    try:
        pt.reset_default_programs()
        main_p, startup = pt.Program(), pt.Program()
        with pt.program_guard(main_p, startup):
            pred = build()
        qt = QuantizeTranspiler(
            activation_quantize_type="moving_average_abs_max")
        qt.training_transpile(main_p, startup)
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(16, 8).astype("float32")}
        for _ in range(3):      # advance the moving-average scales
            exe.run(main_p, feed=feed, fetch_list=[pred])
        ref, = exe.run(main_p, feed=feed, fetch_list=[pred])
        frozen = qt.freeze_program(main_p, scope=exe.scope,
                                   quantize_dtype="int8")
        got, = exe.run(frozen, feed=feed, fetch_list=[pred])
        kinds = {op.type for op in frozen.global_block().ops}
        err = float(np.max(np.abs(got - ref)))
        tol = 0.05 * max(1.0, float(np.max(np.abs(ref))))
        check("int8_freeze_compiles",
              "quantized_matmul" in kinds and err <= tol,
              f"maxdiff={err:.4g} tol={tol:.4g}")
    except Exception as e:      # noqa: BLE001 — smoke must report, not die
        check("int8_freeze_compiles", False, repr(e)[:200])

    # 3. training-side quantize_dtype=int8 path compiles and steps
    try:
        pt.reset_default_programs()
        y = layers.data("y", [4], dtype="float32")
        pred = layers.fc(layers.fc(y, size=8, act="relu"), size=1)
        loss = layers.mean(pred)
        pt.optimizer.SGD(0.1).minimize(loss)
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        old = ptflags.get_flag("quantize_dtype")
        ptflags.set_flag("quantize_dtype", "int8")
        try:
            rng = np.random.RandomState(0)
            lv, = exe.run(pt.default_main_program(),
                          feed={"y": rng.randn(8, 4).astype("float32")},
                          fetch_list=[loss])
        finally:
            ptflags.set_flag("quantize_dtype", old)
        check("int8_train_step", bool(np.isfinite(lv).all()),
              f"loss={float(np.asarray(lv).ravel()[0]):.4g}")
    except Exception as e:      # noqa: BLE001
        check("int8_train_step", False, repr(e)[:200])

    print(json.dumps({"smoke": "ok" if not failures else "fail",
                      "failures": failures}))
    return 0 if not failures else 1


def _fmt_val(v) -> str:
    if v is None:
        return "-"
    return f"{v:g}" if isinstance(v, float) else str(v)


def _natural_key(name: str) -> List:
    """Release order must be numeric where names embed numbers:
    lexicographic sort puts BENCH_r10 before BENCH_r9 and would judge
    the WRONG record as newest."""
    import re
    return [int(t) if t.isdigit() else t
            for t in re.split(r"(\d+)", name)]


def _trend_main(paths: List[str], tolerance: float,
                allow_missing: bool = False) -> int:
    import os
    records = []
    try:
        ordered = sorted(paths, key=lambda p: (
            _natural_key(os.path.basename(p)), _natural_key(p)))
        names = []
        for path in ordered:
            name = os.path.basename(path)
            for suf in (".json",):
                if name.endswith(suf):
                    name = name[:-len(suf)]
            names.append(name)
        if len(set(names)) != len(names):
            # releases/<v>/bench_metrics.json layouts collapse to one
            # basename — disambiguate with the parent directory so the
            # newest-record match in trend() stays unambiguous
            names = ["/".join(p.replace("\\", "/").split("/")[-2:])
                     for p in ordered]
        for name, path in zip(names, ordered):
            with open(path) as f:
                records.append((name, load_trend_record(json.load(f))))
        result = trend(records, tolerance=tolerance,
                       allow_missing=allow_missing)
    except (OSError, ValueError, KeyError) as e:
        print(f"bench_gate: cannot load trend inputs: {e!r}",
              file=sys.stderr)
        return 2
    for r in result["rows"]:
        mark = {"regression": "FAIL", "missing": "miss"}.get(
            r["status"], "  ok")
        series = " -> ".join(_fmt_val(s["value"]) for s in r["series"])
        tail = (f"(FLIP {r['flip']})" if "flip" in r
                else "" if r["best"] is None
                else f"(best {_fmt_val(r['best'])} @{r['best_release']})")
        print(f"[{mark}] {r['metric']}: {series}  {tail}")
    print(json.dumps({k: result[k] for k in
                      ("tolerance", "newest", "regressions", "missing",
                       "ok")}))
    return 0 if result["ok"] else 1


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.bench_gate",
        description="Compare bench metrics against a committed baseline; "
                    "exit 1 on regression.")
    p.add_argument("--baseline", default="BENCH_r05.json")
    p.add_argument("--candidate", default="bench_metrics.json")
    p.add_argument("--tolerance", type=float, default=0.15,
                   help="fractional slowdown tolerated (default 0.15)")
    p.add_argument("--allow-missing", action="store_true",
                   help="baseline metrics absent from the candidate "
                        "do not fail the gate")
    p.add_argument("--smoke", action="store_true",
                   help="run the fast perf-path sanity lane instead of "
                        "a baseline comparison: gate plumbing + the "
                        "quantized-execution path on tiny CPU shapes")
    p.add_argument("--trend", nargs="+", metavar="RECORD",
                   help="cross-release trajectory mode: 2+ BENCH_r*.json "
                        "records (sorted by filename = release order); "
                        "prints per-metric tokens/s + MFU + roofline-"
                        "bound series and exits 1 when the newest record "
                        "regresses the best-ever by > tolerance or flips "
                        "its bound classification")
    args = p.parse_args(argv)
    if args.smoke:
        return smoke()
    if args.trend:
        return _trend_main(args.trend, args.tolerance,
                           args.allow_missing)
    try:
        with open(args.baseline) as f:
            base = load_metric_values(json.load(f))
        with open(args.candidate) as f:
            cand = load_metric_values(json.load(f))
    except (OSError, ValueError, KeyError) as e:
        print(f"bench_gate: cannot load inputs: {e!r}", file=sys.stderr)
        return 2
    if not base:
        print(f"bench_gate: no metrics found in baseline "
              f"{args.baseline}", file=sys.stderr)
        return 2
    result = gate(base, cand, args.tolerance, args.allow_missing)
    for r in result["rows"]:
        mark = {"ok": "  ok", "new": " new",
                "missing": "MISS", "regression": "FAIL"}[r["status"]]
        ratio = f" ({r['ratio']:.3f}x)" if "ratio" in r else ""
        print(f"[{mark}] {r['metric']}{ratio}")
    print(json.dumps({k: result[k] for k in
                      ("tolerance", "regressions", "ok")}))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
