"""Metrics registry: Counter / Gauge / Histogram with labeled series.

The measurement substrate for the framework (ISSUE 1): executor compile/
cache counters, step-latency histograms, trainer throughput gauges and
bench rows all land here, so one exposition (Prometheus text or JSON)
describes a live process and a BENCH_r*.json alike.

Design notes
  * Prometheus data model (metric name + sorted label tuple -> series);
    exposition is pull-by-call here, served live by the HTTP endpoint
    in server.py (obs_http_port flag) and fleet-aggregated across
    workers by fleet.py.
  * `counter()/gauge()/histogram()` are get-or-create and idempotent, so
    every module can declare its metrics at import time without an
    ordering contract.
  * Recording is gated by the ``metrics`` flag (core/flags.py,
    ``PTPU_METRICS=0`` env): when off, inc/set/observe are no-ops and the
    hot paths pay one dict lookup.
  * Thread-safe: AsyncExecutor's feeder threads and reader processes may
    record concurrently; one registry lock covers series creation, and
    per-sample float ops ride the GIL.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..core import flags

flags.define_flag("metrics", True,
                  "Enable the observability metrics registry; when off "
                  "every inc/set/observe is a no-op.")


def enabled() -> bool:
    return bool(flags.get_flag("metrics"))


# -- histogram exemplars (request X-ray, observability/tracectx.py) ------
# A provider callable returns the ambient trace id (or None).  Injected
# by tracectx at import time rather than imported here: metrics is the
# bottom of the observability import graph and must stay cycle-free.
_exemplar_provider = None
_EXEMPLAR_RING = 4      # exemplars retained per bucket (newest kept)


def set_exemplar_provider(fn):
    """Register the ambient-trace-id source (tracectx.current_trace_id).
    When set, every Histogram.observe() that lands under an active
    trace records a (value, trace_id, time) exemplar on its bucket —
    the OpenMetrics-style link from a p99 bucket to a retrievable
    trace."""
    global _exemplar_provider
    _exemplar_provider = fn


def clear_exemplars():
    """Drop every exemplar ring in the registry (conftest: trace ids
    must not leak across tests; bucket counts are untouched)."""
    for m in REGISTRY.metrics():
        if m.buckets is None:
            continue
        with m._lock:
            for s in m._series.values():
                s.exemplars = None


# Latency-oriented default buckets (seconds): 50us .. 60s.
DEFAULT_BUCKETS = (
    5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class _Series:
    """State of one (metric, label-values) time series."""

    __slots__ = ("value", "sum", "count", "bucket_counts", "exemplars")

    def __init__(self, buckets: Optional[Tuple[float, ...]] = None):
        self.value = 0.0
        self.sum = 0.0
        self.count = 0
        self.bucket_counts = [0] * (len(buckets) + 1) if buckets else None
        # bucket index -> bounded newest-first list of exemplar dicts
        # ({value, trace_id, time_unix}); lazily created so the
        # no-tracing hot path pays nothing
        self.exemplars: Optional[Dict[int, List[dict]]] = None


class Metric:
    """Base: a named family of labeled series."""

    type = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets)) if buckets else None
        self._series: Dict[Tuple[str, ...], _Series] = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            self._series[()] = _Series(self.buckets)

    # -- series addressing -------------------------------------------------
    def labels(self, **labelvalues) -> "_Child":
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} has labels {self.labelnames}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        s = self._series.get(key)
        if s is None:
            with self._lock:
                s = self._series.setdefault(key, _Series(self.buckets))
        return _Child(self, s)

    def _default(self) -> _Series:
        if self.labelnames:
            raise ValueError(
                f"metric {self.name!r} is labeled {self.labelnames}; "
                f"use .labels(...)")
        return self._series[()]

    # -- aggregate readers (tests / dashboards) ----------------------------
    def total(self) -> float:
        """Sum of all series values (histograms: sum of observations)."""
        if self.buckets is not None:
            return sum(s.sum for s in self._series.values())
        return sum(s.value for s in self._series.values())

    def total_count(self) -> int:
        """Histogram only: total observation count across series."""
        return sum(s.count for s in self._series.values())

    def series(self) -> Dict[Tuple[str, ...], _Series]:
        return dict(self._series)

    def reset(self):
        with self._lock:
            for key in list(self._series):
                self._series[key] = _Series(self.buckets)

    def clear(self):
        """Drop every labeled series (unlike reset(), which keeps the
        label keys at zero) — for bounded-cardinality publishers that
        re-publish a fresh top-K per sample (observability/tensorstats)
        and must not accumulate stale label values forever."""
        with self._lock:
            self._series = {}
            if not self.labelnames:
                self._series[()] = _Series(self.buckets)


class _Child:
    """One addressed series; exposes the metric-type verbs."""

    __slots__ = ("_metric", "_s")

    def __init__(self, metric: Metric, series: _Series):
        self._metric = metric
        self._s = series

    def inc(self, amount: float = 1.0):
        self._metric._inc(self._s, amount)

    def dec(self, amount: float = 1.0):
        self._metric._inc(self._s, -amount)

    def set(self, value: float):
        self._metric._set(self._s, value)

    def observe(self, value: float):
        self._metric._observe(self._s, value)

    @property
    def value(self) -> float:
        return self._s.value

    @property
    def count(self) -> int:
        return self._s.count

    @property
    def sum(self) -> float:
        return self._s.sum


class Counter(Metric):
    """Monotonically increasing count (compiles, cache hits, steps)."""

    type = "counter"

    def inc(self, amount: float = 1.0):
        self._inc(self._default(), amount)

    def _inc(self, s: _Series, amount: float):
        if amount < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        if enabled():
            s.value += amount

    def _set(self, s, value):
        raise TypeError(f"counter {self.name!r} does not support set()")

    _observe = _set

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(Metric):
    """Point-in-time value (throughput, loss EMA, memory watermark)."""

    type = "gauge"

    def set(self, value: float):
        self._set(self._default(), value)

    def inc(self, amount: float = 1.0):
        self._inc(self._default(), amount)

    def dec(self, amount: float = 1.0):
        self._inc(self._default(), -amount)

    def _set(self, s: _Series, value: float):
        if enabled():
            s.value = float(value)

    def _inc(self, s: _Series, amount: float):
        if enabled():
            s.value += amount

    def _observe(self, s, value):
        raise TypeError(f"gauge {self.name!r} does not support observe()")

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(Metric):
    """Distribution with cumulative buckets (latencies)."""

    type = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None):
        super().__init__(name, help, labelnames,
                         buckets=tuple(buckets or DEFAULT_BUCKETS))

    def observe(self, value: float):
        self._observe(self._default(), value)

    def time(self):
        """``with hist.time(): ...`` observes the block's wall time."""
        return _Timer(self._default_child())

    def _default_child(self) -> _Child:
        return _Child(self, self._default())

    def _observe(self, s: _Series, value: float):
        if not enabled():
            return
        value = float(value)
        s.sum += value
        s.count += 1
        idx = len(self.buckets)         # overflow (+Inf) bucket
        for i, b in enumerate(self.buckets):
            if value <= b:
                idx = i
                break
        s.bucket_counts[idx] += 1
        if _exemplar_provider is not None:
            tid = _exemplar_provider()
            if tid is not None:
                self._note_exemplar(s, idx, value, tid)

    def _note_exemplar(self, s: _Series, idx: int, value: float,
                       trace_id: str):
        ex = {"value": value, "trace_id": trace_id,
              "time_unix": time.time()}
        with self._lock:
            if s.exemplars is None:
                s.exemplars = {}
            ring = s.exemplars.setdefault(idx, [])
            ring.insert(0, ex)
            del ring[_EXEMPLAR_RING:]

    def _set(self, s, value):
        raise TypeError(f"histogram {self.name!r} does not support set()")

    _inc = _set

    @property
    def count(self) -> int:
        return self._default().count

    @property
    def sum(self) -> float:
        return self._default().sum


class _Timer:
    __slots__ = ("_child", "_t0")

    def __init__(self, child: _Child):
        self._child = child

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._child.observe(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    """Name -> Metric store with Prometheus-text and JSON exposition."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if (type(existing) is not type(metric)
                        or existing.labelnames != metric.labelnames):
                    raise ValueError(
                        f"metric {metric.name!r} already registered as "
                        f"{existing.type}{existing.labelnames}")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def metrics(self) -> List[Metric]:
        return sorted(self._metrics.values(), key=lambda m: m.name)

    def reset(self):
        """Zero every series (keep registrations) — tests and bench."""
        for m in self.metrics():
            m.reset()

    # -- exposition --------------------------------------------------------
    def prometheus_text(self, exemplars: bool = False) -> str:
        """Prometheus text exposition (rendered from the same JSON
        document to_json() emits, by the ONE renderer the fleet-merged
        exposition also uses — see render_prometheus).
        ``exemplars=True`` appends OpenMetrics exemplar clauses to
        histogram bucket lines — ONLY valid when served as
        ``application/openmetrics-text`` (a v0.0.4 parser rejects a
        mid-line ``#``); the HTTP endpoint content-negotiates."""
        return render_prometheus(self.to_json(), exemplars=exemplars)

    def to_json(self) -> dict:
        """One JSON document for the whole registry — the schema shared
        with bench.py's metrics dump."""
        out = {}
        for m in self.metrics():
            series = []
            for key, s in sorted(m.series().items()):
                row: dict = {"labels": dict(zip(m.labelnames, key))}
                if m.type == "histogram":
                    row.update(sum=s.sum, count=s.count,
                               buckets={_fmt(b): c for b, c in
                                        zip(m.buckets, s.bucket_counts)},
                               overflow=s.bucket_counts[-1])
                    if s.exemplars:
                        # newest exemplar per bucket, keyed by the
                        # bucket's upper bound ("+Inf" for overflow) —
                        # the /metrics.json hook from a p99 bucket to
                        # a GET /trace/<id> waterfall
                        row["exemplars"] = {
                            (_fmt(m.buckets[i]) if i < len(m.buckets)
                             else "+Inf"): ring[0]
                            for i, ring in sorted(s.exemplars.items())
                            if ring}
                else:
                    row["value"] = s.value
                series.append(row)
            out[m.name] = {"type": m.type, "help": m.help,
                           "series": series}
        return {"schema": "paddle_tpu.metrics.v1", "metrics": out}

    def dump_json(self, path: str):
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)


def render_prometheus(doc: dict, exemplars: bool = False) -> str:
    """Prometheus text for a ``paddle_tpu.metrics.v1`` JSON document —
    the single exposition renderer.  Both the live registry
    (:meth:`MetricsRegistry.prometheus_text`) and the fleet-merged view
    (observability/fleet.py) delegate here, so an exposition fix (e.g.
    escaping) can never diverge the two.

    ``exemplars=False`` (default) is strict v0.0.4: no exemplar
    clauses, because a mid-line ``#`` is a PARSE ERROR there (only a
    line-initial ``#`` is a comment) and one traced observation would
    fail the whole scrape.  ``exemplars=True`` appends OpenMetrics
    exemplar clauses — serve that variant only under the
    ``application/openmetrics-text`` content type (server.py
    content-negotiates on the Accept header)."""
    lines: List[str] = []
    metrics_map = doc.get("metrics", {})
    for name in sorted(metrics_map):
        m = metrics_map[name]
        mtype = m.get("type", "untyped")
        lines.append(f"# HELP {name} {_escape_help(m.get('help', ''))}")
        lines.append(f"# TYPE {name} {mtype}")
        for row in m.get("series", []):
            labels = row.get("labels") or {}
            if mtype == "histogram":
                cum = 0
                buckets = row.get("buckets") or {}
                exem = (row.get("exemplars") or {}) if exemplars else {}
                for b in sorted(buckets, key=float):
                    cum += buckets[b]
                    lines.append(_sample(f"{name}_bucket",
                                         {**labels, "le": _fmt(float(b))},
                                         cum)
                                 + _exemplar_suffix(
                                     exem.get(_fmt(float(b)))))
                cum += row.get("overflow", 0)
                lines.append(_sample(f"{name}_bucket",
                                     {**labels, "le": "+Inf"}, cum)
                             + _exemplar_suffix(exem.get("+Inf")))
                lines.append(_sample(f"{name}_sum", labels,
                                     row.get("sum", 0.0)))
                lines.append(_sample(f"{name}_count", labels,
                                     row.get("count", 0)))
            else:
                suffix = "_total" if (mtype == "counter" and
                                      not name.endswith("_total")) else ""
                lines.append(_sample(name + suffix, labels,
                                     row.get("value", 0.0)))
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    return repr(float(v))


def _exemplar_suffix(ex: Optional[dict]) -> str:
    """OpenMetrics exemplar clause for one bucket sample line:
    `` # {trace_id="<id>"} <value> <ts>``.  Pure-comment syntax to a
    v0.0.4 scraper, a real exemplar to an OpenMetrics one."""
    if not ex:
        return ""
    return (f' # {{trace_id="{_escape(str(ex.get("trace_id", "")))}"}} '
            f'{ex.get("value", 0.0)} {ex.get("time_unix", 0.0)}')


def _sample(name: str, labels: Dict[str, str], value) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape(str(v))}"'
                        for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {value}"
    return f"{name} {value}"


def _escape(s: str) -> str:
    """Label-value escaping per the Prometheus text exposition format:
    backslash, double-quote and line-feed."""
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(s: str) -> str:
    """HELP-line escaping: backslash and line-feed only (a raw newline
    would terminate the comment mid-text and corrupt the scrape)."""
    return s.replace("\\", "\\\\").replace("\n", "\\n")


REGISTRY = MetricsRegistry()


# -- histogram quantiles ----------------------------------------------------
# Promoted out of serving/__init__.py (which re-exports for compat): the
# alert engine needs quantile predicates over any histogram — including
# rows of a FLEET-MERGED document — without importing the serving plane.

def histogram_row_quantiles(row: dict, qs: Sequence[float]
                            ) -> Optional[dict]:
    """Bucket-interpolated quantiles for ONE histogram series row in
    the ``paddle_tpu.metrics.v1`` JSON schema (``buckets`` map +
    ``overflow``/``count``/``sum``) — works on the local registry's
    to_json() rows and on fleet-merged rows alike.  Returns None when
    the row has no observations."""
    count = int(row.get("count", 0))
    if count <= 0:
        return None
    bounds = sorted((float(b), int(c))
                    for b, c in (row.get("buckets") or {}).items())
    out = {}
    for q in qs:
        target = q * count
        cum = 0
        val = None
        for b, c in bounds:
            cum += c
            if cum >= target:
                val = b
                break
        if val is None:              # landed in the overflow bucket
            val = bounds[-1][0] if bounds else 0.0
        out[f"p{int(round(q * 100))}"] = val
    out["count"] = count
    out["mean"] = float(row.get("sum", 0.0)) / count
    return out


def histogram_quantiles(name: str, qs: Sequence[float]
                        ) -> Optional[dict]:
    """Bucket-interpolated quantiles of a registry histogram's
    unlabeled series (the p50/p99 the /serving route reports) — one
    interpolation implementation, shared with the doc-row path.
    Returns None when the histogram has no observations."""
    m = REGISTRY.get(name)
    if m is None or m.buckets is None:
        return None
    s = m._series.get(())
    if s is None or s.count == 0:
        return None
    return histogram_row_quantiles(
        {"buckets": dict(zip(m.buckets, s.bucket_counts)),
         "count": s.count, "sum": s.sum}, qs)


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return REGISTRY.register(Counter(name, help, labelnames))


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return REGISTRY.register(Gauge(name, help, labelnames))


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Optional[Iterable[float]] = None) -> Histogram:
    return REGISTRY.register(Histogram(name, help, labelnames, buckets))
