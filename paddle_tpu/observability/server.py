"""Live observability HTTP endpoint (stdlib http.server, threaded).

Routes (flag ``obs_http_port``, 0 = off; Trainer starts the server on
first ``train()`` when the flag is set, or call
:func:`start_http_server` directly — e.g. on the coordinator next to
``serve_master``):

* ``/metrics`` — Prometheus text exposition (v0.0.4).  With a
  :class:`~.fleet.FleetAggregator` attached this is the FLEET view:
  counters summed across workers, histogram buckets merged, gauges
  per-worker under a ``worker`` label, overlaid on this process's own
  registry (taskmaster queue gauges etc.).
* ``/metrics.json`` — the same document in the registry JSON schema.
* ``/healthz`` — JSON liveness: trainer last-step age, fleet stale /
  straggler state.  HTTP 200 when healthy, 503 when the fleet is
  degraded (a stale worker or a diagnosed straggler).
* ``/flight`` — the latest flight-recorder bundle (built on demand
  when nothing has tripped yet); with an aggregator, per-worker
  bundles ride along under ``workers``.
* ``/model`` — model-health telemetry (tensorstats): this process's
  full last per-variable statistics snapshot, plus every rank's
  latest compact row when aggregating.
* ``/serving`` — serving-plane status (paddle_tpu/serving): queue
  depth, batch occupancy, p50/p99 TTFT and per-token latency,
  request/shed counters, bucket grid.
* ``POST /serving/generate`` — submit one generation request to the
  attached batcher; 200 with tokens+timing, 429 when admission
  control sheds, 503 when no batcher is attached or it is draining.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from ..core import flags
from . import flight as obs_flight
from . import metrics as obs_metrics

# NOTE: .fleet is imported lazily (only when an aggregator is actually
# attached) so `python -m paddle_tpu.observability.fleet` doesn't trip
# runpy's already-imported warning via trainer.py -> server -> fleet.

_PROM_CTYPE = "text/plain; version=0.0.4; charset=utf-8"
_OPENMETRICS_CTYPE = ("application/openmetrics-text; version=1.0.0; "
                      "charset=utf-8")

_lock = threading.Lock()
_server: Optional["ObservabilityServer"] = None

# trainer liveness for /healthz: updated by Trainer.train at every step
_liveness = {"steps": 0, "last_step_unix": None, "running": False}


def _trainer_stale_s() -> float:
    """A RUNNING trainer with no step for this long reads as hung on
    /healthz (degraded); a finished or never-started trainer does not.
    Flag-tunable (was hardcoded 60s): miniature soaks and slow-step
    training both need non-default values, and the Watchtower
    stalled_rank alert rule shares the same knob."""
    return float(flags.get_flag("healthz_stall_seconds"))


def note_trainer_step():
    _liveness["steps"] += 1
    _liveness["last_step_unix"] = time.time()


def note_trainer_running(running: bool):
    """Trainer.train marks entry/exit so /healthz can tell 'hung
    mid-train' (degraded) from 'finished' / 'never trained' (not)."""
    _liveness["running"] = bool(running)
    if running:
        # entering train() restarts the staleness clock: compile of the
        # first step may legitimately take minutes on a cold cache
        _liveness["last_step_unix"] = time.time()


def trainer_liveness() -> dict:
    last = _liveness["last_step_unix"]
    age = None if last is None else time.time() - last
    stale_s = _trainer_stale_s()
    return {"steps": _liveness["steps"],
            "last_step_unix": last,
            "last_step_age_s": None if age is None else round(age, 3),
            "running": _liveness["running"],
            "alive": age is not None and age < stale_s,
            "hung": (_liveness["running"] and age is not None
                     and age > stale_s)}


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle_tpu_obs/1"

    def log_message(self, *a):       # keep test output quiet
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, doc) -> None:
        # gauges may legitimately hold NaN/Inf (a poisoned loss is
        # exactly when people scrape) — stringify them like flight.py
        # does so the body stays strict JSON for jq/JSON.parse
        body = json.dumps(obs_flight._strict_json(doc),
                          allow_nan=False).encode()
        self._send(code, body, "application/json")

    def do_GET(self):
        obs: "ObservabilityServer" = self.server.obs   # type: ignore
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                # exemplar clauses are OpenMetrics-only syntax (a
                # mid-line '#' fails a v0.0.4 parse), so they are
                # served only to scrapers that negotiate for them
                want_om = "openmetrics" in (
                    self.headers.get("Accept") or "").lower()
                body = obs.prometheus_text(exemplars=want_om)
                if want_om and not body.endswith("# EOF\n"):
                    body += "# EOF\n"       # OpenMetrics terminator
                self._send(200, body.encode(),
                           _OPENMETRICS_CTYPE if want_om
                           else _PROM_CTYPE)
            elif path == "/metrics.json":
                self._send_json(200, obs.metrics_json())
            elif path == "/healthz":
                doc = obs.healthz()
                self._send_json(200 if doc["status"] == "ok" else 503,
                                doc)
            elif path == "/flight":
                self._send_json(200, obs.flight())
            elif path == "/model":
                self._send_json(200, obs.model())
            elif path == "/serving":
                self._send_json(200, obs.serving())
            elif path == "/alerts":
                self._send_json(200, obs.alerts())
            elif path == "/controller":
                self._send_json(200, obs.controller())
            elif path == "/perf":
                self._send_json(200, obs.perf())
            elif path == "/memory":
                self._send_json(200, obs.memory())
            elif path == "/goodput":
                self._send_json(200, obs.goodput())
            elif path == "/journal":
                self._send_json(200, obs.journal())
            elif path.startswith("/trace/"):
                trace_id = path[len("/trace/"):]
                doc = obs.trace(trace_id)
                if doc is None:
                    self._send_json(404, {
                        "error": f"no trace {trace_id!r} (evicted, "
                                 "never recorded, or tracing off)"})
                else:
                    self._send_json(200, doc)
            elif path == "/profile":
                from . import deviceprof
                self._send_json(200, deviceprof.status())
            elif path == "/":
                self._send(200, b"paddle_tpu observability: /metrics "
                                b"/metrics.json /healthz /flight "
                                b"/model /serving /alerts /controller "
                                b"/perf /memory /goodput /journal "
                                b"/trace/<id> "
                                b"[POST /serving/generate "
                                b"/serving/drain /profile]\n",
                           "text/plain; charset=utf-8")
            else:
                self._send_json(404, {"error": f"no route {path}"})
        except Exception as e:       # the endpoint must not take the
            try:                     # process down with it
                self._send_json(500, {"error": repr(e)[:500]})
            except OSError:
                pass

    def do_POST(self):
        obs: "ObservabilityServer" = self.server.obs   # type: ignore
        path = self.path.split("?", 1)[0].rstrip("/")
        try:
            if path not in ("/serving/generate", "/serving/drain",
                            "/profile"):
                self._send_json(404, {"error": f"no POST route {path}"})
                return
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b"{}"
            try:
                body = json.loads(raw.decode() or "{}")
            except (ValueError, UnicodeDecodeError) as e:
                self._send_json(400, {"error": f"bad JSON body: {e}"})
                return
            if path == "/profile":
                code, doc = obs.profile(body)
                self._send_json(code, doc)
                return
            if path == "/serving/drain":
                code, doc = obs.serving_drain(body)
                self._send_json(code, doc)
                return
            # request X-ray: honor (or mint) the W3C traceparent so the
            # whole queue->prefill->decode lifecycle lands under ONE
            # trace id, echoed in the response header AND body
            from . import tracectx
            parent = tracectx.parse_traceparent(
                self.headers.get("traceparent"))
            ctx = tracectx.start_trace("serving.request", parent=parent)
            self._trace_ctx = ctx
            code, doc = obs.serving_generate(body, trace=ctx)
            if ctx is not None and isinstance(doc, dict):
                doc.setdefault("trace_id", ctx.trace_id)
            self._send_json(code, doc)
        except Exception as e:
            try:
                self._send_json(500, {"error": repr(e)[:500]})
            except OSError:
                pass

    def end_headers(self):
        ctx = getattr(self, "_trace_ctx", None)
        if ctx is not None:
            self.send_header("traceparent", ctx.traceparent())
            self._trace_ctx = None
        super().end_headers()


class ObservabilityServer:
    """One threaded stdlib HTTP server bound to (host, port); request
    handlers pull live registry / aggregator state at scrape time."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 aggregator: Optional["obs_fleet.FleetAggregator"] = None):
        self.aggregator = aggregator
        try:
            self._httpd = ThreadingHTTPServer((host, port), _Handler)
        except OSError as e:
            raise OSError(
                f"observability server failed to bind {host}:{port}: "
                f"{e}") from e
        self._httpd.daemon_threads = True
        self._httpd.obs = self        # type: ignore[attr-defined]
        # poll_interval: shutdown() blocks one poll tick; keep it short
        # so stop()/test teardown doesn't pay the 0.5s default
        self._thread = threading.Thread(
            target=lambda: self._httpd.serve_forever(poll_interval=0.05),
            daemon=True, name="obs-http-server")
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def stop(self):
        """Shut down and JOIN the server thread (no socket leaks
        between test cases)."""
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)

    # -- route bodies --------------------------------------------------
    @staticmethod
    def _refresh_sampled_state():
        """Re-publish gauges that only move on their owner's activity
        (taskmaster queue state): a scrape must see NOW, not the last
        RPC — a stalled fleet sends no RPCs at all."""
        try:
            from ..distributed import task_queue
            task_queue.refresh_metrics()
        except Exception:
            pass                 # scraping must never 500 on refresh

    def prometheus_text(self, exemplars: bool = False) -> str:
        self._refresh_sampled_state()
        if self.aggregator is not None:
            return self.aggregator.prometheus_text(
                local=obs_metrics.REGISTRY.to_json(),
                exemplars=exemplars)
        return obs_metrics.REGISTRY.prometheus_text(exemplars=exemplars)

    def metrics_json(self) -> dict:
        self._refresh_sampled_state()
        if self.aggregator is not None:
            from . import fleet as obs_fleet
            return obs_fleet.families_to_json(
                self.aggregator.merged_families(
                    local=obs_metrics.REGISTRY.to_json()))
        return obs_metrics.REGISTRY.to_json()

    def healthz(self) -> dict:
        fleet = (self.aggregator.health()
                 if self.aggregator is not None else None)
        trainer = trainer_liveness()
        # degraded when the fleet says so OR this process's own trainer
        # is hung mid-train — a k8s probe keyed on the status must
        # restart a deadlocked worker, not 200 it forever
        degraded = bool(fleet and fleet["degraded"]) or trainer["hung"]
        doc = {"status": "degraded" if degraded else "ok",
               "time_unix": time.time(),
               "trainer": trainer,
               "fleet": fleet}
        # serving worker (ISSUE 20): report batcher state so the
        # router's readiness probe and a human operator read ONE truth
        # (the stdout ready line stops being the only signal).  Looked
        # up via sys.modules, never imported — a process that never
        # served keeps a byte-identical healthz body and import graph.
        import sys as _sys
        serving_mod = _sys.modules.get("paddle_tpu.serving")
        b = serving_mod.get() if serving_mod is not None else None
        if b is not None:
            state = ("draining" if b.draining
                     else "running" if b.running else "stopped")
            doc["serving"] = {"state": state,
                              "queue_depth": b.queue_depth,
                              "replica": serving_mod.replica_id()}
            if state != "running":
                # readiness semantics: a draining/stopped batcher is
                # a 503 probe answer (the route maps non-ok to 503)
                doc["status"] = state
        return doc

    def flight(self) -> dict:
        # a scrape is a pure observer: never advance the counter-delta
        # baseline a real crash dump would otherwise report against
        doc = obs_flight.last_bundle() or obs_flight.bundle(
            "http_on_demand", advance_baseline=False)
        if self.aggregator is not None:
            workers = self.aggregator.flight_bundles()
            if workers:
                doc = dict(doc)
                doc["workers"] = {str(r): b
                                  for r, b in sorted(workers.items())}
        return doc

    def model(self) -> dict:
        """Model-health view (observability/tensorstats.py): this
        process's full last snapshot plus — with an aggregator — every
        rank's latest compact stats row."""
        from . import tensorstats as obs_tensorstats
        doc = {
            "schema": "paddle_tpu.model.v1",
            "time_unix": time.time(),
            "enabled": obs_tensorstats.enabled(),
            "local": obs_tensorstats.snapshot_doc(),
        }
        if self.aggregator is not None:
            doc["workers"] = {str(r): row for r, row in sorted(
                self.aggregator.model_rows().items())}
        return doc

    def serving(self) -> dict:
        """Serving-plane status (paddle_tpu/serving.status_doc): queue
        depth, occupancy, SLO quantiles, admission counters."""
        from .. import serving as serving_mod
        return serving_mod.status_doc()

    def alerts(self) -> dict:
        """``GET /alerts``: the Watchtower engine's state after one
        evaluation — over the FLEET-merged document on a coordinator
        (the engine's doc_fn is wired to metrics_json when an
        aggregator is attached), the local registry otherwise."""
        from . import alerts as obs_alerts
        eng = obs_alerts.ensure_started()
        if eng is None:
            return {"schema": obs_alerts.SCHEMA,
                    "time_unix": time.time(), "enabled": False,
                    "rules": [], "active": [], "firing": [],
                    "history": []}
        self._wire_alerts(eng)
        eng.evaluate()
        doc = eng.status_doc()
        doc["source"] = ("fleet" if self.aggregator is not None
                         else "local")
        return doc

    def controller(self) -> dict:
        """``GET /controller``: the Helmsman status document — breaker
        state, cooldown clocks and the recent decision ring; a
        meaningful disabled doc when the ``controller`` flag is off."""
        from . import controller as obs_controller
        return obs_controller.status_doc()

    def serving_drain(self, body: dict):
        """``POST /serving/drain``: remote drain-on-command (the
        controller's drain actuator reaching a serving worker over
        HTTP, and an operator verb in its own right).  Body:
        ``{"stop": bool}`` — stop=true also ends the batcher loop
        after the drain completes (SIGTERM semantics)."""
        from .. import serving
        b = serving.get()
        if b is None:
            return 503, {"error": "no serving batcher attached"}
        b.begin_drain(stop=bool(body.get("stop", False)))
        return 200, {"status": "draining",
                     "stop": bool(body.get("stop", False)),
                     "queued": b.queue_depth}

    def perf(self) -> dict:
        """``GET /perf``: the perfscope roofline view — this process's
        full status document, plus fleet-merged per-rank roofline rows
        (fleet.perf_rows) on a coordinator."""
        from . import perfscope as obs_perfscope
        doc = obs_perfscope.status_doc()
        doc["source"] = ("fleet" if self.aggregator is not None
                         else "local")
        if self.aggregator is not None:
            doc["ranks"] = self.aggregator.perf_rows()
        return doc

    def memory(self) -> dict:
        """``GET /memory``: the memscope census — this process's full
        status document, plus fleet-merged per-rank census rows
        (fleet.mem_rows) on a coordinator."""
        from . import memscope as obs_memscope
        doc = obs_memscope.status_doc()
        doc["source"] = ("fleet" if self.aggregator is not None
                         else "local")
        if self.aggregator is not None:
            doc["ranks"] = self.aggregator.mem_rows()
        return doc

    def goodput(self) -> dict:
        """``GET /goodput``: the Timecard chip-time accounting — this
        process's full status document, plus fleet-merged per-rank
        breakdown rows (fleet.goodput_rows) on a coordinator."""
        from . import goodput as obs_goodput
        doc = obs_goodput.status_doc()
        doc["source"] = ("fleet" if self.aggregator is not None
                         else "local")
        if self.aggregator is not None:
            doc["ranks"] = self.aggregator.goodput_rows()
        return doc

    def _wire_alerts(self, eng) -> None:
        """Point the engine at THIS server's (possibly fleet-merged)
        metrics view so the ticker and scrapes evaluate one consistent
        source — a coordinator engine flipping between local and merged
        docs would flap every fleet-only series."""
        if self.aggregator is not None:
            if eng.doc_fn is None:
                eng.doc_fn = self.metrics_json
            if eng.snapshot_provider is None:
                eng.snapshot_provider = self.aggregator.worker_metrics

    def journal(self) -> dict:
        """``GET /journal``: the fleet event journal — this process's
        newest events merged (deduped) with the aggregator's
        clock-normalized fleet timeline when one is attached."""
        from . import journal as obs_journal
        streams = [obs_journal.tail(1000)]
        if self.aggregator is not None:
            streams.append(self.aggregator.journal_events())
        events = obs_journal.merge_events(streams)
        return {"schema": obs_journal.SCHEMA,
                "time_unix": time.time(),
                "enabled": obs_journal.enabled(),
                "events": events[-1000:]}

    def trace(self, trace_id: str) -> Optional[dict]:
        """``GET /trace/<id>``: the assembled X-ray waterfall.  With an
        aggregator the FLEET view wins (router + worker spans merged on
        one clock); a plain worker serves its local store, captures
        included."""
        if not trace_id:
            return None
        if self.aggregator is not None:
            doc = self.aggregator.xray_waterfall(trace_id)
            if doc is not None:
                return doc
        from . import tracectx
        return tracectx.waterfall(trace_id)

    def profile(self, body: dict):
        """``POST /profile``: start one bounded jax.profiler capture
        tagged with the active trace ids.  Always 200 — 'unavailable'
        and 'busy' are states, not server errors."""
        from . import deviceprof
        try:
            dur = body.get("duration_s")
            dur = None if dur is None else float(dur)
        except (TypeError, ValueError) as e:
            return 400, {"error": f"malformed duration_s: {e}"}
        logdir = body.get("logdir")
        if logdir is not None and not isinstance(logdir, str):
            return 400, {"error": "logdir must be a string"}
        return 200, deviceprof.start(duration_s=dur, logdir=logdir)

    def serving_generate(self, body: dict, trace=None):
        """``POST /serving/generate`` body: submit to the attached
        batcher and block for the result.  Returns (http_code, doc).

        With an Armada router attached (serving/router.py), the
        request is ROUTED instead — health/load-aware replica choice,
        retry-elsewhere, breakers, deadline propagation.  No router
        (the default) = the single-replica path below, byte for
        byte."""
        from .. import serving as serving_mod
        router = serving_mod.get_router()
        if router is not None:
            return router.handle(body, trace=trace)
        batcher = serving_mod.get()
        if batcher is None or not batcher.running:
            return 503, {"error": "no serving batcher attached"}
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            return 400, {"error": "body needs a non-empty 'prompt' "
                                  "list of token ids"}
        try:
            # coerce ALL client-typed fields here so a malformed body
            # is a 400, not a 500 from deep inside the batcher (and a
            # string eos_id can't silently never match an int token)
            tokens = [int(t) for t in prompt]
            mnt = body.get("max_new_tokens")
            mnt = None if mnt is None else int(mnt)
            temperature = float(body.get("temperature") or 0.0)
            eos = body.get("eos_id")
            eos = None if eos is None else int(eos)
        except (TypeError, ValueError) as e:
            return 400, {"error": f"malformed request field: {e}"}
        try:
            req = batcher.submit(tokens, max_new_tokens=mnt,
                                 temperature=temperature, eos_id=eos,
                                 trace=trace)
        except serving_mod.ShedError as e:
            if getattr(e, "draining", False):
                # instance going away: 503 so clients fail over
                # instead of retrying a draining replica (429 means
                # "back off and retry HERE")
                return 503, {"error": str(e), "status": "drained"}
            return 429, {"error": str(e), "status": "shed",
                         "queue_depth": e.queue_depth}
        except RuntimeError as e:
            # "batcher is not running" — an availability condition
            # (it stopped between the check above and submit), not a
            # client error: 503 so retrying clients classify it right
            return 503, {"error": str(e), "status": "error"}
        except ValueError as e:
            return 400, {"error": str(e), "status": "error"}
        try:
            doc = req.result(timeout=float(body.get("timeout_s") or 60.0))
        except TimeoutError as e:
            return 504, {"error": str(e), "status": "timeout"}
        if doc["status"] != "ok":
            return 503 if doc["status"] == "drained" else 500, doc
        return 200, doc


def start_http_server(port: Optional[int] = None,
                      host: Optional[str] = None,
                      aggregator: Optional[
                          "obs_fleet.FleetAggregator"] = None
                      ) -> Optional[ObservabilityServer]:
    """Start (or return) the process-wide endpoint.  ``port=None`` reads
    the ``obs_http_port`` flag and is a no-op at its 0 default; an
    explicit port always binds (0 = ephemeral, for tests).

    If a server is already running, an ``aggregator`` is attached to it
    when it has none (the coordinator-also-trains case: the Trainer's
    flag-gated ensure_started() may win the race); a CONFLICTING
    explicit port or aggregator raises instead of being silently
    ignored."""
    global _server
    with _lock:
        if _server is not None:
            # validate BEFORE mutating: a raising call must not leave
            # its aggregator attached to the running server
            if port not in (None, 0) and port != _server.address[1]:
                raise RuntimeError(
                    f"observability server already bound to "
                    f"{_server.url}; requested port {port} — "
                    f"stop_http_server() first")
            if aggregator is not None:
                if _server.aggregator is None:
                    _server.aggregator = aggregator
                elif _server.aggregator is not aggregator:
                    raise RuntimeError(
                        "observability server already running with a "
                        "different FleetAggregator; stop_http_server() "
                        "first")
            _start_alert_engine(_server)
            return _server
        if port is None:
            port = int(flags.get_flag("obs_http_port"))
            if port <= 0:
                return None
        if host is None:
            # loopback default; obs_http_host=0.0.0.0 opts into remote
            # scrapes (a Prometheus target / the operator's curl)
            host = str(flags.get_flag("obs_http_host"))
        _server = ObservabilityServer(host, port, aggregator=aggregator)
        _start_alert_engine(_server)
        return _server


def _start_alert_engine(server: "ObservabilityServer"):
    """Flag-gated: bring the Watchtower ticker up alongside the HTTP
    endpoint and point it at this server's metrics view (fleet-merged
    when an aggregator rides along) — alerts must fire on their own
    clock, not only when somebody scrapes /alerts.  Never raises:
    alerting is an overlay, not a dependency."""
    try:
        from . import alerts as obs_alerts
        eng = obs_alerts.ensure_started()
        if eng is not None:
            server._wire_alerts(eng)
            # Helmsman rides the same lifecycle: flag-gated, riding
            # the alert ticker's clock (no thread of its own).  With
            # no coordinator wiring (controller.wire_master) it runs
            # sensor-complete but hands-empty: decisions journal with
            # outcome "no_actuator" — visible, never destructive.
            from . import controller as obs_controller
            obs_controller.ensure_started()
    except Exception:
        pass


def ensure_started() -> Optional[ObservabilityServer]:
    """Flag-gated idempotent start — the Trainer's entry point.  Unlike
    an explicit start_http_server(), a bind failure here WARNS instead
    of raising: obs_http_port is typically set fleet-wide via env, and
    a colocated second worker losing the port race must not lose its
    training run to an observability-only error."""
    import warnings
    try:
        return start_http_server(port=None)
    except (OSError, RuntimeError) as e:
        warnings.warn(f"observability endpoint not started: {e}",
                      RuntimeWarning, stacklevel=2)
        return None


def get_server() -> Optional[ObservabilityServer]:
    return _server


def stop_http_server():
    global _server
    with _lock:
        if _server is not None:
            _server.stop()
            _server = None


def reset():
    """Test hook: stop any running server and zero trainer liveness."""
    stop_http_server()
    _liveness["steps"] = 0
    _liveness["last_step_unix"] = None
    _liveness["running"] = False
