"""Incident reconstruction CLI: one report for "what happened at 14:32".

Joins the artifacts the observability plane already produces — the
merged fleet event journal (observability/journal.py), the alert
engine's fire/resolve history (observability/alerts.py), the run-log
window (observability/runlog.py) and the X-ray trace ids riding all of
them — into ONE ordered timeline (schema ``paddle_tpu.incident.v1``
plus an ASCII rendering), so "rank 0 chaos-killed at T+3.2s, lease
fenced, supervisor respawn #2, p99 alert resolved at T+9.1s" is one
command instead of five hand-joined file formats::

    # a time window over journal files (coordinator + per-rank)
    python -m paddle_tpu.observability.incident coord.jsonl w0.jsonl \
        --window 1700000000:1700000040

    # everything around one alert's fire..resolve, alerts fetched live
    python -m paddle_tpu.observability.incident coord.jsonl \
        --alert dead_rank --url http://127.0.0.1:9100

    # everything stamped with one trace id
    python -m paddle_tpu.observability.incident coord.jsonl \
        --trace-id 4bf92f3577b34da6a3ce929d0e0e4736

    # why did the fleet change size: one Helmsman controller decision
    # (ISSUE 17) joined with the alert + resize it caused
    python -m paddle_tpu.observability.incident coord.jsonl \
        --decision helm-00003

Journal files merge with at-least-once dedupe (an event shipped to the
coordinator AND read from its emitter's own file appears once) and
order on ``time_unix`` — master-normalized for shipped events, so
cross-host skew is already absorbed.  ``--url`` additionally pulls
``GET /alerts`` (history + contexts) and ``GET /journal`` (the
coordinator's in-memory merged tail) from a live endpoint.

Exit codes: 0 report rendered, 1 selector matched nothing / malformed
input, 2 bad usage — the lint/xray/jit_cache CLI contract.
``--self-test`` reconstructs a bundled kill → fence → respawn →
resolve fixture (the tier-1 smoke).
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from . import journal as obs_journal

SCHEMA = "paddle_tpu.incident.v1"

# journal/runlog fields that are record plumbing, not incident detail
_SKIP_FIELDS = {"schema", "kind", "event", "time_unix", "perf_counter",
                "rank", "pid", "seq", "worker_time_unix", "trace_id"}


# -- gathering --------------------------------------------------------------

def _fetch_json(url: str) -> dict:
    import urllib.request
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


def gather_events(journal_paths: List[str],
                  url: Optional[str] = None,
                  alerts_doc: Optional[dict] = None,
                  runlog_records: Optional[List[dict]] = None
                  ) -> Tuple[List[dict], List[dict]]:
    """Collect (timeline events, alert transition records) from every
    source.  Timeline events are journal records plus runlog guard/meta
    records (steps stay a count, not noise) plus alert transitions NOT
    already journaled (the engine journals its own fire/resolve — the
    history is the fallback when only /alerts was captured)."""
    streams = [obs_journal.read_events(p) for p in journal_paths]
    if url:
        doc = _fetch_json(url.rstrip("/") + "/journal")
        streams.append([e for e in doc.get("events", [])
                        if isinstance(e, dict)])
    events = obs_journal.merge_events(streams)

    alert_history: List[dict] = list((alerts_doc or {}).get("history",
                                                            []))
    # the engine journals its own transitions with its OWN clock a few
    # ms after the history entry's evaluation stamp — dedupe by
    # tolerance, not rounded equality (a 0.049 vs 0.051 pair must not
    # double-draw the same fire)
    journaled: Dict[Tuple[str, str], List[float]] = {}
    for e in events:
        if e.get("kind") == "alert":
            journaled.setdefault(
                (e.get("rule"), e.get("event")), []).append(
                float(e.get("time_unix", 0.0)))
    for rec in alert_history:
        state = rec.get("state")
        if state not in ("firing", "resolved"):
            continue
        ev_name = "fire" if state == "firing" else "resolve"
        t = float(rec.get("time_unix", 0.0))
        if any(abs(t - tj) <= 0.5
               for tj in journaled.get((rec.get("rule"), ev_name), ())):
            continue             # the journal already carries it
        events.append({"schema": obs_journal.SCHEMA, "kind": "alert",
                       "event": ev_name, "time_unix": t,
                       "rank": None, "rule": rec.get("rule"),
                       "severity": rec.get("severity"),
                       "value": rec.get("value"),
                       "labels": rec.get("labels")})
    for rec in runlog_records or []:
        kind = rec.get("kind")
        if kind == "guard":
            events.append({
                "schema": obs_journal.SCHEMA, "kind": "runlog",
                "event": f"guard_{rec.get('verdict')}",
                "time_unix": float(rec.get("time_unix", 0.0)),
                "rank": None, "step": rec.get("step"),
                "loss": rec.get("loss"),
                "attribution": rec.get("attribution"),
                "trace_id": rec.get("trace_id")})
        elif kind == "meta":
            events.append({
                "schema": obs_journal.SCHEMA, "kind": "runlog",
                "event": str(rec.get("event")),
                "time_unix": float(rec.get("time_unix", 0.0)),
                "rank": None})
    events.sort(key=lambda r: (float(r.get("time_unix", 0.0) or 0.0),
                               r.get("seq", 0)))
    return events, alert_history


# -- window selection -------------------------------------------------------

def resolve_window(events: List[dict], alert_history: List[dict],
                   window: Optional[str] = None,
                   alert: Optional[str] = None,
                   trace_id: Optional[str] = None,
                   decision: Optional[str] = None,
                   pad: float = 5.0) -> Tuple[float, float, dict]:
    """(t0, t1, selector-doc) per the CLI's four addressing modes;
    raises ValueError when the selector matches nothing."""
    if window:
        try:
            lo, hi = window.split(":", 1)
            t0, t1 = float(lo), float(hi)
        except ValueError:
            raise ValueError(
                f"--window must be '<t0_unix>:<t1_unix>', got "
                f"{window!r}")
        if t1 <= t0:
            raise ValueError(f"--window is empty: {t0} >= {t1}")
        return t0, t1, {"mode": "window", "t0": t0, "t1": t1}
    if alert:
        fires = [e for e in events if e.get("kind") == "alert"
                 and e.get("rule") == alert and e.get("event") == "fire"]
        resolves = [e for e in events if e.get("kind") == "alert"
                    and e.get("rule") == alert
                    and e.get("event") == "resolve"]
        for rec in alert_history:
            if rec.get("rule") != alert:
                continue
            t = float(rec.get("time_unix", 0.0))
            if rec.get("state") == "firing":
                fires.append({"time_unix": t})
            elif rec.get("state") == "resolved":
                resolves.append({"time_unix": t})
        if not fires:
            raise ValueError(f"alert {alert!r} never fired in the "
                             f"given journals/history")
        t_fire = min(float(e["time_unix"]) for e in fires)
        t_end = max((float(e["time_unix"]) for e in resolves),
                    default=t_fire)
        return (t_fire - pad, t_end + pad,
                {"mode": "alert", "alert": alert,
                 "fired_unix": t_fire,
                 "resolved_unix": t_end if resolves else None})
    if trace_id:
        hits = [float(e["time_unix"]) for e in events
                if e.get("trace_id") == trace_id]
        if not hits:
            raise ValueError(f"trace id {trace_id!r} appears in no "
                             f"journal/runlog record")
        return (min(hits) - pad, max(hits) + pad,
                {"mode": "trace", "trace_id": trace_id})
    if decision:
        # ISSUE 17: a Helmsman controller decision is addressable — the
        # window spans the decision event itself plus everything sharing
        # its alert trace id (the firing rule's exemplars, the master's
        # resize_applied/lease events the actuation caused), so "why did
        # the fleet change size" is one command
        matches = [e for e in events
                   if e.get("kind") == "controller"
                   and e.get("event") == "decision"
                   and str(e.get("decision_id")) == decision]
        if not matches:
            raise ValueError(f"decision {decision!r} appears in no "
                             f"journal")
        hits = [float(e["time_unix"]) for e in matches]
        tids = {e.get("alert_trace_id") for e in matches
                if e.get("alert_trace_id")}
        hits.extend(float(e["time_unix"]) for e in events
                    if e.get("trace_id") in tids)
        sel = {"mode": "decision", "decision_id": decision,
               "rule": matches[0].get("rule"),
               "action": matches[0].get("action"),
               "outcome": matches[0].get("outcome")}
        return min(hits) - pad, max(hits) + pad, sel
    if not events:
        raise ValueError("no events at all (empty journals and no "
                         "selector)")
    ts = [float(e.get("time_unix", 0.0)) for e in events]
    return min(ts), max(ts) + 1e-6, {"mode": "all"}


# -- report -----------------------------------------------------------------

def _detail(ev: dict) -> Dict[str, Any]:
    return {k: v for k, v in ev.items() if k not in _SKIP_FIELDS}


def build_report(events: List[dict], alert_history: List[dict],
                 t0: float, t1: float, selector: dict,
                 runlog_records: Optional[List[dict]] = None,
                 with_goodput: bool = False) -> dict:
    """The ``paddle_tpu.incident.v1`` document for one window."""
    rows = []
    trace_ids: List[str] = []
    win_events: List[dict] = []
    for ev in events:
        t = float(ev.get("time_unix", 0.0))
        if not t0 <= t <= t1:
            continue
        win_events.append(ev)
        row = {"time_unix": t, "offset_s": round(t - t0, 6),
               "kind": ev.get("kind"), "event": ev.get("event"),
               "rank": ev.get("rank")}
        det = _detail(ev)
        if det:
            row["detail"] = det
        tid = ev.get("trace_id")
        if tid:
            row["trace_id"] = tid
            if tid not in trace_ids:
                trace_ids.append(tid)
        rows.append(row)
    alerts = []
    for rec in alert_history:
        if rec.get("state") != "firing":
            continue
        t = float(rec.get("time_unix", 0.0))
        if not t0 <= t <= t1:
            continue
        entry = {"rule": rec.get("rule"),
                 "severity": rec.get("severity"),
                 "fired_unix": t, "labels": rec.get("labels"),
                 "value": rec.get("value")}
        ctx = rec.get("context") or {}
        if ctx:
            entry["context"] = ctx
            for tid in ctx.get("exemplar_trace_ids") or []:
                if tid not in trace_ids:
                    trace_ids.append(tid)
            if ctx.get("alert_trace_id") \
                    and ctx["alert_trace_id"] not in trace_ids:
                trace_ids.append(ctx["alert_trace_id"])
        res = [float(h.get("time_unix", 0.0)) for h in alert_history
               if h.get("rule") == rec.get("rule")
               and h.get("state") == "resolved"
               and float(h.get("time_unix", 0.0)) >= t]
        if res:
            entry["resolved_unix"] = min(res)
        alerts.append(entry)
    steps = sum(1 for r in runlog_records or []
                if r.get("kind") == "step"
                and t0 <= float(r.get("time_unix", 0.0)) <= t1)
    ranks = sorted({r["rank"] for r in rows
                    if isinstance(r.get("rank"), int)})
    doc = {"schema": SCHEMA, "generated_unix": time.time(),
           "selector": selector,
           "window": {"t0_unix": t0, "t1_unix": t1,
                      "duration_s": round(t1 - t0, 6)},
           "ranks": ranks,
           "timeline": rows, "alerts": alerts,
           "steps_in_window": steps,
           "trace_ids": trace_ids}
    if with_goodput:
        # ISSUE 19: join the window's Timecard — badput spikes with the
        # alert fires / controller decisions nearest each one
        from . import goodput as obs_goodput
        doc["goodput"] = obs_goodput.incident_section(win_events)
    return doc


def render_report(doc: dict) -> str:
    """ASCII incident timeline — enough forensics for a terminal."""
    if doc.get("schema") != SCHEMA:
        raise ValueError(f"not a {SCHEMA} document "
                         f"(schema={doc.get('schema')!r})")
    w = doc.get("window", {})
    sel = doc.get("selector", {})
    lines = [f"incident  window {w.get('t0_unix')} .. "
             f"{w.get('t1_unix')}  (+{w.get('duration_s')}s, "
             f"selector={sel.get('mode')}"
             + (f" {sel.get('alert')}" if sel.get("alert") else "")
             + (f" {sel.get('trace_id')}" if sel.get("trace_id") else "")
             + (f" {sel.get('decision_id')} "
                f"{sel.get('rule')}->{sel.get('action')}"
                f"={sel.get('outcome')}"
                if sel.get("decision_id") else "")
             + f", ranks={doc.get('ranks')})"]
    for a in doc.get("alerts", []):
        t0 = float(w.get("t0_unix", 0.0))
        fired = float(a.get("fired_unix", 0.0))
        res = a.get("resolved_unix")
        ctx = a.get("context") or {}
        lines.append(
            f"  alert {a.get('rule')} [{a.get('severity')}] "
            f"fired T+{fired - t0:.3f}s"
            + (f", resolved T+{float(res) - t0:.3f}s" if res else
               ", UNRESOLVED")
            + (f", ranks={ctx.get('ranks')}" if ctx.get("ranks") else "")
            + (f", trace={ctx.get('exemplar_trace_ids')[0][:16]}…"
               if ctx.get("exemplar_trace_ids") else ""))
    lines.append(f"  timeline ({len(doc.get('timeline', []))} event(s), "
                 f"{doc.get('steps_in_window', 0)} train step(s) in "
                 f"window):")
    for ev in doc.get("timeline", []):
        rank = ev.get("rank")
        r = f"r{rank}" if isinstance(rank, int) else "--"
        det = ev.get("detail") or {}
        det_s = " ".join(f"{k}={det[k]}" for k in sorted(det)
                         if det[k] is not None)[:100]
        lines.append(f"  T+{ev['offset_s']:>8.3f}s  {r:<3} "
                     f"{str(ev.get('kind')):<10} "
                     f"{str(ev.get('event')):<20} {det_s}")
    gp = doc.get("goodput")
    if gp:
        fleet = gp.get("fleet") or {}
        lines.append(
            f"  goodput: fleet "
            f"{100.0 * (fleet.get('goodput_fraction') or 0.0):.1f}% of "
            f"{fleet.get('chip_seconds') or 0.0:.2f} chip-seconds, "
            f"{len(gp.get('restart_gaps') or [])} restart/park gap(s), "
            f"{len(gp.get('resizes') or [])} resize(s)")
        t0w = float(w.get("t0_unix", 0.0))
        for sp in gp.get("spikes") or []:
            near = "; ".join(sp.get("nearby") or []) or "-"
            lines.append(
                f"    badput r{sp['rank']} {sp['state']:<18} "
                f"T+{sp['start_unix'] - t0w:>8.3f}s "
                f"+{sp['dur']:.3f}s  near: {near}"[:118])
    if doc.get("trace_ids"):
        lines.append(f"  waterfall refs: "
                     f"{', '.join(t[:16] + '…' for t in doc['trace_ids'][:6])}"
                     f"  (GET /trace/<id> or the xray CLI)")
    return "\n".join(lines)


# -- self-test --------------------------------------------------------------

def _fixture_events() -> List[dict]:
    """A miniature but structurally complete incident: rank 0 is
    chaos-killed mid-step, the master fences its lease and declares it
    dead, the supervisor respawns it, the dead-rank alert fires and
    resolves — what --self-test reconstructs with no live fleet."""
    T = 1700000000.0

    def ev(dt, kind, event, rank, seq, **fields):
        return {"schema": obs_journal.SCHEMA, "kind": kind,
                "event": event, "time_unix": T + dt, "rank": rank,
                "pid": 100 + (rank or 0), "seq": seq, **fields}

    return [
        ev(0.0, "worker", "step", 0, 1, step=7,
           trace_id="4bf92f3577b34da6a3ce929d0e0e4736"),
        ev(0.8, "chaos", "injected", 0, 2, site="trainer.step",
           fault_kind="exit", n=8),
        ev(1.4, "master", "worker_dead", None, 3, dead_rank=0),
        ev(1.5, "master", "lease_fenced", None, 4, verb="heartbeat",
           fenced_rank=0),
        ev(1.6, "alert", "fire", None, 5, rule="dead_rank",
           severity="critical", labels={"worker": "0"}),
        ev(2.1, "supervisor", "restart_scheduled", None, 6,
           restart_rank=0, attempt=1),
        ev(2.4, "supervisor", "spawn", None, 7, spawn_rank=0,
           incarnation=1),
        ev(3.0, "master", "worker_registered", None, 8,
           registered_rank=0),
        ev(3.2, "alert", "resolve", None, 9, rule="dead_rank",
           severity="critical"),
        # ISSUE 17: the Helmsman controller acting on a backlog alert —
        # a decision event plus the fleet change it caused, linked by
        # the alert's trace id so --decision joins them into one window
        ev(4.0, "controller", "decision", None, 10,
           decision_id="helm-00001", rule="task_backlog",
           severity="warning", action="request_resize",
           direction="grow", observed=37.0, magnitude=2,
           old_world=2, target_world=4, outcome="applied",
           fence={"generation": 1, "resizes": 0},
           alert_trace_id="9f1a2b3c4d5e6f709f1a2b3c4d5e6f70"),
        ev(4.4, "master", "resize_applied", None, 11, old=2, new=4,
           trace_id="9f1a2b3c4d5e6f709f1a2b3c4d5e6f70"),
    ]


def _self_test() -> int:
    events = _fixture_events()
    t0, t1, sel = resolve_window(events, [], alert="dead_rank",
                                 pad=2.0)
    doc = build_report(events, [], t0, t1, sel)
    order = [(e["kind"], e["event"]) for e in doc["timeline"]]
    want = [("chaos", "injected"), ("master", "worker_dead"),
            ("alert", "fire"), ("supervisor", "spawn"),
            ("alert", "resolve")]
    pos = []
    for item in want:
        if item not in order:
            print(f"incident --self-test FAILED: {item} missing from "
                  f"{order}")
            return 1
        pos.append(order.index(item))
    if pos != sorted(pos):
        print(f"incident --self-test FAILED: out of order {order}")
        return 1
    text = render_report(doc)
    needed = ["chaos", "worker_dead", "spawn", "resolve",
              "waterfall refs"]
    missing = [n for n in needed if n not in text]
    if missing or doc["schema"] != SCHEMA:
        print(f"incident --self-test FAILED: render missing {missing}\n"
              f"{text}")
        return 1
    # the --decision selector: one controller decision id resolves to a
    # window holding the decision AND the resize it caused (joined on
    # the alert trace id)
    t0, t1, sel = resolve_window(events, [], decision="helm-00001",
                                 pad=1.0)
    doc = build_report(events, [], t0, t1, sel)
    order = [(e["kind"], e["event"]) for e in doc["timeline"]]
    if ("controller", "decision") not in order \
            or ("master", "resize_applied") not in order \
            or sel.get("outcome") != "applied":
        print(f"incident --self-test FAILED: --decision window missing "
              f"decision/resize pair: {order} sel={sel}")
        return 1
    try:
        resolve_window(events, [], decision="helm-99999")
    except ValueError:
        pass
    else:
        print("incident --self-test FAILED: unknown decision id did "
              "not raise")
        return 1
    print("incident --self-test OK (kill -> fence -> respawn -> "
          "resolve reconstructed in order; --decision joins decision "
          "-> resize)")
    return 0


# -- CLI --------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.incident",
        description="Reconstruct one fleet incident from journal + "
                    "alerts + runlog artifacts: a paddle_tpu."
                    "incident.v1 report and an ASCII timeline.")
    ap.add_argument("journals", nargs="*",
                    help="journal JSONL file(s) — the coordinator's "
                         "merged file and/or per-rank files (deduped)")
    ap.add_argument("--url", help="live endpoint root: pulls GET "
                                  "/alerts and GET /journal")
    ap.add_argument("--alerts", metavar="JSON",
                    help="a saved paddle_tpu.alerts.v1 document "
                         "(GET /alerts output) for history/contexts")
    ap.add_argument("--runlog", metavar="JSONL",
                    help="a paddle_tpu.runlog.v1 run history: guard "
                         "records join the timeline, steps are counted")
    ap.add_argument("--window", metavar="T0:T1",
                    help="unix-seconds window")
    ap.add_argument("--alert", metavar="RULE",
                    help="window = RULE's first fire .. last resolve "
                         "(+/- --pad)")
    ap.add_argument("--trace-id", metavar="ID",
                    help="window = every record stamped with ID "
                         "(+/- --pad)")
    ap.add_argument("--decision", metavar="ID",
                    help="window = one controller decision (helm-NNNNN) "
                         "plus everything on its alert trace: why the "
                         "fleet changed size, as one timeline")
    ap.add_argument("--pad", type=float, default=5.0,
                    help="seconds of context around --alert/--trace-id "
                         "(default 5)")
    ap.add_argument("--goodput", action="store_true",
                    help="join the window's Timecard (ISSUE 19): badput "
                         "spikes annotated with nearby alerts/decisions")
    ap.add_argument("--json", action="store_true",
                    help="print the raw report document")
    ap.add_argument("--self-test", action="store_true",
                    help="reconstruct the bundled fixture and exit")
    args = ap.parse_args(argv)
    if args.self_test:
        return _self_test()
    if not args.journals and not args.url:
        ap.print_usage()
        print("incident: need at least one journal file or --url",
              file=sys.stderr)
        return 2
    if sum(bool(x) for x in (args.window, args.alert,
                             args.trace_id, args.decision)) > 1:
        print("incident: --window/--alert/--trace-id/--decision are "
              "mutually exclusive", file=sys.stderr)
        return 2
    try:
        alerts_doc = None
        if args.alerts:
            with open(args.alerts, encoding="utf-8") as f:
                alerts_doc = json.load(f)
        elif args.url:
            alerts_doc = _fetch_json(args.url.rstrip("/") + "/alerts")
        runlog_records = None
        if args.runlog:
            # runlog is a CLI module: import only when actually asked
            # for (the PR 7 runpy idiom)
            from . import runlog as obs_runlog
            runlog_records = obs_runlog.read_records(args.runlog)
        events, history = gather_events(
            args.journals, url=args.url, alerts_doc=alerts_doc,
            runlog_records=runlog_records)
        t0, t1, sel = resolve_window(
            events, history, window=args.window, alert=args.alert,
            trace_id=args.trace_id, decision=args.decision,
            pad=args.pad)
        doc = build_report(events, history, t0, t1, sel,
                           runlog_records=runlog_records,
                           with_goodput=args.goodput)
    except (OSError, ValueError) as e:
        print(f"incident: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True, default=repr))
        return 0
    print(render_report(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
