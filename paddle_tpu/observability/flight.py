"""Crash flight recorder: an always-on bounded ring of recent events
dumped as ONE JSON diagnostic bundle when something goes wrong.

Production training failures are post-mortem puzzles: the NaN that
tripped the guard, the retry budget that ran dry, the SIGTERM that
landed mid-epoch — by the time a human looks, the process state is
gone.  This module keeps a cheap ring buffer (``flight_recorder_events``
entries) of recent spans, compile/chaos/guard/retry events and metric
deltas, and on a trip writes a single self-contained bundle:

* the event ring (what just happened, in order)
* a full metrics-registry snapshot + counter deltas since the last dump
* per-program cost summaries (costmodel.py)
* the diagnosed compile log (forensics.py)
* the full flag state

Dump triggers (wired in trainer.py / resilience/):
NumericGuard trips, circuit-breaker opens, retry exhaustion, preemption,
and uncaught trainer exceptions.  ``flight_recorder_path`` names the
file; when empty the bundle is still built and held in memory
(:func:`last_bundle`) so tests and REPLs can inspect it without a
filesystem side effect.  Recording is O(1) dict appends — always on.
"""
from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..core import flags
from . import metrics as obs_metrics

_MAX_BUNDLE_BYTES = 1 << 20      # hard bundle bound: 1 MiB

_lock = threading.Lock()
_ring: deque = deque(maxlen=256)
_ring_cap = 256
_last_bundle: Optional[dict] = None
_last_counter_snapshot: Dict[str, float] = {}
_dumps = 0


def _capacity() -> int:
    try:
        return int(flags.get_flag("flight_recorder_events"))
    except Exception:
        return 256


def record(kind: str, name: str, **data: Any):
    """Append one event to the ring.  Cheap and always-on; capacity 0
    disables recording."""
    global _ring, _ring_cap
    cap = _capacity()
    if cap <= 0:
        return
    ev = {"ts": time.time(), "kind": kind, "name": name}
    if data:
        ev["data"] = {k: _safe(v) for k, v in data.items()}
    with _lock:
        if cap != _ring_cap:
            _ring = deque(_ring, maxlen=cap)
            _ring_cap = cap
        _ring.append(ev)


def _safe(v: Any):
    """JSON-safe, size-bounded event payload value.  Non-finite floats
    become strings: the flagship trigger IS a NaN loss, and a bare
    ``NaN`` token would make the whole bundle invalid strict JSON."""
    if isinstance(v, float):
        return v if math.isfinite(v) else repr(v)
    if isinstance(v, (int, bool)) or v is None:
        return v
    if isinstance(v, str):
        return v[:300]
    if isinstance(v, (list, tuple)):
        return [_safe(x) for x in list(v)[:20]]
    if isinstance(v, dict):
        return {str(k)[:80]: _safe(x) for k, x in list(v.items())[:20]}
    return repr(v)[:300]


def _strict_json(doc: Any):
    """Deep-copy `doc` with every non-finite float stringified, so the
    bundle always serializes under ``allow_nan=False`` (metric gauges
    may legitimately hold NaN/Inf — e.g. a poisoned bench loss)."""
    if isinstance(doc, float):
        return doc if math.isfinite(doc) else repr(doc)
    if isinstance(doc, dict):
        return {k: _strict_json(v) for k, v in doc.items()}
    if isinstance(doc, (list, tuple)):
        return [_strict_json(v) for v in doc]
    return doc


def events() -> List[dict]:
    with _lock:
        return list(_ring)


def reset():
    global _last_bundle, _last_counter_snapshot, _dumps
    with _lock:
        _ring.clear()
        _last_bundle = None
        _last_counter_snapshot = {}
        _dumps = 0


def last_bundle() -> Optional[dict]:
    """The most recently built bundle (also what the last dump wrote)."""
    return _last_bundle


def dump_count() -> int:
    return _dumps


def _counter_totals() -> Dict[str, float]:
    out = {}
    for m in obs_metrics.REGISTRY.metrics():
        if m.type == "counter":
            out[m.name] = m.total()
    return out


def bundle(reason: str, extra: Optional[dict] = None,
           advance_baseline: bool = True) -> dict:
    """Build the diagnostic bundle (no file I/O).  The counter-delta
    baseline advances here, under the lock: concurrent dumps each get a
    consistent window, and even when a later file write fails the
    window's deltas survive in :func:`last_bundle`.
    ``advance_baseline=False`` is for pure observers (the /flight HTTP
    route): they must not shrink the delta window of the next REAL
    crash dump."""
    global _last_counter_snapshot
    totals = _counter_totals()
    with _lock:
        prev = _last_counter_snapshot
        if advance_baseline:
            _last_counter_snapshot = totals
    deltas = {k: v - prev.get(k, 0.0) for k, v in totals.items()
              if v - prev.get(k, 0.0) != 0.0}
    from . import costmodel, forensics, tensorstats
    doc = {
        "schema": "paddle_tpu.flight.v1",
        "reason": reason,
        "time_unix": time.time(),
        "flags": {k: _safe(v) for k, v in flags.all_flags().items()},
        "events": events(),
        "counter_deltas": deltas,
        "program_costs": costmodel.summaries(),
        "compile_log": forensics.compile_log()[-32:],
        "metrics": obs_metrics.REGISTRY.to_json(),
        # the full last tensorstats snapshot (per-variable min/max/rms/
        # NaN counts): on a NumericGuard trip this is the first-bad-
        # layer evidence, frozen into the post-mortem
        "tensor_stats": tensorstats.snapshot_doc(),
    }
    if extra:
        doc["extra"] = {k: _safe(v) for k, v in extra.items()}
    doc = _strict_json(doc)
    # hard size bound: the bundle must stay shippable (one log line /
    # one blob upload); the full registry is the first thing to go,
    # then the per-variable stats matrix (its scalar summary survives
    # in the guard event), then the event ring shrinks
    if len(json.dumps(doc)) > _MAX_BUNDLE_BYTES:
        doc["metrics"] = {"truncated": True}
        if len(json.dumps(doc)) > _MAX_BUNDLE_BYTES \
                and doc.get("tensor_stats"):
            doc["tensor_stats"] = {"truncated": True}
        if len(json.dumps(doc)) > _MAX_BUNDLE_BYTES:
            doc["events"] = doc["events"][-32:]
            doc["truncated_events"] = True
    return doc


def dump(reason: str, extra: Optional[dict] = None,
         path: Optional[str] = None) -> Optional[str]:
    """Build the bundle, remember it, and write it to
    ``flight_recorder_path`` (or `path`) when one is configured.
    Returns the written path, or None for in-memory-only.  Never raises:
    the recorder must not mask the failure it is documenting."""
    global _last_bundle, _dumps
    try:
        doc = bundle(reason, extra)
    except Exception:
        return None
    with _lock:
        _last_bundle = doc
        _dumps += 1
    record("flight", "dump", reason=reason)
    target = path or str(flags.get_flag("flight_recorder_path") or "")
    if not target:
        return None
    try:
        with open(target, "w") as f:
            json.dump(doc, f, allow_nan=False)   # bundle() stringified
        return target                            # every non-finite float
    except (OSError, ValueError):
        return None
