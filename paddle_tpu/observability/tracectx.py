"""Request X-ray: W3C-style trace context + per-request span store.

The observability planes so far (PRs 1/3/4/7) answer aggregate
questions — "what is p99 TTFT", "how often do we recompile".  This
module answers the operator's most common one: *why was THIS request
(or THIS training step) slow?*  It provides:

* Trace identity: 128-bit ``trace_id`` / 64-bit ``span_id`` hex ids and
  ``traceparent`` parsing/formatting per the W3C Trace Context header
  (``00-<trace_id>-<span_id>-<flags>``), so an upstream proxy's trace
  ids flow through ``POST /serving/generate`` into every span this
  process stamps — and back out in the response.
* Ambient context: a ``contextvars``-based current span.  ``span()``
  opens a child of the active context; code deep in the stack (the
  executor's compile path, forensics, chaos) asks :func:`current_trace`
  with no plumbing.  Worker threads that service a request activate its
  context explicitly (serving/batcher.py does).
* The span store: a bounded per-trace dict of finished spans
  (``start_unix``/``start_perf``/``dur``/``parent_id``/attrs), with a
  generation counter and cursor reads so the FleetReporter ships new
  spans incrementally (at-least-once; the aggregator dedupes by
  ``span_id``).  ``waterfall()`` assembles one trace's spans into the
  ``paddle_tpu.xray.v1`` document ``GET /trace/<id>`` serves and the
  ``python -m paddle_tpu.observability.xray`` CLI renders.
* Flight-style capture: :func:`capture` freezes a trace's assembled
  waterfall plus a small metrics excerpt under its trace id (bounded
  ring) — the batcher calls it when a request breaches the
  ``serving_p99_budget_ms`` SLO, so the evidence survives even after
  the span store evicts the trace.

Gated by the ``request_tracing`` flag: when off, ``span()`` is a
zero-allocation no-op context, no ids are minted and nothing is stored
— compile keys, explain() reports and step outputs are byte-identical
to a build without this module (the PR 7 flag-off idiom, tier-1
tested).
"""
from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..core import flags
from . import metrics as obs_metrics

flags.define_flag("request_tracing", True,
                  "Request/step X-ray tracing: per-request trace ids, "
                  "span capture and histogram exemplars.  Off = "
                  "zero-overhead no-ops, byte-identical outputs.")

SCHEMA = "paddle_tpu.xray.v1"
TRACEPARENT_VERSION = "00"

_MAX_TRACES = 512          # traces retained (oldest evicted)
_MAX_SPANS_PER_TRACE = 512  # spans per trace (excess dropped, counted)
_MAX_CAPTURES = 16         # SLO-breach capture bundles retained

_m_spans = obs_metrics.counter(
    "xray_spans_total", "X-ray spans recorded into the local store.")
_m_dropped = obs_metrics.counter(
    "xray_spans_dropped_total",
    "X-ray spans dropped by the per-trace bound.")
_m_captures = obs_metrics.counter(
    "xray_captures_total",
    "SLO-breach trace captures (flight-style bundles keyed by trace "
    "id).", ("reason",))

_lock = threading.Lock()
# trace_id -> list of finished span dicts, insertion-ordered per trace
_traces: Dict[str, List[dict]] = {}
_span_log: List[dict] = []      # flat append-order log (fleet cursor)
_log_base = 0                   # absolute index of _span_log[0]: the
#                                 log trims from the front, so cursors
#                                 are ABSOLUTE positions, not list
#                                 indices (a trim must not shift them)
_generation = 0
_captures: Dict[str, dict] = {}
_capture_seq = 0                # bumped per capture(): the fleet
#                                 reporter's ship-on-change watermark
_rank = 0                       # stamped on every span (fleet identity)


def enabled() -> bool:
    return bool(flags.get_flag("request_tracing"))


def set_rank(rank: int):
    """Identity stamped on locally-recorded spans (the supervisor's
    PTPU elastic workers call this; 0 is the single-process default)."""
    global _rank
    _rank = int(rank)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


class TraceContext:
    """One position in a trace: (trace_id, span_id) plus sampled flag.
    Immutable; children are derived via :func:`span`."""

    __slots__ = ("trace_id", "span_id", "flags")

    def __init__(self, trace_id: str, span_id: str, flags: str = "01"):
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = flags

    def traceparent(self) -> str:
        return (f"{TRACEPARENT_VERSION}-{self.trace_id}-"
                f"{self.span_id}-{self.flags}")

    def __repr__(self):
        return f"TraceContext({self.traceparent()})"


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """W3C ``traceparent`` -> TraceContext; None on anything malformed
    (a bad header must never 500 the request — we just mint fresh)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    ver, tid, sid, fl = parts
    if len(tid) != 32 or len(sid) != 16 or len(ver) != 2:
        return None
    try:
        int(tid, 16), int(sid, 16), int(ver, 16), int(fl, 16)
    except ValueError:
        return None
    if set(tid) == {"0"} or set(sid) == {"0"}:
        return None                     # all-zero ids are invalid per spec
    return TraceContext(tid, sid, fl or "01")


_current: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("ptpu_trace_ctx", default=None)


def current() -> Optional[TraceContext]:
    """The ambient trace context, or None (tracing off OR no active
    trace)."""
    if not enabled():
        return None
    return _current.get()


def current_trace_id() -> Optional[str]:
    ctx = current()
    return ctx.trace_id if ctx is not None else None


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]):
    """Make `ctx` the ambient context for the with-block (worker
    threads servicing a request; the RPC handler).  None = no-op."""
    if ctx is None or not enabled():
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def start_trace(name: str,
                parent: Optional[TraceContext] = None
                ) -> Optional[TraceContext]:
    """Mint trace identity: a fresh trace, or — when `parent` carries
    upstream identity (a traceparent header, an ambient step trace) —
    a child position in that trace (same trace_id, new span_id).
    Identity only: the root SPAN is recorded by whoever owns the
    request lifecycle (batcher ``_finish``, trainer
    ``_record_step_spans``) once its duration is known; ``name`` is
    call-site documentation.  None when tracing is off."""
    if not enabled():
        return None
    if parent is not None:
        return TraceContext(parent.trace_id, new_span_id(), parent.flags)
    return TraceContext(new_trace_id(), new_span_id())


@contextlib.contextmanager
def span(name: str, kind: str = "internal",
         ctx: Optional[TraceContext] = None, **attrs):
    """Record one timed span under the ambient (or given) context.
    Yields the child TraceContext (None when tracing is off or no
    context is active: spans never mint orphan traces by themselves)."""
    parent = ctx if ctx is not None else current()
    if parent is None or not enabled():
        yield None
        return
    child = TraceContext(parent.trace_id, new_span_id(), parent.flags)
    t_unix = time.time()
    t0 = time.perf_counter()
    token = _current.set(child)
    try:
        yield child
    finally:
        _current.reset(token)
        record_span(name, parent.trace_id, child.span_id,
                    parent.span_id, t_unix, t0,
                    time.perf_counter() - t0, kind=kind, attrs=attrs)


def record_span(name: str, trace_id: str, span_id: str,
                parent_id: Optional[str], start_unix: float,
                start_perf: float, dur: float, kind: str = "internal",
                attrs: Optional[Dict[str, Any]] = None):
    """Append one finished span to the store (also the path for spans
    timed outside a with-block, e.g. the batcher's queue-wait)."""
    if not enabled():
        return
    ev = {"name": str(name), "trace_id": trace_id, "span_id": span_id,
          "parent_id": parent_id, "kind": kind, "rank": _rank,
          "start_unix": float(start_unix),
          "start_perf": float(start_perf), "dur": float(dur)}
    if attrs:
        ev["attrs"] = {str(k)[:60]: _safe_attr(v)
                       for k, v in list(attrs.items())[:16]}
    with _lock:
        spans = _traces.get(trace_id)
        if spans is None:
            while len(_traces) >= _MAX_TRACES:
                evicted = next(iter(_traces))
                _traces.pop(evicted)
            spans = _traces[trace_id] = []
        if len(spans) >= _MAX_SPANS_PER_TRACE:
            _m_dropped.inc()
            return
        spans.append(ev)
        _span_log.append(ev)
        # the flat log is a delivery cursor, not an archive: keep it
        # bounded by the same budget the per-trace store implies.  The
        # base offset advances with the trim so outstanding cursors
        # (absolute positions) stay valid — a reporter slower than the
        # trim loses the trimmed window, it does not resend/skip
        # arbitrary spans
        if len(_span_log) > _MAX_TRACES * 64:
            global _log_base
            cut = len(_span_log) // 2
            _log_base += cut
            del _span_log[:cut]
    _m_spans.inc()


def instant(name: str, kind: str = "marker", **attrs):
    """Zero-duration marker under the ambient context (retire events,
    recompile markers)."""
    ctx = current()
    if ctx is None:
        return
    record_span(name, ctx.trace_id, new_span_id(), ctx.span_id,
                time.time(), time.perf_counter(), 0.0, kind=kind,
                attrs=attrs or None)


def _safe_attr(v: Any):
    if isinstance(v, (int, float, bool)) or v is None:
        return v
    if isinstance(v, str):
        return v[:200]
    return repr(v)[:200]


# -- store reads -----------------------------------------------------------

def spans_for(trace_id: str) -> List[dict]:
    with _lock:
        return list(_traces.get(trace_id, ()))


def trace_ids() -> List[str]:
    with _lock:
        return list(_traces)


def generation() -> int:
    return _generation


def spans_since(cursor: int, gen: Optional[int] = None):
    """Atomic (generation, absolute length, tail) read for the
    FleetReporter — same contract as trace.events_since: a generation
    mismatch means reset() wiped the log, so the whole buffer returns.
    Cursors are ABSOLUTE append positions (the log trims from the
    front; ``_log_base`` keeps them stable across trims)."""
    with _lock:
        g = _generation
        start_abs = cursor if gen == g else 0
        idx = max(0, min(start_abs - _log_base, len(_span_log)))
        return g, _log_base + len(_span_log), _span_log[idx:]


def ingest_span(ev: dict):
    """Store an externally-produced span dict verbatim (the aggregator
    path uses its own store; this one is for single-process tooling /
    tests).  Dedupes by span_id within the trace."""
    with _lock:
        spans = _traces.setdefault(ev["trace_id"], [])
        if any(s["span_id"] == ev.get("span_id") for s in spans):
            return
        spans.append(dict(ev))


def reset():
    """Test hook (conftest): wipe traces, captures and the span log;
    bump the generation so cursor consumers resync."""
    global _generation, _log_base
    with _lock:
        _traces.clear()
        _span_log.clear()
        _captures.clear()
        _log_base = 0
        _generation += 1


# -- waterfall assembly ----------------------------------------------------

def build_waterfall(trace_id: str, spans: List[dict],
                    capture: Optional[dict] = None) -> dict:
    """Assemble one trace's spans into the ``paddle_tpu.xray.v1``
    waterfall document: spans sorted by start, offsets relative to the
    trace origin, parent links preserved, per-span rank attribution.
    Works on locally-recorded spans AND on the aggregator's
    clock-normalized fleet spans — the caller supplies them."""
    spans = sorted(spans, key=lambda s: s["start_unix"])
    t0 = spans[0]["start_unix"] if spans else 0.0
    end = max((s["start_unix"] + s["dur"] for s in spans), default=t0)
    ids = {s["span_id"] for s in spans}
    out = []
    for s in spans:
        row = {k: s[k] for k in ("name", "span_id", "kind", "rank",
                                 "dur") if k in s}
        row["offset_s"] = round(s["start_unix"] - t0, 6)
        row["start_unix"] = s["start_unix"]
        parent = s.get("parent_id")
        # a parent outside the collected set (the client's upstream
        # span, or an evicted sibling) renders at top level but keeps
        # the id so nothing silently pretends to be a root
        row["parent_id"] = parent
        row["orphan"] = bool(parent) and parent not in ids
        if s.get("attrs"):
            row["attrs"] = s["attrs"]
        out.append(row)
    doc = {"schema": SCHEMA, "trace_id": trace_id,
           "span_count": len(out), "duration_s": round(end - t0, 6),
           "start_unix": t0, "spans": out}
    if capture is not None:
        doc["capture"] = capture
    return doc


def waterfall(trace_id: str) -> Optional[dict]:
    """The local store's assembled waterfall for one trace (what
    ``GET /trace/<id>`` serves on a worker without an aggregator);
    None when the trace is unknown AND uncaptured."""
    spans = spans_for(trace_id)
    cap = _captures.get(trace_id)
    if not spans and cap is None:
        return None
    if not spans and cap is not None:
        return cap.get("waterfall") or build_waterfall(trace_id, [],
                                                       capture=cap)
    return build_waterfall(trace_id, spans,
                           capture=None if cap is None else
                           {k: v for k, v in cap.items()
                            if k != "waterfall"})


# -- SLO-breach capture ----------------------------------------------------

def capture(trace_id: str, reason: str, **detail) -> Optional[dict]:
    """Freeze a flight-style mini-bundle for one trace: its assembled
    waterfall plus the triggering detail and a timestamp, retrievable
    via ``GET /trace/<id>`` even after span-store eviction.  Bounded
    ring (oldest evicted); one capture per trace id."""
    if not enabled():
        return None
    doc = {"reason": str(reason), "time_unix": time.time(),
           "detail": {k: _safe_attr(v) for k, v in detail.items()},
           "waterfall": build_waterfall(trace_id, spans_for(trace_id))}
    global _capture_seq
    with _lock:
        if trace_id not in _captures:
            while len(_captures) >= _MAX_CAPTURES:
                _captures.pop(next(iter(_captures)))
        _captures[trace_id] = doc
        _capture_seq += 1
    _m_captures.labels(reason=str(reason)).inc()
    from . import flight as obs_flight
    obs_flight.record("xray", "capture", trace_id=trace_id,
                      reason=reason, **detail)
    return doc


def captures() -> Dict[str, dict]:
    with _lock:
        return dict(_captures)


def capture_seq() -> int:
    """Monotonic capture counter — the FleetReporter ships the capture
    dict to the coordinator whenever this moved since its last flush
    (so a worker's SLO-breach evidence is retrievable at the
    coordinator's GET /trace/<id>, not just locally)."""
    return _capture_seq


# Histogram exemplars: every observe() under an active trace records a
# (value, trace_id) exemplar on its bucket (metrics.py keeps the ring;
# registered here so metrics stays import-cycle-free).
obs_metrics.set_exemplar_provider(current_trace_id)
