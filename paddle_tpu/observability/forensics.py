"""Recompile forensics: WHY did the executor compile again?

PR 1's recompile-storm warning (framework/executor.py) can only say
*that* a (program, fetch-list) key keeps compiling; it guesses at the
cause ("shapes/dtypes or mutation").  This module retains the last cache
key per (program, fetch-list) and, on every miss, diffs the new key
component-wise — program version vs feed shapes vs feed dtypes vs
scope-state signature vs fetch names vs numerics flags — so the warning
and the ``compile_log()`` report name the component that actually
churned.  The reference's closest analogue is the Dapper-style habit of
attaching a *cause* to every expensive event; XLA itself logs "hit the
compilation cache miss" with no reason at all.

Causes (the vocabulary of ``executor_recompile_cause_total``):

* ``first_compile``   — no prior key for this (program, fetch-list)
* ``fetch_names``     — same program compiled before, new fetch set
* ``program_version`` — the Program mutated (ops appended/removed)
* ``feed_set``        — feed names added/removed
* ``feed_shapes``     — same feed names, a shape drifted
* ``feed_dtypes``     — same feed names, a dtype drifted
* ``state_signature`` — persistable scope state changed shape/dtype/set
* ``flags``           — a numerics flag (amp_bf16 / pallas) toggled
* ``identical``       — defensive fallback: the jit key changed in a
  component this vocabulary does not model (should not happen)

Retention is scoped per executor (``KeyParts.owner``): two Executors
compiling the same program each get honest ``first_compile`` records
instead of phantom drifts against each other's keys.

Also here: the compile-cache explorer (:func:`cache_report`) listing
every cached executable with its cost/memory summary (costmodel.py).
"""
from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import metrics as obs_metrics

_m_cause = obs_metrics.counter(
    "executor_recompile_cause_total",
    "Executor compilations by diagnosed cache-key drift cause "
    "(observability/forensics.py vocabulary).", ("cause",))

_MAX_LOG = 256          # bounded compile log (newest kept)
_MAX_KEYS = 4096        # bounded key retention (oldest-inserted evicted)

# Monotonic executor ids: id(self) would be reused after GC and make a
# fresh executor inherit a dead one's retained keys (phantom drifts).
_owner_counter = itertools.count(1)


def new_owner() -> int:
    """A process-unique owner id for one executor's jit cache."""
    return next(_owner_counter)


@dataclass
class KeyParts:
    """The cache-key components the executor hands us on every miss.
    ``owner`` scopes retention to ONE executor's jit cache: a second
    Executor compiling the same program is a first compile in ITS
    cache, not a drift against another executor's key."""

    program_uid: int
    program_version: int
    feeds: Tuple[Tuple[str, Tuple[int, ...], str], ...]   # (name, shape, dtype)
    fetch_names: Tuple[str, ...]
    state: Tuple[Tuple[str, Tuple[int, ...], str], ...]
    flags: Tuple[Tuple[str, Any], ...]
    owner: int = 0


@dataclass
class CompileRecord:
    """One diagnosed compilation."""

    ts: float
    program_uid: int
    program_version: int
    fetch_names: Tuple[str, ...]
    causes: List[str]
    details: List[str] = field(default_factory=list)
    # request X-ray: the trace that was active when the miss happened —
    # a recompile TRIGGERED by a request/step names that request in the
    # compile log (and shows inside its waterfall)
    trace_id: str = ""
    # persistent executable cache: "miss" = this compile also missed
    # the on-disk cache (and will be serialized for the next restart).
    # A disk HIT never produces a CompileRecord at all — nothing
    # compiled — so any record under an armed jit_cache_dir is
    # distinguishable from the silent warm path.
    jit_cache: str = ""

    def to_dict(self) -> dict:
        d = {"ts": self.ts, "program": self.program_uid,
             "version": self.program_version,
             "fetches": list(self.fetch_names),
             "causes": list(self.causes),
             "details": list(self.details)}
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.jit_cache:
            d["jit_cache"] = self.jit_cache
        return d


_lock = threading.Lock()
_last_key: Dict[Tuple[int, Tuple[str, ...]], KeyParts] = {}
_cause_counts: Dict[Tuple[int, Tuple[str, ...]], Dict[str, int]] = {}
_log: List[CompileRecord] = []


def reset():
    with _lock:
        _last_key.clear()
        _cause_counts.clear()
        _log.clear()


def _sig_diff(old, new, shape_cause: str, dtype_cause: str,
              set_cause: str) -> List[Tuple[str, str]]:
    """Diff two (name, shape, dtype) signature tuples into
    (cause, detail) pairs."""
    out: List[Tuple[str, str]] = []
    o = {n: (s, d) for n, s, d in old}
    n_ = {n: (s, d) for n, s, d in new}
    added = sorted(set(n_) - set(o))
    removed = sorted(set(o) - set(n_))
    if added or removed:
        out.append((set_cause,
                    f"+{added} -{removed}" if added and removed
                    else (f"+{added}" if added else f"-{removed}")))
    for name in sorted(set(o) & set(n_)):
        (os_, od), (ns, nd) = o[name], n_[name]
        if os_ != ns:
            out.append((shape_cause, f"{name}: {os_}->{ns}"))
        if od != nd:
            out.append((dtype_cause, f"{name}: {od}->{nd}"))
    return out


def diff_keys(old: KeyParts, new: KeyParts) -> List[Tuple[str, str]]:
    """Component-wise diff of two cache keys -> ordered
    (cause, detail) pairs; empty when the keys are identical."""
    out: List[Tuple[str, str]] = []
    if old.program_version != new.program_version:
        out.append(("program_version",
                    f"v{old.program_version}->v{new.program_version}"))
    out += _sig_diff(old.feeds, new.feeds,
                     "feed_shapes", "feed_dtypes", "feed_set")
    if old.fetch_names != new.fetch_names:
        out.append(("fetch_names",
                    f"{list(old.fetch_names)}->{list(new.fetch_names)}"))
    out += _sig_diff(old.state, new.state, "state_signature",
                     "state_signature", "state_signature")
    if old.flags != new.flags:
        od, nd = dict(old.flags), dict(new.flags)
        # symmetric: a flag present only in the OLD key (e.g. the
        # tensorstats variant's appended tensor_stats entry, absent
        # from the plain key) still names itself in the detail
        keys = list(od) + [k for k in nd if k not in od]
        drifted = [f"{k}: {od.get(k)}->{nd.get(k)}"
                   for k in keys if od.get(k) != nd.get(k)]
        out.append(("flags", "; ".join(drifted)))
    return out


def note_compile(parts: KeyParts,
                 jit_cache: str = "") -> CompileRecord:
    """Called by the executor on every compiled-program cache miss.
    Diagnoses the drift cause vs the retained key, updates the per-key
    cause histogram, the cause counter, the bounded compile log and the
    flight recorder; returns the record."""
    fkey = (parts.owner, parts.program_uid, parts.fetch_names)
    with _lock:
        prev = _last_key.pop(fkey, None)
        _last_key[fkey] = parts         # re-insert: LRU-ish ordering
        while len(_last_key) > _MAX_KEYS:
            _last_key.pop(next(iter(_last_key)))
        siblings = any(
            k[0] == parts.owner and k[1] == parts.program_uid
            and k != fkey for k in _last_key)
    if prev is not None:
        pairs = diff_keys(prev, parts)
        # identical: defensive fallback — the jit key changed in a
        # component the forensics vocabulary does not model (should not
        # happen; keeps the record honest if key/KeyParts ever diverge)
        causes = list(dict.fromkeys(c for c, _ in pairs)) or ["identical"]
        details = [f"{c}: {d}" for c, d in pairs]
    elif siblings:
        # same executor compiled this program before, under a different
        # fetch set
        causes, details = ["fetch_names"], [
            f"new fetch set {list(parts.fetch_names)}"]
    else:
        causes, details = ["first_compile"], []
    from . import tracectx
    rec = CompileRecord(ts=time.time(), program_uid=parts.program_uid,
                        program_version=parts.program_version,
                        fetch_names=parts.fetch_names, causes=causes,
                        details=details,
                        trace_id=tracectx.current_trace_id() or "",
                        jit_cache=jit_cache)
    with _lock:
        hist = _cause_counts.setdefault(fkey, {})
        for c in causes:
            hist[c] = hist.get(c, 0) + 1
        while len(_cause_counts) > _MAX_KEYS:
            _cause_counts.pop(next(iter(_cause_counts)))
        _log.append(rec)
        del _log[:-_MAX_LOG]
    _m_cause.labels(cause=causes[0]).inc()
    from . import flight
    flight.record("compile", f"p{parts.program_uid}",
                  version=parts.program_version, causes=causes,
                  detail="; ".join(details)[:200],
                  **({"trace_id": rec.trace_id} if rec.trace_id else {}))
    # the triggering request/step's own timeline shows the recompile
    # as an instant marker (kind=compile) with the diagnosed cause
    tracectx.instant("executor.compile", kind="compile",
                     program=parts.program_uid,
                     version=parts.program_version,
                     cause=causes[0])
    return rec


def cause_histogram(program_uid: int, fetch_names: Tuple[str, ...],
                    owner: Optional[int] = None) -> Dict[str, int]:
    """Cause -> count for one (program, fetch-list) key — what the
    recompile-storm warning names.  ``owner`` restricts to one
    executor's cache (what the executor itself passes); None aggregates
    across executors."""
    out: Dict[str, int] = {}
    with _lock:
        for (own, uid, fetches), hist in _cause_counts.items():
            if uid != program_uid or fetches != fetch_names:
                continue
            if owner is not None and own != owner:
                continue
            for c, n in hist.items():
                out[c] = out.get(c, 0) + n
    return out


def dominant_cause(program_uid: int, fetch_names: Tuple[str, ...],
                   owner: Optional[int] = None) -> str:
    """The most frequent non-first-compile cause for one
    (program, fetch-list) key — what the storm counter's label carries."""
    hist = cause_histogram(program_uid, fetch_names, owner)
    drifting = {c: n for c, n in hist.items() if c != "first_compile"}
    if not drifting:
        return "first_compile"
    return max(sorted(drifting), key=lambda c: drifting[c])


def describe_causes(program_uid: int, fetch_names: Tuple[str, ...],
                    owner: Optional[int] = None) -> str:
    hist = cause_histogram(program_uid, fetch_names, owner)
    drifting = {c: n for c, n in hist.items() if c != "first_compile"}
    if not drifting:
        return "first compiles only"
    return ", ".join(f"{c} x{n}" for c, n in
                     sorted(drifting.items(), key=lambda kv: -kv[1]))


def compile_log(program_uid: Optional[int] = None) -> List[dict]:
    """The bounded log of diagnosed compilations, newest last."""
    with _lock:
        recs = list(_log)
    if program_uid is not None:
        recs = [r for r in recs if r.program_uid == program_uid]
    return [r.to_dict() for r in recs]


def cache_report(executor, compute_costs: bool = True) -> dict:
    """Compile-cache explorer: every executable cached by `executor`
    (step programs AND run_steps device loops) with its cost/memory
    summary.  ``compute_costs=True`` triggers the lazy cost analysis
    for entries whose abstract args are known."""
    programs = []
    for cp in executor._cache.values():
        cost = cp.cost() if compute_costs else cp._cost
        multi = []
        for mkey in cp._multi_cache:
            steps, seq_names = mkey
            mcost = (cp.multi_cost(mkey) if compute_costs
                     else cp._multi_cost.get(mkey))
            multi.append({"steps": steps, "seq_feeds": list(seq_names),
                          "cost": mcost.to_dict() if mcost else None})
        programs.append({
            "program": cp.program._uid,
            "version": cp.program._version,
            "feeds": list(cp.feed_names),
            "fetches": list(cp.fetch_names),
            "state_vars": len(cp.in_state_names),
            "cost": cost.to_dict() if cost else None,
            "multi": multi,
        })
    return {"schema": "paddle_tpu.cache_report.v1",
            "cached_programs": len(programs), "programs": programs}
