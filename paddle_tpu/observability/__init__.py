"""Observability plane: metrics, traces, and compiled-program
introspection.

Submodules:
  * :mod:`.metrics` — Counter/Gauge/Histogram registry with labeled
    series and Prometheus-text / JSON exposition.  The measurement
    substrate every perf PR regress-tests against.
  * :mod:`.trace` — one host-span buffer (RecordEvent scopes, executor
    op/step spans, trainer markers) exported as a single perfetto-
    loadable chrome-trace JSON.
  * :mod:`.costmodel` — XLA ``cost_analysis``/``memory_analysis`` (plus
    a jaxpr-walking analytic fallback) for every compiled program:
    per-program FLOPs / bytes / peak-HBM gauges, ``Executor.explain``
    and the trainer's model-agnostic MFU gauge.
  * :mod:`.forensics` — recompile-cause diagnosis (which cache-key
    component churned), the bounded compile log and the compile-cache
    explorer.
  * :mod:`.flight` — always-on bounded flight recorder; dumps one JSON
    diagnostic bundle on guard trips / retry exhaustion / preemption /
    uncaught trainer exceptions.
  * :mod:`.bench_gate` — ``python -m paddle_tpu.observability.bench_gate``
    compares a bench_metrics.json against a committed BENCH_r*.json
    baseline and exits nonzero on regression.
  * :mod:`.fleet` — the distributed half: worker-side
    ``FleetReporter`` pushes snapshots/spans over the task-queue TCP
    transport; coordinator-side ``FleetAggregator`` merges per-worker
    series (counters sum, histograms merge, gauges keep a ``worker``
    label), tracks liveness/stragglers and merges traces into one
    chrome timeline (pid = rank).  Also the offline
    ``python -m paddle_tpu.observability.fleet --merge-traces`` CLI.
  * :mod:`.server` — live HTTP endpoint (``obs_http_port`` flag):
    ``/metrics`` ``/metrics.json`` ``/healthz`` ``/flight`` ``/model``.
  * :mod:`.tensorstats` — model-health telemetry computed INSIDE the
    compiled train step (``tensor_stats`` flag): per-variable
    min/max/mean/rms, NaN/Inf counts, grad norms and update ratios as
    fused in-graph reductions, fetched as one packed array every Nth
    step.  Feeds the ``model_*`` gauges, the NumericGuard's
    first-bad-layer attribution, the flight bundle, the fleet
    divergence check and the runlog.
  * :mod:`.runlog` — append-only JSONL run history (``runlog_path``
    flag, schema ``paddle_tpu.runlog.v1``) written by the Trainer and
    ``bench.py``; ``python -m paddle_tpu.observability.runlog`` tails,
    step-aligned-diffs (``--compare``) and ASCII-plots it.
  * :mod:`.tracectx` — request X-ray (``request_tracing`` flag): W3C
    traceparent in/out, ambient per-request/per-step trace context,
    bounded span store, histogram exemplars, ``GET /trace/<id>``
    waterfalls (schema ``paddle_tpu.xray.v1``), SLO-breach captures.
  * :mod:`.xray` — ``python -m paddle_tpu.observability.xray`` ASCII
    waterfall renderer (``--self-test`` runs in tier-1).
  * :mod:`.deviceprof` — ``POST /profile`` bounded ``jax.profiler``
    captures tagged with the active trace ids; graceful fallback.
  * :mod:`.alerts` — Watchtower (``alert_rules_path`` flag):
    declarative threshold/rate/absence/burn-rate rules over the local
    or fleet-merged metrics with ``for:`` holds and a pending ->
    firing -> resolved state machine; firing alerts carry exemplar
    trace ids, a flight-bundle ref and the firing rank set; surfaced
    as ``alerts_firing``/``alerts_transitions_total``, ``GET /alerts``
    and the ``alerts --check`` CI validator.
  * :mod:`.journal` — append-only JSONL fleet event journal
    (``journal_path`` flag, schema ``paddle_tpu.journal.v1``):
    supervisor/master/guard/chaos/checkpoint/serving lifecycle events
    shipped to the coordinator and merged into ONE clock-normalized
    fleet timeline (``GET /journal``).
  * :mod:`.incident` — ``python -m paddle_tpu.observability.incident``
    joins the merged journal, alert history and runlog window into one
    ``paddle_tpu.incident.v1`` report + ASCII timeline (``--self-test``
    runs in tier-1).

The instrumented call sites live where the work happens:
framework/executor.py (compile/cache counters, step latency, per-op
timings, cost-model wiring), trainer.py (throughput, loss EMA, memory
watermark, MFU, step anatomy), parallel/parallel_executor.py, bench.py,
reader/decorator.py (buffer depth), distributed/task_queue.py (queue
gauges + fleet RPC verbs).  docs/OBSERVABILITY.md has the catalog.
"""
from __future__ import annotations

# fleet is NOT imported eagerly: it doubles as `python -m
# paddle_tpu.observability.fleet` and runpy warns when the module is
# already in sys.modules (the bench_gate precedent).  server rides with
# it — both load on first use (trainer.py, serve_master callers, tests).
from . import costmodel, flight, forensics, metrics, trace   # noqa: F401
from .metrics import (REGISTRY, Counter, Gauge, Histogram,    # noqa: F401
                      MetricsRegistry, counter, gauge, histogram)
from .trace import export_chrome_trace                        # noqa: F401

import os as _os
import time as _time

# fallback anchor when /proc is unavailable: first paddle_tpu import
_IMPORT_UNIX = _time.time()


def process_start_unix() -> float:
    """Wall-clock time this PROCESS started (not this module): the
    anchor for the cold-start metrics ``restart_to_first_step_seconds``
    (trainer.py) and ``serving_ready_seconds`` (serving/worker.py) —
    a supervisor-respawned worker's restart cost is exec-to-useful,
    which includes interpreter + import time that an import-time
    anchor would hide.  Linux: /proc/self/stat starttime (field 22,
    clock ticks since boot) + /proc/uptime; elsewhere: the time this
    package was imported."""
    try:
        with open("/proc/self/stat") as f:
            stat = f.read()
        # field 2 (comm) may contain spaces — parse after the ')'
        fields = stat.rsplit(")", 1)[1].split()
        start_ticks = float(fields[19])          # starttime, field 22
        hertz = _os.sysconf("SC_CLK_TCK")
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        boot_unix = _time.time() - uptime
        return boot_unix + start_ticks / hertz
    except Exception:
        return _IMPORT_UNIX


_mem_live = metrics.gauge(
    "device_memory_live_bytes",
    "Bytes held by live jax.Arrays on this process's devices.")
_mem_peak = metrics.gauge(
    "device_memory_peak_bytes",
    "High-watermark of device_memory_live_bytes within this process.")
_mem_stats = metrics.gauge(
    "device_memory_stats_bytes",
    "Allocator stats per device (when the backend reports them).",
    ("device", "stat"))


def record_device_memory() -> int:
    """Sample device-memory occupancy into the registry; returns the
    live-bytes figure.  Since the memscope PR this delegates to
    memscope.sample() — ONE measurement path: the legacy
    device_memory_* watermark gauges above always publish, and the
    per-plane census rides the same walk when the memscope flag is on.
    (Lazy import: memscope has a ``python -m`` CLI, and eager
    package-graph imports trip runpy's sys.modules warning.)"""
    from . import memscope

    return memscope.sample(reason="boundary")
