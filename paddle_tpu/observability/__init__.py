"""Observability plane: metrics, traces, and compiled-program
introspection.

Submodules:
  * :mod:`.metrics` — Counter/Gauge/Histogram registry with labeled
    series and Prometheus-text / JSON exposition.  The measurement
    substrate every perf PR regress-tests against.
  * :mod:`.trace` — one host-span buffer (RecordEvent scopes, executor
    op/step spans, trainer markers) exported as a single perfetto-
    loadable chrome-trace JSON.
  * :mod:`.costmodel` — XLA ``cost_analysis``/``memory_analysis`` (plus
    a jaxpr-walking analytic fallback) for every compiled program:
    per-program FLOPs / bytes / peak-HBM gauges, ``Executor.explain``
    and the trainer's model-agnostic MFU gauge.
  * :mod:`.forensics` — recompile-cause diagnosis (which cache-key
    component churned), the bounded compile log and the compile-cache
    explorer.
  * :mod:`.flight` — always-on bounded flight recorder; dumps one JSON
    diagnostic bundle on guard trips / retry exhaustion / preemption /
    uncaught trainer exceptions.
  * :mod:`.bench_gate` — ``python -m paddle_tpu.observability.bench_gate``
    compares a bench_metrics.json against a committed BENCH_r*.json
    baseline and exits nonzero on regression.
  * :mod:`.fleet` — the distributed half: worker-side
    ``FleetReporter`` pushes snapshots/spans over the task-queue TCP
    transport; coordinator-side ``FleetAggregator`` merges per-worker
    series (counters sum, histograms merge, gauges keep a ``worker``
    label), tracks liveness/stragglers and merges traces into one
    chrome timeline (pid = rank).  Also the offline
    ``python -m paddle_tpu.observability.fleet --merge-traces`` CLI.
  * :mod:`.server` — live HTTP endpoint (``obs_http_port`` flag):
    ``/metrics`` ``/metrics.json`` ``/healthz`` ``/flight`` ``/model``.
  * :mod:`.tensorstats` — model-health telemetry computed INSIDE the
    compiled train step (``tensor_stats`` flag): per-variable
    min/max/mean/rms, NaN/Inf counts, grad norms and update ratios as
    fused in-graph reductions, fetched as one packed array every Nth
    step.  Feeds the ``model_*`` gauges, the NumericGuard's
    first-bad-layer attribution, the flight bundle, the fleet
    divergence check and the runlog.
  * :mod:`.runlog` — append-only JSONL run history (``runlog_path``
    flag, schema ``paddle_tpu.runlog.v1``) written by the Trainer and
    ``bench.py``; ``python -m paddle_tpu.observability.runlog`` tails,
    step-aligned-diffs (``--compare``) and ASCII-plots it.

The instrumented call sites live where the work happens:
framework/executor.py (compile/cache counters, step latency, per-op
timings, cost-model wiring), trainer.py (throughput, loss EMA, memory
watermark, MFU, step anatomy), parallel/parallel_executor.py, bench.py,
reader/decorator.py (buffer depth), distributed/task_queue.py (queue
gauges + fleet RPC verbs).  docs/OBSERVABILITY.md has the catalog.
"""
from __future__ import annotations

# fleet is NOT imported eagerly: it doubles as `python -m
# paddle_tpu.observability.fleet` and runpy warns when the module is
# already in sys.modules (the bench_gate precedent).  server rides with
# it — both load on first use (trainer.py, serve_master callers, tests).
from . import costmodel, flight, forensics, metrics, trace   # noqa: F401
from .metrics import (REGISTRY, Counter, Gauge, Histogram,    # noqa: F401
                      MetricsRegistry, counter, gauge, histogram)
from .trace import export_chrome_trace                        # noqa: F401

_mem_live = metrics.gauge(
    "device_memory_live_bytes",
    "Bytes held by live jax.Arrays on this process's devices.")
_mem_peak = metrics.gauge(
    "device_memory_peak_bytes",
    "High-watermark of device_memory_live_bytes within this process.")
_mem_stats = metrics.gauge(
    "device_memory_stats_bytes",
    "Allocator stats per device (when the backend reports them).",
    ("device", "stat"))


def record_device_memory() -> int:
    """Sample device-memory occupancy into the registry; returns the
    live-bytes figure.  Uses jax.live_arrays() (always available) plus
    Device.memory_stats() where the backend provides it (TPU does;
    CPU returns None)."""
    import jax

    if not metrics.enabled():
        return 0
    live = 0
    for a in jax.live_arrays():
        try:
            live += a.nbytes
        except Exception:       # deleted/donated arrays race the walk
            pass
    _mem_live.set(live)
    if live > _mem_peak.value:
        _mem_peak.set(live)
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        if not stats:
            continue
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                _mem_stats.labels(device=str(d.id), stat=key).set(
                    stats[key])
    return live
