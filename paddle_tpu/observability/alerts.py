"""Watchtower: a declarative alert rule engine over the metrics plane.

Everything before this PR *emits* — fleet-merged metrics (PR 4),
exemplar-linked traces (PR 11), flight bundles (PR 3), run history
(PR 7) — but nothing *watches*: an operator learns about a recompile
storm or a dead rank only by scraping ``/metrics`` at the right
moment.  This module closes the loop: declarative rules evaluated over
the local registry (or, on the coordinator, the fleet-merged document)
with hold durations and a pending → firing → resolved state machine.

Rule grammar (JSON; a file named by the ``alert_rules_path`` flag
loads ON TOP of the built-in default set — same-name rules override)::

    {"rules": [
      {"name": "slow_steps",            # unique id
       "metric": "trainer_step_seconds",# any registry/fleet family
       "predicate": "threshold",        # threshold|rate|absence|burn_rate
       "quantile": 0.99,                # histograms: compare this quantile
       "op": ">",                       # > >= < <= == !=
       "value": 0.5,                    # the bar
       "for": 2.0,                      # seconds the breach must hold
       "window": 60.0,                  # rate/burn_rate lookback
       "labels": {"worker": "0"},       # optional exact label subset
       "severity": "critical",          # warning (default) | critical
       "description": "p99 step > 500ms"}]}

Predicates:

* ``threshold`` — compare a series value (gauges/counters: the value;
  histograms: the bucket-interpolated ``quantile``) against ``value``.
* ``rate`` — per-second increase over ``window`` (counters: value;
  histograms: observation count) compared against ``value``.
* ``absence`` — fires while NO series matches (metric missing or every
  matching label set gone) — the dead-exporter/dead-plane alarm.
* ``burn_rate`` — SLO error-budget burn: the fraction of NEW
  observations above bucket bound ``bound`` over ``window``, divided
  by the allowed fraction ``budget``, compared against ``value`` —
  ``value=10`` fires when the budget burns 10x faster than allowed.

Firing alerts carry context for free: newest exemplar trace ids from
the breaching histogram series (or, via the aggregator's per-rank
snapshots, from the firing rank), the latest flight-bundle ref (the
first fire of each rule auto-captures one), the firing rank set, and
an alert trace id whose ``alert.fire``/``alert.resolve`` X-ray
instants resolve at ``GET /trace/<id>``.  Transitions also land in the
fleet event journal (observability/journal.py), so the incident CLI
reconstructs fire/resolve against the rest of the timeline.

Surfaces: ``alerts_firing{rule}`` / ``alerts_transitions_total{rule,
state}`` metrics, the ``GET /alerts`` route (local + fleet-merged),
and ``python -m paddle_tpu.observability.alerts --check rules.json``
(exit 0 valid / 1 invalid naming the rule+field or JSON line / 2 bad
usage — the lint/xray CLI contract).

Gated by ``alert_rules_path``: "" = no engine, no thread, no metrics —
byte-identical outputs and compile keys (regression-tested).  The
sentinel value ``builtin`` enables the default set with no file.
"""
from __future__ import annotations

import json
import operator
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import flags
from . import flight as obs_flight
from . import journal as obs_journal
from . import metrics as obs_metrics

SCHEMA = "paddle_tpu.alerts.v1"

# alert_rules_path / alert_eval_interval are defined in core/flags.py:
# the Trainer gates on the flag BEFORE this (deliberately lazy) module
# ever imports.

_m_firing = obs_metrics.gauge(
    "alerts_firing",
    "Alert series currently in the firing state, by rule.", ("rule",))
_m_transitions = obs_metrics.counter(
    "alerts_transitions_total",
    "Alert state-machine transitions, by rule and entered state "
    "(pending | firing | resolved).", ("rule", "state"))

PREDICATES = ("threshold", "rate", "absence", "burn_rate")
SEVERITIES = ("warning", "critical")
# action: clause verbs (ISSUE 17 Helmsman) — what a FIRING rule may do
# to the fleet when the controller flag is on.  "log" is the dry-run:
# the full decision pipeline (cooldowns, clamps, journal) without an
# actuator call.  spawn_replica/drain_replica (ISSUE 20) scale the
# Armada serving fleet through the router (controller.wire_router).
ACTIONS = ("request_resize", "drain", "revive", "log",
           "spawn_replica", "drain_replica")
# action-clause fields that only make sense on a resize verb
_RESIZE_ONLY_FIELDS = ("direction", "step", "proportional", "max_step",
                       "min_world", "max_world", "immediate")
OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": operator.gt, ">=": operator.ge, "<": operator.lt,
    "<=": operator.le, "==": operator.eq, "!=": operator.ne,
}

_HISTORY_MAX = 256
# per-series (time, value) history for rate/burn_rate.  Samples are
# time-granulated to window/_SAMPLES_PER_WINDOW on append, so the
# deque covers the FULL configured window no matter how often the
# ticker (or /alerts scrapes) evaluate — a raw count cap would shrink
# a 120s lookback to ~13s under a 0.1s ticker.
_SAMPLES_MAX = 128
_SAMPLES_PER_WINDOW = 64


class RuleError(ValueError):
    """A rules file (or rule object) failed validation; the message
    names the file/rule and the offending field."""


class RulesUnreadable(RuleError):
    """The rules file could not be read at all (missing / permission)
    — the ``alerts --check`` exit-2 case, distinct from exit-1
    invalid-content (a typed split, not a message-substring one)."""


class Rule:
    """One declarative alert rule (validated, immutable-by-convention)."""

    __slots__ = ("name", "metric", "predicate", "op", "value",
                 "for_seconds", "window", "quantile", "labels",
                 "severity", "description", "bound", "budget", "source",
                 "context_fn", "action")

    def __init__(self, name: str, metric: str, predicate: str,
                 op: str = ">", value: float = 0.0,
                 for_seconds: float = 0.0, window: float = 60.0,
                 quantile: Optional[float] = None,
                 labels: Optional[Dict[str, str]] = None,
                 severity: str = "warning", description: str = "",
                 bound: Optional[float] = None, budget: float = 0.01,
                 source: str = "file",
                 context_fn: Optional[Callable[
                     [Dict[str, str]], dict]] = None,
                 action: Optional[dict] = None):
        self.name = name
        self.metric = metric
        self.predicate = predicate
        self.op = op
        self.value = float(value)
        self.for_seconds = float(for_seconds)
        self.window = float(window)
        self.quantile = quantile
        self.labels = dict(labels or {})
        self.severity = severity
        self.description = description
        self.bound = bound
        self.budget = float(budget)
        self.source = source
        # code-only hook (NOT a rules-file field): built-in rules whose
        # breaching metric is a bare gauge (no exemplars, no per-rank
        # snapshot) supply their own context — perfscope's
        # perf_regression names the phase + an exemplar trace id
        self.context_fn = context_fn
        # normalized action clause (parse_action) or None; the rule
        # itself never actuates — the controller reads this off
        # firing states the engine hands its action_sink
        self.action = dict(action) if action else None

    def to_dict(self) -> dict:
        d = {"name": self.name, "metric": self.metric,
             "predicate": self.predicate, "op": self.op,
             "value": self.value, "for": self.for_seconds,
             "window": self.window, "severity": self.severity,
             "source": self.source}
        if self.quantile is not None:
            d["quantile"] = self.quantile
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.description:
            d["description"] = self.description
        if self.predicate == "burn_rate":
            d["bound"] = self.bound
            d["budget"] = self.budget
        if self.action:
            d["action"] = dict(self.action)
        return d


def parse_action(obj: Any, where: str, predicate: str) -> dict:
    """Validate one ``action:`` clause -> normalized dict; raises
    :class:`RuleError` naming `where` and the offending field.  Runs
    inside ``alerts --check`` (exit 1 on any of these), so an
    unactuatable clause is a CI failure, not a runtime surprise."""

    def fail(field, why):
        raise RuleError(f"{where}: action field {field!r} {why}")

    if not isinstance(obj, dict):
        raise RuleError(f"{where}: field 'action' must be a JSON "
                        f"object, got {type(obj).__name__}")
    kind = obj.get("kind")
    if kind not in ACTIONS:
        fail("kind", f"must be one of {ACTIONS}, got {kind!r}")
    if predicate == "absence":
        # an absence rule has no numeric observed value — there is
        # nothing to scale a step by and no band to hold, so an action
        # on it is a config error, not a degenerate controller input
        fail("kind", "cannot act on an 'absence' rule (no numeric "
                     "observed value; alert on a gauge instead)")
    known = {"kind", "cooldown", "hysteresis"} | set(_RESIZE_ONLY_FIELDS)
    unknown = sorted(set(obj) - known)
    if unknown:
        fail(unknown[0],
             f"is not an action field (known: {sorted(known)})")
    if kind != "request_resize":
        for f in _RESIZE_ONLY_FIELDS:
            if f in obj:
                fail(f, f"only applies to request_resize actions, "
                        f"not {kind!r}")

    def num(field, lo, integral=False):
        v = obj[field]
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            fail(field, f"must be a number, got {v!r}")
        if integral and int(v) != v:
            fail(field, f"must be an integer, got {v!r}")
        if v < lo:
            fail(field, f"must be >= {lo}, got {v!r}")
        return int(v) if integral else float(v)

    act: Dict[str, Any] = {"kind": kind}
    for field, lo in (("cooldown", 0.0), ("hysteresis", 0.0)):
        if field in obj:
            act[field] = num(field, lo)
    if kind == "request_resize":
        direction = obj.get("direction")
        if direction not in ("grow", "shrink"):
            fail("direction", f"must be 'grow' or 'shrink', "
                              f"got {direction!r}")
        act["direction"] = direction
        for field, lo in (("step", 1), ("max_step", 1),
                          ("min_world", 1), ("max_world", 0)):
            if field in obj:
                act[field] = num(field, lo, integral=True)
        for field in ("proportional", "immediate"):
            if field in obj:
                v = obj[field]
                if not isinstance(v, bool):
                    fail(field, f"must be a boolean, got {v!r}")
                act[field] = v
        if act.get("max_world") and \
                act.get("min_world", 1) > act["max_world"]:
            fail("min_world", f"must be <= max_world, got "
                              f"{act['min_world']} > {act['max_world']}")
    return act


def parse_rule(obj: Any, where: str, source: str = "file") -> Rule:
    """One rule object -> Rule; raises :class:`RuleError` naming
    `where` (file + rule index/name) and the offending field."""
    if not isinstance(obj, dict):
        raise RuleError(f"{where}: rule must be a JSON object, "
                        f"got {type(obj).__name__}")

    def fail(field, why):
        raise RuleError(f"{where}: field {field!r} {why}")

    name = obj.get("name")
    if not isinstance(name, str) or not name:
        fail("name", "must be a non-empty string")
    metric = obj.get("metric")
    predicate = obj.get("predicate", "threshold")
    if predicate not in PREDICATES:
        fail("predicate", f"must be one of {PREDICATES}, "
                          f"got {predicate!r}")
    if not isinstance(metric, str) or not metric:
        fail("metric", "must be a non-empty metric family name")
    op = obj.get("op", ">")
    if op not in OPS:
        fail("op", f"must be one of {tuple(OPS)}, got {op!r}")
    known = {"name", "metric", "predicate", "op", "value", "for",
             "window", "quantile", "labels", "severity", "description",
             "bound", "budget", "action"}
    unknown = sorted(set(obj) - known)
    if unknown:
        fail(unknown[0], f"is not a rule field (known: {sorted(known)})")

    def num(field, default, lo=None):
        v = obj.get(field, default)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            fail(field, f"must be a number, got {v!r}")
        if lo is not None and v < lo:
            fail(field, f"must be >= {lo}, got {v!r}")
        return float(v)

    value = num("value", 0.0)
    for_s = num("for", 0.0, lo=0.0)
    window = num("window", 60.0, lo=0.0)
    quantile = obj.get("quantile")
    if quantile is not None:
        if isinstance(quantile, bool) or \
                not isinstance(quantile, (int, float)) \
                or not 0.0 < float(quantile) < 1.0:
            fail("quantile", f"must be a number in (0, 1), "
                             f"got {quantile!r}")
        if predicate != "threshold":
            fail("quantile", "only applies to threshold rules")
        quantile = float(quantile)
    labels = obj.get("labels") or {}
    if not isinstance(labels, dict) or \
            not all(isinstance(k, str) for k in labels):
        fail("labels", "must be an object of label -> value strings")
    labels = {k: str(v) for k, v in labels.items()}
    severity = obj.get("severity", "warning")
    if severity not in SEVERITIES:
        fail("severity", f"must be one of {SEVERITIES}, "
                         f"got {severity!r}")
    description = obj.get("description", "")
    if not isinstance(description, str):
        fail("description", "must be a string")
    bound = obj.get("bound")
    budget = obj.get("budget", 0.01)
    if predicate == "burn_rate":
        if isinstance(bound, bool) or not isinstance(bound, (int, float)):
            fail("bound", "burn_rate rules need a numeric bucket "
                          "bound (seconds)")
        budget = num("budget", 0.01)
        if not 0.0 < budget <= 1.0:
            fail("budget", f"must be in (0, 1], got {budget!r}")
        bound = float(bound)
    elif bound is not None:
        fail("bound", "only applies to burn_rate rules")
    action = None
    if obj.get("action") is not None:
        action = parse_action(obj["action"], where, predicate)
    return Rule(name=name, metric=metric, predicate=predicate, op=op,
                value=value, for_seconds=for_s, window=window,
                quantile=quantile, labels=labels, severity=severity,
                description=description, bound=bound, budget=budget,
                source=source, action=action)


def load_rules(path: str) -> List[Rule]:
    """Parse a rules file.  Raises :class:`RuleError` naming the line
    (JSON syntax) or the rule index + field (semantics) — the
    malformed-rules contract ``alerts --check`` exits 1 on."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        raise RulesUnreadable(f"{path}: unreadable ({e})") from e
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise RuleError(
            f"{path}:{e.lineno}:{e.colno}: not JSON ({e.msg})") from e
    rules = doc.get("rules") if isinstance(doc, dict) else doc
    if not isinstance(rules, list):
        raise RuleError(
            f"{path}: expected a list of rules (or an object with a "
            f"'rules' list), got {type(doc).__name__}")
    out = []
    names = set()
    for i, obj in enumerate(rules):
        where = f"{path}: rule #{i}"
        if isinstance(obj, dict) and isinstance(obj.get("name"), str):
            where += f" ({obj['name']!r})"
        rule = parse_rule(obj, where)
        if rule.name in names:
            raise RuleError(f"{where}: field 'name' duplicates an "
                            f"earlier rule in this file")
        names.add(rule.name)
        out.append(rule)
    return out


def default_rules() -> List[Rule]:
    """The built-in rule set, constructed from the CURRENT flag values
    (docs/OBSERVABILITY.md has the table).  Rules whose gating flag is
    off (e.g. no serving p99 budget) are omitted rather than inert."""
    out: List[Rule] = []

    def r(**kw):
        out.append(Rule(source="builtin", **kw))

    budget_ms = float(flags.get_flag("serving_p99_budget_ms"))
    if budget_ms > 0:
        r(name="serving_p99_budget",
          metric="serving_token_seconds", predicate="threshold",
          quantile=0.99, op=">", value=budget_ms / 1e3,
          for_seconds=1.0, severity="critical",
          description="serving per-token p99 over serving_p99_budget_ms")
        r(name="ttft_burn_rate",
          metric="serving_ttft_seconds", predicate="burn_rate",
          bound=budget_ms / 1e3, budget=0.01, op=">", value=10.0,
          window=60.0, for_seconds=1.0, severity="critical",
          description="TTFT error budget (1% over the p99 budget) "
                      "burning > 10x its sustainable rate")
    r(name="recompile_storm",
      metric="executor_recompile_storm_total", predicate="rate",
      op=">", value=0.0, window=120.0, severity="critical",
      description="the executor diagnosed a recompile storm "
                  "(forensics names the drifting key component)")
    r(name="dead_rank",
      metric="fleet_worker_dead", predicate="threshold",
      op=">", value=0.0, for_seconds=0.0, severity="critical",
      description="a fleet rank is dead or stale (membership truth / "
                  "report staleness, fleet-merged view only; a "
                  "cleanly-departed rank leaves the family and never "
                  "alarms)")
    r(name="stalled_rank",
      metric="fleet_worker_report_age_seconds", predicate="threshold",
      op=">", value=float(flags.get_flag("healthz_stall_seconds")),
      for_seconds=0.0, severity="warning",
      description="a rank stopped reporting for longer than "
                  "healthz_stall_seconds (the /healthz hung-trainer "
                  "knob — one flag tunes both)")
    r(name="sparse_push_reject_spike",
      metric="sparse_push_rejected_total", predicate="rate",
      op=">", value=1.0, window=30.0, for_seconds=2.0,
      description="sparse staleness rejections spiking (> 1/s): "
                  "workers are re-pulling faster than the staleness "
                  "bound admits — grow sparse_staleness_bound or shed "
                  "load")
    queue_limit = int(flags.get_flag("serving_queue_limit"))
    if queue_limit > 0:
        r(name="queue_saturation",
          metric="serving_queue_depth", predicate="threshold",
          op=">=", value=float(queue_limit), for_seconds=1.0,
          severity="critical",
          description="serving admission queue at its shed bound — "
                      "requests are being 429d")
    r(name="nan_guard",
      metric="trainer_bad_steps_total", predicate="rate",
      op=">", value=0.0, window=120.0, severity="critical",
      description="the numeric guard tripped (NaN/Inf or loss spike; "
                  "the metric's first_var label and the journal carry "
                  "the attribution)")
    r(name="jit_cache_errors",
      metric="jit_cache_errors_total", predicate="rate",
      op=">", value=0.0, window=120.0,
      description="persistent executable cache entries failing to "
                  "load/store (corrupt or stale-build artifacts; "
                  "starts degrade to recompiles)")
    # perfscope regression watch: present only when the perfscope flag
    # is on (the rules-whose-gating-flag-is-off-are-omitted idiom) —
    # the context_fn supplies the offending phase + an exemplar trace
    # id, which a bare gauge series cannot carry itself
    from . import perfscope
    factor = float(flags.get_flag("perf_regression_factor"))
    if perfscope.enabled() and factor > 1.0:
        r(name="perf_regression",
          metric="perf_regression_ratio", predicate="threshold",
          op=">=", value=factor, for_seconds=0.0, severity="warning",
          description="a trainer/serving phase's rolling step-time "
                      "median regressed past perf_regression_factor x "
                      "its frozen baseline (perfscope context names "
                      "the phase + an exemplar trace id)",
          context_fn=perfscope.alert_context)
    # memscope HBM pressure: same gated idiom — the context_fn names
    # the fattest owner plane, which the scalar pressure gauge cannot
    from . import memscope
    pfrac = float(flags.get_flag("memscope_pressure_fraction"))
    if memscope.enabled() and pfrac > 0.0:
        r(name="hbm_pressure",
          metric="mem_pressure_fraction", predicate="threshold",
          op=">=", value=pfrac, for_seconds=1.0, severity="critical",
          description="device memory used/limit held at or above "
                      "memscope_pressure_fraction — the next "
                      "allocation is an OOM candidate (memscope "
                      "context names the fattest plane and top owner)",
          context_fn=memscope.alert_context)
    # Timecard goodput collapse: same gated idiom — the context_fn
    # names the dominant badput state (the scalar fraction says "bad",
    # the breakdown says WHERE the chip-seconds went).  The rule
    # thresholds badput_fraction (the published complement), not
    # goodput_fraction: a labelless gauge's 0.0 default series would
    # make a "goodput low" rule false-fire on a rank that has not
    # tracked any chip-time yet, while 0.0 badput is the safe end
    from . import goodput
    gfrac = float(flags.get_flag("goodput_collapse_fraction"))
    if goodput.enabled() and gfrac > 0.0:
        r(name="goodput_collapse",
          metric="badput_fraction", predicate="threshold",
          op=">=", value=round(1.0 - gfrac, 6),
          for_seconds=float(flags.get_flag("goodput_collapse_for_s")),
          severity="critical",
          description="non-compute's share of tracked chip-seconds "
                      "held at or above 1 - goodput_collapse_fraction "
                      "— the fleet is paying for chips it is not "
                      "training on (goodput context names the "
                      "dominant badput state)",
          context_fn=goodput.alert_context)
    return out


# -- doc plumbing -----------------------------------------------------------

def _match_series(doc: dict, rule: Rule) -> List[dict]:
    fam = (doc.get("metrics") or {}).get(rule.metric)
    if not fam:
        return []
    rows = []
    for row in fam.get("series", []):
        labels = row.get("labels") or {}
        if all(labels.get(k) == v for k, v in rule.labels.items()):
            rows.append(row)
    return rows


def _series_key(row: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v))
                 for k, v in (row.get("labels") or {}).items()))


def _row_count_above(row: dict, bound: float) -> int:
    """Observations strictly above bucket bound `bound` in one
    histogram row (total count minus the cumulative count of buckets
    <= bound; align `bound` with a bucket boundary for exactness)."""
    total = int(row.get("count", 0))
    below = 0
    for b, c in (row.get("buckets") or {}).items():
        if float(b) <= bound + 1e-12:
            below += int(c)
    return max(0, total - below)


class AlertEngine:
    """Rule evaluation + per-(rule, series) state machines.

    ``doc_fn`` supplies the metrics document each evaluation reads —
    the local registry by default; the coordinator wires the
    fleet-merged view (server.metrics_json) so hold durations are
    measured against ONE consistent source.  ``snapshot_provider``
    (rank -> that worker's last metrics doc) lets gauge-rule contexts
    (dead_rank) surface the victim's newest exemplar trace ids."""

    def __init__(self, rules: List[Rule],
                 doc_fn: Optional[Callable[[], dict]] = None,
                 snapshot_provider: Optional[
                     Callable[[int], Optional[dict]]] = None,
                 now_fn: Callable[[], float] = time.time):
        self.rules = list(rules)
        self.doc_fn = doc_fn
        self.snapshot_provider = snapshot_provider
        self._now = now_fn
        self._lock = threading.RLock()
        # (rule, series_key) -> {"state", "since", "value", "labels",
        #                        "context", "fired_unix", ...}
        self._states: Dict[Tuple[str, tuple], dict] = {}
        # (rule, series_key) -> deque[(t, v0, v1)] rate/burn history
        self._samples: Dict[Tuple[str, tuple], deque] = {}
        self._history: deque = deque(maxlen=_HISTORY_MAX)
        self._fired_rules: set = set()
        self._warned_inert: set = set()
        self._eval_count = 0
        self._last_eval_unix: Optional[float] = None
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()
        # Helmsman hook (ISSUE 17): fn(actionable, now) called after
        # each evaluation with the currently-FIRING states whose rule
        # carries an action clause.  Called OUTSIDE the engine lock
        # (actuation does RPCs) and never allowed to raise into the
        # ticker.  None (default) = observe-only Watchtower.
        self.action_sink: Optional[
            Callable[[List[dict], float], None]] = None

    # -- evaluation --------------------------------------------------------
    def evaluate(self, doc: Optional[dict] = None,
                 now: Optional[float] = None) -> dict:
        """One evaluation pass over `doc` (default: this engine's
        doc_fn, else the local registry).  Returns the status
        document.  Thread-safe: the ticker and /alerts scrapes share
        one lock."""
        with self._lock:
            if doc is None:
                doc = self.doc_fn() if self.doc_fn is not None \
                    else obs_metrics.REGISTRY.to_json()
            t = self._now() if now is None else float(now)
            self._eval_count += 1
            self._last_eval_unix = t
            for rule in self.rules:
                self._eval_rule(rule, doc, t)
            self._prune(t)
            status = self._status_locked()
            actionable = self._actionable_locked()
        sink = self.action_sink
        if sink is not None and actionable:
            try:
                sink(actionable, t)
            except Exception as e:   # the watchdog outlives its hands
                obs_flight.record("alert", "action_sink_error",
                                  error=repr(e)[:200])
        return status

    def _actionable_locked(self) -> List[dict]:
        """Firing states whose rule has an action clause (call under
        the lock): the controller's per-tick input.  Each entry is a
        self-contained snapshot — the sink runs outside the lock."""
        if self.action_sink is None:
            return []
        by_name = {r.name: r for r in self.rules}
        out = []
        for (rname, _skey), st in self._states.items():
            rule = by_name.get(rname)
            if rule is None or rule.action is None \
                    or st["state"] != "firing":
                continue
            out.append({"rule": rule, "value": st.get("value"),
                        "labels": dict(st.get("labels") or {}),
                        "context": dict(st.get("context") or {}),
                        "fired_unix": st.get("fired_unix"),
                        "since": st.get("since")})
        # deterministic actuation order: criticals first, then by name
        out.sort(key=lambda e: (e["rule"].severity != "critical",
                                e["rule"].name))
        return out

    # resolved states linger this long for /alerts recent_resolved,
    # then drop — on a churning elastic fleet every (rule, worker)
    # series that ever fired would otherwise accumulate forever
    _RESOLVED_KEEP_S = 3600.0

    def _prune(self, now: float):
        """Bound long-lived engine state (call under the lock): aged
        resolved states drop (history keeps the transition record),
        and rate/burn sample histories for series with no live state
        and no sample within 2x their retention window drop too."""
        for key, st in list(self._states.items()):
            if st["state"] == "resolved" and \
                    now - st.get("resolved_unix", now) \
                    > self._RESOLVED_KEEP_S:
                self._states.pop(key, None)
        windows = {r.name: r.window for r in self.rules}
        for key, dq in list(self._samples.items()):
            horizon = max(windows.get(key[0], 60.0),
                          1.0) * 2.0
            if key not in self._states and \
                    (not dq or now - dq[-1][0] > horizon):
                self._samples.pop(key, None)

    def _eval_rule(self, rule: Rule, doc: dict, now: float):
        rows = _match_series(doc, rule)
        if rule.predicate == "absence":
            self._advance(rule, ("__absent__",), not rows, None,
                          dict(rule.labels), now, None)
            return
        seen = set()
        for row in rows:
            skey = _series_key(row)
            seen.add(skey)
            labels = dict(row.get("labels") or {})
            measured = self._measure(rule, skey, row, now)
            if measured is None:
                continue
            cond = OPS[rule.op](measured, rule.value)
            self._advance(rule, skey, cond, measured, labels, now, row)
        # series that vanished from the doc (a departed worker's gauge)
        # resolve rather than latch firing forever
        for (rname, skey), st in list(self._states.items()):
            if rname == rule.name and skey not in seen \
                    and skey != ("__absent__",) \
                    and st["state"] in ("pending", "firing"):
                self._advance(rule, skey, False, None,
                              st.get("labels", {}), now, None)

    def _measure(self, rule: Rule, skey: tuple, row: dict,
                 now: float) -> Optional[float]:
        is_hist = "buckets" in row
        if rule.predicate == "threshold":
            if is_hist:
                if rule.quantile is None:
                    # a threshold rule pointed at a histogram with no
                    # quantile can never evaluate — a watchdog that
                    # silently doesn't watch must at least say so once
                    if rule.name not in self._warned_inert:
                        self._warned_inert.add(rule.name)
                        warnings.warn(
                            f"alert rule {rule.name!r}: metric "
                            f"{rule.metric!r} is a histogram but the "
                            f"threshold rule has no 'quantile' — the "
                            f"rule matches series it can never "
                            f"evaluate", RuntimeWarning, stacklevel=2)
                    return None
                qs = obs_metrics.histogram_row_quantiles(
                    row, [rule.quantile])
                if qs is None:
                    return None
                return float(qs[f"p{int(round(rule.quantile * 100))}"])
            return float(row.get("value", 0.0))
        if rule.predicate == "rate":
            v = float(row.get("count", 0)) if is_hist \
                else float(row.get("value", 0.0))
            return self._rate_from_history(rule, skey, now, v)
        if rule.predicate == "burn_rate":
            if not is_hist:
                return None
            total = float(row.get("count", 0))
            above = float(_row_count_above(row, rule.bound or 0.0))
            return self._burn_from_history(rule, skey, now, total, above)
        return None

    def _push_sample(self, rule: Rule, skey: tuple, now: float,
                     v0: float, v1: float):
        """Record one (time, v0, v1) sample, time-granulated so the
        bounded deque spans the FULL window: at most one retained
        sample per window/_SAMPLES_PER_WINDOW, trimmed past the
        window.  Returns the deque."""
        dq = self._samples.setdefault((rule.name, skey),
                                      deque(maxlen=_SAMPLES_MAX))
        granule = rule.window / _SAMPLES_PER_WINDOW
        if not dq or granule <= 0 or now - dq[-1][0] >= granule:
            dq.append((now, v0, v1))
        while dq and now - dq[0][0] > rule.window:
            dq.popleft()
        return dq

    def _anchor(self, dq, now: float, window: float):
        """Oldest retained sample still inside the window — what the
        rate/burn deltas measure against."""
        for sample in dq:
            if now - sample[0] <= window:
                return sample
        return None

    def _rate_from_history(self, rule, skey, now, v) -> Optional[float]:
        dq = self._samples.setdefault((rule.name, skey),
                                      deque(maxlen=_SAMPLES_MAX))
        anchor = self._anchor(dq, now, rule.window)
        self._push_sample(rule, skey, now, v, 0.0)
        if anchor is None or now <= anchor[0]:
            return 0.0
        dv = v - anchor[1]
        if dv < 0:
            return 0.0               # restarted process: counter reset
        return dv / (now - anchor[0])

    def _burn_from_history(self, rule, skey, now, total,
                           above) -> Optional[float]:
        dq = self._samples.setdefault((rule.name, skey),
                                      deque(maxlen=_SAMPLES_MAX))
        anchor = self._anchor(dq, now, rule.window)
        self._push_sample(rule, skey, now, total, above)
        if anchor is None:
            return 0.0
        d_total = total - anchor[1]
        d_above = above - anchor[2]
        if d_total <= 0 or d_above < 0:
            return 0.0
        breach_fraction = d_above / d_total
        return breach_fraction / max(rule.budget, 1e-9)

    # -- state machine -----------------------------------------------------
    def _advance(self, rule: Rule, skey: tuple, cond: bool,
                 measured: Optional[float], labels: Dict[str, str],
                 now: float, row: Optional[dict]):
        key = (rule.name, skey)
        st = self._states.get(key)
        if cond:
            if st is None or st["state"] == "resolved":
                st = {"state": "pending", "since": now,
                      "labels": labels, "context": None,
                      "fired_unix": None}
                self._states[key] = st
                self._transition(rule, st, "pending", measured, now)
            st["value"] = measured
            if st["state"] == "pending" \
                    and now - st["since"] >= rule.for_seconds:
                st["state"] = "firing"
                st["fired_unix"] = now
                st["context"] = self._build_context(rule, labels, row,
                                                    measured)
                self._transition(rule, st, "firing", measured, now)
        else:
            if st is not None and st["state"] in ("pending", "firing"):
                was_firing = st["state"] == "firing"
                st["state"] = "resolved"
                st["resolved_unix"] = now
                st["value"] = measured
                if was_firing:
                    self._transition(rule, st, "resolved", measured,
                                     now)
                else:
                    # a pending breach that never held for `for:` just
                    # clears — no resolved noise in history/journal
                    self._states.pop(key, None)
        self._refresh_gauge(rule.name)

    def _refresh_gauge(self, rule_name: str):
        n = sum(1 for (rn, _k), s in self._states.items()
                if rn == rule_name and s["state"] == "firing")
        _m_firing.labels(rule=rule_name).set(n)

    def _transition(self, rule: Rule, st: dict, state: str,
                    measured: Optional[float], now: float):
        _m_transitions.labels(rule=rule.name, state=state).inc()
        rec = {"time_unix": now, "rule": rule.name, "state": state,
               "severity": rule.severity, "value": measured,
               "labels": dict(st.get("labels") or {})}
        if state in ("firing", "resolved") and st.get("context"):
            rec["context"] = st["context"]
        self._history.append(rec)
        obs_flight.record("alert", state, rule=rule.name,
                          value=measured,
                          labels=dict(st.get("labels") or {}))
        if state in ("firing", "resolved"):
            ctx = st.get("context") or {}
            obs_journal.emit(
                "alert", "fire" if state == "firing" else "resolve",
                rule=rule.name, severity=rule.severity, value=measured,
                labels=dict(st.get("labels") or {}),
                alert_trace_id=ctx.get("alert_trace_id"))
            self._xray_instant(rule, st, state, now)

    def _xray_instant(self, rule: Rule, st: dict, state: str,
                      now: float):
        """alert.fire / alert.resolve as zero-duration X-ray spans
        under the alert's OWN trace id, so ``GET /trace/<id>`` renders
        the alert lifecycle like any request."""
        from . import tracectx as obs_tracectx
        if not obs_tracectx.enabled():
            return
        ctx = st.get("context")
        tid = (ctx or {}).get("alert_trace_id")
        if tid is None:
            return
        obs_tracectx.record_span(
            f"alert.{'fire' if state == 'firing' else 'resolve'}",
            tid, obs_tracectx.new_span_id(), None, now,
            time.perf_counter(), 0.0, kind="alert",
            attrs={"rule": rule.name, "severity": rule.severity,
                   "value": st.get("value")})

    # -- context -----------------------------------------------------------
    def _build_context(self, rule: Rule, labels: Dict[str, str],
                       row: Optional[dict],
                       measured: Optional[float]) -> dict:
        from . import tracectx as obs_tracectx
        ctx: Dict[str, Any] = {}
        ranks = sorted({labels["worker"]} if "worker" in labels else [])
        if ranks:
            ctx["ranks"] = ranks
        # exemplar trace ids: the breaching histogram series' own
        # exemplars first; for gauge rules on a labeled rank, that
        # rank's last snapshot (the aggregator keeps it)
        trace_ids = self._exemplar_ids(row)
        if not trace_ids and ranks and self.snapshot_provider:
            for r in ranks:
                try:
                    snap = self.snapshot_provider(int(r))
                except (TypeError, ValueError):
                    snap = None
                trace_ids.extend(self._newest_doc_exemplars(snap))
        if trace_ids:
            ctx["exemplar_trace_ids"] = trace_ids[:4]
        # flight-bundle ref: auto-capture one on the FIRST fire of each
        # rule (the post-mortem evidence), then reference the latest
        if rule.name not in self._fired_rules:
            self._fired_rules.add(rule.name)
            path = obs_flight.dump(
                f"alert:{rule.name}",
                extra={"rule": rule.name, "labels": labels,
                       "value": measured})
            ctx["flight_bundle"] = path or "in-memory"
        last = obs_flight.last_bundle()
        ctx["flight"] = {"dumps": obs_flight.dump_count(),
                         "last_reason": (last or {}).get("reason")}
        if obs_tracectx.enabled():
            ctx["alert_trace_id"] = obs_tracectx.new_trace_id()
        # rule-supplied context (perf_regression: phase + exemplar
        # trace id) — merged last, never clobbering engine keys
        fn = getattr(rule, "context_fn", None)
        if fn is not None:
            try:
                extra = fn(dict(labels))
            except Exception:
                extra = None
            for k, v in (extra or {}).items():
                ctx.setdefault(k, v)
        return ctx

    @staticmethod
    def _exemplar_ids(row: Optional[dict]) -> List[str]:
        if not row:
            return []
        exem = row.get("exemplars") or {}
        ranked = sorted(exem.values(),
                        key=lambda e: -float(e.get("time_unix", 0.0)))
        out = []
        for e in ranked:
            tid = e.get("trace_id")
            if tid and tid not in out:
                out.append(str(tid))
        return out

    @classmethod
    def _newest_doc_exemplars(cls, doc: Optional[dict]) -> List[str]:
        """Newest exemplar trace ids anywhere in a metrics document —
        the 'what was that rank doing' hook for rules that fire on a
        gauge (dead_rank) rather than a histogram."""
        if not isinstance(doc, dict):
            return []
        best: List[Tuple[float, str]] = []
        for fam in (doc.get("metrics") or {}).values():
            for row in fam.get("series", []):
                for e in (row.get("exemplars") or {}).values():
                    tid = e.get("trace_id")
                    if tid:
                        best.append((float(e.get("time_unix", 0.0)),
                                     str(tid)))
        best.sort(reverse=True)
        out = []
        for _t, tid in best:
            if tid not in out:
                out.append(tid)
        return out[:4]

    # -- views -------------------------------------------------------------
    def status_doc(self) -> dict:
        with self._lock:
            return self._status_locked()

    def _status_locked(self) -> dict:
        active = []
        for (rname, skey), st in sorted(self._states.items()):
            row = {"rule": rname, "state": st["state"],
                   "labels": dict(st.get("labels") or {}),
                   "since_unix": st.get("since"),
                   "value": st.get("value")}
            if st.get("fired_unix") is not None:
                row["fired_unix"] = st["fired_unix"]
            if st.get("resolved_unix") is not None:
                row["resolved_unix"] = st["resolved_unix"]
            if st.get("context"):
                row["context"] = st["context"]
            active.append(row)
        return {
            "schema": SCHEMA,
            "time_unix": time.time(),
            "enabled": True,
            "eval_count": self._eval_count,
            "last_eval_unix": self._last_eval_unix,
            "rules": [r.to_dict() for r in self.rules],
            "active": [a for a in active
                       if a["state"] in ("pending", "firing")],
            "recent_resolved": [a for a in active
                                if a["state"] == "resolved"],
            "firing": sorted({a["rule"] for a in active
                              if a["state"] == "firing"}),
            "history": list(self._history),
        }

    # -- ticker ------------------------------------------------------------
    def start_ticker(self):
        if self._ticker is not None and self._ticker.is_alive():
            return
        self._ticker_stop.clear()

        def _loop():
            # clamped: interval <= 0 must not busy-spin a daemon core
            # rebuilding the fleet-merged doc (scrapes still evaluate)
            while not self._ticker_stop.wait(max(
                    0.05, float(flags.get_flag("alert_eval_interval")))):
                try:
                    self.evaluate()
                except Exception:
                    pass     # watching must never take the watched down

        self._ticker = threading.Thread(target=_loop, daemon=True,
                                        name="alert-engine")
        self._ticker.start()

    def stop_ticker(self):
        self._ticker_stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5.0)
            self._ticker = None


# -- module singleton -------------------------------------------------------

_lock = threading.Lock()
_engine: Optional[AlertEngine] = None


def enabled() -> bool:
    return bool(str(flags.get_flag("alert_rules_path") or ""))


def effective_rules() -> List[Rule]:
    """Builtins + the rules file (same-name file rules override), per
    the CURRENT alert_rules_path flag.  Raises RuleError on a bad
    file — ensure_started() softens that to a warning."""
    path = str(flags.get_flag("alert_rules_path") or "")
    by_name = {r.name: r for r in default_rules()}
    if path and path not in ("builtin", "default"):
        for r in load_rules(path):
            by_name[r.name] = r
    return list(by_name.values())


def get_engine() -> Optional[AlertEngine]:
    return _engine


def ensure_started(doc_fn=None, snapshot_provider=None
                   ) -> Optional[AlertEngine]:
    """Flag-gated idempotent engine start (the Trainer's and the HTTP
    server's entry point): returns the process-wide engine with its
    ticker running, or None when alerting is off.  A malformed rules
    file WARNS and falls back to the builtins — alerting must not take
    a training run down (use ``alerts --check`` in CI to reject it
    loudly)."""
    global _engine
    if not enabled():
        return None
    with _lock:
        if _engine is None:
            try:
                rules = effective_rules()
            except RuleError as e:
                warnings.warn(
                    f"alert rules file rejected ({e}); running with "
                    f"the built-in default set only",
                    RuntimeWarning, stacklevel=2)
                rules = default_rules()
            _engine = AlertEngine(rules)
        if doc_fn is not None:
            _engine.doc_fn = doc_fn
        if snapshot_provider is not None:
            _engine.snapshot_provider = snapshot_provider
        _engine.start_ticker()
        return _engine


def reset():
    """Test hook (conftest): stop the ticker, drop the engine, and
    clear the alert metric families so one case's firing state cannot
    leak into the next."""
    global _engine
    with _lock:
        if _engine is not None:
            _engine.stop_ticker()
            _engine = None
    _m_firing.clear()
    _m_transitions.clear()


# -- CLI --------------------------------------------------------------------

def _self_test() -> int:
    """Engine smoke without any live process: a synthetic doc drives a
    threshold rule through pending -> firing -> resolved."""
    rule = Rule(name="probe", metric="m", predicate="threshold",
                op=">", value=1.0, for_seconds=1.0, source="builtin")
    eng = AlertEngine([rule])
    doc_hi = {"metrics": {"m": {"type": "gauge", "help": "",
                                "series": [{"labels": {}, "value": 5.0}]}}}
    doc_lo = {"metrics": {"m": {"type": "gauge", "help": "",
                                "series": [{"labels": {}, "value": 0.0}]}}}
    eng.evaluate(doc_hi, now=100.0)
    s1 = eng.status_doc()
    eng.evaluate(doc_hi, now=102.0)
    s2 = eng.status_doc()
    eng.evaluate(doc_lo, now=103.0)
    s3 = eng.status_doc()
    ok = (s1["active"] and s1["active"][0]["state"] == "pending"
          and s2["firing"] == ["probe"]
          and not s3["firing"]
          and s3["recent_resolved"]
          and s3["schema"] == SCHEMA)
    if not ok:
        print(f"alerts --self-test FAILED: {s1} / {s2} / {s3}")
        return 1
    print("alerts --self-test OK "
          "(pending -> firing -> resolved, schema valid)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.alerts",
        description="Watchtower alert tooling: validate a rules file "
                    "(--check, the CI gate) or list the effective rule "
                    "set.")
    ap.add_argument("--check", metavar="RULES_JSON",
                    help="validate a rules file; exit 0 valid / 1 "
                         "invalid (naming the rule and field, or the "
                         "JSON line) / 2 unreadable or bad usage")
    ap.add_argument("--list", action="store_true",
                    help="print the effective rule set (builtins + "
                         "alert_rules_path) as JSON")
    ap.add_argument("--self-test", action="store_true",
                    help="drive a synthetic rule through the state "
                         "machine and exit")
    args = ap.parse_args(argv)
    if args.self_test:
        return _self_test()
    if args.check:
        try:
            rules = load_rules(args.check)
        except RulesUnreadable as e:
            print(f"alerts: {e}")
            return 2
        except RuleError as e:
            print(f"alerts: INVALID rules file: {e}")
            return 1
        print(f"alerts: {args.check} OK ({len(rules)} rule(s): "
              f"{[r.name for r in rules]})")
        return 0
    if args.list:
        try:
            rules = effective_rules()
        except RuleError as e:
            print(f"alerts: {e}")
            return 1
        print(json.dumps({"schema": SCHEMA,
                          "rules": [r.to_dict() for r in rules]},
                         indent=1))
        return 0
    ap.print_usage()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
