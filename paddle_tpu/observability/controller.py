"""Helmsman: closed-loop self-healing on top of Watchtower (ISSUE 17).

The reference's EDL controller loop (Go master + etcd + the k8s
autoscaler it fed) exists so the fleet *governs itself*: observed load
decides the world size, dead pods respawn, overloaded servers drain —
no human in the loop.  Our reproduction had every sensor (PR 15 alert
rules over the fleet-merged metrics doc) and every actuator (PR 14
``request_resize`` + supervisor park/revive, PR 8 serving drain) but
nothing connecting them.  This module is the connection: alert rules
gain an ``action:`` clause (alerts.parse_action), and a firing rule's
action flows through the policy layer here before anything touches the
fleet.

The policy layer IS the robustness — every clause exists because the
naive "alert fires -> call resize" loop fails in a specific way:

  * per-action-class **cooldowns** + direction-reversal **hysteresis**
    bound the decision rate (no flapping: applied decisions per class
    <= duration/cooldown + 1);
  * **min/max world clamps** make a runaway rule a "clamped" journal
    entry, not a cost incident;
  * burn-proportional **step** sizing (capped by ``max_step``) reacts
    harder to hotter signals without unbounded jumps;
  * a **single-flight** lock per action class plus a **fence token**
    captured from the master's (generation, resizes) at decision time
    means a stale decision — made before a master restart or a
    concurrent resize — is REJECTED by the master, never
    double-applied;
  * actuator failures back off exponentially and a **circuit breaker**
    degrades the controller to alert-only mode after
    ``controller_breaker_threshold`` consecutive failures: a broken
    controller must never be worse than no controller;
  * **state persistence** (``controller_state_path``) lets a restarted
    coordinator resume its cooldown clocks instead of instantly
    re-firing every still-held action.

Every decision — applied or not — is journaled as a
``controller.decision`` event (triggering rule, observed value, action
+ magnitude, fence token, outcome) and carries the alert's trace id,
so ``incident --decision <id>`` reconstructs *why the fleet changed
size*.  Flag ``controller`` off (default): :func:`ensure_started`
returns None, no sink attaches, no thread exists, no events are
emitted — Watchtower stays observe-only (the PR 7 flag-off-invariance
contract).
"""
from __future__ import annotations

import json
import os
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..core import flags
from . import alerts as obs_alerts
from . import flight as obs_flight
from . import journal as obs_journal
from . import metrics as obs_metrics

SCHEMA = "paddle_tpu.controller.v1"
_STATE_SCHEMA = "paddle_tpu.controller_state.v1"

# decision outcomes (the journal/metric vocabulary):
#   applied     — the actuator accepted the action
#   dry_run     — action kind "log": full pipeline, no actuator
#   clamped     — policy reduced the action to a no-op (world already
#                 at the bound / nothing to revive); cooldown still
#                 charges so a pinned rule can't spam
#   fenced      — the master rejected a stale fence token (a resize or
#                 master restart happened after the decision was cut);
#                 NOT a failure: retried next tick with a fresh token
#   failed      — the actuator raised; feeds the backoff/breaker
#   no_actuator — the action kind has no wired actuator (controller on,
#                 hands not connected); visible, cooldown charges
OUTCOMES = ("applied", "dry_run", "clamped", "fenced", "failed",
            "no_actuator")

_m_decisions = obs_metrics.counter(
    "controller_decisions_total",
    "Helmsman decisions by action kind and outcome (see "
    "controller.OUTCOMES; a 'fenced' outcome is a correctness save, "
    "not an error).", ("action", "outcome"))
_m_fence_rejections = obs_metrics.counter(
    "controller_fence_rejections_total",
    "Decisions the master rejected on a stale fence token "
    "(generation/resizes moved between decision and actuation) — "
    "counted, never absorbed: each one is a double-apply that did "
    "NOT happen.")
_m_skips = obs_metrics.counter(
    "controller_skips_total",
    "Firing actionable rules the policy layer declined to act on, "
    "by reason (cooldown | hysteresis | inflight | resize_pending | "
    "backoff | degraded | no_fleet).", ("reason",))
_m_degraded = obs_metrics.gauge(
    "controller_degraded",
    "1 while the circuit breaker holds the controller in alert-only "
    "mode (actuator failures >= controller_breaker_threshold); 0 "
    "otherwise.  Cleared only by reset_breaker().")


def _flag(name: str, override: Any) -> Any:
    return flags.get_flag(name) if override is None else override


class Controller:
    """Policy layer between firing action-rules and the fleet.

    ``fleet_fn`` returns the master's stats doc (target_world_size,
    pending_world_size, generation, resizes, workers) — the fence
    source of truth.  ``actuators`` maps action kind -> callable:

      * ``request_resize``: fn(target_world, fence, immediate) ->
        master reply dict (honours ``fenced``/``applied`` keys);
      * ``drain``:          fn() -> any;
      * ``revive``:         fn(ranks) -> list of revived ranks.

    Decisions arrive via :meth:`consider` — wired as the alert
    engine's ``action_sink``, so the controller runs on the alert
    ticker's clock and owns NO thread of its own."""

    def __init__(self,
                 fleet_fn: Optional[Callable[[], Optional[dict]]] = None,
                 actuators: Optional[Dict[str, Callable]] = None,
                 now_fn: Callable[[], float] = time.time,
                 state_path: Optional[str] = None,
                 pre_actuate: Optional[Callable[[dict], None]] = None):
        self.fleet_fn = fleet_fn
        self.actuators = dict(actuators or {})
        self._now = now_fn
        # test/chaos seam: called with the decision doc after the
        # fence token is cut but BEFORE the actuator runs — the soak's
        # "kill the coordinator mid-decision" window
        self.pre_actuate = pre_actuate
        self.state_path = str(_flag("controller_state_path", state_path)
                              or "")
        self._lock = threading.RLock()
        self._seq = 0
        # action class (= kind) -> unix time of the last decision that
        # charged a cooldown (applied/dry_run/clamped/no_actuator)
        self._last_action: Dict[str, float] = {}
        # last APPLIED resize: (direction, unix time) — hysteresis
        self._last_resize: Optional[List] = None
        # last APPLIED replica-scale action (spawn_replica=grow /
        # drain_replica=shrink, unix time) — the same reversal guard
        # applied to the Armada serving fleet (ISSUE 20)
        self._last_replica: Optional[List] = None
        self._fails: Dict[str, int] = {}       # consecutive failures
        self._retry_at: Dict[str, float] = {}  # post-failure backoff
        self._inflight: set = set()            # single-flight classes
        self.degraded = False
        self._decisions: deque = deque(maxlen=128)
        self._load_state()
        _m_degraded.set(1.0 if self.degraded else 0.0)

    # -- persistence -------------------------------------------------------
    def _load_state(self):
        if not self.state_path or not os.path.exists(self.state_path):
            return
        try:
            with open(self.state_path, encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("schema") != _STATE_SCHEMA:
                raise ValueError(f"unknown schema {doc.get('schema')!r}")
            self._seq = int(doc.get("seq", 0))
            self._last_action = {str(k): float(v) for k, v
                                 in (doc.get("last_action") or {}).items()}
            lr = doc.get("last_resize")
            self._last_resize = [str(lr[0]), float(lr[1])] if lr else None
            lp = doc.get("last_replica")
            self._last_replica = [str(lp[0]), float(lp[1])] if lp \
                else None
            self._fails = {str(k): int(v) for k, v
                           in (doc.get("fails") or {}).items()}
            self._retry_at = {str(k): float(v) for k, v
                              in (doc.get("retry_at") or {}).items()}
            self.degraded = bool(doc.get("degraded", False))
        except (OSError, ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            # a corrupt state file must not brick the coordinator —
            # fresh cooldowns are safe (at worst one early decision)
            warnings.warn(
                f"controller state {self.state_path!r} is unreadable "
                f"({e}); starting with fresh cooldowns",
                RuntimeWarning, stacklevel=3)

    def _save_state(self):
        if not self.state_path:
            return
        doc = {"schema": _STATE_SCHEMA, "seq": self._seq,
               "last_action": self._last_action,
               "last_resize": self._last_resize,
               "last_replica": self._last_replica,
               "fails": self._fails, "retry_at": self._retry_at,
               "degraded": self.degraded,
               "time_unix": self._now()}
        try:
            tmp = self.state_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, self.state_path)    # atomic, like snapshots
        except OSError:
            pass      # persistence is an optimization, never a crash

    # -- policy ------------------------------------------------------------
    def _skip(self, reason: str):
        _m_skips.labels(reason=reason).inc()

    def consider(self, actionable: List[dict],
                 now: Optional[float] = None) -> List[dict]:
        """One policy pass over the engine's firing actionable states
        (the ``action_sink`` signature).  Returns the decision docs it
        cut this pass (empty when everything was skipped)."""
        if not flags.get_flag("controller"):
            return []
        t = self._now() if now is None else float(now)
        out = []
        for ent in actionable:
            dec = self._consider_one(ent, t)
            if dec is not None:
                out.append(dec)
        return out

    def _consider_one(self, ent: dict, now: float) -> Optional[dict]:
        rule = ent["rule"]
        act = rule.action or {}
        kind = act.get("kind")
        if kind not in obs_alerts.ACTIONS:
            return None
        cls = kind
        with self._lock:
            if self.degraded and kind != "log":
                self._skip("degraded")
                return None
            cooldown = float(_flag("controller_cooldown_s",
                                   act.get("cooldown")))
            last = self._last_action.get(cls)
            if last is not None and now - last < cooldown:
                self._skip("cooldown")
                return None
            retry_at = self._retry_at.get(cls)
            if retry_at is not None and now < retry_at:
                self._skip("backoff")
                return None
            if cls in self._inflight:
                self._skip("inflight")
                return None
            if kind in ("request_resize", "revive"):
                fleet = self._fleet()
                if fleet is None:
                    self._skip("no_fleet")
                    return None
            else:
                fleet = self._fleet()
            plan = self._plan(rule, act, ent, fleet, now)
            if plan is None:
                return None
            self._inflight.add(cls)
        try:
            return self._actuate(rule, ent, plan, now)
        finally:
            with self._lock:
                self._inflight.discard(cls)

    def _fleet(self) -> Optional[dict]:
        if self.fleet_fn is None:
            return None
        try:
            return self.fleet_fn()
        except Exception:
            return None

    def _plan(self, rule, act: dict, ent: dict,
              fleet: Optional[dict], now: float) -> Optional[dict]:
        """Turn a firing rule into a concrete decision plan (call under
        the lock).  None = skipped (metrics say why); a plan with
        ``noop`` set journals as "clamped"."""
        kind = act["kind"]
        plan: Dict[str, Any] = {"kind": kind, "magnitude": 0,
                                "noop": False, "fence": None}
        if kind == "request_resize":
            if fleet.get("pending_world_size") is not None:
                # one resize in flight fleet-wide, whoever asked for it
                self._skip("resize_pending")
                return None
            direction = act["direction"]
            hys = float(_flag("controller_hysteresis_s",
                              act.get("hysteresis")))
            if self._last_resize is not None \
                    and self._last_resize[0] != direction \
                    and now - self._last_resize[1] < hys:
                self._skip("hysteresis")
                return None
            world = int(fleet.get("target_world_size") or 0)
            step = self._step(rule, act, ent)
            lo = int(_flag("controller_min_world", act.get("min_world")))
            hi = int(_flag("controller_max_world", act.get("max_world")))
            target = world + step if direction == "grow" \
                else world - step
            target = max(target, lo)
            if hi > 0:
                target = min(target, hi)
            plan.update(direction=direction, magnitude=abs(target - world),
                        target_world=target, old_world=world,
                        noop=target == world,
                        immediate=bool(act.get("immediate", False)),
                        # the fence: actuation is valid only against
                        # the exact fleet this decision observed
                        fence={"generation": int(fleet.get("generation",
                                                           0)),
                               "resizes": int(fleet.get("resizes", 0))})
        elif kind == "revive":
            workers = (fleet or {}).get("workers") or {}
            world = int((fleet or {}).get("target_world_size") or 0)
            dead = sorted(int(r) for r, s in workers.items()
                          if s == "dead" and (world <= 0 or int(r) < world))
            plan.update(ranks=dead, magnitude=len(dead),
                        noop=not dead)
        elif kind == "drain":
            plan.update(magnitude=1)
        elif kind in ("spawn_replica", "drain_replica"):
            # Armada serving-fleet scaling (ISSUE 20): one replica per
            # decision, with the resize-style direction-reversal guard
            # so a spawn cannot chase a drain (or vice versa) inside
            # the hysteresis window
            direction = ("grow" if kind == "spawn_replica"
                         else "shrink")
            hys = float(_flag("controller_hysteresis_s",
                              act.get("hysteresis")))
            if self._last_replica is not None \
                    and self._last_replica[0] != direction \
                    and now - self._last_replica[1] < hys:
                self._skip("hysteresis")
                return None
            plan.update(direction=direction, magnitude=1)
        else:                                    # "log" dry-run
            plan.update(magnitude=0)
        return plan

    def _step(self, rule, act: dict, ent: dict) -> int:
        step = int(act.get("step", 1))
        if act.get("proportional") and rule.value != 0 \
                and ent.get("value") is not None:
            # burn-proportional: a signal at 3x the rule's threshold
            # asks for 3x the step — hotter breach, harder correction
            try:
                ratio = abs(float(ent["value"])) / abs(float(rule.value))
                step = max(step, int(step * ratio))
            except (TypeError, ValueError, ZeroDivisionError):
                pass
        return max(1, min(step, int(_flag("controller_max_step",
                                          act.get("max_step")))))

    # -- actuation ---------------------------------------------------------
    def _actuate(self, rule, ent: dict, plan: dict,
                 now: float) -> dict:
        kind = plan["kind"]
        with self._lock:
            self._seq += 1
            decision_id = f"helm-{self._seq:05d}"
        dec: Dict[str, Any] = {
            "decision_id": decision_id, "time_unix": now,
            "rule": rule.name, "severity": rule.severity,
            "action": kind, "observed": ent.get("value"),
            "magnitude": plan["magnitude"], "fence": plan["fence"],
            "alert_trace_id": (ent.get("context") or {}).get(
                "alert_trace_id"),
        }
        for k in ("direction", "target_world", "old_world", "ranks"):
            if k in plan:
                dec[k] = plan[k]
        error = None
        if plan["noop"]:
            outcome = "clamped"
        elif kind == "log":
            outcome = "dry_run"
        else:
            outcome, error = self._run_actuator(kind, plan, dec)
        dec["outcome"] = outcome
        if error:
            dec["error"] = error
        with self._lock:
            self._settle(kind, plan, outcome, now)
            self._decisions.append(dec)
            self._save_state()
        self._record(dec)
        return dec

    def _run_actuator(self, kind: str, plan: dict, dec: dict):
        """Run the wired actuator through the chaos seam; returns
        (outcome, error)."""
        fn = self.actuators.get(kind)
        if fn is None:
            return "no_actuator", None
        try:
            from ..resilience import chaos
            chaos.trigger("controller.actuate")
            if self.pre_actuate is not None:
                self.pre_actuate(dict(dec))
            if kind == "request_resize":
                reply = fn(plan["target_world"], plan["fence"],
                           plan.get("immediate", False)) or {}
                if reply.get("fenced"):
                    _m_fence_rejections.inc()
                    return "fenced", None
                return "applied", None
            if kind == "revive":
                fn(plan.get("ranks") or [])
                return "applied", None
            fn()
            return "applied", None
        except Exception as e:
            return "failed", repr(e)[:200]

    def _settle(self, kind: str, plan: dict, outcome: str, now: float):
        """Cooldown / hysteresis / breaker bookkeeping (under lock)."""
        cls = kind
        if outcome in ("applied", "dry_run", "clamped", "no_actuator"):
            # every counted decision charges the class cooldown —
            # including clamped ones, or a rule pinned at a bound
            # would journal-spam every tick
            self._last_action[cls] = now
            self._fails.pop(cls, None)
            self._retry_at.pop(cls, None)
            if outcome == "applied" and kind == "request_resize":
                self._last_resize = [plan["direction"], now]
            if outcome == "applied" and kind in ("spawn_replica",
                                                 "drain_replica"):
                self._last_replica = [plan["direction"], now]
        elif outcome == "fenced":
            # a correctness save, not an error and not an action: no
            # cooldown (retry with a fresh token next tick), no
            # breaker strike
            pass
        elif outcome == "failed":
            n = self._fails.get(cls, 0) + 1
            self._fails[cls] = n
            base = float(flags.get_flag("controller_backoff_s"))
            self._retry_at[cls] = now + base * (2 ** (n - 1))
            if not self.degraded and \
                    n >= int(flags.get_flag("controller_breaker_threshold")):
                self._degrade(cls, n, now)

    def _degrade(self, cls: str, fails: int, now: float):
        """Trip the breaker (under lock): alert-only until
        reset_breaker()."""
        self.degraded = True
        _m_degraded.set(1.0)
        obs_journal.emit("controller", "degraded", action=cls,
                         consecutive_failures=fails)
        obs_flight.record("controller", "degraded", action=cls,
                          consecutive_failures=fails)
        warnings.warn(
            f"controller degraded to alert-only mode after {fails} "
            f"consecutive {cls!r} actuator failures; rules keep "
            f"firing, nothing actuates until reset_breaker()",
            RuntimeWarning, stacklevel=4)

    def reset_breaker(self):
        """Operator hook: re-arm a degraded controller."""
        with self._lock:
            was = self.degraded
            self.degraded = False
            self._fails.clear()
            self._retry_at.clear()
            _m_degraded.set(0.0)
            self._save_state()
        if was:
            obs_journal.emit("controller", "breaker_reset")

    def _record(self, dec: dict):
        _m_decisions.labels(action=dec["action"],
                            outcome=dec["outcome"]).inc()
        obs_flight.record("controller", "decision",
                          decision_id=dec["decision_id"],
                          rule=dec["rule"], action=dec["action"],
                          outcome=dec["outcome"],
                          magnitude=dec["magnitude"])
        # time_unix is a journal-reserved field (emit stamps its own);
        # the decision's own clock rides in the record body
        obs_journal.emit("controller", "decision",
                         **{k: v for k, v in dec.items()
                            if k != "time_unix"},
                         decided_unix=dec["time_unix"])
        # X-ray: the decision lands on the triggering alert's own
        # trace, so GET /trace/<id> shows fire -> decision -> resize
        tid = dec.get("alert_trace_id")
        if tid:
            from . import tracectx as obs_tracectx
            if obs_tracectx.enabled():
                obs_tracectx.record_span(
                    f"controller.{dec['action']}", tid,
                    obs_tracectx.new_span_id(), None, dec["time_unix"],
                    time.perf_counter(), 0.0, kind="controller",
                    attrs={"decision_id": dec["decision_id"],
                           "outcome": dec["outcome"],
                           "magnitude": dec["magnitude"]})

    # -- introspection -----------------------------------------------------
    def status_doc(self) -> dict:
        now = self._now()
        with self._lock:
            cooldowns = {}
            for cls, last in sorted(self._last_action.items()):
                cooldowns[cls] = {"last_decision_unix": last,
                                  "age_s": round(now - last, 3)}
            return {
                "schema": SCHEMA, "time_unix": now, "enabled": True,
                "degraded": self.degraded,
                "seq": self._seq,
                "actuators": sorted(self.actuators),
                "breaker": {
                    "consecutive_failures": dict(self._fails),
                    "retry_at": dict(self._retry_at),
                    "threshold": int(flags.get_flag(
                        "controller_breaker_threshold"))},
                "cooldowns": cooldowns,
                "last_resize": list(self._last_resize)
                if self._last_resize else None,
                "last_replica": list(self._last_replica)
                if self._last_replica else None,
                "decisions": [dict(d) for d in self._decisions],
            }


# -- module singleton (the alerts.py idiom) ---------------------------------

_lock = threading.Lock()
_ctrl: Optional[Controller] = None


def enabled() -> bool:
    return bool(flags.get_flag("controller"))


def get_controller() -> Optional[Controller]:
    return _ctrl


def ensure_started(fleet_fn=None, actuators: Optional[dict] = None,
                   state_path: Optional[str] = None,
                   pre_actuate=None) -> Optional[Controller]:
    """Start (or re-wire) the process-wide controller and attach it as
    the alert engine's action sink.  No-op returning None while the
    ``controller`` flag is off — the flag-off path allocates nothing
    and hooks nothing (invariance contract).  Requires the alert plane
    (``alert_rules_path``): a controller with no sensors is refused
    loudly rather than silently idle."""
    if not enabled():
        return None
    engine = obs_alerts.ensure_started()
    if engine is None:
        warnings.warn(
            "controller flag is on but the alert plane is off "
            "(alert_rules_path empty) — the controller has no sensor "
            "input and will not start", RuntimeWarning, stacklevel=2)
        return None
    global _ctrl
    with _lock:
        if _ctrl is None:
            _ctrl = Controller(fleet_fn=fleet_fn, actuators=actuators,
                               state_path=state_path,
                               pre_actuate=pre_actuate)
        else:
            if fleet_fn is not None:
                _ctrl.fleet_fn = fleet_fn
            if actuators:
                _ctrl.actuators.update(actuators)
            if pre_actuate is not None:
                _ctrl.pre_actuate = pre_actuate
        engine.action_sink = _ctrl.consider
        return _ctrl


def wire_master(master, supervisor=None,
                serving_drain: Optional[Callable] = None,
                state_path: Optional[str] = None) -> Optional[Controller]:
    """Convenience wiring for a coordinator that owns an in-process
    TaskMaster (and optionally the Supervisor + serving plane): fleet
    doc from ``master.stats()``; resize actuation goes through the
    master's fenced ``request_resize`` and is mirrored to the
    supervisor AFTER the master accepts (the read-the-resize-log
    discipline — the master's ledger is the truth, the supervisor
    follows it)."""

    def _fleet():
        return master.stats()

    def _resize(target, fence, immediate=False):
        reply = master.request_resize(target, fence=fence,
                                      immediate=immediate)
        if not reply.get("fenced") and supervisor is not None:
            supervisor.set_world_size(target)
        return reply

    actuators: Dict[str, Callable] = {"request_resize": _resize}
    if supervisor is not None:
        actuators["revive"] = supervisor.revive
    if serving_drain is None:
        def serving_drain():
            from .. import serving
            return serving.drain()
    actuators["drain"] = serving_drain
    return ensure_started(fleet_fn=_fleet, actuators=actuators,
                          state_path=state_path)


def wire_router(router, spawn_replica: Optional[Callable] = None,
                state_path: Optional[str] = None
                ) -> Optional[Controller]:
    """Convenience wiring for an Armada router frontend (ISSUE 20):
    ``drain_replica`` actuates the router's graceful scale-down verb
    (least-loaded ready replica stops admitting, then drains);
    ``spawn_replica`` is the fleet owner's grow callback
    (ServingFleet.spawn_replica) when it owns one.  Both kinds run
    through the same fenced single-flight policy layer — cooldowns,
    hysteresis, breaker — and journal as ``controller.decision``."""
    actuators: Dict[str, Callable] = {
        "drain_replica": lambda: router.drain_replica()}
    if spawn_replica is not None:
        actuators["spawn_replica"] = spawn_replica
    return ensure_started(actuators=actuators, state_path=state_path)


def status_doc() -> dict:
    """The ``GET /controller`` document — meaningful even while
    disabled (enabled=False, empty decision list)."""
    ctrl = _ctrl
    if ctrl is not None:
        return ctrl.status_doc()
    return {"schema": SCHEMA, "time_unix": time.time(),
            "enabled": enabled(), "degraded": False, "seq": 0,
            "actuators": [], "breaker": None, "cooldowns": {},
            "last_resize": None, "last_replica": None, "decisions": []}


def reset():
    """Test hook (conftest): detach from the engine, drop the
    singleton, zero the metric families."""
    global _ctrl
    with _lock:
        eng = obs_alerts.get_engine()
        if eng is not None:
            eng.action_sink = None
        _ctrl = None
    _m_decisions.clear()
    _m_fence_rejections.clear()
    _m_skips.clear()
    _m_degraded.clear()
