"""Fleet telemetry: cross-worker metric aggregation and trace merge.

The reference's cloud era ran a coordinator (go/master + pserver) that
could see the whole fleet; PRs 1/3 built a strictly in-process
observability plane.  This module is the distributed half:

* :class:`FleetReporter` — worker side.  Periodically pushes this
  process's metric snapshot (``metrics.REGISTRY.to_json()``), any new
  trace spans and the latest flight-recorder bundle to the coordinator
  over the existing task-queue JSON-lines TCP transport
  (``distributed/task_queue.py`` RPC verbs ``report_metrics`` /
  ``report_events``, payload schema ``paddle_tpu.fleet.v1``).
* :class:`FleetAggregator` — master side.  Merges per-worker series
  into one fleet view (counters sum, histogram buckets merge, gauges
  keep a ``worker`` label), tracks per-worker liveness and step rate,
  warns when a rank straggles behind the fleet median
  (``straggler_factor`` flag), and merges per-worker trace spans into
  ONE perfetto-valid chrome trace (pid = rank, clocks normalized via
  the report-time offset handshake below).

Clock normalization: every payload carries a paired
(``time_unix``, ``perf_counter``) sample taken at send time, and the
master records its own receive time.  A worker's span timestamps (all
``perf_counter`` seconds) map onto the master's wall clock as
``ts + (time_unix - perf_counter) + (recv_unix - time_unix)`` — the
last term absorbs inter-host clock skew (bounded by one RPC transit).

Offline: ``python -m paddle_tpu.observability.fleet --merge-traces
<dir> -o fleet_trace.json`` merges per-rank chrome-trace dumps using
the same normalization (via the ``clock_sync`` metadata
``trace.to_chrome_trace()`` embeds).
"""
from __future__ import annotations

import gzip
import json
import os
import re
import socket
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

from ..core import flags
from . import flight as obs_flight
from . import journal as obs_journal
from . import metrics as obs_metrics
from . import tensorstats as obs_tensorstats
from . import trace as obs_trace

SCHEMA = "paddle_tpu.fleet.v1"

# cap on retained normalized spans per rank (newest win): a fleet trace
# is a debugging artifact, not an unbounded log
_MAX_SPANS_PER_RANK = 100_000
# SLO-breach capture bundles retained at the aggregator, fleet-wide
# (each worker keeps at most tracectx._MAX_CAPTURES=16 of its own)
_MAX_CAPTURE_KEEP = 16

_m_reports = obs_metrics.counter(
    "fleet_reports_total",
    "Fleet reports ingested by the coordinator's FleetAggregator.",
    ("verb",))
_m_report_failures = obs_metrics.counter(
    "fleet_report_failures_total",
    "FleetReporter pushes that failed (coordinator unreachable or "
    "rejected the payload); reporting continues on the next tick.")
_m_stragglers = obs_metrics.counter(
    "fleet_straggler_warnings_total",
    "Straggler warnings emitted by the FleetAggregator (a rank fell "
    "behind the fleet-median step count by > straggler_factor).",
    ("worker",))
_m_divergence = obs_metrics.counter(
    "fleet_grad_divergence_warnings_total",
    "Cross-rank gradient-divergence warnings: same-step per-rank "
    "global grad norms (tensorstats rows shipped by FleetReporter) "
    "differed by more than grad_divergence_factor under data "
    "parallelism — a desynced rank.")


# -- worker side -----------------------------------------------------------

def snapshot_payload(rank: int, closing: bool = False) -> dict:
    """This process's metric snapshot as one versioned fleet payload.
    ``closing=True`` marks a clean departure: the aggregator keeps the
    rank's counters in the fleet sums but stops expecting reports from
    it (no stale/straggler alarms for a worker that finished)."""
    steps = obs_metrics.REGISTRY.get("trainer_steps_total")
    return {
        "schema": SCHEMA,
        "rank": int(rank),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "time_unix": time.time(),
        "perf_counter": time.perf_counter(),
        "steps_total": float(steps.total()) if steps is not None else 0.0,
        "closing": bool(closing),
        "metrics": obs_metrics.REGISTRY.to_json(),
        # model-health row (observability/tensorstats.py): this rank's
        # last sampled grad norm / update ratio / NaN census — what the
        # coordinator's cross-rank divergence check and /model route
        # read.  None until a sample lands (tensor_stats flag off, or
        # no train step yet).
        "model": obs_tensorstats.fleet_row(),
    }


def events_payload(rank: int, spans: List[dict],
                   flight_bundle: Optional[dict] = None,
                   xray_spans: Optional[List[dict]] = None,
                   xray_captures: Optional[Dict[str, dict]] = None,
                   journal_events: Optional[List[dict]] = None
                   ) -> dict:
    """Trace spans (+ optional flight bundle + X-ray spans) as one
    fleet payload.  Span timestamps stay in this process's
    perf_counter seconds; the aggregator normalizes them with the
    clock pair below.  X-ray spans additionally carry their own
    ``span_id`` so at-least-once redelivery (and a restarted worker
    re-shipping its window) dedupes instead of duplicating bars in the
    request waterfall."""
    return {
        "schema": SCHEMA,
        "rank": int(rank),
        "time_unix": time.time(),
        "perf_counter": time.perf_counter(),
        "spans": spans,
        "flight": flight_bundle,
        "xray": xray_spans or [],
        # SLO-breach capture bundles keyed by trace id (shipped when
        # the worker's capture watermark moves): the coordinator's
        # GET /trace/<id> must serve the evidence, not just the worker
        "xray_captures": xray_captures or {},
        # fleet event journal (observability/journal.py): this
        # worker's new lifecycle events; the aggregator normalizes
        # their clocks onto the master timeline and appends them to
        # the coordinator's journal — ONE ordered fleet record
        "journal": journal_events or [],
    }


class FleetReporter:
    """Worker-side push loop: metric snapshots, new trace spans and
    fresh flight bundles go to the coordinator every
    ``fleet_report_interval`` seconds (flag).  Failures are counted and
    absorbed — telemetry must never take the training loop down."""

    def __init__(self, host: str, port: int, rank: int,
                 interval: Optional[float] = None, client=None):
        self.rank = int(rank)
        self.interval = float(interval if interval is not None
                              else flags.get_flag("fleet_report_interval"))
        self._host, self._port = host, int(port)
        self._own_client = client is None
        # dial LAZILY on first flush: workers and coordinator start
        # concurrently, and a constructor that raises ConnectionRefused
        # before the master binds would take the training process down
        # on an observability-only error
        self._client = client
        self._span_cursor = 0
        self._trace_gen = obs_trace.generation()
        self._flight_dumps = obs_flight.dump_count()
        from . import tracectx as obs_tracectx
        self._xray_cursor = 0
        self._xray_gen = obs_tracectx.generation()
        self._xray_capture_seq = obs_tracectx.capture_seq()
        self._journal_cursor = 0
        self._journal_gen = obs_journal.generation()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes flushes: stop()'s closing flush must not interleave
        # frames with a loop flush still stuck in connect/retry on the
        # same (non-thread-safe) client socket
        self._flush_lock = threading.Lock()

    def start(self) -> "FleetReporter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"fleet-reporter-r{self.rank}")
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.flush()
            except Exception:
                _m_report_failures.inc()

    def flush(self, closing: bool = False):
        """One synchronous report: metrics always; events only when new
        spans or a new flight bundle exist since the last flush.  The
        span cursor / flight watermark advance only AFTER a successful
        push, so an unreachable coordinator delays the window instead of
        dropping it (re-sends are at-least-once, like the task-queue
        RPCs; a snapshot is idempotent and duplicate spans are merely
        duplicate trace events)."""
        with self._flush_lock:
            self._flush_locked(closing)

    def _dial(self):
        if self._client is None:
            from ..distributed.task_queue import TaskMasterClient
            self._client = TaskMasterClient(self._host, self._port)
        return self._client

    def _flush_locked(self, closing: bool = False):
        client = self._dial()
        client.report_metrics(snapshot_payload(self.rank,
                                               closing=closing))
        # a generation mismatch means trace.reset() wiped the buffer:
        # everything in it is new (a length heuristic would miss a
        # reset the buffer regrew past before this tick); events_since
        # copies only the tail, not the whole ring, per tick
        gen, total, new_spans = obs_trace.events_since(
            self._span_cursor, self._trace_gen)
        from . import tracectx as obs_tracectx
        xgen, xtotal, new_xray = obs_tracectx.spans_since(
            self._xray_cursor, self._xray_gen)
        cap_seq = obs_tracectx.capture_seq()
        caps = (obs_tracectx.captures()
                if cap_seq != self._xray_capture_seq else None)
        jgen, jtotal, new_journal = obs_journal.events_since(
            self._journal_cursor, self._journal_gen)
        bundle = None
        dumps = obs_flight.dump_count()
        if dumps != self._flight_dumps:
            bundle = obs_flight.last_bundle()
        if new_spans or new_xray or caps or new_journal \
                or bundle is not None:
            self._client.report_events(
                events_payload(self.rank, new_spans, bundle,
                               xray_spans=new_xray,
                               xray_captures=caps,
                               journal_events=new_journal))
        self._span_cursor = total
        self._trace_gen = gen
        self._xray_cursor = xtotal
        self._xray_gen = xgen
        self._xray_capture_seq = cap_seq
        self._journal_cursor = jtotal
        self._journal_gen = jgen
        self._flight_dumps = dumps

    def stop(self, flush: bool = True):
        """Stop the loop; the final flush (when requested) carries the
        ``closing`` mark so the coordinator retires this rank from
        liveness/straggler tracking instead of alarming on it.

        Bounded: when a loop flush is still stuck retrying against a
        dead coordinator (it holds the flush lock through connect
        timeouts), the closing flush is SKIPPED after one interval of
        waiting rather than stacking a second multi-retry cycle on the
        shutdown path — the lease/stale machinery covers an unreported
        departure."""
        self._stop.set()
        loop_alive = False
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval + 5.0)
            loop_alive = self._thread.is_alive()
            self._thread = None
        if flush:
            if self._flush_lock.acquire(timeout=self.interval + 1.0):
                try:
                    self._flush_locked(closing=True)
                except Exception:
                    _m_report_failures.inc()
                finally:
                    self._flush_lock.release()
            else:
                _m_report_failures.inc()
        # never yank the socket from under a loop flush still stuck in
        # connect/retry: the daemon thread (and its socket) die with the
        # process — a leaked fd beats a corrupted in-flight RPC
        if self._own_client and not loop_alive \
                and self._client is not None:
            self._client.close()

    def __enter__(self) -> "FleetReporter":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


# -- merge semantics -------------------------------------------------------

def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def merge_metric_docs(docs: Dict[int, dict]) -> Dict[str, dict]:
    """Merge per-worker ``paddle_tpu.metrics.v1`` documents into one
    fleet family map (name -> {type, help, series}).  Semantics:

    * counters: summed across workers per label set (the fleet total);
    * histograms: bucket counts / sum / count added per label set;
    * gauges (and untyped): kept per-worker under a ``worker`` label —
      a throughput or watermark summed across ranks would lie.
    """
    out: Dict[str, dict] = {}
    for rank in sorted(docs):
        doc = docs[rank] or {}
        for name, m in (doc.get("metrics") or {}).items():
            mtype = m.get("type", "untyped")
            fam = out.setdefault(name, {"type": mtype,
                                        "help": m.get("help", ""),
                                        "series": {}})
            for row in m.get("series", []):
                labels = dict(row.get("labels") or {})
                if mtype == "counter":
                    key = _series_key(labels)
                    ent = fam["series"].setdefault(
                        key, {"labels": labels, "value": 0.0})
                    ent["value"] += float(row.get("value", 0.0))
                elif mtype == "histogram":
                    key = _series_key(labels)
                    ent = fam["series"].setdefault(
                        key, {"labels": labels, "sum": 0.0, "count": 0,
                              "buckets": {}, "overflow": 0})
                    ent["sum"] += float(row.get("sum", 0.0))
                    ent["count"] += int(row.get("count", 0))
                    ent["overflow"] += int(row.get("overflow", 0))
                    for b, c in (row.get("buckets") or {}).items():
                        ent["buckets"][b] = ent["buckets"].get(b, 0) + c
                    if row.get("exemplars"):
                        # exemplar per bucket survives the merge:
                        # newest across ranks wins (each one already
                        # carries its trace id, which is rank-agnostic)
                        ex = ent.setdefault("exemplars", {})
                        for b, e in row["exemplars"].items():
                            if (b not in ex
                                    or float(e.get("time_unix", 0.0))
                                    > float(ex[b].get("time_unix", 0.0))):
                                ex[b] = e
                else:   # gauge / untyped: per-worker series
                    labels["worker"] = str(rank)
                    fam["series"][_series_key(labels)] = {
                        "labels": labels,
                        "value": float(row.get("value", 0.0))}
    return out


def _has_signal(fam: dict) -> bool:
    """True when a merged counter/histogram family carries any actual
    recording (nonzero value / observation count)."""
    for row in fam["series"].values():
        if row.get("value") or row.get("count") or row.get("sum"):
            return True
    return False


def render_prometheus(families: Dict[str, dict],
                      exemplars: bool = False) -> str:
    """Prometheus text for a merged family map — delegates to the
    registry's single exposition renderer so the fleet view can never
    diverge from the local one (exemplars only under OpenMetrics
    negotiation, see metrics.render_prometheus)."""
    return obs_metrics.render_prometheus(families_to_json(families),
                                         exemplars=exemplars)


def families_to_json(families: Dict[str, dict]) -> dict:
    """The merged family map in the registry's JSON schema (series maps
    back to a list)."""
    out = {}
    for name, fam in families.items():
        out[name] = {"type": fam["type"], "help": fam["help"],
                     "series": [fam["series"][k]
                                for k in sorted(fam["series"])]}
    return {"schema": "paddle_tpu.metrics.v1", "metrics": out}


# -- master side -----------------------------------------------------------

class FleetAggregator:
    """Coordinator-side fleet state: the latest metric snapshot, the
    normalized span stream and liveness/step-rate per reporting rank.
    Attach to a task-queue server via ``serve_master(aggregator=...)``
    and to the HTTP endpoint via ``server.start_http_server``."""

    def __init__(self, stale_after: Optional[float] = None,
                 straggler_factor: Optional[float] = None,
                 straggler_min_steps: int = 3,
                 grad_divergence_factor: Optional[float] = None):
        self._lock = threading.Lock()
        self.stale_after = float(
            stale_after if stale_after is not None
            else 3.0 * float(flags.get_flag("fleet_report_interval")))
        self.straggler_factor = float(
            straggler_factor if straggler_factor is not None
            else flags.get_flag("straggler_factor"))
        self.straggler_min_steps = int(straggler_min_steps)
        self.grad_divergence_factor = float(
            grad_divergence_factor if grad_divergence_factor is not None
            else flags.get_flag("grad_divergence_factor"))
        self._workers: Dict[int, dict] = {}
        self._spans: Dict[int, List[dict]] = {}
        self._flights: Dict[int, dict] = {}
        # request X-ray assembly: trace_id -> {span_id: span}, spans
        # from EVERY rank merged on the master's wall clock.  Keyed by
        # span_id so at-least-once redelivery and a restarted worker's
        # re-shipped window dedupe instead of double-drawing bars.
        self._xray: Dict[str, Dict[str, dict]] = {}
        # SLO-breach captures shipped by workers, keyed by trace id
        self._xray_captures: Dict[str, dict] = {}
        # fleet event journal (observability/journal.py): worker
        # lifecycle events normalized onto the master clock, one
        # bounded ordered timeline (and appended to the coordinator's
        # own journal file for durability)
        self._journal: List[dict] = []
        self._straggler_warned: set = set()
        # tensorstats sample steps already diagnosed as diverged (warn
        # once per step, bounded — a desynced rank stays desynced)
        self._divergence_warned: set = set()
        # membership truth pushed by the TaskMaster (register / death /
        # goodbye transitions, wired via serve_master(aggregator=...)):
        # rank -> {"state": live|dead|departed, ...}.  When present it
        # outranks metric-report staleness in health()/straggler logic.
        self._membership: Dict[int, dict] = {}

    # -- ingest (called from the task-queue RPC handler) ---------------
    def ingest(self, verb: str, payload: dict) -> dict:
        if not isinstance(payload, dict) or payload.get("schema") != SCHEMA:
            raise ValueError(
                f"fleet payload schema "
                f"{payload.get('schema') if isinstance(payload, dict) else payload!r} "
                f"!= {SCHEMA}")
        recv = time.time()
        if verb == "report_metrics":
            self.ingest_metrics(payload, recv_unix=recv)
        elif verb == "report_events":
            self.ingest_events(payload, recv_unix=recv)
        else:
            raise ValueError(f"unknown fleet verb {verb!r}")
        _m_reports.labels(verb=verb).inc()
        return {"server_time_unix": recv}

    def _worker(self, payload: dict, recv_unix: float) -> dict:
        rank = int(payload["rank"])
        sent = float(payload["time_unix"])
        w = self._workers.setdefault(rank, {
            "rank": rank, "steps_total": 0.0, "step_rate": 0.0,
            "metrics": None, "host": None, "pid": None,
            "prev_steps": None, "prev_time": None, "departed": False})
        w["last_seen_unix"] = recv_unix
        # offset handshake: worker perf seconds -> master wall clock
        w["offset"] = (sent - float(payload["perf_counter"])
                       + (recv_unix - sent))
        w["skew"] = recv_unix - sent
        return w

    def ingest_metrics(self, payload: dict,
                       recv_unix: Optional[float] = None):
        recv = time.time() if recv_unix is None else recv_unix
        with self._lock:
            w = self._worker(payload, recv)
            steps = float(payload.get("steps_total", 0.0))
            if w["prev_steps"] is not None and recv > w["prev_time"]:
                if steps < w["prev_steps"]:
                    # restarted process: fresh registry, counter went
                    # backwards — a negative rate would be a lie
                    w["step_rate"] = 0.0
                else:
                    w["step_rate"] = ((steps - w["prev_steps"])
                                      / (recv - w["prev_time"]))
            w["prev_steps"], w["prev_time"] = steps, recv
            w["steps_total"] = steps
            w["metrics"] = payload.get("metrics")
            w["host"] = payload.get("host")
            w["pid"] = payload.get("pid")
            # a closing report retires the rank from liveness/straggler
            # tracking (its counters stay in the fleet sums); a later
            # non-closing report (restart) re-enrolls it
            w["departed"] = bool(payload.get("closing"))
            if w["departed"]:
                self._straggler_warned.discard(w["rank"])
            w["model"] = payload.get("model")
            stragglers = self._find_stragglers()
            divergence = self._find_grad_divergence()
        for rank, steps, median in stragglers:
            _m_stragglers.labels(worker=str(rank)).inc()
            warnings.warn(
                f"fleet straggler: rank {rank} at {steps:.0f} steps is "
                f"> {self.straggler_factor:g}x behind the fleet median "
                f"{median:.0f}", RuntimeWarning, stacklevel=2)
        for (epoch, step), lo_rank, lo, hi_rank, hi in divergence:
            _m_divergence.inc()
            pos = (f"step {step}" if epoch < 0
                   else f"epoch {epoch} step {step}")
            warnings.warn(
                f"fleet grad divergence: tensorstats {pos} global "
                f"grad norms differ by > "
                f"{self.grad_divergence_factor:g}x across ranks "
                f"(rank {lo_rank}: {lo:.4g}, rank {hi_rank}: {hi:.4g}) "
                f"— under data parallelism same-step gradients must "
                f"match; a desynced rank (bad collective, silent data "
                f"corruption) looks exactly like this",
                RuntimeWarning, stacklevel=2)

    def note_worker(self, rank: int, state: str, host=None, pid=None,
                    **info):
        """Membership transition from the TaskMaster's heartbeat plane
        (register -> "live", heartbeat-lease expiry -> "dead", goodbye
        -> "departed").  This is ground truth: a rank the master
        declared dead is degraded NOW, not after 3 missed report
        intervals, and a live-heartbeating rank is not "stale" just
        because its metric reporter is quiet."""
        with self._lock:
            self._membership[int(rank)] = {
                "state": str(state), "host": host, "pid": pid,
                "time_unix": time.time()}
            if state in ("dead", "departed"):
                self._straggler_warned.discard(int(rank))

    def membership(self) -> Dict[int, str]:
        with self._lock:
            return {r: m["state"] for r, m in self._membership.items()}

    def ingest_local(self, rank: int):
        """Enroll THIS process as a reporting rank without TCP — for a
        coordinator that also trains.  Its steps then land in the fleet
        sums with proper per-worker attribution (the local overlay in
        :meth:`merged_families` deliberately does NOT add local series
        into fleet sums: that would double-count any process that also
        reports).  Call once per report interval, e.g. from an epoch
        handler, or just before a scrape."""
        self.ingest_metrics(snapshot_payload(rank))

    def ingest_events(self, payload: dict,
                      recv_unix: Optional[float] = None):
        recv = time.time() if recv_unix is None else recv_unix
        with self._lock:
            w = self._worker(payload, recv)
            offset = w["offset"]
            rank = int(payload["rank"])
            spans = self._spans.setdefault(rank, [])
            for e in payload.get("spans") or []:
                ev = dict(e)
                ev["ts"] = float(ev["ts"]) + offset   # unix seconds now
                spans.append(ev)
            if len(spans) > _MAX_SPANS_PER_RANK:
                del spans[:len(spans) - _MAX_SPANS_PER_RANK]
            if payload.get("flight") is not None:
                self._flights[rank] = payload["flight"]
            for e in payload.get("xray") or []:
                self._ingest_xray_span(e, rank, offset)
            journaled = [ev for ev in
                         (self._ingest_journal_event(e, rank, offset)
                          for e in payload.get("journal") or [])
                         if ev is not None]
            for tid, cap in (payload.get("xray_captures") or {}).items():
                if not isinstance(cap, dict):
                    continue
                while len(self._xray_captures) >= 4 * _MAX_CAPTURE_KEEP \
                        and str(tid) not in self._xray_captures:
                    self._xray_captures.pop(
                        next(iter(self._xray_captures)))
                self._xray_captures[str(tid)] = cap
        # the durable append happens OUTSIDE the aggregator lock: a
        # per-event write+flush under it would serialize disk I/O into
        # every fleet RPC and every metrics/healthz scrape
        for ev in journaled:
            obs_journal.append_raw(ev)

    _MAX_XRAY_TRACES = 2048
    _MAX_JOURNAL = 8192

    def _ingest_journal_event(self, e: dict, rank: int,
                              offset: float) -> Optional[dict]:
        """One fleet journal event onto the master clock (call under
        the lock).  ``perf_counter + offset`` — NOT the worker's own
        wall clock — the PR 11 X-ray normalization, so a respawned
        incarnation's fresh perf epoch and a skewed host both land in
        order on ONE timeline; the original sender stamp survives as
        ``worker_time_unix``.  Returns the normalized event so the
        caller can append it to the coordinator's journal file AFTER
        releasing the lock (one durable merged fleet record, without
        disk I/O inside the aggregator's critical section)."""
        try:
            ev = dict(e)
            ev["rank"] = int(ev.get("rank", rank))
            if "perf_counter" in ev:
                ev["worker_time_unix"] = ev.get("time_unix")
                ev["time_unix"] = float(ev["perf_counter"]) + offset
        except (TypeError, ValueError):
            return None                 # malformed event: drop, not 500
        self._journal.append(ev)
        if len(self._journal) > self._MAX_JOURNAL:
            del self._journal[:len(self._journal) - self._MAX_JOURNAL]
        return ev

    def journal_events(self) -> List[dict]:
        """The merged fleet journal timeline, ordered on the master
        clock (what GET /journal serves next to the local ring)."""
        with self._lock:
            out = list(self._journal)
        out.sort(key=lambda r: (float(r.get("time_unix", 0.0) or 0.0),
                                r.get("seq", 0)))
        return out

    def worker_metrics(self, rank: int) -> Optional[dict]:
        """The latest metric snapshot one rank shipped — the alert
        engine's context hook: a dead_rank firing pulls the victim's
        newest exemplar trace ids out of its last snapshot."""
        with self._lock:
            w = self._workers.get(int(rank))
            return w.get("metrics") if w else None

    def _ingest_xray_span(self, e: dict, rank: int, offset: float):
        """One X-ray span onto the master clock (call under the lock).
        ``start_perf + offset`` — NOT the worker's own start_unix — so
        a restarted worker (fresh perf_counter epoch, same request's
        later spans) and a skewed host both land on ONE timeline; the
        offset is re-derived from THIS payload's clock pair, which is
        exactly the sender incarnation that recorded these spans."""
        try:
            ev = dict(e)
            ev["rank"] = int(ev.get("rank", rank))
            ev["start_unix"] = float(ev["start_perf"]) + offset
            tid, sid = str(ev["trace_id"]), str(ev["span_id"])
        except (KeyError, TypeError, ValueError):
            return                      # malformed span: drop, not 500
        spans = self._xray.get(tid)
        if spans is None:
            while len(self._xray) >= self._MAX_XRAY_TRACES:
                self._xray.pop(next(iter(self._xray)))
            spans = self._xray[tid] = {}
        # dedupe by span id: redelivered windows overwrite, identical
        spans[sid] = ev

    def xray_waterfall(self, trace_id: str) -> Optional[dict]:
        """The fleet-assembled ``paddle_tpu.xray.v1`` waterfall for one
        request: spans from router AND workers merged on the master
        clock, the worker-shipped SLO-breach capture attached (what
        ``GET /trace/<id>`` serves on the coordinator)."""
        from . import tracectx as obs_tracectx
        with self._lock:
            spans = list(self._xray.get(trace_id, {}).values())
            cap = self._xray_captures.get(trace_id)
        if not spans and cap is None:
            return None
        if not spans:
            # spans evicted (or never shipped) but the breach evidence
            # survives: serve the capture's own frozen waterfall
            return cap.get("waterfall") or obs_tracectx.build_waterfall(
                trace_id, [], capture=cap)
        return obs_tracectx.build_waterfall(
            trace_id, spans,
            capture=None if cap is None else
            {k: v for k, v in cap.items() if k != "waterfall"})

    def xray_trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._xray)

    def _find_stragglers(self) -> List[Tuple[int, float, float]]:
        """Ranks newly fallen behind median/straggler_factor (call under
        the lock; warning emission happens outside it).  A diagnosed
        rank that catches back up is cleared — /healthz must recover,
        not latch at 503 forever — and warns again on a fresh lapse."""
        live = {r: w for r, w in self._workers.items()
                if not w["departed"]
                and self._membership.get(r, {}).get("state")
                not in ("dead", "departed")}
        if self.straggler_factor <= 1.0 or len(live) < 2:
            # no basis for a diagnosis — and a prior one must not
            # latch /healthz at 503 after the fleet shrinks around it
            self._straggler_warned.clear()
            return []
        counts = sorted(w["steps_total"] for w in live.values())
        n = len(counts)
        median = (counts[n // 2] if n % 2 else
                  0.5 * (counts[n // 2 - 1] + counts[n // 2]))
        if median < self.straggler_min_steps:
            self._straggler_warned.clear()
            return []
        out = []
        for rank, w in live.items():
            behind = w["steps_total"] * self.straggler_factor < median
            if behind and rank not in self._straggler_warned:
                self._straggler_warned.add(rank)
                out.append((rank, w["steps_total"], median))
            elif not behind:
                self._straggler_warned.discard(rank)
        return out

    def _find_grad_divergence(self) -> List[Tuple[Tuple[int, int], int,
                                                  float, int, float]]:
        """Same-step cross-rank grad-norm divergence (call under the
        lock; warning emission outside).  Compares the latest
        tensorstats rows of live ranks that sampled the SAME
        (epoch, step) position — under dp those gradients are
        post-allreduce identical, so a > factor gap means a desynced
        rank.  Returns ((epoch, step), min_rank, min_norm, max_rank,
        max_norm) tuples, one per newly-diagnosed position."""
        if self.grad_divergence_factor <= 1.0:
            return []
        by_step: Dict[Tuple[int, int], List[Tuple[int, float]]] = {}
        for r, w in self._workers.items():
            row = w.get("model")
            if w["departed"] or not isinstance(row, dict):
                continue
            if self._membership.get(r, {}).get("state") in ("dead",
                                                            "departed"):
                continue
            try:
                # (epoch, step-in-epoch) from the trainer's resumable
                # position — a respawned worker's dispatch counter
                # restarts at 0, so a bare step would either never
                # re-align with the survivors or collide with a
                # different training step; epoch -1 = direct executor
                # users with no trainer position
                epoch = row.get("epoch")
                step = (int(epoch) if epoch is not None else -1,
                        int(row["step"]))
                norm = float(row["grad_norm"])
            except (KeyError, TypeError, ValueError):
                continue
            if not (norm == norm and abs(norm) != float("inf")):
                continue         # non-finite norms are the guard's
                                 # problem, not a sync diagnosis
            by_step.setdefault(step, []).append((r, norm))
        out = []
        for step, rows in by_step.items():
            if len(rows) < 2 or step in self._divergence_warned:
                continue
            lo_rank, lo = min(rows, key=lambda kv: kv[1])
            hi_rank, hi = max(rows, key=lambda kv: kv[1])
            if hi > self.grad_divergence_factor * max(lo, 1e-30):
                self._divergence_warned.add(step)
                if len(self._divergence_warned) > 1024:
                    self._divergence_warned = set(sorted(
                        self._divergence_warned)[-512:])
                out.append((step, lo_rank, lo, hi_rank, hi))
        return out

    # -- fleet views ---------------------------------------------------
    def workers(self) -> Dict[int, dict]:
        with self._lock:
            return {r: {k: v for k, v in w.items() if k != "metrics"}
                    for r, w in self._workers.items()}

    def model_rows(self) -> Dict[int, dict]:
        """Latest per-rank tensorstats rows (what /model serves)."""
        with self._lock:
            return {r: w["model"] for r, w in self._workers.items()
                    if isinstance(w.get("model"), dict)}

    def perf_rows(self) -> Dict[str, dict]:
        """Per-rank roofline rows reconstructed from each worker's
        last shipped metric snapshot (perf_* gauge families) — the
        fleet-merged half of GET /perf."""
        from . import perfscope as obs_perfscope
        with self._lock:
            docs = {r: w.get("metrics") for r, w in self._workers.items()
                    if isinstance(w.get("metrics"), dict)}
        return {str(r): obs_perfscope.rows_from_metrics_doc(doc)
                for r, doc in sorted(docs.items())}

    def mem_rows(self) -> Dict[str, dict]:
        """Per-rank census rows reconstructed from each worker's last
        shipped metric snapshot (mem_*/serving_kv_* gauge families) —
        the fleet-merged half of GET /memory."""
        from . import memscope as obs_memscope
        with self._lock:
            docs = {r: w.get("metrics") for r, w in self._workers.items()
                    if isinstance(w.get("metrics"), dict)}
        return {str(r): obs_memscope.rows_from_metrics_doc(doc)
                for r, doc in sorted(docs.items())}

    def goodput_rows(self) -> Dict[str, dict]:
        """Per-rank chip-time breakdown reconstructed from each
        worker's last shipped metric snapshot (chip_seconds_total /
        goodput_fraction families) — the fleet-merged half of
        GET /goodput."""
        from . import goodput as obs_goodput
        with self._lock:
            docs = {r: w.get("metrics") for r, w in self._workers.items()
                    if isinstance(w.get("metrics"), dict)}
        return {str(r): obs_goodput.rows_from_metrics_doc(doc)
                for r, doc in sorted(docs.items())}

    def health(self) -> dict:
        """Liveness summary for /healthz: per-worker report age, stale
        set, straggler set, and the fleet degraded verdict."""
        now = time.time()
        with self._lock:
            per = {}
            stale = []
            dead = []
            ranks = sorted(set(self._workers) | set(self._membership))
            for rank in ranks:
                w = self._workers.get(rank, {})
                mem = self._membership.get(rank, {}).get("state")
                age = now - w.get("last_seen_unix", 0.0) \
                    if w else float("inf")
                departed = bool(w.get("departed")) or mem == "departed"
                # membership outranks report-age inference: a rank the
                # master's heartbeat plane declares dead is degraded
                # immediately; a live-heartbeating rank is not stale no
                # matter how quiet its metric reporter is; a
                # cleanly-departed rank stops aging out entirely
                if mem == "dead":
                    is_stale = False
                    dead.append(rank)
                elif mem == "live":
                    is_stale = False
                else:
                    is_stale = (bool(w) and age > self.stale_after
                                and not departed)
                if is_stale:
                    stale.append(rank)
                per[str(rank)] = {
                    "host": w.get("host")
                    or self._membership.get(rank, {}).get("host"),
                    "pid": w.get("pid")
                    or self._membership.get(rank, {}).get("pid"),
                    "steps_total": w.get("steps_total", 0.0),
                    "step_rate": round(w.get("step_rate", 0.0), 3),
                    "last_report_age_s":
                        round(age, 3) if w else None,
                    "stale": is_stale,
                    "departed": departed,
                    "membership": mem,
                }
            stragglers = sorted(self._straggler_warned)
        return {"workers": len(per), "per_worker": per, "stale": stale,
                "dead": dead, "stragglers": stragglers,
                "stale_after_s": self.stale_after,
                "degraded": bool(stale or stragglers or dead)}

    def merged_families(self, local: Optional[dict] = None
                        ) -> Dict[str, dict]:
        """Fleet-merged family map, optionally overlaid on a local
        registry document, plus synthesized ``fleet_worker_*`` gauges.

        Overlay semantics per family: gauges UNION (fleet series carry
        a ``worker`` label, local ones don't); counters/histograms with
        fleet signal REPLACE the local series (the coordinator's
        zero-valued trainer counters must not shadow the fleet's), and
        all-zero fleet families yield to populated local ones (workers
        eagerly declare unlabeled metrics at 0).  Local counters are
        never ADDED into fleet sums — a coordinator that also trains
        should enroll itself via :meth:`ingest_local` so its counts
        carry per-worker attribution instead."""
        with self._lock:
            docs = {r: w["metrics"] for r, w in self._workers.items()
                    if w.get("metrics")}
        fleet = merge_metric_docs(docs)
        out: Dict[str, dict] = {}
        if local:
            for name, m in (local.get("metrics") or {}).items():
                fam = {"type": m.get("type", "untyped"),
                       "help": m.get("help", ""), "series": {}}
                for row in m.get("series", []):
                    labels = dict(row.get("labels") or {})
                    ent = dict(row)
                    ent["labels"] = labels
                    fam["series"][_series_key(labels)] = ent
                out[name] = fam
        for name, fam in fleet.items():
            local_fam = out.get(name)
            if local_fam is None:
                out[name] = fam
            elif fam["type"] in ("gauge", "untyped"):
                # gauges coexist: fleet series carry a worker label,
                # local ones don't — one family, disjoint label sets
                merged = dict(local_fam["series"])
                merged.update(fam["series"])
                out[name] = {**fam, "series": merged}
            elif _has_signal(fam):
                out[name] = fam
            # else: an all-zero fleet counter/histogram family (workers
            # declare unlabeled metrics eagerly at value 0, e.g. every
            # worker's taskmaster_lease_expired_total) carries no
            # information — keep the coordinator's local series
        h = self.health()
        # merge, don't clobber: the coordinator's local registry carries
        # the TaskMaster's fleet_workers{state} membership gauges in the
        # same family; the label sets are disjoint (unlabeled count vs
        # state=...), so both coexist
        fw = out.setdefault("fleet_workers", {
            "type": "gauge",
            "help": "Workers that have reported to the FleetAggregator "
                    "(unlabeled) / task-master membership by state.",
            "series": {}})
        fw["series"][()] = {"labels": {}, "value": float(h["workers"])}
        up = {"type": "gauge",
              "help": "1 when the rank reported within stale_after "
                      "seconds, else 0.", "series": {}}
        age = {"type": "gauge",
               "help": "Seconds since the rank's last fleet report.",
               "series": {}}
        rate = {"type": "gauge",
                "help": "Rank step rate (steps/s) between its last two "
                        "reports.", "series": {}}
        dead = {"type": "gauge",
                "help": "1 when the rank is DEAD (heartbeat-declared) "
                        "or stale without reports; cleanly-departed "
                        "ranks leave the family entirely — the "
                        "dead_rank alert keys on this, so a goodbye "
                        "is not an alarm.", "series": {}}
        for rank, w in h["per_worker"].items():
            labels = {"worker": rank}
            key = _series_key(labels)
            up["series"][key] = {
                "labels": labels,
                "value": 0.0 if (w["stale"] or w["departed"]
                                 or w.get("membership") == "dead")
                else 1.0}
            # a cleanly-departed rank's report age grows forever and
            # means nothing — leave it out of the family (like
            # fleet_worker_dead below) so the stalled_rank alert can't
            # latch a permanent false alarm on every scale-down
            if not w["departed"]:
                age["series"][key] = {
                    "labels": labels,
                    "value": w["last_report_age_s"]
                    if w["last_report_age_s"] is not None else -1.0}
            rate["series"][key] = {"labels": labels,
                                   "value": w["step_rate"]}
            if not w["departed"]:
                dead["series"][key] = {
                    "labels": labels,
                    "value": 1.0 if (w["stale"]
                                     or w.get("membership") == "dead")
                    else 0.0}
        out["fleet_worker_up"] = up
        out["fleet_worker_report_age_seconds"] = age
        out["fleet_worker_step_rate"] = rate
        out["fleet_worker_dead"] = dead
        return out

    def prometheus_text(self, local: Optional[dict] = None,
                        exemplars: bool = False) -> str:
        return render_prometheus(self.merged_families(local),
                                 exemplars=exemplars)

    def flight_bundles(self) -> Dict[int, dict]:
        with self._lock:
            return dict(self._flights)

    def merged_trace(self) -> dict:
        """ONE chrome trace for the fleet: pid = rank, per-rank process
        metadata, every span on the master's wall clock (µs since the
        earliest fleet event)."""
        with self._lock:
            per_rank = {r: list(evs) for r, evs in self._spans.items()}
            hosts = {r: (w.get("host"), w.get("pid"))
                     for r, w in self._workers.items()}
        return _compose_trace(
            {r: (evs, hosts.get(r, (None, None))[0])
             for r, evs in per_rank.items()})


def _compose_trace(per_rank: Dict[int, Tuple[List[dict], Optional[str]]]
                   ) -> dict:
    """Build the merged chrome trace from {rank: (normalized-seconds
    events, host)}.  Shared by the live aggregator and the offline CLI."""
    all_ts = [e["ts"] for evs, _ in per_rank.values() for e in evs]
    t0 = min(all_ts) if all_ts else 0.0
    out: List[dict] = []
    body: List[dict] = []
    for rank in sorted(per_rank):
        evs, host = per_rank[rank]
        pname = f"rank {rank}" + (f" ({host})" if host else "")
        out.append({"name": "process_name", "ph": "M", "pid": rank,
                    "tid": 0, "args": {"name": pname}})
        tids = sorted({int(e.get("tid", 0)) for e in evs})
        for tid in tids:
            lane = obs_trace._LANE_NAMES.get(tid, f"tid {tid}")
            out.append({"name": "thread_name", "ph": "M", "pid": rank,
                        "tid": tid, "args": {"name": lane}})
        for e in evs:
            ev = {"name": e["name"], "ph": e["ph"], "pid": rank,
                  "tid": int(e.get("tid", 0)),
                  "ts": (e["ts"] - t0) * 1e6,
                  "cat": e.get("cat", "host")}
            if e["ph"] == "X":
                ev["dur"] = float(e.get("dur", 0.0)) * 1e6
            if e["ph"] == "i":
                ev["s"] = "t"
            if e.get("args"):
                ev["args"] = e["args"]
            body.append(ev)
    body.sort(key=lambda e: e["ts"])
    return {"traceEvents": out + body, "displayTimeUnit": "ms",
            "metadata": {"fleet_ranks": sorted(per_rank),
                         "t0_unix": t0}}


# -- offline trace merge ---------------------------------------------------

def _load_trace_file(path: str) -> dict:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def _rank_of(path: str, fallback: int) -> int:
    """Rank from the filename's last integer group (trace.0.json,
    rank1_trace.json, ...), else the file's sort position."""
    groups = re.findall(r"\d+", os.path.basename(path))
    return int(groups[-1]) if groups else fallback


def merge_trace_files(paths: List[str],
                      out_path: Optional[str] = None) -> dict:
    """Merge per-rank chrome-trace dumps into one fleet trace — the
    offline twin of :meth:`FleetAggregator.merged_trace`.  Files whose
    ``metadata.clock_sync`` is present (every dump from
    ``trace.export_chrome_trace``) normalize exactly like live reports:
    event µs -> that process's wall clock.  Files without it fall back
    to aligning their earliest event at the fleet origin."""
    loaded = []      # (rank, raw events, clock offset-or-None)
    for i, path in enumerate(sorted(paths)):
        doc = _load_trace_file(path)
        if "traceEvents" not in doc:
            continue             # not a chrome trace (e.g. a result
                                 # json in the same dir)
        if "fleet_ranks" in (doc.get("metadata") or {}):
            continue             # OUR OWN merged output from a prior
                                 # run — re-ingesting it would duplicate
                                 # every event under a bogus rank
        events = [e for e in doc["traceEvents"]
                  if e.get("ph") != "M"]
        sync = (doc.get("metadata") or {}).get("clock_sync") or {}
        if "time_unix" in sync and "perf_counter" in sync:
            # exported ts are perf_counter µs; shift onto the wall clock
            offset = (float(sync["time_unix"])
                      - float(sync["perf_counter"]))
        else:
            offset = None
        loaded.append((_rank_of(path, i), events, offset, path))
    # files WITHOUT clock_sync (pre-fleet or foreign dumps) can't be
    # cross-correlated; align their earliest event at the fleet origin
    # — the earliest clock-synced timestamp when one exists (NOT unix
    # zero, which would strand the synced ranks ~epoch-seconds away)
    synced_start = min(
        (e["ts"] / 1e6 + off for _, evs, off, _p in loaded
         if off is not None for e in evs), default=0.0)
    per_rank: Dict[int, Tuple[List[dict], Optional[str]]] = {}
    for rank, events, offset, path in loaded:
        if offset is None:
            offset = synced_start - min(
                (e["ts"] for e in events), default=0.0) / 1e6
        norm = []
        for e in events:
            ev = dict(e)
            ev["ts"] = e["ts"] / 1e6 + offset         # unix seconds
            if "dur" in ev:
                ev["dur"] = ev["dur"] / 1e6           # seconds
            norm.append(ev)
        orig = rank
        while rank in per_rank:     # duplicate filename ranks: next slot
            rank += 1
        if rank != orig:
            # silent remapping would mislead whoever is debugging a
            # specific rank's timeline — name the file and the new pid
            warnings.warn(
                f"merge-traces: rank {orig} already taken; events from "
                f"{os.path.basename(path)} appear under pid {rank}",
                RuntimeWarning, stacklevel=2)
        per_rank[rank] = (norm, None)
    merged = _compose_trace(per_rank)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged


def _main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.fleet",
        description="Merge per-rank chrome-trace dumps into one "
                    "perfetto-valid fleet trace (pid = rank).")
    ap.add_argument("--merge-traces", metavar="DIR", required=True,
                    help="directory of per-rank trace .json/.json.gz "
                         "dumps")
    ap.add_argument("-o", "--output", default="fleet_trace.json",
                    help="merged trace path (default fleet_trace.json)")
    args = ap.parse_args(argv)
    out_abs = os.path.abspath(args.output)
    paths = sorted(
        p for p in (os.path.join(args.merge_traces, n)
                    for n in os.listdir(args.merge_traces))
        if (p.endswith(".json") or p.endswith(".json.gz"))
        and os.path.abspath(p) != out_abs)   # -o inside the input dir
    if not paths:
        ap.error(f"no .json/.json.gz traces under {args.merge_traces}")
    merged = merge_trace_files(paths, out_path=args.output)
    spans = sum(1 for e in merged["traceEvents"] if e.get("ph") != "M")
    print(f"merged {len(paths)} trace(s), "
          f"{len(merged['metadata']['fleet_ranks'])} rank(s), "
          f"{spans} events -> {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
