"""Memscope: live HBM attribution, OOM forensics, KV occupancy.

The memory tier's sensing layer (the role perfscope plays for time):
the reference framework ships memory_optimization_transpiler and
contrib/memory_usage_calc but never *observes* residency — this module
closes that loop with four instruments, all behind the ``memscope``
flag (default off: byte-identical outputs and compile keys, zero
step-path work — the invariance idiom shared with tensorstats/
perfscope/journal):

  census      jax.live_arrays() + Device.memory_stats() walked at step/
              dispatch boundaries (and an optional bounded ticker),
              attributing resident bytes per owner plane — params,
              optimizer_state, serving_kv, sparse_tables,
              jit_executables, executor_feeds, other — into
              mem_resident_bytes{plane} / mem_device_used_bytes /
              mem_device_free_bytes / mem_pressure_fraction.
  reconcile   per compiled program, the cost model's predicted
              peak_hbm_bytes joined with the measured high-water mark:
              mem_peak_ratio{program} + a drift verdict surfaced by
              Executor.explain(memory=True).
  kv ledger   DecodeEngine slab occupancy: reserved-vs-written
              positions per slot (and per prompt bucket) →
              serving_kv_reserved_bytes / serving_kv_written_bytes /
              serving_kv_waste_fraction — the number that makes the
              paged-KV case (ROADMAP item 1) quantitatively.
  forensics   the memory.alloc chaos site (simulated
              RESOURCE_EXHAUSTED at executor/serving dispatch) dumps a
              flight bundle carrying the census + top-K owners + the
              triggering program's cost row, journals a
              memory/alloc_failure event for ``incident``, and the
              built-in hbm_pressure Watchtower rule names the fattest
              plane in its context.

Satellite contract: observability.record_device_memory() (the PR 1
trainer watermark path) delegates to sample() here, so the legacy
device_memory_* gauges and the census are ONE measurement path — the
old names stay valid for dashboards and runlogs.

CLI: ``python -m paddle_tpu.observability.memscope`` (top-N owners,
--doc, --self-test).  HTTP: GET /memory (fleet-merged per rank).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from ..core import flags
from . import flight
from . import journal
from . import metrics

SCHEMA = "paddle_tpu.mem.v1"

# Same metric objects observability/__init__.py registered at import
# (the registry returns the existing instance for a same-shape name):
# the legacy watermark names keep publishing from the unified path.
_m_live = metrics.gauge(
    "device_memory_live_bytes",
    "Bytes held by live jax.Arrays on this process's devices.")
_m_peak = metrics.gauge(
    "device_memory_peak_bytes",
    "High-watermark of device_memory_live_bytes within this process.")
_m_stats = metrics.gauge(
    "device_memory_stats_bytes",
    "Allocator stats per device (when the backend reports them).",
    ("device", "stat"))

_m_resident = metrics.gauge(
    "mem_resident_bytes",
    "Census: resident bytes attributed to one owner plane.", ("plane",))
_m_used = metrics.gauge(
    "mem_device_used_bytes",
    "Census: allocator bytes_in_use per device (live bytes on "
    "backends without allocator stats).", ("device",))
_m_free = metrics.gauge(
    "mem_device_free_bytes",
    "Census: device budget minus used bytes (needs a bytes_limit "
    "stat or the memscope_hbm_limit_bytes flag).", ("device",))
_m_pressure = metrics.gauge(
    "mem_pressure_fraction",
    "Max over devices of used/limit — what the built-in hbm_pressure "
    "alert watches.")
_m_ratio = metrics.gauge(
    "mem_peak_ratio",
    "Measured high-water bytes / cost-model predicted peak_hbm_bytes "
    "per compiled program.", ("program",))
_m_kv_reserved = metrics.gauge(
    "serving_kv_reserved_bytes",
    "KV slab bytes reserved by active decode slots (active_slots x "
    "max_len worth of positions).")
_m_kv_written = metrics.gauge(
    "serving_kv_written_bytes",
    "KV slab bytes actually written (sum of active slot lengths).")
_m_kv_waste = metrics.gauge(
    "serving_kv_waste_fraction",
    "1 - written/reserved over active decode slots: the worst-case "
    "over-reservation a paged KV cache would reclaim.")
_m_kv_bucket = metrics.gauge(
    "serving_kv_bucket_waste_fraction",
    "Per prompt-bucket KV waste fraction.", ("bucket",))

# Optimizer accumulators are named "{opt}.{param}.{acc}" (see
# optimizer.py _add_accumulator) — these substrings split the
# executor-scope plane into params vs optimizer_state.
_OPT_MARKERS = ("velocity", "moment", "_pow", "grad_acc", "mean_square")

_lock = threading.RLock()
_state: Dict[str, Any] = {}
_programs: Dict[str, Dict[str, Any]] = {}
# Providers survive reset() on purpose: engines/shards register once at
# construction, and conftest resets between tests while module-scoped
# fixtures live on.  WeakSets drop dead providers automatically.
_kv_engines: "weakref.WeakSet" = weakref.WeakSet()
_sparse_shards: "weakref.WeakSet" = weakref.WeakSet()
# Scopes seen at dispatch boundaries: the Trainer (and any caller of
# Executor(scope=...)) runs against a PRIVATE scope, not the global
# one — without tracking these the census would file its params under
# "other".
_scopes: "weakref.WeakSet" = weakref.WeakSet()
_ticker: Optional[threading.Thread] = None
_ticker_stop: Optional[threading.Event] = None


def enabled() -> bool:
    return bool(flags.get_flag("memscope"))


# --- provider registry -----------------------------------------------------

def register_kv_engine(engine) -> None:
    """Called by DecodeEngine.__init__ (construction-time, not step
    path): lets the census claim the engine's KV slabs."""
    try:
        _kv_engines.add(engine)
    except TypeError:
        pass


def register_sparse_shard(shard) -> None:
    """Called by sparse EmbeddingShard.__init__: host-side table bytes
    join the census as the sparse_tables plane."""
    try:
        _sparse_shards.add(shard)
    except TypeError:
        pass


# --- the census ------------------------------------------------------------

def sample(reason: str = "tick") -> int:
    """The unified device-memory measurement path.  Always publishes
    the legacy device_memory_* watermark gauges (what
    observability.record_device_memory() did since PR 1); when the
    memscope flag is on, additionally attributes the live set per
    owner plane and refreshes the mem_* gauges.  Returns live bytes."""
    import jax

    if not metrics.enabled():
        return 0
    live = 0
    arrays: List[Any] = []
    for a in jax.live_arrays():
        try:
            nb = int(a.nbytes)
        except Exception:       # deleted/donated arrays race the walk
            continue
        live += nb
        arrays.append((nb, a))
    _m_live.set(live)
    if live > _m_peak.value:
        _m_peak.set(live)
    device_stats: Dict[str, dict] = {}
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        if not stats:
            continue
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                _m_stats.labels(device=str(d.id), stat=key).set(stats[key])
        device_stats[str(d.id)] = stats
    if enabled():
        _census(arrays, live, device_stats, reason)
        start_ticker()
    return live


def _scope_claims() -> Dict[int, tuple]:
    """id(array) -> (plane, name) for every executor-scope var (the
    global scope plus every private scope seen at a dispatch
    boundary), split params vs optimizer_state by accumulator
    naming."""
    claims: Dict[int, tuple] = {}
    scopes = []
    try:
        from ..framework import executor as executor_mod
        scopes.append(executor_mod._global_scope)
    except Exception:
        pass
    scopes.extend(list(_scopes))
    for scope in scopes:
        try:
            names = scope.var_names()
        except Exception:
            continue
        for name in names:
            try:
                v = scope.find_var(name)
            except Exception:
                continue
            if v is None or not hasattr(v, "nbytes"):
                continue
            plane = ("optimizer_state"
                     if any(m in name for m in _OPT_MARKERS)
                     else "params")
            claims[id(v)] = (plane, name)
    return claims


def _census(arrays, live: int, device_stats: Dict[str, dict],
            reason: str) -> None:
    topk = max(1, int(flags.get_flag("memscope_topk")))
    claims = _scope_claims()
    for i, eng in enumerate(list(_kv_engines)):
        for part, a in (("k", getattr(eng, "_kv_k", None)),
                        ("v", getattr(eng, "_kv_v", None))):
            if a is not None:
                claims[id(a)] = ("serving_kv", f"kv_slab_{part}.{i}")

    planes: Dict[str, int] = {}
    owners: List[dict] = []
    for nb, a in arrays:
        plane, name = claims.get(id(a), ("other", None))
        planes[plane] = planes.get(plane, 0) + nb
        owners.append({"bytes": nb, "plane": plane, "name": name,
                       "shape": list(getattr(a, "shape", ()) or ()),
                       "dtype": str(getattr(a, "dtype", "?"))})
    owners.sort(key=lambda o: -o["bytes"])
    owners = owners[:topk]

    # host/disk-side planes (not jax arrays): sparse tables and the
    # persistent-executable cache footprint
    sparse_b = 0
    for sh in list(_sparse_shards):
        try:
            sparse_b += int(sh.state_bytes())
        except Exception:
            pass
    if sparse_b:
        planes["sparse_tables"] = sparse_b
    try:
        from ..framework import jit_cache
        if jit_cache.enabled():
            planes["jit_executables"] = int(jit_cache.stats().get(
                "bytes", 0))
    except Exception:
        pass
    with _lock:
        feed_b = float(_state.get("feed_bytes") or 0.0)
    if feed_b:
        planes["executor_feeds"] = int(feed_b)

    limit_flag = int(flags.get_flag("memscope_hbm_limit_bytes"))
    device_doc: Dict[str, dict] = {}
    pressure: Optional[float] = None
    for dev, stats in device_stats.items():
        used = stats.get("bytes_in_use")
        if used is None:
            continue
        limit = limit_flag or int(stats.get("bytes_limit") or 0)
        _m_used.labels(device=dev).set(used)
        row = {"used_bytes": int(used), "limit_bytes": limit or None,
               "peak_bytes": stats.get("peak_bytes_in_use")}
        if limit > 0:
            row["free_bytes"] = max(0, limit - int(used))
            _m_free.labels(device=dev).set(row["free_bytes"])
            pressure = max(pressure or 0.0, used / limit)
        device_doc[dev] = row
    if not device_stats:
        # allocator-stats-less backend (CPU): the live-array total is
        # the best available "used"; pressure needs the explicit budget
        _m_used.labels(device="host").set(live)
        row = {"used_bytes": live,
               "limit_bytes": limit_flag or None, "peak_bytes": None}
        if limit_flag > 0:
            row["free_bytes"] = max(0, limit_flag - live)
            _m_free.labels(device="host").set(row["free_bytes"])
            pressure = live / limit_flag
        device_doc["host"] = row
    if pressure is not None:
        _m_pressure.set(pressure)

    threshold = float(flags.get_flag("memscope_pressure_fraction"))
    with _lock:
        known = _state.setdefault("known_planes", set())
        known |= set(planes)
        for plane in known:
            _m_resident.labels(plane=plane).set(planes.get(plane, 0))
        was_active = bool(_state.get("pressure_active"))
        now_active = (pressure is not None and threshold > 0
                      and pressure >= threshold)
        _state.update(planes=planes, owners=owners, device=device_doc,
                      pressure=pressure, live_bytes=live,
                      pressure_active=now_active,
                      last_sample={"reason": reason,
                                   "time_unix": time.time()})
    if now_active and not was_active:
        fattest = max(planes, key=planes.get) if planes else None
        journal.emit("memory", "pressure",
                     fraction=round(float(pressure), 4),
                     threshold=threshold, live_bytes=live,
                     plane=fattest, trigger=reason)


# --- predicted-vs-measured reconciliation ----------------------------------

def _verdict(ratio: float) -> str:
    factor = max(1.0, float(flags.get_flag("memscope_ratio_factor")))
    if ratio > factor:
        return "under_predicted"
    if ratio < 1.0 / factor:
        return "over_predicted"
    return "ok"


def note_dispatch(label: str, cost=None, feed_bytes: float = 0.0,
                  scope=None) -> None:
    """Dispatch-boundary hook (executor.run): census + per-program
    high-water mark joined with the cost model's predicted peak."""
    if not enabled():
        return
    if scope is not None:
        try:
            _scopes.add(scope)
        except TypeError:
            pass
    with _lock:
        _state["feed_bytes"] = float(feed_bytes)
    live = sample(reason="dispatch")
    measured = float(live)
    with _lock:
        for row in (_state.get("device") or {}).values():
            used = row.get("used_bytes")
            if used:
                measured = max(measured, float(used))
        rec = _programs.setdefault(label, {
            "dispatches": 0, "measured_high_water_bytes": 0.0,
            "predicted_peak_bytes": None, "ratio": None,
            "verdict": "unpredicted"})
        rec["dispatches"] += 1
        rec["measured_high_water_bytes"] = max(
            rec["measured_high_water_bytes"], measured)
        predicted = 0.0
        if cost is not None:
            predicted = float(getattr(cost, "peak_hbm_bytes", 0.0) or 0.0)
        if predicted > 0:
            rec["predicted_peak_bytes"] = predicted
            ratio = rec["measured_high_water_bytes"] / predicted
            rec["ratio"] = ratio
            rec["verdict"] = _verdict(ratio)
            _m_ratio.labels(program=label).set(ratio)


# --- KV-cache occupancy ledger ---------------------------------------------

def kv_occupancy(engine) -> dict:
    """Reserved-vs-written slot math over a DecodeEngine's slabs (pure
    host-side arithmetic; also exercised by --self-test on a synthetic
    engine)."""
    import numpy as np

    slab = int(engine._kv_k.nbytes) + int(engine._kv_v.nbytes)
    nslots = int(engine.max_batch)
    max_len = int(engine.max_len)
    per_slot = slab // max(1, nslots)
    bpp = per_slot // max(1, max_len)
    lengths = np.asarray(engine._lengths)
    active = np.asarray(engine._active, dtype=bool)
    n_active = int(active.sum())
    written_pos = int(lengths[active].sum()) if n_active else 0
    reserved = n_active * per_slot
    written = written_pos * bpp
    waste = (1.0 - written / reserved) if reserved else 0.0
    buckets: Dict[str, dict] = {}
    slot_bucket = getattr(engine, "_slot_bucket", None)
    if slot_bucket is not None:
        for slot in np.nonzero(active)[0]:
            b = str(int(slot_bucket[slot]))
            row = buckets.setdefault(b, {"slots": 0, "reserved_bytes": 0,
                                         "written_bytes": 0})
            row["slots"] += 1
            row["reserved_bytes"] += per_slot
            row["written_bytes"] += int(lengths[slot]) * bpp
        for row in buckets.values():
            row["waste_fraction"] = (
                1.0 - row["written_bytes"] / row["reserved_bytes"]
                if row["reserved_bytes"] else 0.0)
    return {"slab_bytes": slab, "slots": nslots,
            "active_slots": n_active, "max_len": max_len,
            "bytes_per_position": bpp, "reserved_bytes": reserved,
            "written_bytes": written, "waste_fraction": waste,
            "buckets": buckets}


def note_kv(engine) -> None:
    """Serving-boundary hook (start_sequence / decode_step /
    retire_slot): refresh the occupancy ledger + gauges."""
    if not enabled():
        return
    doc = kv_occupancy(engine)
    _m_kv_reserved.set(doc["reserved_bytes"])
    _m_kv_written.set(doc["written_bytes"])
    _m_kv_waste.set(doc["waste_fraction"])
    _m_kv_bucket.clear()
    for b, row in doc["buckets"].items():
        _m_kv_bucket.labels(bucket=b).set(row["waste_fraction"])
    with _lock:
        _state["kv"] = doc
        if doc["active_slots"]:
            _state["kv_peak_waste"] = max(
                float(_state.get("kv_peak_waste") or 0.0),
                doc["waste_fraction"])


# --- OOM forensics ---------------------------------------------------------

def _cost_row(cost) -> Optional[dict]:
    if cost is None:
        return None
    row = {}
    for f in ("label", "flops", "bytes_accessed", "argument_bytes",
              "output_bytes", "temp_bytes", "alias_bytes",
              "peak_hbm_bytes", "source"):
        v = getattr(cost, f, None)
        if v is not None:
            row[f] = v
    return row or None


def note_alloc_failure(where: str, label: Optional[str] = None,
                       cost=None) -> Optional[str]:
    """An allocation failed (the memory.alloc chaos site, or a real
    RESOURCE_EXHAUSTED caller): freeze the census + top-K owners + the
    triggering program's cost row into a flight bundle and journal the
    event so ``incident`` can reconstruct the kill timeline."""
    if not enabled():
        return None
    try:
        sample(reason="alloc_failure")
    except Exception:
        pass
    with _lock:
        planes = dict(_state.get("planes") or {})
        census = {"planes": planes,
                  "owners": [dict(o) for o in _state.get("owners") or []],
                  "device": {k: dict(v) for k, v in
                             (_state.get("device") or {}).items()},
                  "live_bytes": _state.get("live_bytes"),
                  "pressure_fraction": _state.get("pressure")}
        _state["alloc_failures"] = int(_state.get("alloc_failures") or 0) + 1
    fattest = max(planes, key=planes.get) if planes else None
    journal.emit("memory", "alloc_failure", where=where,
                 program=label, plane=fattest,
                 live_bytes=census["live_bytes"])
    path = flight.dump("memory_alloc_failure",
                       extra={"memory": {"where": where, "program": label,
                                         "cost": _cost_row(cost),
                                         "census": census}})
    with _lock:
        _state["last_alloc_failure"] = {
            "where": where, "program": label, "plane": fattest,
            "time_unix": time.time(), "bundle_path": path}
    return path


def alert_context(labels: Optional[Dict[str, str]] = None) -> dict:
    """Context for a firing hbm_pressure alert: the pressure numbers
    and the fattest plane/owner (the engine cannot derive ownership
    from a scalar gauge itself)."""
    with _lock:
        planes = dict(_state.get("planes") or {})
        owners = [dict(o) for o in _state.get("owners") or []]
        ctx: Dict[str, Any] = {
            "pressure_fraction": _state.get("pressure"),
            "live_bytes": _state.get("live_bytes")}
        last = _state.get("last_alloc_failure")
    if planes:
        fattest = max(planes, key=planes.get)
        ctx["fattest_plane"] = fattest
        ctx["fattest_plane_bytes"] = planes[fattest]
    if owners:
        ctx["top_owner"] = owners[0]
    if last:
        ctx["last_alloc_failure"] = dict(last)
    return ctx


# --- ticker ----------------------------------------------------------------

def start_ticker() -> None:
    """Idempotent: one bounded daemon thread sampling the census every
    memscope_interval seconds (0 = boundary-only, the default)."""
    global _ticker, _ticker_stop
    interval = float(flags.get_flag("memscope_interval"))
    if interval <= 0 or not enabled():
        return
    with _lock:
        if _ticker is not None and _ticker.is_alive():
            return
        stop = threading.Event()
        t = threading.Thread(target=_ticker_loop, args=(stop, interval),
                             name="memscope-ticker", daemon=True)
        _ticker, _ticker_stop = t, stop
    t.start()


def _ticker_loop(stop: threading.Event, interval: float) -> None:
    while not stop.wait(interval):
        if not enabled():
            break
        try:
            sample(reason="tick")
        except Exception:
            break


# --- reporting -------------------------------------------------------------

def status_doc() -> dict:
    """The paddle_tpu.mem.v1 document (GET /memory body; --doc)."""
    with _lock:
        doc = {
            "schema": SCHEMA, "enabled": enabled(),
            "live_bytes": _state.get("live_bytes"),
            "peak_bytes": _m_peak.value,
            "planes": dict(_state.get("planes") or {}),
            "owners": [dict(o) for o in _state.get("owners") or []],
            "device": {k: dict(v) for k, v in
                       (_state.get("device") or {}).items()},
            "pressure": {
                "fraction": _state.get("pressure"),
                "threshold": float(
                    flags.get_flag("memscope_pressure_fraction")),
                "active": bool(_state.get("pressure_active"))},
            "programs": {k: dict(v) for k, v in _programs.items()},
            "kv": (dict(_state["kv"]) if _state.get("kv") else None),
            "kv_peak_waste_fraction": _state.get("kv_peak_waste"),
            "alloc_failures": int(_state.get("alloc_failures") or 0),
            "last_alloc_failure": (dict(_state["last_alloc_failure"])
                                   if _state.get("last_alloc_failure")
                                   else None),
            "ratio_factor": float(flags.get_flag("memscope_ratio_factor")),
            "last_sample": _state.get("last_sample"),
        }
    return doc


def explain_section(cost) -> dict:
    """The explain(memory=True) body for one compiled program: the
    predicted peak + components next to the measured high-water mark
    and the drift verdict."""
    label = getattr(cost, "label", None)
    with _lock:
        rec = dict(_programs.get(label) or {})
        planes = dict(_state.get("planes") or {})
    return {
        "predicted_peak_bytes": getattr(cost, "peak_hbm_bytes", None),
        "components": {
            "argument": getattr(cost, "argument_bytes", None),
            "output": getattr(cost, "output_bytes", None),
            "temp": getattr(cost, "temp_bytes", None),
            "alias": getattr(cost, "alias_bytes", None)},
        "measured_high_water_bytes":
            rec.get("measured_high_water_bytes"),
        "ratio": rec.get("ratio"),
        "verdict": rec.get("verdict", "unmeasured"),
        "ratio_factor": float(flags.get_flag("memscope_ratio_factor")),
        "planes": planes,
    }


def report(top: int = 8) -> List[str]:
    """ASCII census for the CLI."""
    doc = status_doc()

    def mb(b):
        return "-" if b is None else f"{b / (1 << 20):10.2f} MiB"

    lines = [f"memscope census (live {mb(doc['live_bytes'])}, "
             f"peak {mb(doc['peak_bytes'])})"]
    lines.append("  plane                      resident")
    for plane, b in sorted(doc["planes"].items(), key=lambda kv: -kv[1]):
        lines.append(f"  {plane:<24} {mb(b)}")
    lines.append(f"  top {top} owners:")
    for o in doc["owners"][:top]:
        lines.append(f"    {mb(o['bytes'])}  {o['plane']:<16} "
                     f"{o.get('name') or '?'} {o['shape']} {o['dtype']}")
    p = doc["pressure"]
    if p["fraction"] is not None:
        lines.append(f"  pressure {p['fraction']:.3f} "
                     f"(threshold {p['threshold']:.2f}"
                     f"{', ACTIVE' if p['active'] else ''})")
    for label, rec in sorted(doc["programs"].items()):
        if rec.get("ratio") is not None:
            lines.append(
                f"  program {label}: measured "
                f"{mb(rec['measured_high_water_bytes'])} / predicted "
                f"{mb(rec['predicted_peak_bytes'])} = "
                f"{rec['ratio']:.3f} [{rec['verdict']}]")
    kv = doc.get("kv")
    if kv:
        lines.append(
            f"  kv: {kv['active_slots']}/{kv['slots']} slots, reserved "
            f"{mb(kv['reserved_bytes'])}, written "
            f"{mb(kv['written_bytes'])}, waste "
            f"{kv['waste_fraction']:.3f}")
    if doc["alloc_failures"]:
        lines.append(f"  alloc failures: {doc['alloc_failures']} "
                     f"(last: {doc['last_alloc_failure']})")
    return lines


def rows_from_metrics_doc(doc: Optional[dict]) -> dict:
    """Reconstruct census rows from a metrics DOCUMENT (this process's
    registry or a fleet worker's shipped snapshot) — what
    fleet.mem_rows() builds the per-rank merged view from."""
    fams = (doc or {}).get("metrics") or {}

    def series(name):
        return (fams.get(name) or {}).get("series") or []

    planes = {}
    for row in series("mem_resident_bytes"):
        plane = (row.get("labels") or {}).get("plane")
        if plane is not None:
            planes[plane] = row.get("value", 0.0)
    device: Dict[str, dict] = {}
    for metric, key in (("mem_device_used_bytes", "used_bytes"),
                        ("mem_device_free_bytes", "free_bytes")):
        for row in series(metric):
            dev = (row.get("labels") or {}).get("device")
            if dev is not None:
                device.setdefault(dev, {})[key] = row.get("value", 0.0)
    pressure = None
    for row in series("mem_pressure_fraction"):
        pressure = float(row.get("value", 0.0))
    ratios = {}
    for row in series("mem_peak_ratio"):
        prog = (row.get("labels") or {}).get("program")
        if prog is not None:
            ratios[prog] = row.get("value", 0.0)
    kv = {}
    for metric, key in (("serving_kv_reserved_bytes", "reserved_bytes"),
                        ("serving_kv_written_bytes", "written_bytes"),
                        ("serving_kv_waste_fraction", "waste_fraction")):
        for row in series(metric):
            kv[key] = row.get("value", 0.0)
    live = None
    for row in series("device_memory_live_bytes"):
        live = float(row.get("value", 0.0))
    return {"planes": planes, "device": device,
            "pressure_fraction": pressure, "peak_ratio": ratios,
            "kv": kv, "live_bytes": live}


# --- lifecycle -------------------------------------------------------------

def reset() -> None:
    """Stop the ticker thread (joined), drop census/program state and
    every mem_*/serving_kv_* gauge series (conftest: one test's
    residency or pressure verdict must not leak into the next).  The
    provider weaksets survive — registration happens once at object
    construction and module-scoped fixtures outlive a single test.
    The legacy device_memory_* watermarks are left alone (pre-memscope
    behavior: never cleared between tests)."""
    global _ticker, _ticker_stop
    with _lock:
        t, stop = _ticker, _ticker_stop
        _ticker, _ticker_stop = None, None
    if stop is not None:
        stop.set()
    if t is not None and t.is_alive():
        t.join(timeout=2.0)
    with _lock:
        _state.clear()
        _programs.clear()
    for m in (_m_resident, _m_used, _m_free, _m_pressure, _m_ratio,
              _m_kv_reserved, _m_kv_written, _m_kv_waste, _m_kv_bucket):
        m.clear()


# --- CLI -------------------------------------------------------------------

def _self_test() -> int:
    """Hermetic smoke against TEMPORARY flag state: a census over a
    synthetic live array (pressure forced via a 1-byte budget), the
    ratio-verdict math, and the KV slot ledger on a synthetic engine.
    Prints one MEMSCOPE_SELF_TEST json line; exit 0 on pass."""
    import types

    import numpy as np

    saved = {k: flags.get_flag(k) for k in
             ("memscope", "memscope_interval", "memscope_topk",
              "memscope_hbm_limit_bytes", "memscope_pressure_fraction",
              "memscope_ratio_factor")}
    flags.set_flag("memscope", True)
    flags.set_flag("memscope_interval", 0.0)
    flags.set_flag("memscope_hbm_limit_bytes", 1)
    notes: Dict[str, Any] = {}
    ok = True
    try:
        import jax.numpy as jnp
        x = jnp.ones((64, 64), jnp.float32)
        live = sample(reason="self_test")
        doc = status_doc()
        notes["live_bytes"] = live
        notes["planes"] = sorted(doc["planes"])
        ok &= live >= x.nbytes and bool(doc["planes"])
        ok &= (doc["pressure"]["fraction"] or 0.0) >= 1.0
        ok &= doc["pressure"]["active"]
        ctx = alert_context({})
        ok &= bool(ctx.get("fattest_plane"))

        cost = types.SimpleNamespace(label="selftest.prog",
                                     peak_hbm_bytes=float(live))
        note_dispatch("selftest.prog", cost=cost)
        rec = status_doc()["programs"]["selftest.prog"]
        notes["ratio"] = rec["ratio"]
        ok &= rec["verdict"] == "ok" and rec["ratio"] is not None

        eng = types.SimpleNamespace(
            max_batch=4, max_len=16,
            _kv_k=np.zeros((2, 4, 2, 16, 8), np.float32),
            _kv_v=np.zeros((2, 4, 2, 16, 8), np.float32),
            _lengths=np.array([4, 8, 0, 0], np.int32),
            _active=np.array([True, True, False, False]),
            _slot_bucket=np.array([8, 16, 0, 0], np.int32))
        occ = kv_occupancy(eng)
        notes["kv_waste"] = occ["waste_fraction"]
        ok &= abs(occ["waste_fraction"] - (1.0 - 12 / 32)) < 1e-9
        ok &= occ["reserved_bytes"] == 2 * (occ["slab_bytes"] // 4)
        ok &= set(occ["buckets"]) == {"8", "16"}
        note_kv(eng)
        ok &= abs(_m_kv_waste.value - occ["waste_fraction"]) < 1e-9
        del x
    except Exception as e:          # pragma: no cover - diagnosed by ok
        notes["error"] = f"{type(e).__name__}: {e}"
        ok = False
    finally:
        reset()
        for k, v in saved.items():
            flags.set_flag(k, v)
    print("MEMSCOPE_SELF_TEST " + json.dumps(
        {"ok": bool(ok), **notes}, sort_keys=True, default=str))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.memscope",
        description="Live HBM census: per-plane attribution, top-N "
                    "owners, predicted-vs-measured peaks and the KV "
                    "occupancy ledger.")
    ap.add_argument("--doc", action="store_true",
                    help="print the paddle_tpu.mem.v1 json document")
    ap.add_argument("--top", type=int, default=None,
                    help="owners to list (default: memscope_topk flag)")
    ap.add_argument("--self-test", action="store_true",
                    help="hermetic synthetic-census smoke (tier-1)")
    args = ap.parse_args(argv)
    if args.self_test:
        return _self_test()
    if not enabled():
        print("memscope is disabled — set PTPU_MEMSCOPE=1 (flag "
              "'memscope') and rerun.", file=sys.stderr)
        return 2
    sample(reason="cli")
    for line in report(args.top or int(flags.get_flag("memscope_topk"))):
        print(line)
    if args.doc:
        print(json.dumps(status_doc(), indent=2, sort_keys=True,
                         default=str))
    return 0


if __name__ == "__main__":         # pragma: no cover
    raise SystemExit(main())
