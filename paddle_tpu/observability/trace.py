"""Unified trace plane: one buffer, one chrome-trace export.

Merges every span source — host ``RecordEvent`` scopes (core/profiler.py),
executor per-op timings and step spans (framework/executor.py), trainer
step markers (trainer.py) — into a single perfetto-loadable
chrome://tracing JSON, replacing the reference's two-file story
(host profile protobuf + CUPTI device trace stitched by tools/timeline.py).

Lanes: every source records under a stable ``tid`` so the timeline groups
host scopes, executor steps, per-op work and trainer markers as separate
tracks of one process.  Device-side work still comes from
``jax.profiler.start_trace`` (XPlane); this file owns the host story.

All timestamps are ``time.perf_counter()`` seconds; export converts to
the microseconds chrome tracing expects and emits events sorted by ts.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

# Stable lane ids (thread_name metadata is emitted per lane on export).
HOST_TID = 0        # RecordEvent / RecordBlock host scopes
EXECUTOR_TID = 1    # executor step dispatches
OP_TID = 2          # per-op eager timings (PTPU_PROFILE_OPS=1)
TRAINER_TID = 3     # trainer step/epoch markers
_LANE_NAMES = {HOST_TID: "host scopes", EXECUTOR_TID: "executor steps",
               OP_TID: "ops (interpreted)", TRAINER_TID: "trainer"}

_MAX_EVENTS = 1_000_000     # hard cap; beyond it events drop (counted)

_events: List[dict] = []
_dropped = 0
_enabled = False
_generation = 0     # bumped by reset(); consumers with a cursor into
_lock = threading.Lock()        # the buffer use it to detect the wipe


def enabled() -> bool:
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def reset():
    global _dropped, _generation
    with _lock:
        _events.clear()
        _dropped = 0
        _generation += 1


def generation() -> int:
    """Bumped on every reset(); lets cursor-based consumers (the fleet
    reporter) tell 'buffer wiped and refilled' from 'buffer grew'."""
    return _generation


def dropped() -> int:
    return _dropped


def add_span(name: str, ts: float, dur: float, tid: int = HOST_TID,
             cat: str = "host", args: Optional[Dict[str, Any]] = None):
    """Record one complete ('X') event; ts/dur in perf_counter seconds."""
    _append({"name": name, "ph": "X", "ts": ts, "dur": dur, "tid": tid,
             "cat": cat, "args": args})


def add_instant(name: str, ts: float, tid: int = TRAINER_TID,
                cat: str = "marker",
                args: Optional[Dict[str, Any]] = None):
    """Record one instant ('i') marker event."""
    _append({"name": name, "ph": "i", "ts": ts, "tid": tid, "cat": cat,
             "args": args})


def _append(e: dict):
    global _dropped
    if not _enabled:
        return
    with _lock:
        if len(_events) >= _MAX_EVENTS:
            _dropped += 1
            return
        _events.append(e)


def events(cat: Optional[str] = None) -> List[dict]:
    with _lock:
        evs = list(_events)
    if cat is not None:
        evs = [e for e in evs if e.get("cat") == cat]
    return evs


def events_since(cursor: int, generation: Optional[int] = None):
    """Atomic (generation, length, tail-from-cursor) read for
    cursor-based consumers (the fleet reporter).  A mismatched
    `generation` means reset() wiped the buffer since the cursor was
    taken: the whole buffer returns.  Copies only the tail — a full
    events() copy is O(buffer) per report tick."""
    with _lock:
        gen = _generation
        start = cursor if generation == gen else 0
        return gen, len(_events), _events[min(start, len(_events)):]


def to_chrome_trace() -> dict:
    """The merged trace as a chrome://tracing / perfetto JSON object."""
    with _lock:
        evs = sorted(_events, key=lambda e: e["ts"])
    out: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "paddle_tpu host"}}]
    for tid, lane in sorted(_LANE_NAMES.items()):
        out.append({"name": "thread_name", "ph": "M", "pid": 0,
                    "tid": tid, "args": {"name": lane}})
    for e in evs:
        ev = {"name": e["name"], "ph": e["ph"], "pid": 0,
              "tid": e["tid"], "ts": e["ts"] * 1e6,
              "cat": e.get("cat", "host")}
        if e["ph"] == "X":
            ev["dur"] = e["dur"] * 1e6
        if e["ph"] == "i":
            ev["s"] = "t"           # instant scope: thread
        if e.get("args"):
            ev["args"] = e["args"]
        out.append(ev)
    # clock_sync pairs one wall-clock sample with one perf_counter sample
    # so fleet.py can map this process's perf timeline onto the shared
    # wall clock (same normalization live FleetReporter payloads use)
    trace = {"traceEvents": out, "displayTimeUnit": "ms",
             "metadata": {"clock_sync": {"time_unix": time.time(),
                                         "perf_counter":
                                             time.perf_counter()}}}
    if _dropped:
        trace["metadata"]["dropped_events"] = _dropped
    return trace


def export_chrome_trace(path: str) -> str:
    """Write the merged trace JSON to `path`; returns the path."""
    with open(path, "w") as f:
        json.dump(to_chrome_trace(), f)
    return path
