"""Run-history log: an append-only JSONL scalar trajectory per run.

The metrics registry (PR 1) is process-lifetime state: when a bench or
soak run ends, every per-step scalar it measured dies with the process,
and "did loss diverge from last week's run at step 40?" is unanswerable.
This module gives a run a durable trajectory — the Trainer appends one
record per step (loss, lr, throughput, MFU, guard verdicts, sampled
tensor statistics), ``bench.py`` appends one per workload row, and the
CLI below reads it all back:

    python -m paddle_tpu.observability.runlog run.jsonl             # tail
    python -m paddle_tpu.observability.runlog run.jsonl --plot loss # trend
    python -m paddle_tpu.observability.runlog \
        --compare a.jsonl b.jsonl --metric loss --tolerance 0.05

``--compare`` joins two runs step-aligned, prints the FIRST diverging
step and exits nonzero when any aligned step's values differ by more
than the (relative) tolerance — the bisection primitive for "which
commit changed the loss curve".  ``--plot`` renders an ASCII trend so a
soak box with no browser still shows a curve.

File format: one JSON object per line, every record carrying
``schema: paddle_tpu.runlog.v1`` plus ``kind`` (``meta`` | ``step`` |
``guard`` | ``bench``) and ``time_unix``.  Non-finite floats are
stringified (a NaN loss is exactly what gets logged) so every line is
strict JSON.  Opening a path that already holds a previous run rotates
it to ``<path>.1`` first (atomic ``os.replace``), so a restarted run
never interleaves with its predecessor.  Writes never raise — a full
disk must not take training down — failures land in
``runlog_write_failures_total``.

Enable via the ``runlog_path`` flag (``PTPU_RUNLOG_PATH``); the Trainer
opens it per ``train()``.  The reference's closest analogue is scraping
scalars out of its ``Print`` op's stderr — this is that, structured.
"""
from __future__ import annotations

import argparse
import json
import math
import operator
import os
import sys
import threading
import time
import warnings
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..core import flags
from . import metrics as obs_metrics

SCHEMA = "paddle_tpu.runlog.v1"

_m_records = obs_metrics.counter(
    "runlog_records_total",
    "Records appended to the run-history JSONL log.")
_m_failures = obs_metrics.counter(
    "runlog_write_failures_total",
    "Runlog appends that failed (disk full / permission) and were "
    "absorbed — telemetry must not take training down.")

# every open writer, so tests can reset()/close leaked handles
_open_logs: "weakref.WeakSet[RunLog]" = weakref.WeakSet()


def _strict(v: Any):
    """JSON-safe copy: non-finite floats stringified (strict JSON),
    numpy scalars coerced, unknown objects repr-bounded."""
    if isinstance(v, float):
        return v if math.isfinite(v) else repr(v)
    if isinstance(v, (int, bool, str)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _strict(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_strict(x) for x in v]
    try:                      # integral numpy scalar (np.int64 step):
        # must stay an int — a float-coerced step (3.0) would be
        # rejected by _step_key on read-back and silently drop the
        # record from --compare/--plot alignment
        return int(operator.index(v))
    except TypeError:
        pass
    try:                      # numpy scalar / 0-d array
        return _strict(float(v))
    except (TypeError, ValueError):
        return repr(v)[:300]


class RunLog:
    """One append-only JSONL run history.  ``rotate=True`` (default)
    atomically moves a pre-existing non-empty file to ``<path>.1``
    before the first append, so each RunLog owns a fresh trajectory."""

    def __init__(self, path: str, rotate: bool = True,
                 meta: Optional[dict] = None):
        self.path = str(path)
        self.failed_writes = 0
        self._lock = threading.Lock()
        if rotate:
            try:
                if os.path.getsize(self.path) > 0:
                    os.replace(self.path, self.path + ".1")
            except FileNotFoundError:
                pass             # no previous run
            except OSError as e:
                # rename needs DIR write; append may still succeed and
                # would interleave two runs in one file — say so rather
                # than silently corrupting --compare's step alignment
                _m_failures.inc()
                warnings.warn(
                    f"runlog could not rotate {self.path!r} aside "
                    f"({e}); appending to the previous run's file — "
                    f"step records from both runs will interleave",
                    RuntimeWarning, stacklevel=3)
        self._f = open(self.path, "a", encoding="utf-8")
        _open_logs.add(self)
        if meta is not None:
            self.write(kind="meta", **meta)

    @property
    def closed(self) -> bool:
        return self._f is None

    def write(self, **fields) -> Optional[dict]:
        """Append one record (schema + time_unix added here).  Returns
        the record, or None when the write failed / the log is closed —
        never raises."""
        rec: Dict[str, Any] = {"schema": SCHEMA, "time_unix": time.time()}
        for k, v in fields.items():
            rec[k] = _strict(v)
        try:
            line = json.dumps(rec, allow_nan=False,
                              separators=(",", ":"))
        except (TypeError, ValueError):
            self.failed_writes += 1
            _m_failures.inc()
            return None
        with self._lock:
            if self._f is None:
                return None
            try:
                self._f.write(line + "\n")
                self._f.flush()
            except (OSError, ValueError):
                self.failed_writes += 1
                _m_failures.inc()
                return None
        _m_records.inc()
        return rec

    def close(self):
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def open_runlog(path: Optional[str] = None,
                meta: Optional[dict] = None) -> Optional[RunLog]:
    """Flag-driven writer factory (the Trainer's entry point):
    ``path=None`` reads the ``runlog_path`` flag and returns None at its
    "" default.  An unopenable path WARNS and returns None — a run must
    not die on a telemetry-only error."""
    import warnings
    if path is None:
        path = str(flags.get_flag("runlog_path") or "")
    if not path:
        return None
    try:
        return RunLog(path, meta=meta)
    except OSError as e:
        _m_failures.inc()
        warnings.warn(f"runlog not opened ({path}): {e}",
                      RuntimeWarning, stacklevel=2)
        return None


def reset():
    """Test hook: close every open writer so file handles (and their
    half-written records) never leak across test cases."""
    for log in list(_open_logs):
        log.close()


# -- reading / analysis -----------------------------------------------------

def read_records(path: str) -> List[dict]:
    """Parse a runlog back into records.  Strict: every non-blank line
    must be a JSON object carrying this module's schema — the
    round-trip contract the CLI (and tests) rely on."""
    out: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(
                    f"{path}:{i}: not JSON ({e})") from e
            if not isinstance(rec, dict) or rec.get("schema") != SCHEMA:
                raise ValueError(
                    f"{path}:{i}: schema "
                    f"{rec.get('schema') if isinstance(rec, dict) else rec!r}"
                    f" != {SCHEMA}")
            out.append(rec)
    return out


def _value(rec: dict, metric: str) -> Optional[float]:
    """A record's value for `metric` as a float; stringified non-finite
    floats ("nan"/"inf", how _strict writes them) parse back; missing /
    non-numeric -> None."""
    v = rec.get(metric)
    if isinstance(v, str):
        try:
            v = float(v)
        except ValueError:
            return None
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def step_records(records: Sequence[dict]) -> List[dict]:
    """Alignable records: trainer steps plus bench rows (one per
    workload, step = fixed workload index) so two bench runlogs diff
    and plot with the same CLI as two training runs."""
    return [r for r in records if r.get("kind") in ("step", "bench")]


def _step_key(rec: dict) -> Optional[int]:
    for k in ("global_step", "step"):
        if isinstance(rec.get(k), int):
            return int(rec[k])
    return None


def compare(a: Sequence[dict], b: Sequence[dict], metric: str = "loss",
            tolerance: float = 0.05) -> dict:
    """Step-aligned diff of two runs on one metric.  Divergence at a
    step: relative difference > `tolerance` (against the larger
    magnitude), or exactly one side non-finite.  Returns the verdict
    plus the FIRST diverging step — what a bisection prints."""
    av = {s: _value(r, metric) for r in step_records(a)
          if (s := _step_key(r)) is not None}
    bv = {s: _value(r, metric) for r in step_records(b)
          if (s := _step_key(r)) is not None}
    common = sorted(s for s in av if s in bv
                    and av[s] is not None and bv[s] is not None)
    first = None
    max_rel = 0.0
    for s in common:
        x, y = av[s], bv[s]
        fx, fy = math.isfinite(x), math.isfinite(y)
        if fx and fy:
            rel = abs(x - y) / max(abs(x), abs(y), 1e-12)
        elif x == y or (math.isnan(x) and math.isnan(y)):
            rel = 0.0            # both went bad the same way
        else:
            rel = float("inf")   # one side NaN/Inf = divergence
        max_rel = max(max_rel, rel)
        if rel > tolerance and first is None:
            first = {"step": s, "a": _strict(x), "b": _strict(y),
                     "rel_diff": _strict(rel)}
    return {"schema": "paddle_tpu.runlog_compare.v1", "metric": metric,
            "tolerance": tolerance, "steps_compared": len(common),
            "only_a": len(av) - len(common), "only_b": len(bv) - len(common),
            "max_rel_diff": _strict(max_rel),
            "first_divergence": first, "diverged": first is not None}


def render_trend(records: Sequence[dict], metric: str = "loss",
                 width: int = 60, height: int = 10) -> str:
    """ASCII trend of one metric over the run's steps — enough curve to
    eyeball a soak box over ssh.  Steps bucket into `width` columns
    (bucket mean); non-finite values render as ``!`` on the top row."""
    pts: List[Tuple[int, float]] = []
    bad_steps = []
    for r in step_records(records):
        s = _step_key(r)
        v = _value(r, metric)
        if s is None or v is None:
            continue
        if math.isfinite(v):
            pts.append((s, v))
        else:
            bad_steps.append(s)
    if not pts and not bad_steps:
        return f"(no finite {metric!r} samples)"
    pts.sort()
    lo_s = min([s for s, _ in pts] + bad_steps)
    hi_s = max([s for s, _ in pts] + bad_steps)
    span = max(1, hi_s - lo_s)
    width = max(8, int(width))
    height = max(3, int(height))
    cols: List[List[float]] = [[] for _ in range(width)]
    bad_cols = set()
    for s, v in pts:
        cols[min(width - 1, (s - lo_s) * width // (span + 1))].append(v)
    for s in bad_steps:
        bad_cols.add(min(width - 1, (s - lo_s) * width // (span + 1)))
    means = [sum(c) / len(c) if c else None for c in cols]
    finite = [m for m in means if m is not None]
    lo_v = min(finite) if finite else 0.0
    hi_v = max(finite) if finite else 1.0
    if hi_v == lo_v:
        hi_v = lo_v + 1.0
    grid = [[" "] * width for _ in range(height)]
    for x, m in enumerate(means):
        if m is None:
            continue
        y = int(round((m - lo_v) / (hi_v - lo_v) * (height - 1)))
        grid[height - 1 - y][x] = "*"
    for x in sorted(bad_cols):
        grid[0][x] = "!"
    label_w = 11
    lines = []
    for i, row in enumerate(grid):
        if i == 0:
            label = f"{hi_v:>10.4g} "
        elif i == height - 1:
            label = f"{lo_v:>10.4g} "
        else:
            label = " " * label_w
        lines.append(label + "|" + "".join(row))
    lines.append(" " * label_w + "+" + "-" * width)
    lines.append(" " * label_w + f"step {lo_s} .. {hi_s}  ({metric}"
                 + (", ! = NaN/Inf" if bad_cols else "") + ")")
    return "\n".join(lines)


# -- CLI --------------------------------------------------------------------

def _fmt_tail(rec: dict) -> str:
    skip = {"schema", "time_unix"}
    body = " ".join(f"{k}={rec[k]!r}" if isinstance(rec[k], str)
                    else f"{k}={rec[k]}"
                    for k in rec if k not in skip)
    return body


def _main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.runlog",
        description="Inspect paddle_tpu.runlog.v1 JSONL run histories: "
                    "tail records, diff two runs step-aligned, or "
                    "render an ASCII trend.")
    ap.add_argument("file", nargs="?",
                    help="runlog to tail / plot")
    ap.add_argument("--tail", type=int, default=10, metavar="N",
                    help="records to show (default 10)")
    ap.add_argument("--plot", metavar="METRIC",
                    help="render an ASCII trend of METRIC instead of "
                         "tailing")
    ap.add_argument("--compare", nargs=2, metavar=("A", "B"),
                    help="step-aligned diff of two runlogs; exits 1 on "
                         "divergence")
    ap.add_argument("--metric", default="loss",
                    help="metric for --compare (default loss)")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="relative tolerance for --compare "
                         "(default 0.05)")
    ap.add_argument("--width", type=int, default=60)
    ap.add_argument("--height", type=int, default=10)
    args = ap.parse_args(argv)

    try:
        if args.compare:
            a = read_records(args.compare[0])
            b = read_records(args.compare[1])
            res = compare(a, b, metric=args.metric,
                          tolerance=args.tolerance)
            if res["steps_compared"] == 0:
                print(f"runlog: no aligned steps carrying "
                      f"{args.metric!r} in both runs", file=sys.stderr)
                return 2
            print(json.dumps(res))
            if res["diverged"]:
                f = res["first_divergence"]
                print(f"DIVERGED at step {f['step']}: "
                      f"{args.metric} {f['a']} vs {f['b']} "
                      f"(rel diff {f['rel_diff']}, tolerance "
                      f"{args.tolerance})")
                return 1
            print(f"ok: {res['steps_compared']} aligned steps within "
                  f"tolerance {args.tolerance} "
                  f"(max rel diff {res['max_rel_diff']})")
            return 0
        if not args.file:
            ap.error("need a runlog file (or --compare A B)")
        records = read_records(args.file)
        if args.plot:
            print(render_trend(records, metric=args.plot,
                               width=args.width, height=args.height))
            return 0
        for rec in records[-max(1, args.tail):]:
            print(_fmt_tail(rec))
        return 0
    except (OSError, ValueError) as e:
        print(f"runlog: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    raise SystemExit(_main())
