"""On-demand device profiling (``POST /profile``).

Closes the X-ray loop from "this request was slow" (the trace
waterfall) to "this is the device timeline": one bounded
``jax.profiler`` capture, started over HTTP against a live process,
stopped by a watchdog thread after ``duration_s``, its artifact
directory tagged with the trace ids that were active while it ran —
the reference framework's profiler plane (``profiler.proto`` +
tools/timeline.py) as a serving-era endpoint.

Graceful degradation is the contract: a build/platform where
``jax.profiler.start_trace`` is unavailable or fails returns a clean
``unavailable`` document instead of 500ing the endpoint; only one
capture runs at a time (a second request gets ``busy`` + the running
capture's document).
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from . import metrics as obs_metrics
from . import tracectx

_MAX_DURATION_S = 60.0
_DEFAULT_DURATION_S = 2.0

_m_captures = obs_metrics.counter(
    "deviceprof_captures_total",
    "On-demand jax.profiler captures by outcome "
    "(started|unavailable|busy).", ("outcome",))

_lock = threading.Lock()
_state: Dict[str, Any] = {"running": False, "last": None}


def _profiler():
    try:
        import jax.profiler as prof
        if not hasattr(prof, "start_trace"):
            return None
        return prof
    except Exception:
        return None


def status() -> dict:
    with _lock:
        return {"running": bool(_state["running"]),
                "last": _state["last"]}


def start(duration_s: Optional[float] = None,
          logdir: Optional[str] = None) -> dict:
    """Begin one bounded capture; returns its document immediately
    (the capture finishes in the background).  Outcomes:

    * ``started`` — capture running; ``logdir`` holds the XPlane dump.
    * ``busy`` — another capture is in flight; its doc rides along.
    * ``unavailable`` — no usable jax.profiler on this build/platform
      (or start_trace raised); a no-op, never an error."""
    dur = float(duration_s if duration_s is not None
                else _DEFAULT_DURATION_S)
    dur = max(0.1, min(dur, _MAX_DURATION_S))
    with _lock:
        if _state["running"]:
            _m_captures.labels(outcome="busy").inc()
            return {"status": "busy", "capture": _state["last"]}
        prof = _profiler()
        if prof is None:
            _m_captures.labels(outcome="unavailable").inc()
            return {"status": "unavailable",
                    "reason": "jax.profiler.start_trace not available"}
        logdir = logdir or tempfile.mkdtemp(prefix="ptpu_xprof_")
        # trace ids active NOW: the link back from the device timeline
        # to the request waterfalls that asked for it
        active: List[str] = tracectx.trace_ids()[-8:]
        cur = tracectx.current_trace_id()
        if cur and cur not in active:
            active.append(cur)
        doc = {"status": "started", "logdir": logdir,
               "duration_s": dur, "time_unix": time.time(),
               "trace_ids": active, "done": False}
        try:
            prof.start_trace(logdir)
        except Exception as e:
            _m_captures.labels(outcome="unavailable").inc()
            return {"status": "unavailable",
                    "reason": f"start_trace failed: {e!r}"[:300]}
        _state["running"] = True
        _state["last"] = doc
    _m_captures.labels(outcome="started").inc()
    t = threading.Thread(target=_stop_after, args=(dur, prof),
                         daemon=True, name="deviceprof-watchdog")
    t.start()
    # the requester's own trace remembers it asked (the waterfall then
    # points at the device timeline artifact)
    tracectx.instant("deviceprof.start", kind="profile",
                     logdir=logdir, duration_s=dur)
    return dict(doc)


def _stop_after(dur: float, prof):
    time.sleep(dur)
    err = None
    try:
        prof.stop_trace()
    except Exception as e:              # stop must never kill the host
        err = repr(e)[:300]
    with _lock:
        _state["running"] = False
        if _state["last"] is not None:
            _state["last"] = {**_state["last"], "done": True,
                              **({"stop_error": err} if err else {})}


def reset():
    """Test hook: forget capture state (a running capture's watchdog
    still stops it)."""
    with _lock:
        _state["running"] = False
        _state["last"] = None
