"""DataFeeder: reader minibatch rows -> feed dict of dense numpy arrays.

Capability parity with /root/reference/python/paddle/fluid/data_feeder.py:83
(DataFeeder.feed batches rows into LoDTensors).  TPU-first difference: there
is no LoD — variable-length fields are padded to the batch max (or a fixed
`pad_to`) with an optional companion `<name>_mask` float array, which is the
dense/segment-mask story the models consume (SURVEY.md hard part (a)).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.dtypes import convert_dtype
from .framework.program import Variable


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None,
                 pad_to: Optional[Dict[str, int]] = None,
                 emit_masks: bool = False):
        self.feed_vars: List[Variable] = list(feed_list)
        self.pad_to = dict(pad_to or {})
        self.emit_masks = emit_masks

    def feed(self, minibatch: Sequence[Sequence]) -> Dict[str, np.ndarray]:
        """minibatch: list of rows, each row one value per feed var."""
        out: Dict[str, np.ndarray] = {}
        for i, var in enumerate(self.feed_vars):
            col = [row[i] for row in minibatch]
            dtype = convert_dtype(var.dtype)
            first = np.asarray(col[0])
            is_ragged = any(np.asarray(c).shape != first.shape for c in col)
            if is_ragged or var.name in self.pad_to:
                arrs = [np.atleast_1d(np.asarray(c)) for c in col]
                maxlen = self.pad_to.get(
                    var.name, max(a.shape[0] for a in arrs))
                tail = arrs[0].shape[1:]
                batch = np.zeros((len(arrs), maxlen) + tail, dtype=dtype)
                mask = np.zeros((len(arrs), maxlen), dtype="float32")
                for j, a in enumerate(arrs):
                    n = min(a.shape[0], maxlen)
                    batch[j, :n] = a[:n]
                    mask[j, :n] = 1.0
                out[var.name] = batch
                if self.emit_masks:
                    out[var.name + "_mask"] = mask
            else:
                batch = np.asarray(col).astype(dtype)
                # reference feeds scalars as [N, 1] (labels)
                want_rank = len(var.shape) if var.shape else None
                if want_rank is not None and batch.ndim < want_rank:
                    batch = batch.reshape(batch.shape + (1,) * (
                        want_rank - batch.ndim))
                out[var.name] = batch
        return out
