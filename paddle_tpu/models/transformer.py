"""Transformer-base NMT — BASELINE config 3 (WMT en-de class model).

Capability parity with the reference's transformer configs
(/root/reference/python/paddle/fluid/tests/unittests/dist_transformer.py and
benchmark/fluid/models/machine_translation.py), re-designed TPU-first:

  * dense static shapes (bucketed padding + additive attention bias) instead
    of LoD ragged batches — see SURVEY.md "hard parts (a)";
  * attention is expressed as batched matmuls that XLA tiles onto the MXU;
    the fused Pallas flash-attention kernel (kernels/flash_attention.py) is
    used by the executor when FLAGS_use_pallas_attention is on;
  * the same graph shards over a Mesh for dp/tp/sp without change — the
    Parameter.sharding PartitionSpecs carry the layout.
"""
from __future__ import annotations

import numpy as np

from .. import layers
from ..framework.initializer import NumpyArrayInitializer
from ..framework.layer_helper import ParamAttr


def position_encoding_table(max_len: int, d_model: int) -> np.ndarray:
    """Sinusoid table (ref dist_transformer.py position_encoding_init)."""
    pos = np.arange(max_len)[:, None].astype("float64")
    div = np.power(10000.0, 2 * (np.arange(d_model) // 2) / d_model)[None, :]
    ang = pos / div
    table = np.zeros((max_len, d_model), dtype="float32")
    table[:, 0::2] = np.sin(ang[:, 0::2])
    table[:, 1::2] = np.cos(ang[:, 1::2])
    return table


def multi_head_attention(queries, keys, values, attn_bias, d_key, d_value,
                         d_model, n_head=1, dropout_rate=0.0,
                         causal=False, fused=False):
    """ref dist_transformer.py multi_head_attention — q/k/v projections,
    split heads, scaled-dot-product with additive bias, combine, out-proj.

    fused=True routes the pre-projected q/k/v through the single
    fused_attention op (Pallas flash kernel, O(T) memory) instead of the
    matmul+softmax composition; it supports causal masking but not an
    arbitrary attn_bias or attention-prob dropout, so it requires dense
    (pad-free) batches — the bench/long-context path."""
    self_attn = (keys is None or keys is queries) and (
        values is None or values is keys or values is queries)
    keys = queries if keys is None else keys
    values = keys if values is None else values

    if fused:
        if values is not keys:
            raise ValueError("fused attention path projects V from the "
                             "same source as K (one kv input); pass "
                             "values=keys or use fused=False")
        if attn_bias is not None:
            raise ValueError("fused attention path cannot apply an "
                             "additive attn_bias; pass dense batches")
        if dropout_rate:
            raise ValueError("fused attention path has no attention-prob "
                             "dropout (FlashAttention contract); use "
                             "fused=False or dropout_rate=0")
        if d_key != d_value:
            raise ValueError("fused attention path requires "
                             "d_key == d_value")
        # projection-fused op: q/k/v/o projections live INSIDE the op so
        # the whole sublayer lowers transpose-free (head-major Pallas
        # kernel); replaces fc(3E) -> split -> fused_attention -> fc(D)
        return layers.fused_mha(queries, n_head, causal=causal,
                                kv=None if self_attn else keys,
                                size=d_key * n_head, out_size=d_model)

    if self_attn and d_key == d_value:
        # one [B,T,D]@[D,3E] projection instead of three (bigger MXU
        # tiles, one pass over the activations)
        qkv = layers.fc(queries, size=(2 * d_key + d_value) * n_head,
                        num_flatten_dims=2, bias_attr=False)
        q, k, v = layers.split(qkv, num_or_sections=3, dim=-1)
    else:
        q = layers.fc(queries, size=d_key * n_head, num_flatten_dims=2,
                      bias_attr=False)
        k = layers.fc(keys, size=d_key * n_head, num_flatten_dims=2,
                      bias_attr=False)
        v = layers.fc(values, size=d_value * n_head, num_flatten_dims=2,
                      bias_attr=False)

    def split_heads(x, d):
        # [B,T,nh*d] -> [B,nh,T,d]
        y = layers.reshape(x, [0, 0, n_head, d])
        return layers.transpose(y, [0, 2, 1, 3])

    q, k, v = split_heads(q, d_key), split_heads(k, d_key), split_heads(
        v, d_value)

    scores = layers.matmul(q, k, transpose_y=True,
                           alpha=float(d_key) ** -0.5)
    if attn_bias is not None:
        scores = layers.elementwise_add(scores, attn_bias)
    weights = layers.softmax(scores)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_rate,
                                 dropout_implementation="upscale_in_train")
    ctx = layers.matmul(weights, v)                     # [B,nh,T,dv]
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, n_head * d_value])
    return layers.fc(ctx, size=d_model, num_flatten_dims=2, bias_attr=False)


def positionwise_ffn(x, d_inner, d_model, dropout_rate=0.0):
    hidden = layers.fc(x, size=d_inner, num_flatten_dims=2, act="relu")
    if dropout_rate:
        hidden = layers.dropout(hidden, dropout_rate,
                                dropout_implementation="upscale_in_train")
    return layers.fc(hidden, size=d_model, num_flatten_dims=2)


def pre_post_process(prev_out, out, cmd, dropout_rate=0.0):
    """ref dist_transformer.py pre_post_process_layer: a=add, n=norm, d=drop."""
    for c in cmd:
        if c == "a":
            out = layers.elementwise_add(out, prev_out) if prev_out is not None else out
        elif c == "n":
            out = layers.layer_norm(out, begin_norm_axis=len(out.shape) - 1)
        elif c == "d" and dropout_rate:
            out = layers.dropout(out, dropout_rate,
                                 dropout_implementation="upscale_in_train")
    return out


def encoder_layer(x, attn_bias, n_head, d_key, d_value, d_model, d_inner,
                  dropout_rate=0.0, causal=False, fused=False):
    attn = multi_head_attention(
        pre_post_process(None, x, "n"), None, None, attn_bias,
        d_key, d_value, d_model, n_head, dropout_rate,
        causal=causal, fused=fused)
    attn_out = pre_post_process(x, attn, "da", dropout_rate)
    ffn = positionwise_ffn(pre_post_process(None, attn_out, "n"),
                           d_inner, d_model, dropout_rate)
    return pre_post_process(attn_out, ffn, "da", dropout_rate)


def decoder_layer(x, enc_out, slf_attn_bias, dec_enc_attn_bias, n_head,
                  d_key, d_value, d_model, d_inner, dropout_rate=0.0):
    slf = multi_head_attention(
        pre_post_process(None, x, "n"), None, None, slf_attn_bias,
        d_key, d_value, d_model, n_head, dropout_rate)
    slf_out = pre_post_process(x, slf, "da", dropout_rate)
    cross = multi_head_attention(
        pre_post_process(None, slf_out, "n"), enc_out, enc_out,
        dec_enc_attn_bias, d_key, d_value, d_model, n_head, dropout_rate)
    cross_out = pre_post_process(slf_out, cross, "da", dropout_rate)
    ffn = positionwise_ffn(pre_post_process(None, cross_out, "n"),
                           d_inner, d_model, dropout_rate)
    return pre_post_process(cross_out, ffn, "da", dropout_rate)


def pad_bias(mask, neg: float = -1e9):
    """[B,T] {0,1} padding mask -> [B,1,1,T] additive attention bias
    (0 where attendable, `neg` at pads)."""
    b = layers.scale(mask, scale=-neg, bias=neg)
    return layers.unsqueeze(b, [1, 2])


def prepare_embedding(ids, vocab_size, d_model, max_len, dropout_rate=0.0,
                      name="src"):
    """Token embedding * sqrt(d_model) + sinusoid position encoding."""
    emb = layers.embedding(
        ids, size=[vocab_size, d_model],
        param_attr=ParamAttr(name=f"{name}_word_emb"))
    emb = layers.scale(emb, scale=float(d_model) ** 0.5)
    seq_len = int(ids.shape[1])
    if seq_len > max_len:
        raise ValueError(f"sequence length {seq_len} exceeds the model's "
                         f"max_length {max_len}")
    pos_table = position_encoding_table(max_len, d_model)[:seq_len]
    helper_attr = ParamAttr(
        name=f"{name}_pos_enc_{seq_len}", trainable=False,
        initializer=NumpyArrayInitializer(pos_table))
    from ..framework.layer_helper import LayerHelper
    helper = LayerHelper("pos_enc")
    pos = helper.create_parameter(helper_attr, shape=[seq_len, d_model],
                                  dtype="float32")
    pos_var = layers.unsqueeze(pos, [0])                 # [1,T,D]
    out = layers.elementwise_add(emb, pos_var)
    if dropout_rate:
        out = layers.dropout(out, dropout_rate,
                             dropout_implementation="upscale_in_train")
    return out


def encoder(src_ids, src_attn_bias, n_layer, n_head, d_key, d_value,
            d_model, d_inner, vocab_size, max_len, dropout_rate=0.0):
    x = prepare_embedding(src_ids, vocab_size, d_model, max_len,
                          dropout_rate, name="src")
    for _ in range(n_layer):
        x = encoder_layer(x, src_attn_bias, n_head, d_key, d_value,
                          d_model, d_inner, dropout_rate)
    return pre_post_process(None, x, "n")


def decoder(tgt_ids, enc_out, slf_attn_bias, dec_enc_attn_bias, n_layer,
            n_head, d_key, d_value, d_model, d_inner, vocab_size, max_len,
            dropout_rate=0.0):
    x = prepare_embedding(tgt_ids, vocab_size, d_model, max_len,
                          dropout_rate, name="tgt")
    for _ in range(n_layer):
        x = decoder_layer(x, enc_out, slf_attn_bias, dec_enc_attn_bias,
                          n_head, d_key, d_value, d_model, d_inner,
                          dropout_rate)
    return pre_post_process(None, x, "n")


class TransformerConfig:
    """Transformer-base hyperparameters (ref dist_transformer.py
    TrainTaskConfig/ModelHyperParams)."""

    def __init__(self, src_vocab_size=30000, tgt_vocab_size=30000,
                 max_length=256, n_layer=6, n_head=8, d_model=512,
                 d_inner=2048, dropout=0.1, label_smooth_eps=0.1):
        self.src_vocab_size = src_vocab_size
        self.tgt_vocab_size = tgt_vocab_size
        self.max_length = max_length
        self.n_layer = n_layer
        self.n_head = n_head
        self.d_model = d_model
        self.d_key = d_model // n_head
        self.d_value = d_model // n_head
        self.d_inner = d_inner
        self.dropout = dropout
        self.label_smooth_eps = label_smooth_eps


def build_train_net(cfg: TransformerConfig, src_len: int, tgt_len: int,
                    is_test: bool = False):
    """Feeds: src_ids [B,Ts] int64, tgt_ids [B,Tt] int64, lbl_ids [B,Tt]
    int64, src_mask [B,Ts] float32 (1=token, 0=pad), tgt_mask [B,Tt].
    Attention biases are derived in-graph from the masks (dense, TPU-first —
    no LoD)."""
    dropout = 0.0 if is_test else cfg.dropout
    src_ids = layers.data("src_ids", [src_len], dtype="int64")
    tgt_ids = layers.data("tgt_ids", [tgt_len], dtype="int64")
    lbl_ids = layers.data("lbl_ids", [tgt_len], dtype="int64")
    src_mask = layers.data("src_mask", [src_len], dtype="float32")
    tgt_mask = layers.data("tgt_mask", [tgt_len], dtype="float32")

    neg_inf = -1e9
    src_attn_bias = pad_bias(src_mask)
    tgt_pad_bias = pad_bias(tgt_mask)
    # causal bias [1,1,Tt,Tt]
    causal = np.triu(np.full((tgt_len, tgt_len), neg_inf, dtype="float32"), 1)
    causal_var = layers.assign(causal[None, None, :, :])
    tgt_slf_bias = layers.elementwise_add(tgt_pad_bias, causal_var)

    enc_out = encoder(src_ids, src_attn_bias, cfg.n_layer, cfg.n_head,
                      cfg.d_key, cfg.d_value, cfg.d_model, cfg.d_inner,
                      cfg.src_vocab_size, cfg.max_length, dropout)
    dec_out = decoder(tgt_ids, enc_out, tgt_slf_bias, src_attn_bias,
                      cfg.n_layer, cfg.n_head, cfg.d_key, cfg.d_value,
                      cfg.d_model, cfg.d_inner, cfg.tgt_vocab_size,
                      cfg.max_length, dropout)

    logits = layers.fc(dec_out, size=cfg.tgt_vocab_size, num_flatten_dims=2,
                       bias_attr=False)
    logits2d = layers.reshape(logits, [-1, cfg.tgt_vocab_size])
    label2d = layers.reshape(lbl_ids, [-1, 1])
    if cfg.label_smooth_eps and not is_test:
        soft = layers.label_smooth(
            layers.one_hot(label2d, cfg.tgt_vocab_size),
            epsilon=cfg.label_smooth_eps)
        soft = layers.reshape(soft, [-1, cfg.tgt_vocab_size])
        cost = layers.softmax_with_cross_entropy(logits2d, soft,
                                                 soft_label=True)
    else:
        cost = layers.softmax_with_cross_entropy(logits2d, label2d)
    weights2d = layers.reshape(tgt_mask, [-1, 1])
    weighted = layers.elementwise_mul(cost, weights2d)
    sum_cost = layers.reduce_sum(weighted)
    token_count = layers.reduce_sum(weights2d)
    avg_cost = layers.elementwise_div(sum_cost, token_count)

    feeds = [src_ids, tgt_ids, lbl_ids, src_mask, tgt_mask]
    return feeds, avg_cost, logits


def build_lm_net(cfg: TransformerConfig, seq_len: int, is_test: bool = False,
                 fused_attention: bool = True, fused_head: bool = False,
                 pp_stages: int = 1):
    """Decoder-only causal LM on the encoder stack (the flagship bench
    config; the reference's closest analogue is the language-model rows of
    benchmark/fluid/).  Feeds: tokens [B,T] int64, labels [B,T] int64 —
    dense batches, causal masking inside the attention op (flash kernel
    when fused_attention), no LoD.

    Returns (feeds, avg_cost, logits); with fused_head=True the logits
    are never materialized (chunked remat head) so the third element is
    None."""
    dropout = 0.0 if is_test else cfg.dropout
    tokens = layers.data("tokens", [seq_len], dtype="int64")
    labels = layers.data("labels", [seq_len], dtype="int64")
    x = prepare_embedding(tokens, cfg.src_vocab_size, cfg.d_model,
                          cfg.max_length, dropout, name="src")
    if fused_attention:
        attn_bias = None
    else:
        # build the causal bias IN-GRAPH from a [T] iota — baking a
        # [T, T] constant into the program breaks compilation at long T
        # (e.g. 268MB at T=8192)
        r = layers.assign(np.arange(seq_len, dtype="float32"))
        row = layers.reshape(r, [seq_len, 1])
        col = layers.reshape(r, [1, seq_len])
        future = layers.cast(layers.greater_than(col, row), "float32")
        attn_bias = layers.reshape(layers.scale(future, scale=-1e9),
                                   [1, 1, seq_len, seq_len])
    if cfg.n_layer % pp_stages:
        raise ValueError(f"n_layer {cfg.n_layer} not divisible by "
                         f"pp_stages {pp_stages}")
    per_stage = cfg.n_layer // pp_stages
    for li in range(cfg.n_layer):
        x = encoder_layer(x, attn_bias, cfg.n_head, cfg.d_key, cfg.d_value,
                          cfg.d_model, cfg.d_inner, dropout,
                          causal=True, fused=fused_attention)
        # pipeline-ready build: mark the stage cuts for
        # transpiler/pipeline.py (identity ops otherwise)
        if (pp_stages > 1 and (li + 1) % per_stage == 0
                and li + 1 < cfg.n_layer):
            x = layers.pipeline_boundary(x)
    x = pre_post_process(None, x, "n")
    if fused_head:
        # chunked remat head: no [N, V] logits in HBM (fwd or bwd)
        x2d = layers.reshape(x, [-1, cfg.d_model])
        label1d = layers.reshape(labels, [-1])
        avg_cost = layers.fused_lm_head_loss(x2d, cfg.src_vocab_size,
                                             label1d)
        return [tokens, labels], avg_cost, None
    logits = layers.fc(x, size=cfg.src_vocab_size, num_flatten_dims=2,
                       bias_attr=False)
    logits2d = layers.reshape(logits, [-1, cfg.src_vocab_size])
    label2d = layers.reshape(labels, [-1, 1])
    cost = layers.softmax_with_cross_entropy(logits2d, label2d)
    avg_cost = layers.mean(cost)
    return [tokens, labels], avg_cost, logits


def lm_program_spec(program):
    """Parameter-name structure of an UNFUSED ``build_lm_net`` program.

    Walks the op list of a program built with ``fused_attention=False,
    fused_head=False`` and maps each trained parameter to its role in
    the decoder stack — the binding the serving decode engine
    (serving/kv_cache.py) uses to run the SAME weights through an
    incremental KV-cache step without re-tracing the program.  The op
    topology per layer is fixed by :func:`encoder_layer`:

      layer_norm -> mul(qkv) -> ... -> mul(out_proj) -> residual ->
      layer_norm -> mul(ffn1)+bias -> relu -> mul(ffn2)+bias -> residual

    followed by one final layer_norm and the mul LM head.  Returns::

        {"emb": name, "layers": [{"ln1": (scale, bias), "w_qkv": name,
          "w_o": name, "ln2": (scale, bias), "w_fc1": name,
          "b_fc1": name, "w_fc2": name, "b_fc2": name}, ...],
         "ln_f": (scale, bias), "w_head": name, "n_layer": L}

    Raises ValueError when the program does not look like that build
    (e.g. the fused_mha path, whose projections live inside one op).
    """
    from ..framework.program import Parameter
    block = program.global_block()

    def _is_param(name: str) -> bool:
        try:
            return isinstance(block.var(name), Parameter)
        except KeyError:
            return False

    emb = None
    muls, lns, biases = [], [], []
    for op in block.ops:
        if op.type == "lookup_table" and emb is None:
            emb = op.inputs["W"][0]
        elif op.type == "layer_norm":
            lns.append((op.inputs["Scale"][0], op.inputs["Bias"][0]))
        elif op.type == "mul" and _is_param(op.inputs["Y"][0]):
            muls.append(op.inputs["Y"][0])
        elif op.type == "elementwise_add" and _is_param(op.inputs["Y"][0]):
            biases.append(op.inputs["Y"][0])
    if emb is None or not muls or (len(muls) - 1) % 4:
        raise ValueError(
            "lm_program_spec: program is not an unfused build_lm_net "
            f"graph (found {len(muls)} fc weights, embedding="
            f"{emb!r}); build with fused_attention=False, "
            "fused_head=False")
    n_layer = (len(muls) - 1) // 4
    if len(lns) != 2 * n_layer + 1 or len(biases) != 2 * n_layer:
        raise ValueError(
            f"lm_program_spec: op census mismatch — {len(muls)} fc "
            f"weights imply {n_layer} layers but found {len(lns)} "
            f"layer_norms (want {2 * n_layer + 1}) and {len(biases)} "
            f"fc biases (want {2 * n_layer})")
    layers = []
    for li in range(n_layer):
        w_qkv, w_o, w_fc1, w_fc2 = muls[4 * li:4 * li + 4]
        layers.append({
            "ln1": lns[2 * li], "w_qkv": w_qkv, "w_o": w_o,
            "ln2": lns[2 * li + 1],
            "w_fc1": w_fc1, "b_fc1": biases[2 * li],
            "w_fc2": w_fc2, "b_fc2": biases[2 * li + 1]})
    return {"emb": emb, "layers": layers, "ln_f": lns[-1],
            "w_head": muls[-1], "n_layer": n_layer}


def make_fake_lm_batch(cfg: TransformerConfig, batch_size: int,
                       seq_len: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(1, cfg.src_vocab_size,
                         (batch_size, seq_len)).astype("int64")
    return {"tokens": tokens, "labels": np.roll(tokens, -1, axis=1)}


def make_fake_batch(cfg: TransformerConfig, batch_size: int, src_len: int,
                    tgt_len: int, seed: int = 0):
    """Synthetic WMT-like batch for tests/benchmarks."""
    rng = np.random.RandomState(seed)
    feed = {
        "src_ids": rng.randint(1, cfg.src_vocab_size, (batch_size, src_len)).astype("int64"),
        "tgt_ids": rng.randint(1, cfg.tgt_vocab_size, (batch_size, tgt_len)).astype("int64"),
        "lbl_ids": rng.randint(1, cfg.tgt_vocab_size, (batch_size, tgt_len)).astype("int64"),
        "src_mask": np.ones((batch_size, src_len), dtype="float32"),
        "tgt_mask": np.ones((batch_size, tgt_len), dtype="float32"),
    }
    return feed
