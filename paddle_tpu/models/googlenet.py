"""GoogLeNet (Inception v1) — the second half of the reference's GPU
headline table (BASELINE.md: benchmark/README.md GoogLeNet rows,
1149 ms/batch at bs128 on K40m; IntelOptimizedPaddle.md CPU rows).

Inception module = four parallel towers (1x1 / 1x1->3x3 / 1x1->5x5 /
pool->1x1) concatenated on channels; three classifier heads at train
time (main + two auxiliary, reference weighting 1.0/0.3/0.3).
"""
from __future__ import annotations

from .. import layers


def _inception(x, c1, c3r, c3, c5r, c5, proj):
    t1 = layers.conv2d(x, num_filters=c1, filter_size=1, act="relu")
    t2 = layers.conv2d(x, num_filters=c3r, filter_size=1, act="relu")
    t2 = layers.conv2d(t2, num_filters=c3, filter_size=3, padding=1,
                       act="relu")
    t3 = layers.conv2d(x, num_filters=c5r, filter_size=1, act="relu")
    t3 = layers.conv2d(t3, num_filters=c5, filter_size=5, padding=2,
                       act="relu")
    t4 = layers.pool2d(x, pool_size=3, pool_stride=1, pool_padding=1)
    t4 = layers.conv2d(t4, num_filters=proj, filter_size=1, act="relu")
    return layers.concat([t1, t2, t3, t4], axis=1)


def _aux_head(x, class_dim, is_test):
    a = layers.adaptive_pool2d(x, pool_size=4, pool_type="avg")
    a = layers.conv2d(a, num_filters=128, filter_size=1, act="relu")
    a = layers.fc(a, size=1024, act="relu")
    a = layers.dropout(a, 0.7, is_test=is_test,
                       dropout_implementation="upscale_in_train")
    return layers.fc(a, size=class_dim, act="softmax")


def googlenet(images, class_dim: int = 1000, is_test: bool = False):
    """Returns (main_pred, aux1_pred, aux2_pred)."""
    x = layers.conv2d(images, num_filters=64, filter_size=7, stride=2,
                      padding=3, act="relu")
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1)
    x = layers.conv2d(x, num_filters=64, filter_size=1, act="relu")
    x = layers.conv2d(x, num_filters=192, filter_size=3, padding=1,
                      act="relu")
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1)
    x = _inception(x, 64, 96, 128, 16, 32, 32)        # 3a -> 256
    x = _inception(x, 128, 128, 192, 32, 96, 64)      # 3b -> 480
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1)
    x = _inception(x, 192, 96, 208, 16, 48, 64)       # 4a -> 512
    aux1 = _aux_head(x, class_dim, is_test)
    x = _inception(x, 160, 112, 224, 24, 64, 64)      # 4b
    x = _inception(x, 128, 128, 256, 24, 64, 64)      # 4c
    x = _inception(x, 112, 144, 288, 32, 64, 64)      # 4d
    aux2 = _aux_head(x, class_dim, is_test)
    x = _inception(x, 256, 160, 320, 32, 128, 128)    # 4e -> 832
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1)
    x = _inception(x, 256, 160, 320, 32, 128, 128)    # 5a
    x = _inception(x, 384, 192, 384, 48, 128, 128)    # 5b -> 1024
    x = layers.pool2d(x, pool_size=7, pool_stride=1, pool_type="avg",
                      global_pooling=True)
    x = layers.dropout(x, 0.4, is_test=is_test,
                      dropout_implementation="upscale_in_train")
    main = layers.fc(x, size=class_dim, act="softmax")
    return main, aux1, aux2


def build_train_net(class_dim: int = 1000, img_shape=(3, 224, 224),
                    is_test: bool = False):
    """Builds (feeds, avg_loss, acc, prediction); loss = main + 0.3 *
    (aux1 + aux2), the reference's deep-supervision weighting."""
    images = layers.data("img", list(img_shape), dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    main, aux1, aux2 = googlenet(images, class_dim, is_test=is_test)
    cost = layers.mean(layers.cross_entropy(main, label))
    cost1 = layers.mean(layers.cross_entropy(aux1, label))
    cost2 = layers.mean(layers.cross_entropy(aux2, label))
    avg_loss = layers.elementwise_add(
        cost, layers.scale(layers.elementwise_add(cost1, cost2),
                           scale=0.3))
    acc = layers.accuracy(input=main, label=label)
    return [images, label], avg_loss, acc, main
