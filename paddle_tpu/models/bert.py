"""BERT-base pretraining — BASELINE config 5 (data-parallel on v5e-64).

Encoder reuses the Transformer blocks (models/transformer.py); adds token-type
embeddings, learned position embeddings, MLM + NSP heads.  Capability parity
target: "BERT-base pretraining (ParallelExecutor data-parallel on v5e-64)"
(BASELINE.json); the reference has no BERT in-tree — its equivalent scale
path is ParallelExecutor+NCCL (paddle/fluid/framework/parallel_executor.cc),
which here is the Mesh/pjit plane.
"""
from __future__ import annotations

import numpy as np

from .. import layers
from ..framework.layer_helper import ParamAttr
from .transformer import encoder_layer, pad_bias, pre_post_process


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072, max_position=512,
                 type_vocab_size=2, dropout=0.1):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout


def bert_embeddings(input_ids, token_type_ids, cfg: BertConfig,
                    dropout_rate: float):
    seq_len = int(input_ids.shape[1])
    if seq_len > cfg.max_position:
        raise ValueError(f"sequence length {seq_len} exceeds max_position "
                         f"{cfg.max_position}")
    word = layers.embedding(input_ids, [cfg.vocab_size, cfg.hidden_size],
                            param_attr=ParamAttr(name="word_embedding"))
    ttype = layers.embedding(token_type_ids,
                             [cfg.type_vocab_size, cfg.hidden_size],
                             param_attr=ParamAttr(name="token_type_embedding"))
    from ..framework.layer_helper import LayerHelper
    helper = LayerHelper("pos_emb")
    pos = helper.create_parameter(ParamAttr(name="position_embedding"),
                                  shape=[cfg.max_position, cfg.hidden_size],
                                  dtype="float32")
    pos_slice = layers.slice(pos, axes=[0], starts=[0], ends=[seq_len])
    pos_b = layers.unsqueeze(pos_slice, [0])
    x = layers.elementwise_add(layers.elementwise_add(word, ttype), pos_b)
    x = layers.layer_norm(x, begin_norm_axis=2)
    if dropout_rate:
        x = layers.dropout(x, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return x


def bert_encoder(input_ids, token_type_ids, input_mask, cfg: BertConfig,
                 is_test=False):
    dropout = 0.0 if is_test else cfg.dropout
    attn_bias = pad_bias(input_mask)
    x = bert_embeddings(input_ids, token_type_ids, cfg, dropout)
    d_key = cfg.hidden_size // cfg.num_heads
    for _ in range(cfg.num_layers):
        x = encoder_layer(x, attn_bias, cfg.num_heads, d_key, d_key,
                          cfg.hidden_size, cfg.intermediate_size, dropout)
    return pre_post_process(None, x, "n")


def build_pretrain_net(cfg: BertConfig, seq_len: int,
                       is_test: bool = False):
    """MLM (gathered masked positions) + NSP heads.

    Feeds: input_ids [B,T] i64, token_type_ids [B,T] i64, input_mask [B,T]
    f32, mask_pos [B*P] i64 (flattened positions into [B*T]), mask_label
    [B*P,1] i64, mask_weight [B*P,1] f32, nsp_label [B,1] i64.
    """
    input_ids = layers.data("input_ids", [seq_len], dtype="int64")
    token_type_ids = layers.data("token_type_ids", [seq_len], dtype="int64")
    input_mask = layers.data("input_mask", [seq_len], dtype="float32")
    # flattened masked-position feeds: [B*max_preds(,1)]
    mask_pos = layers.data("mask_pos", [-1], dtype="int64",
                           append_batch_size=False)
    mask_label = layers.data("mask_label", [-1, 1], dtype="int64",
                             append_batch_size=False)
    mask_weight = layers.data("mask_weight", [-1, 1], dtype="float32",
                              append_batch_size=False)
    nsp_label = layers.data("nsp_label", [1], dtype="int64")

    enc = bert_encoder(input_ids, token_type_ids, input_mask, cfg,
                       is_test=is_test)                      # [B,T,H]

    # --- MLM head ---------------------------------------------------------
    flat = layers.reshape(enc, [-1, cfg.hidden_size])        # [B*T,H]
    picked = layers.gather(flat, mask_pos)                   # [B*P,H]
    h = layers.fc(picked, size=cfg.hidden_size, act="gelu")
    h = layers.layer_norm(h, begin_norm_axis=1)
    mlm_logits = layers.fc(h, size=cfg.vocab_size)           # [B*P,V]
    mlm_cost = layers.softmax_with_cross_entropy(mlm_logits, mask_label)
    mlm_loss = layers.elementwise_div(
        layers.reduce_sum(layers.elementwise_mul(mlm_cost, mask_weight)),
        layers.elementwise_add(layers.reduce_sum(mask_weight),
                               layers.assign(np.array(1e-6, "float32"))))

    # --- NSP head ---------------------------------------------------------
    cls = layers.slice(enc, axes=[1], starts=[0], ends=[1])  # [B,1,H]
    cls = layers.reshape(cls, [-1, cfg.hidden_size])
    pooled = layers.fc(cls, size=cfg.hidden_size, act="tanh")
    nsp_logits = layers.fc(pooled, size=2)
    nsp_cost = layers.softmax_with_cross_entropy(nsp_logits, nsp_label)
    nsp_loss = layers.mean(nsp_cost)

    total_loss = layers.elementwise_add(mlm_loss, nsp_loss)
    feeds = [input_ids, token_type_ids, input_mask, mask_pos, mask_label,
             mask_weight, nsp_label]
    return feeds, total_loss, (mlm_loss, nsp_loss)


def make_fake_batch(cfg: BertConfig, batch_size: int, seq_len: int,
                    max_preds: int = 20, seed: int = 0):
    rng = np.random.RandomState(seed)
    n = batch_size * max_preds
    # positions index into the flattened [B*T] token axis
    pos = (np.arange(n) % seq_len
           + (np.arange(n) // max_preds) * seq_len).astype("int64")
    return {
        "input_ids": rng.randint(0, cfg.vocab_size,
                                 (batch_size, seq_len)).astype("int64"),
        "token_type_ids": rng.randint(0, cfg.type_vocab_size,
                                      (batch_size, seq_len)).astype("int64"),
        "input_mask": np.ones((batch_size, seq_len), dtype="float32"),
        "mask_pos": pos,
        "mask_label": rng.randint(0, cfg.vocab_size, (n, 1)).astype("int64"),
        "mask_weight": np.ones((n, 1), dtype="float32"),
        "nsp_label": rng.randint(0, 2, (batch_size, 1)).astype("int64"),
    }
