"""AlexNet — the reference's most-benchmarked config (BASELINE.md:
benchmark/README.md tables at bs64..512 on K40m; IntelOptimizedPaddle.md
CPU rows).

Classic topology (conv11/4 + LRN + pool, conv5, 3x conv3, two fc4096
with dropout), NCHW, built on the layers DSL like the reference's
benchmark/fluid model definitions.
"""
from __future__ import annotations

from .. import layers


def alexnet(images, class_dim: int = 1000, is_test: bool = False):
    drop = 0.0 if is_test else 0.5
    x = layers.conv2d(images, num_filters=96, filter_size=11, stride=4,
                      padding=2, act="relu")
    x = layers.lrn(x, n=5, alpha=1e-4, beta=0.75)
    x = layers.pool2d(x, pool_size=3, pool_stride=2)
    x = layers.conv2d(x, num_filters=256, filter_size=5, padding=2,
                      groups=2, act="relu")
    x = layers.lrn(x, n=5, alpha=1e-4, beta=0.75)
    x = layers.pool2d(x, pool_size=3, pool_stride=2)
    x = layers.conv2d(x, num_filters=384, filter_size=3, padding=1,
                      act="relu")
    x = layers.conv2d(x, num_filters=384, filter_size=3, padding=1,
                      groups=2, act="relu")
    x = layers.conv2d(x, num_filters=256, filter_size=3, padding=1,
                      groups=2, act="relu")
    x = layers.pool2d(x, pool_size=3, pool_stride=2)
    x = layers.fc(x, size=4096, act="relu")
    x = layers.dropout(x, drop, is_test=is_test,
                       dropout_implementation="upscale_in_train")
    x = layers.fc(x, size=4096, act="relu")
    x = layers.dropout(x, drop, is_test=is_test,
                       dropout_implementation="upscale_in_train")
    return layers.fc(x, size=class_dim, act="softmax")


def build_train_net(class_dim: int = 1000, img_shape=(3, 224, 224),
                    is_test: bool = False):
    """Builds (feeds, avg_loss, acc, prediction) in the default program."""
    images = layers.data("img", list(img_shape), dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    pred = alexnet(images, class_dim, is_test=is_test)
    cost = layers.cross_entropy(input=pred, label=label)
    avg_loss = layers.mean(cost)
    acc = layers.accuracy(input=pred, label=label)
    return [images, label], avg_loss, acc, pred
