"""Stacked-LSTM text classification — the reference's LSTM benchmark config.

Capability parity with /root/reference/benchmark/fluid/stacked_dynamic_lstm.py
and benchmark/README.md:103-119 (2x lstm + fc, h=512, bs64 rows of the GPU
table): embedding -> [fc(4H) -> dynamic_lstm] x n_layers -> max pool over
time -> fc softmax.

TPU-first: sequences are dense [B, T] int32 with a float mask [B, T]
(1=token) instead of LoD ragged batches (SURVEY.md hard part (a)); the
per-timestep recurrence is ONE lax.scan inside the whole-program jit
(ops/rnn_ops.py), so XLA keeps h/c resident across steps.
"""
from __future__ import annotations

import numpy as np

from .. import layers


def stacked_lstm_net(words, mask, dict_dim, num_classes=2, emb_dim=128,
                     hidden_dim=128, num_layers=2):
    """words [B, T] int64, mask [B, T] float32.  Returns softmax prediction.

    Mirrors the reference net: each stack level feeds the previous hidden
    sequence through an fc to 4H gates then an LSTM; final max-over-time
    pool of the last layer's hidden states -> fc softmax.
    """
    emb = layers.embedding(words, size=[dict_dim, emb_dim])
    x = emb
    for _ in range(num_layers):
        proj = layers.fc(x, size=hidden_dim * 4, num_flatten_dims=2,
                         bias_attr=False)
        x, _ = layers.dynamic_lstm(proj, size=hidden_dim * 4, mask=mask)
    # masked max pool over time: push padded steps to a large negative
    neg = layers.scale(mask, scale=-1.0, bias=1.0)        # 1 at pad
    neg = layers.scale(neg, scale=-1e9)                   # -1e9 at pad
    x = layers.elementwise_add(x, layers.unsqueeze(neg, [2]))
    pooled = layers.reduce_max(x, dim=1)                  # [B, H]
    return layers.fc(pooled, size=num_classes, act="softmax")


def build_train_net(dict_dim=1000, seq_len=32, num_classes=2,
                    emb_dim=64, hidden_dim=64, num_layers=2):
    """Builds (feeds, avg_loss, acc, prediction) in the default program."""
    words = layers.data("words", [seq_len], dtype="int64")
    mask = layers.data("mask", [seq_len], dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    pred = stacked_lstm_net(words, mask, dict_dim, num_classes=num_classes,
                            emb_dim=emb_dim, hidden_dim=hidden_dim,
                            num_layers=num_layers)
    cost = layers.cross_entropy(input=pred, label=label)
    avg_loss = layers.mean(cost)
    acc = layers.accuracy(input=pred, label=label)
    return [words, mask, label], avg_loss, acc, pred


def make_fake_batch(batch_size, dict_dim=1000, seq_len=32, num_classes=2,
                    seed=0):
    """Synthetic separable task: class decides which vocab half dominates."""
    rng = np.random.RandomState(seed)
    label = rng.randint(0, num_classes, (batch_size, 1)).astype("int64")
    band = dict_dim // num_classes          # each class owns a vocab band
    words = rng.randint(0, band, (batch_size, seq_len)).astype("int64")
    words = words + band * label
    lens = rng.randint(seq_len // 2, seq_len + 1, (batch_size,))
    mask = (np.arange(seq_len)[None, :] < lens[:, None]).astype("float32")
    words = (words * mask).astype("int64")
    return {"words": words, "mask": mask, "label": label}
