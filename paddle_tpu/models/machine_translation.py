"""Seq2seq machine translation with attention + beam-search decode.

Capability parity with the reference book example
(/root/reference/python/paddle/fluid/tests/book/test_machine_translation.py:
GRU encoder-decoder with attention, trained with teacher forcing, decoded
with beam search via beam_search/beam_search_decode ops) — redesigned
TPU-first: dense [B, T] batches, the decoder step inside layers.StaticRNN
(ONE lax.scan under jit), and the whole beam loop compiled — no per-step
host control flow (ref uses a While loop over LoD beams).

Both programs (train, decode) name every parameter explicitly so a decode
program built after training reuses the trained weights from the scope.
"""
from __future__ import annotations

import numpy as np

from .. import layers
from ..framework.layer_helper import ParamAttr


def _attr(name):
    return ParamAttr(name=name)


def _attention(h, enc_states):
    """Dot-product attention: h [N, H], enc_states [N, Ts, H] ->
    context [N, H] (ref book example simple_attention)."""
    scores = layers.matmul(enc_states, layers.unsqueeze(h, [2]))  # [N,Ts,1]
    w = layers.softmax(scores, axis=1)
    ctx = layers.reduce_sum(layers.elementwise_mul(enc_states, w), dim=[1])
    return ctx


def _encoder(src, vocab_size, emb_dim, hidden_dim):
    emb = layers.embedding(src, size=[vocab_size, emb_dim],
                           param_attr=_attr("src_emb"))
    proj = layers.fc(emb, size=hidden_dim * 3, num_flatten_dims=2,
                     param_attr=_attr("enc_fc.w"), bias_attr=False)
    states = layers.dynamic_gru(proj, size=hidden_dim,
                                param_attr=_attr("enc_gru.w"),
                                bias_attr=_attr("enc_gru.b"))   # [B,Ts,H]
    Ts = int(states.shape[1])
    last = layers.squeeze(
        layers.slice(states, axes=[1], starts=[Ts - 1], ends=[Ts]), [1])
    return states, last


def _decoder_step(tok_emb, h_prev, enc_states, hidden_dim):
    """One decoder step: attention + GRU cell.  Shared by the teacher-
    forcing train loop and the beam decode loop (same parameter names)."""
    ctx = _attention(h_prev, enc_states)
    inp = layers.fc(layers.concat([tok_emb, ctx], axis=1),
                    size=hidden_dim * 3,
                    param_attr=_attr("dec_fc.w"), bias_attr=False)
    h, _, _ = layers.gru_unit(inp, h_prev, hidden_dim * 3,
                              param_attr=_attr("dec_gru.w"),
                              bias_attr=_attr("dec_gru.b"))
    return h


def build_train_net(src_vocab, tgt_vocab, src_len, tgt_len, emb_dim=32,
                    hidden_dim=32):
    """Feeds: src [B,Ts] int64, tgt [B,Tt] int64 (decoder input,
    start-token shifted), lbl [B,Tt] int64.  Returns (feeds, avg_cost)."""
    src = layers.data("src", [src_len], dtype="int64")
    tgt = layers.data("tgt", [tgt_len], dtype="int64")
    lbl = layers.data("lbl", [tgt_len], dtype="int64")

    enc_states, enc_last = _encoder(src, src_vocab, emb_dim, hidden_dim)
    tgt_emb = layers.embedding(tgt, size=[tgt_vocab, emb_dim],
                               param_attr=_attr("tgt_emb"))

    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(tgt_emb)                 # [B, E]
        h_prev = rnn.memory(init=enc_last)            # [B, H]
        h = _decoder_step(x_t, h_prev, enc_states, hidden_dim)
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    dec_out = rnn()                                   # [B, Tt, H]

    logits = layers.fc(dec_out, size=tgt_vocab, num_flatten_dims=2,
                       param_attr=_attr("out_fc.w"),
                       bias_attr=_attr("out_fc.b"))
    cost = layers.softmax_with_cross_entropy(
        layers.reshape(logits, [-1, tgt_vocab]),
        layers.reshape(lbl, [-1, 1]))
    avg_cost = layers.mean(cost)
    return [src, tgt, lbl], avg_cost


def build_decode_net(src_vocab, tgt_vocab, src_len, beam_size=4,
                     max_len=8, start_id=0, end_id=1, emb_dim=32,
                     hidden_dim=32):
    """Beam-search decode program (built AFTER training, same scope).

    Returns (feeds, sentence_ids [B,K,Tmax], sentence_scores [B,K])."""
    from ..framework.layer_helper import LayerHelper
    K = beam_size
    src = layers.data("src", [src_len], dtype="int64")
    enc_states, enc_last = _encoder(src, src_vocab, emb_dim, hidden_dim)

    # tile encoder outputs across beams: [B,Ts,H] -> [B*K,Ts,H]
    Ts, H = int(enc_states.shape[1]), hidden_dim
    enc_k = layers.reshape(
        layers.expand(layers.unsqueeze(enc_states, [1]), [1, K, 1, 1]),
        [-1, Ts, H])
    h0 = layers.reshape(
        layers.expand(layers.unsqueeze(enc_last, [1]), [1, K, 1]), [-1, H])

    # beam state: scores [B,K] (row 0 live, others -inf), tokens [B,K]
    scores0 = layers.fill_constant_batch_size_like(src, [-1, K], "float32",
                                                   0.0)
    mask_row = np.full((1, K), -1e9, "float32")
    mask_row[0, 0] = 0.0
    scores0 = layers.elementwise_add(scores0, layers.assign(mask_row))
    tok0 = layers.fill_constant_batch_size_like(src, [-1, K], "int32",
                                                start_id)
    dummy = layers.fill_constant_batch_size_like(src, [-1, max_len, 1],
                                                 "float32", 0.0)

    helper = LayerHelper("beam_decode")
    rnn = layers.StaticRNN()
    with rnn.step():
        rnn.step_input(dummy)                          # drives the length
        h_prev = rnn.memory(init=h0)                   # [B*K, H]
        sc_prev = rnn.memory(init=scores0)             # [B, K]
        tok_prev = rnn.memory(init=tok0)               # [B, K]

        emb = layers.reshape(
            layers.embedding(tok_prev, size=[tgt_vocab, emb_dim],
                             param_attr=_attr("tgt_emb")),
            [-1, emb_dim])                             # [B*K, E]
        h = _decoder_step(emb, h_prev, enc_k, hidden_dim)
        logits = layers.fc(h, size=tgt_vocab,
                           param_attr=_attr("out_fc.w"),
                           bias_attr=_attr("out_fc.b"))
        logp = layers.reshape(layers.log_softmax(logits),
                              [-1, K, tgt_vocab])      # [B, K, V]

        sc = helper.create_variable_for_type_inference("float32")
        ids = helper.create_variable_for_type_inference("int32")
        parents = helper.create_variable_for_type_inference("int32")
        h_re = helper.create_variable_for_type_inference("float32")
        helper.main_program.current_block().append_op(
            "beam_search",
            {"PreScores": [sc_prev.name], "PreIds": [tok_prev.name],
             "LogProbs": [logp.name],
             "State": [layers.reshape(h, [-1, K, H]).name]},
            {"Scores": [sc.name], "Ids": [ids.name],
             "Parents": [parents.name], "StateOut": [h_re.name]},
            {"beam_size": K, "end_id": end_id})

        rnn.update_memory(h_prev, layers.reshape(h_re, [-1, H]))
        rnn.update_memory(sc_prev, sc)
        rnn.update_memory(tok_prev, ids)
        rnn.step_output(ids)
        rnn.step_output(parents)
        rnn.step_output(sc)
    ids_t, parents_t, scores_t = rnn.outputs()         # each [Tmax, B, K]

    final_scores = layers.squeeze(
        layers.slice(scores_t, axes=[0], starts=[max_len - 1],
                     ends=[max_len]), [0])             # [B, K]
    sent = helper.create_variable_for_type_inference("int32")
    sent_scores = helper.create_variable_for_type_inference("float32")
    helper.main_program.current_block().append_op(
        "beam_search_decode",
        {"Ids": [ids_t.name], "Parents": [parents_t.name],
         "Scores": [final_scores.name]},
        {"SentenceIds": [sent.name], "SentenceScores": [sent_scores.name]},
        {})
    return [src], sent, sent_scores


def make_copy_task_batch(batch, src_len, vocab, seed=0, start_id=0,
                         end_id=1):
    """Toy task: target = source sequence (ids >= 2), ended with end_id.
    Separable enough that a few hundred steps make greedy decode echo."""
    rng = np.random.RandomState(seed)
    src = rng.randint(2, vocab, (batch, src_len)).astype("int64")
    tgt_in = np.concatenate(
        [np.full((batch, 1), start_id, "int64"), src[:, :-1]], axis=1)
    lbl = src.copy()
    return {"src": src, "tgt": tgt_in, "lbl": lbl}
