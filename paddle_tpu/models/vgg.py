"""VGG — capability parity with /root/reference/benchmark/fluid/models/vgg.py
(vgg16_bn_drop) on the paddle_tpu layers DSL."""
from __future__ import annotations

from .. import layers, nets


def vgg16_bn_drop(input, class_dim=1000):
    def conv_block(ipt, num_filter, groups, dropouts):
        return nets.img_conv_group(
            input=ipt, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3, conv_act="relu",
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type="max")

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = layers.dropout(conv5, dropout_prob=0.5)
    fc1 = layers.fc(drop, size=4096, act=None)
    bn = layers.batch_norm(fc1, act="relu")
    drop2 = layers.dropout(bn, dropout_prob=0.5)
    fc2 = layers.fc(drop2, size=4096, act=None)
    return layers.fc(fc2, size=class_dim, act="softmax")


def build_train_net(class_dim=10, img_shape=(3, 32, 32)):
    images = layers.data("img", list(img_shape), dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    prediction = vgg16_bn_drop(images, class_dim)
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_loss = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    return [images, label], avg_loss, acc, prediction
