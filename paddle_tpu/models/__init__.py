"""Model zoo: the five BASELINE.json configs (+ extras), built on the layers
DSL so every model is a serializable Program that compiles to one XLA
executable."""
from . import alexnet
from . import googlenet
from . import lenet
from . import resnet
from . import vgg
from . import transformer
from . import deepfm
from . import bert
from . import stacked_lstm
from . import machine_translation
from . import se_resnext
from . import book
