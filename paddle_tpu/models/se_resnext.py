"""SE-ResNeXt (50/101) — the reference's heavyweight vision config
(/root/reference/python/paddle/fluid/tests/unittests/dist_se_resnext.py
trains it through the distributed harness; also
benchmark/fluid/models/se_resnext-style).  Re-expressed on the dense
layers DSL: grouped 3x3 convolutions (cardinality 32) + squeeze-excite
channel gating.
"""
from __future__ import annotations

import numpy as np

from .. import layers


def conv_bn(x, num_filters, filter_size, stride=1, groups=1, act=None):
    conv = layers.conv2d(x, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, groups=groups,
                         bias_attr=False)
    return layers.batch_norm(conv, act=act)


def squeeze_excite(x, num_channels, reduction_ratio=16):
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    squeeze = layers.fc(pool, size=max(1, num_channels // reduction_ratio),
                        act="relu")
    excite = layers.fc(squeeze, size=num_channels, act="sigmoid")
    # [B, C] gate scales the [B, C, H, W] feature map channel-wise
    return layers.elementwise_mul(x, excite, axis=0)


def bottleneck(x, num_filters, stride, cardinality=32,
               reduction_ratio=16):
    expansion = 2          # SE-ResNeXt bottleneck expands width by 2
    conv0 = conv_bn(x, num_filters, 1, act="relu")
    conv1 = conv_bn(conv0, num_filters, 3, stride=stride,
                    groups=cardinality, act="relu")
    conv2 = conv_bn(conv1, num_filters * expansion, 1)
    scaled = squeeze_excite(conv2, num_filters * expansion,
                            reduction_ratio)
    in_c = int(x.shape[1])
    if in_c != num_filters * expansion or stride != 1:
        shortcut = conv_bn(x, num_filters * expansion, 1, stride=stride)
    else:
        shortcut = x
    return layers.relu(layers.elementwise_add(scaled, shortcut))


def se_resnext(x, class_dim=1000, depth=50, cardinality=32,
               reduction_ratio=16, stage_blocks=None):
    assert depth in (50, 101), depth
    if stage_blocks is None:
        stage_blocks = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3]}[depth]
    stage_filters = [128, 256, 512, 1024][:len(stage_blocks)]
    conv = conv_bn(x, 64, 7, stride=2, act="relu")
    conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max")
    for stage, n_blocks in enumerate(stage_blocks):
        for i in range(n_blocks):
            stride = 2 if i == 0 and stage > 0 else 1
            conv = bottleneck(conv, stage_filters[stage], stride,
                              cardinality, reduction_ratio)
    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool, dropout_prob=0.2)
    return layers.fc(drop, size=class_dim, act="softmax")


def build_train_net(class_dim=1000, img_shape=(3, 224, 224), depth=50,
                    is_test: bool = False, stage_blocks=None):
    """Builds (feeds, avg_loss, acc, prediction) in the default program."""
    images = layers.data("img", list(img_shape), dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    pred = se_resnext(images, class_dim, depth, stage_blocks=stage_blocks)
    cost = layers.cross_entropy(input=pred, label=label)
    avg_loss = layers.mean(cost)
    acc = layers.accuracy(input=pred, label=label)
    return [images, label], avg_loss, acc, pred


def make_fake_batch(batch_size, img_shape=(3, 224, 224), class_dim=1000,
                    seed=0):
    rng = np.random.RandomState(seed)
    return {"img": rng.rand(batch_size, *img_shape).astype("float32"),
            "label": rng.randint(0, class_dim,
                                 (batch_size, 1)).astype("int64")}
