"""ResNet family — BASELINE config 2 (ResNet-50 / ImageNet).

Capability parity with the reference benchmark model
(/root/reference/benchmark/fluid/models/resnet.py) and the SE-ResNeXt
distributed test model (python/paddle/fluid/tests/unittests/dist_se_resnext.py)
— re-expressed on the paddle_tpu layers DSL.  NCHW layout; XLA picks the
TPU-optimal internal layout and fuses BN+ReLU into the conv epilogue.
"""
from __future__ import annotations

from .. import layers


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False):
    conv = layers.conv2d(input, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, groups=groups,
                         act=None, bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def shortcut(input, ch_out, stride, is_test=False):
    ch_in = int(input.shape[1])
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride, act="relu",
                          is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, act=None,
                          is_test=is_test)
    short = shortcut(input, num_filters * 4, stride, is_test=is_test)
    return layers.elementwise_add(short, conv2, act="relu")


def basic_block(input, num_filters, stride, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 3, stride=stride, act="relu",
                          is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, act=None, is_test=is_test)
    short = shortcut(input, num_filters, stride, is_test=is_test)
    return layers.elementwise_add(short, conv1, act="relu")


_DEPTH_CFG = {
    18: (basic_block, [2, 2, 2, 2]),
    34: (basic_block, [3, 4, 6, 3]),
    50: (bottleneck_block, [3, 4, 6, 3]),
    101: (bottleneck_block, [3, 4, 23, 3]),
    152: (bottleneck_block, [3, 8, 36, 3]),
}


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False):
    block_fn, counts = _DEPTH_CFG[depth]
    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu", is_test=is_test)
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max")
    num_filters = [64, 128, 256, 512]
    for stage, n in enumerate(counts):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            pool = block_fn(pool, num_filters[stage], stride, is_test=is_test)
    pool = layers.pool2d(pool, pool_type="avg", global_pooling=True)
    return layers.fc(pool, size=class_dim, act="softmax")


def resnet50(input, class_dim=1000, is_test=False):
    return resnet_imagenet(input, class_dim, depth=50, is_test=is_test)


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False):
    """ref benchmark/fluid/models/resnet.py resnet_cifar10 (6n+2 layout)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv = conv_bn_layer(input, 16, 3, act="relu", is_test=is_test)
    for stage, nf in enumerate([16, 32, 64]):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            conv = basic_block(conv, nf, stride, is_test=is_test)
    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
    return layers.fc(pool, size=class_dim, act="softmax")


def build_train_net(class_dim=1000, img_shape=(3, 224, 224), depth=50,
                    is_test=False):
    images = layers.data("img", list(img_shape), dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    prediction = resnet_imagenet(images, class_dim, depth, is_test=is_test)
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_loss = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    return [images, label], avg_loss, acc, prediction
