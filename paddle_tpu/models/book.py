"""The remaining reference book examples as model builders.

Parity targets (/root/reference/python/paddle/fluid/tests/book/):
  * test_fit_a_line.py            -> fit_a_line
  * test_word2vec.py              -> word2vec (N-gram LM)
  * test_recommender_system.py    -> recommender_system
  * test_rnn_encoder_decoder.py   -> rnn_encoder_decoder
  * test_label_semantic_roles.py  -> db_lstm (SRL with CRF)

(the other book examples live in their own modules: lenet.py
= recognize_digits, resnet/vgg = image_classification,
machine_translation.py = machine_translation.)

Dense-idiom note: LoD-level-1 inputs of the reference become padded
[B, T] int tensors (+ optional masks); everything compiles to one XLA
program through the Executor.
"""
from __future__ import annotations

import numpy as np

from .. import layers, nets
from ..framework.layer_helper import ParamAttr


# --- fit_a_line (test_fit_a_line.py:30) -----------------------------------

def fit_a_line(x_dim: int = 13):
    """Linear regression on UCI housing: fc(1) + square_error_cost."""
    x = layers.data("x", [x_dim], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    y_predict = layers.fc(x, size=1, act=None)
    cost = layers.square_error_cost(input=y_predict, label=y)
    avg_cost = layers.mean(cost)
    return [x, y], avg_cost, y_predict


# --- word2vec (test_word2vec.py:35) ---------------------------------------

def word2vec(dict_size: int, embed_size: int = 32, hidden_size: int = 256,
             n_gram: int = 4):
    """N-gram LM: per-position embeddings over ONE shared table
    ('shared_w', as the reference shares via param_attr name), concat,
    fc sigmoid, softmax over the vocab."""
    words = [layers.data(f"word_{i}", [1], dtype="int64")
             for i in range(n_gram)]
    next_word = layers.data("next_word", [1], dtype="int64")
    embeds = [layers.embedding(w, size=[dict_size, embed_size],
                               param_attr=ParamAttr(name="shared_w"))
              for w in words]
    concat = layers.concat(embeds, axis=-1)
    concat = layers.reshape(concat, [-1, n_gram * embed_size])
    hidden = layers.fc(concat, size=hidden_size, act="sigmoid")
    predict = layers.fc(hidden, size=dict_size, act="softmax")
    cost = layers.cross_entropy(input=predict, label=next_word)
    avg_cost = layers.mean(cost)
    return words + [next_word], avg_cost, predict


# --- recommender_system (test_recommender_system.py:34,93,143) ------------

def recommender_system(user_dict=100, gender_dict=2, age_dict=7,
                       job_dict=21, movie_dict=200, category_dict=10,
                       title_dict=500, title_len=8, categories_len=3):
    """Dual-tower CTR: user tower (id/gender/age/job embeddings -> fcs ->
    concat -> fc200 tanh) x movie tower (id emb fc + category sum-pool +
    title sequence_conv_pool -> fc200 tanh), cos_sim scaled to [0,5],
    square error vs score."""
    def emb_fc(data_name, vocab, emb_dim, fc_dim, table):
        v = layers.data(data_name, [1], dtype="int64")
        e = layers.embedding(v, size=[vocab, emb_dim],
                             param_attr=ParamAttr(name=table))
        e = layers.reshape(e, [-1, emb_dim])
        return v, layers.fc(e, size=fc_dim)

    uid, usr_fc = emb_fc("user_id", user_dict, 32, 32, "user_table")
    gid, gender_fc = emb_fc("gender_id", gender_dict, 16, 16,
                            "gender_table")
    aid, age_fc = emb_fc("age_id", age_dict, 16, 16, "age_table")
    jid, job_fc = emb_fc("job_id", job_dict, 16, 16, "job_table")
    usr_combined = layers.fc(
        layers.concat([usr_fc, gender_fc, age_fc, job_fc], axis=1),
        size=200, act="tanh")

    mid, mov_fc = emb_fc("movie_id", movie_dict, 32, 32, "movie_table")
    cat = layers.data("category_id", [categories_len], dtype="int64")
    cat_emb = layers.embedding(cat, size=[category_dict, 32],
                               param_attr=ParamAttr(name="category_table"))
    cat_pool = layers.sequence_pool(cat_emb, "sum")
    title = layers.data("movie_title", [title_len], dtype="int64")
    title_emb = layers.embedding(title, size=[title_dict, 32],
                                 param_attr=ParamAttr(name="title_table"))
    title_conv = nets.sequence_conv_pool(title_emb, num_filters=32,
                                         filter_size=3, act="tanh",
                                         pool_type="sum")
    mov_combined = layers.fc(
        layers.concat([mov_fc, cat_pool, title_conv], axis=1),
        size=200, act="tanh")

    inference = layers.cos_sim(usr_combined, mov_combined)
    scale_infer = layers.scale(inference, scale=5.0)
    score = layers.data("score", [1], dtype="float32")
    cost = layers.square_error_cost(input=scale_infer, label=score)
    avg_cost = layers.mean(cost)
    feeds = [uid, gid, aid, jid, mid, cat, title, score]
    return feeds, avg_cost, scale_infer


# --- rnn_encoder_decoder (test_rnn_encoder_decoder.py:42,87,121) ----------

def rnn_encoder_decoder(src_dict=100, tgt_dict=100, embed_dim=16,
                        encoder_size=32, decoder_size=32, src_len=8,
                        tgt_len=8):
    """Seq2seq without attention: bi-LSTM encoder; the backward-direction
    first step boots the decoder (fc tanh), the forward last step is the
    per-step context (reference concatenates context each step; dense
    redesign: context is tiled over decoder time and concatenated with
    the target embedding before one dynamic_lstm)."""
    src = layers.data("src_word", [src_len], dtype="int64")
    tgt = layers.data("tgt_word", [tgt_len], dtype="int64")
    label = layers.data("label", [tgt_len], dtype="int64")

    src_emb = layers.embedding(src, size=[src_dict, embed_dim])
    fwd, _ = layers.lstm_layer(src_emb, encoder_size)
    bwd, _ = layers.lstm_layer(src_emb, encoder_size, is_reverse=True)
    src_forward_last = layers.sequence_last_step(fwd)
    src_backward_first = layers.sequence_first_step(bwd)
    context = layers.concat([src_forward_last, src_backward_first], axis=1)
    decoder_boot = layers.fc(src_backward_first, size=decoder_size,
                             act="tanh")

    tgt_emb = layers.embedding(tgt, size=[tgt_dict, embed_dim])
    ctx = layers.reshape(context, [-1, 1, 2 * encoder_size])
    ctx = layers.expand(ctx, [1, tgt_len, 1])
    dec_in = layers.concat([tgt_emb, ctx], axis=2)
    boot_c = layers.fill_constant_batch_size_like(
        decoder_boot, shape=[-1, decoder_size], dtype="float32", value=0.0)
    proj = layers.fc(dec_in, size=4 * decoder_size, num_flatten_dims=2)
    hidden, _ = layers.dynamic_lstm(proj, 4 * decoder_size,
                                    h_0=decoder_boot, c_0=boot_c)
    predict = layers.fc(hidden, size=tgt_dict, act="softmax",
                        num_flatten_dims=2)
    cost = layers.cross_entropy(
        input=layers.reshape(predict, [-1, tgt_dict]),
        label=layers.reshape(label, [-1, 1]))
    avg_cost = layers.mean(cost)
    return [src, tgt, label], avg_cost, predict


# --- label_semantic_roles (test_label_semantic_roles.py:53) ---------------

def db_lstm(word_dict=100, label_dict=10, pred_dict=50, mark_dict=2,
            word_dim=32, mark_dim=5, hidden_dim=64, depth=4, seq_len=8,
            emb_lr=2.0):
    """SRL deep bidirectional LSTM + CRF: 6 word-feature slots share one
    embedding table, predicate + mark have their own; per-slot fcs are
    summed; `depth` alternating-direction LSTM layers; final fc pair
    feeds linear_chain_crf (train) / crf_decoding (predict)."""
    word_slots = ["word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2"]
    datas = [layers.data(f"{s}_data", [seq_len], dtype="int64")
             for s in word_slots]
    predicate = layers.data("verb_data", [seq_len], dtype="int64")
    mark = layers.data("mark_data", [seq_len], dtype="int64")
    target = layers.data("target", [seq_len], dtype="int64")

    pred_emb = layers.embedding(predicate, size=[pred_dict, word_dim],
                                param_attr=ParamAttr(name="vemb"))
    mark_emb = layers.embedding(mark, size=[mark_dict, mark_dim])
    emb_layers = [
        layers.embedding(x, size=[word_dict, word_dim],
                         param_attr=ParamAttr(name="emb",
                                              learning_rate=emb_lr))
        for x in datas]
    emb_layers += [pred_emb, mark_emb]

    hidden_0 = layers.sequence.sum(
        [layers.fc(e, size=hidden_dim, num_flatten_dims=2)
         for e in emb_layers])
    lstm_0, _ = layers.dynamic_lstm(
        layers.fc(hidden_0, size=4 * hidden_dim, num_flatten_dims=2),
        size=4 * hidden_dim)

    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = layers.sequence.sum([
            layers.fc(input_tmp[0], size=hidden_dim, num_flatten_dims=2),
            layers.fc(input_tmp[1], size=hidden_dim, num_flatten_dims=2)])
        lstm, _ = layers.dynamic_lstm(
            layers.fc(mix_hidden, size=4 * hidden_dim, num_flatten_dims=2),
            size=4 * hidden_dim, is_reverse=((i % 2) == 1))
        input_tmp = [mix_hidden, lstm]

    feature_out = layers.sequence.sum([
        layers.fc(input_tmp[0], size=label_dict, act="tanh",
                  num_flatten_dims=2),
        layers.fc(input_tmp[1], size=label_dict, act="tanh",
                  num_flatten_dims=2)])

    crf_cost = layers.linear_chain_crf(
        feature_out, target,
        param_attr=ParamAttr(name="crfw", learning_rate=1.0))
    avg_cost = layers.mean(layers.scale(crf_cost, scale=-1.0))
    crf_decode = layers.crf_decoding(feature_out,
                                     param_attr=ParamAttr(name="crfw"))
    feeds = datas + [predicate, mark, target]
    return feeds, avg_cost, crf_decode
