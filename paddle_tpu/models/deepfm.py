"""DeepFM CTR — BASELINE config 4 (high-dim sparse embedding).

Capability parity with the reference's CTR models
(/root/reference/python/paddle/fluid/tests/unittests/dist_ctr.py and the
distributed-lookup-table path, transpiler/distribute_transpiler.py:1010) —
the pserver-sharded embedding becomes a Mesh-sharded in-HBM table: the
embedding Parameter carries a PartitionSpec that row-shards it over the
'model' axis, and XLA turns the lookup into all-gather/collective ops
(see parallel/sharded_embedding.py for the shard_map path).
"""
from __future__ import annotations

import numpy as np

from .. import layers
from ..framework.layer_helper import ParamAttr


class DeepFMConfig:
    def __init__(self, num_field=39, vocab_size=1000001, embed_dim=10,
                 fc_sizes=(400, 400, 400), sparse_shard_axis=None):
        self.num_field = num_field
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.fc_sizes = tuple(fc_sizes)
        # PartitionSpec axis name to row-shard the big tables over (e.g.
        # "model"); None = replicated.
        self.sparse_shard_axis = sparse_shard_axis


def deepfm(feat_ids, feat_vals, cfg: DeepFMConfig):
    """feat_ids [B,F] int64, feat_vals [B,F] float32 -> p(click) [B,1].

    FM first-order + second-order + deep MLP (DeepFM, Guo et al. 2017);
    same capability class as the reference CTR example but one dense graph.
    """
    shard = ((cfg.sparse_shard_axis, None)
             if cfg.sparse_shard_axis else None)
    # first-order weights: [V,1] table
    w1 = layers.embedding(
        feat_ids, size=[cfg.vocab_size, 1],
        param_attr=ParamAttr(name="fm_w1", sharding=shard))      # [B,F,1]
    first_order = layers.reduce_sum(
        layers.elementwise_mul(layers.squeeze(w1, [2]), feat_vals),
        dim=[1], keep_dim=True)                                   # [B,1]

    # second-order: embeddings [V,K]
    emb = layers.embedding(
        feat_ids, size=[cfg.vocab_size, cfg.embed_dim],
        param_attr=ParamAttr(name="fm_emb", sharding=shard))      # [B,F,K]
    vals = layers.unsqueeze(feat_vals, [2])                       # [B,F,1]
    xv = layers.elementwise_mul(emb, vals)                        # [B,F,K]
    sum_sq = layers.square(layers.reduce_sum(xv, dim=[1]))        # [B,K]
    sq_sum = layers.reduce_sum(layers.square(xv), dim=[1])        # [B,K]
    second_order = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum),
                          dim=[1], keep_dim=True), scale=0.5)     # [B,1]

    # deep part
    deep = layers.reshape(xv, [-1, cfg.num_field * cfg.embed_dim])
    for size in cfg.fc_sizes:
        deep = layers.fc(deep, size=size, act="relu")
    deep_out = layers.fc(deep, size=1, act=None)                  # [B,1]

    logit = layers.elementwise_add(
        layers.elementwise_add(first_order, second_order), deep_out)
    return logit


def build_train_net(cfg: DeepFMConfig):
    feat_ids = layers.data("feat_ids", [cfg.num_field], dtype="int64")
    feat_vals = layers.data("feat_vals", [cfg.num_field], dtype="float32")
    label = layers.data("label", [1], dtype="float32")
    logit = deepfm(feat_ids, feat_vals, cfg)
    cost = layers.sigmoid_cross_entropy_with_logits(logit, label)
    avg_cost = layers.mean(cost)
    prob = layers.sigmoid(logit)
    return [feat_ids, feat_vals, label], avg_cost, prob


def make_fake_batch(cfg: DeepFMConfig, batch_size: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    return {
        "feat_ids": rng.randint(0, cfg.vocab_size,
                                (batch_size, cfg.num_field)).astype("int64"),
        "feat_vals": rng.rand(batch_size, cfg.num_field).astype("float32"),
        "label": rng.randint(0, 2, (batch_size, 1)).astype("float32"),
    }
