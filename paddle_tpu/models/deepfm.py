"""DeepFM CTR — BASELINE config 4 (high-dim sparse embedding).

Capability parity with the reference's CTR models
(/root/reference/python/paddle/fluid/tests/unittests/dist_ctr.py and the
distributed-lookup-table path, transpiler/distribute_transpiler.py:1010) —
the pserver-sharded embedding becomes a Mesh-sharded in-HBM table: the
embedding Parameter carries a PartitionSpec that row-shards it over the
'model' axis, and XLA turns the lookup into all-gather/collective ops
(see parallel/sharded_embedding.py for the shard_map path).
"""
from __future__ import annotations

import numpy as np

from .. import layers
from ..framework.layer_helper import ParamAttr


class DeepFMConfig:
    def __init__(self, num_field=39, vocab_size=1000001, embed_dim=10,
                 fc_sizes=(400, 400, 400), sparse_shard_axis=None):
        self.num_field = num_field
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.fc_sizes = tuple(fc_sizes)
        # PartitionSpec axis name to row-shard the big tables over (e.g.
        # "model"); None = replicated.
        self.sparse_shard_axis = sparse_shard_axis


def deepfm(feat_ids, feat_vals, cfg: DeepFMConfig, embed=None):
    """feat_ids [B,F] int64, feat_vals [B,F] float32 -> p(click) [B,1].

    FM first-order + second-order + deep MLP (DeepFM, Guo et al. 2017);
    same capability class as the reference CTR example but one dense
    graph.  ``embed(input, size, name)`` builds the table lookups —
    default is :func:`layers.embedding` with the config's sharding;
    the sparse plane swaps in the hash-bucketed
    :func:`layers.sparse_embedding` (see build_sparse_train_net), so
    ONE forward serves both table disciplines.
    """
    if embed is None:
        shard = ((cfg.sparse_shard_axis, None)
                 if cfg.sparse_shard_axis else None)

        def embed(input, size, name):
            return layers.embedding(
                input, size=size,
                param_attr=ParamAttr(name=name, sharding=shard))

    # first-order weights: [V,1] table
    w1 = embed(feat_ids, [cfg.vocab_size, 1], "fm_w1")           # [B,F,1]
    first_order = layers.reduce_sum(
        layers.elementwise_mul(layers.squeeze(w1, [2]), feat_vals),
        dim=[1], keep_dim=True)                                   # [B,1]

    # second-order: embeddings [V,K]
    emb = embed(feat_ids, [cfg.vocab_size, cfg.embed_dim],
                "fm_emb")                                         # [B,F,K]
    vals = layers.unsqueeze(feat_vals, [2])                       # [B,F,1]
    xv = layers.elementwise_mul(emb, vals)                        # [B,F,K]
    sum_sq = layers.square(layers.reduce_sum(xv, dim=[1]))        # [B,K]
    sq_sum = layers.reduce_sum(layers.square(xv), dim=[1])        # [B,K]
    second_order = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum),
                          dim=[1], keep_dim=True), scale=0.5)     # [B,1]

    # deep part
    deep = layers.reshape(xv, [-1, cfg.num_field * cfg.embed_dim])
    for size in cfg.fc_sizes:
        deep = layers.fc(deep, size=size, act="relu")
    deep_out = layers.fc(deep, size=1, act=None)                  # [B,1]

    logit = layers.elementwise_add(
        layers.elementwise_add(first_order, second_order), deep_out)
    return logit


def build_train_net(cfg: DeepFMConfig, embed=None):
    feat_ids = layers.data("feat_ids", [cfg.num_field], dtype="int64")
    feat_vals = layers.data("feat_vals", [cfg.num_field], dtype="float32")
    label = layers.data("label", [1], dtype="float32")
    logit = deepfm(feat_ids, feat_vals, cfg, embed=embed)
    cost = layers.sigmoid_cross_entropy_with_logits(logit, label)
    avg_cost = layers.mean(cost)
    prob = layers.sigmoid(logit)
    return [feat_ids, feat_vals, label], avg_cost, prob


def make_fake_batch(cfg: DeepFMConfig, batch_size: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    return {
        "feat_ids": rng.randint(0, cfg.vocab_size,
                                (batch_size, cfg.num_field)).astype("int64"),
        "feat_vals": rng.rand(batch_size, cfg.num_field).astype("float32"),
        "label": rng.randint(0, 2, (batch_size, 1)).astype("float32"),
    }


def build_sparse_train_net(cfg: DeepFMConfig):
    """DeepFM over the sparse plane's Program-level ops: the SAME
    :func:`deepfm` forward with the embedding lookups swapped for
    ``sparse_embedding_lookup`` (hash bucketing on, so raw ids of any
    magnitude fold into the ``vocab_size`` buckets — the CTR
    id-folding discipline; the host-plane twin is
    paddle_tpu/sparse/table.hash_bucket).  Registered as the 19th
    model of the ``python -m paddle_tpu.analysis.lint`` gate."""
    def embed(input, size, name):
        return layers.sparse_embedding(
            input, size=size, param_attr=ParamAttr(name=name))

    return build_train_net(cfg, embed=embed)


# -- criteo-shaped synthetic dataset (MultiSlot text, sparse plane) --------
#
# The streaming pipeline's ground-truth dataset: per line, one id slot
# per categorical field, one dense value slot of width num_field, one
# label slot — the shape of a criteo-style CTR shard fed to the
# reference's MultiSlotDataFeed.  Labels are DRAWN from a seeded
# logistic model over hidden per-id weights, so the task is learnable
# (AUC well above 0.5) and two runs over the same files chase the same
# optimum — the async-vs-sync parity tests depend on that.

def criteo_slots(num_field: int):
    from ..framework.async_executor import Slot
    return ([Slot(f"C{f}", "uint64", dim=1) for f in range(num_field)]
            + [Slot("feat_vals", "float", is_dense=True,
                    dim=num_field),
               Slot("label", "float", is_dense=True, dim=1)])


def criteo_feed_desc(num_field: int, batch_size: int = 32):
    from ..framework.async_executor import DataFeedDesc
    return DataFeedDesc(criteo_slots(num_field),
                        batch_size=batch_size, name="criteo")


def make_criteo_files(dirpath, n_files: int, lines_per_file: int,
                      num_field: int = 8, vocab_size: int = 128,
                      seed: int = 0):
    """Write ``n_files`` MultiSlot shards under ``dirpath``; returns
    the sorted path list.  Line format (criteo_slots order)::

        1 <id_0> ... 1 <id_{F-1}> <F> <v_0> ... <v_{F-1}> 1 <label>
    """
    import os
    rng = np.random.RandomState(seed)
    w_true = np.random.RandomState(seed + 7919).randn(
        vocab_size).astype("float64") * 2.0
    paths = []
    for fi in range(n_files):
        path = os.path.join(dirpath, f"criteo-part-{fi:05d}")
        with open(path, "w") as f:
            for _ in range(lines_per_file):
                ids = rng.randint(0, vocab_size, num_field)
                vals = rng.rand(num_field)
                logit = float((w_true[ids] * vals).sum())
                label = int(rng.rand() < 1.0 / (1.0 + np.exp(-logit)))
                f.write(" ".join(f"1 {i}" for i in ids)
                        + f" {num_field} "
                        + " ".join(f"{v:.4f}" for v in vals)
                        + f" 1 {label}\n")
        paths.append(path)
    return paths


def load_criteo_files(files, num_field: int):
    """Parse shards back into dense arrays (ids [N,F] int64, vals
    [N,F] f32, label [N,1] f32) — the reference-run/eval side of the
    parity tests; the streaming path never calls this."""
    desc = criteo_feed_desc(num_field)
    ids, vals, labels = [], [], []
    for path in files:
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                row = desc.parse_line(line, lineno=lineno, source=path)
                ids.append([int(row[f"C{i}"][0])
                            for i in range(num_field)])
                vals.append(row["feat_vals"])
                labels.append(row["label"])
    return (np.asarray(ids, "int64"), np.asarray(vals, "float32"),
            np.asarray(labels, "float32").reshape(-1, 1))
