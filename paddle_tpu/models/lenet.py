"""MNIST LeNet — BASELINE config 1.

Capability parity with the reference book example
(/root/reference/python/paddle/fluid/tests/book/test_recognize_digits.py:
`convolutional_neural_network`), built on the paddle_tpu layers DSL and
compiled as one XLA program by the Executor.
"""
from __future__ import annotations

from .. import layers, nets


def lenet(images, num_classes: int = 10):
    """conv5x5x20-pool2 -> conv5x5x50-pool2 (+BN) -> fc10 softmax."""
    conv_pool_1 = nets.simple_img_conv_pool(
        input=images, filter_size=5, num_filters=20,
        pool_size=2, pool_stride=2, act="relu")
    conv_pool_1 = layers.batch_norm(conv_pool_1)
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50,
        pool_size=2, pool_stride=2, act="relu")
    return layers.fc(conv_pool_2, size=num_classes, act="softmax")


def softmax_regression(images, num_classes: int = 10):
    """ref book test_recognize_digits.py softmax_regression."""
    return layers.fc(images, size=num_classes, act="softmax")


def multilayer_perceptron(images, num_classes: int = 10):
    h1 = layers.fc(images, size=200, act="tanh")
    h2 = layers.fc(h1, size=200, act="tanh")
    return layers.fc(h2, size=num_classes, act="softmax")


def build_train_net(net_fn=lenet, img_shape=(1, 28, 28)):
    """Builds (feeds, avg_loss, acc, prediction) in the default program."""
    images = layers.data("img", list(img_shape), dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    prediction = net_fn(images)
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_loss = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    return [images, label], avg_loss, acc, prediction
