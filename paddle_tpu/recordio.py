"""Pure-Python recordio: identical on-disk format to native/recordio.cc.

Capability parity with the reference's recordio
(/root/reference/paddle/fluid/recordio/ + python recordio_writer.py):
chunked records with CRC32, crash-tolerant scan.  The native C++ path
(paddle_tpu/fast) is preferred for throughput; this module guarantees the
format works everywhere and is the cross-check in tests.
"""
from __future__ import annotations

import struct
import zlib
from typing import Iterator, List

MAGIC = 0x50545243
_HEADER = struct.Struct("<IIIQI")   # magic, flags, num_records, payload_len, crc


class RecordIOWriter:
    def __init__(self, path: str, max_chunk_records: int = 1000,
                 max_chunk_bytes: int = 1 << 20):
        self._f = open(path, "wb")
        self._pending: List[bytes] = []
        self._pending_bytes = 0
        self.max_chunk_records = max_chunk_records
        self.max_chunk_bytes = max_chunk_bytes

    def write(self, record: bytes):
        self._pending.append(bytes(record))
        self._pending_bytes += len(record)
        if (len(self._pending) >= self.max_chunk_records
                or self._pending_bytes >= self.max_chunk_bytes):
            self._flush_chunk()

    def _flush_chunk(self):
        if not self._pending:
            return
        payload = b"".join(struct.pack("<I", len(r)) + r
                           for r in self._pending)
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._f.write(_HEADER.pack(MAGIC, 0, len(self._pending),
                                   len(payload), crc))
        self._f.write(payload)
        self._pending = []
        self._pending_bytes = 0

    def close(self):
        self._flush_chunk()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def scan(path: str) -> Iterator[bytes]:
    """Yield records; skip corrupted/truncated chunks (crash tolerance)."""
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    n = len(data)
    while off + _HEADER.size <= n:
        magic, flags, num, plen, crc = _HEADER.unpack_from(data, off)
        if magic != MAGIC:
            off += 1   # resync scan
            continue
        start = off + _HEADER.size
        end = start + plen
        if end > n:
            break      # truncated tail
        payload = data[start:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            off += 1   # corrupted: resync from next byte
            continue
        p = 0
        records = []
        ok = True
        for _ in range(num):
            if p + 4 > len(payload):
                ok = False
                break
            (rlen,) = struct.unpack_from("<I", payload, p)
            p += 4
            if p + rlen > len(payload):
                ok = False
                break
            records.append(payload[p:p + rlen])
            p += rlen
        if ok:
            yield from records
        off = end


def write_records(path: str, records) -> int:
    cnt = 0
    with RecordIOWriter(path) as w:
        for r in records:
            w.write(r)
            cnt += 1
    return cnt
