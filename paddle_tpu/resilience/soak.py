"""Elastic-fleet chaos-matrix soak runner.

``python -m paddle_tpu.resilience.soak`` drives the full supervisor
end-to-end — master with snapshot + membership reaper, N supervised
``elastic_worker`` processes — under seeded fault schedules, and exits
nonzero when any schedule hangs or the completion ledger is not
exactly-once:

* ``worker_kill``     — chaos ``exit`` hard-kills rank 0 mid-task; the
  supervisor restarts it and it resumes from checkpoint.
* ``master_restart``  — the master is shut down mid-run and restarted
  on the same port from its snapshot (generation bump, leases void);
  clients re-dial and the fleet drains the queue.
* ``rpc_refuse``      — chaos ``refuse`` opens connection-refused
  windows at the RPC site; clients back off / re-dial through them.
* ``combined``        — all of the above in one run.
* ``fixed``           — no faults, no resizes: the fixed-fleet baseline
  the elastic runs are compared against.
* ``resize_grow`` / ``resize_shrink`` / ``resize_combined`` — elastic
  resizes (ISSUE 14): the task master's ``request_resize`` drains the
  current epoch, then the fleet grows (2→3), shrinks (2→1), or grows
  while chaos kill-9s rank 0 (``resize_combined``); the supervisor
  spawns/retires rank processes to match.
* ``resize_soak``     — the headline: 2→4→1→3 across four epochs.

Every schedule asserts: all workers exit 0 inside the deadline, every
(task, epoch) pair completes EXACTLY once in the master's persisted
ledger, fenced acks were rejected (never recorded), and — per
schedule — the dead worker was restarted within its backoff budget /
the generation bumped.  Resize schedules additionally assert the fleet
landed on the planned final world, epochs after a shrink were worked
ONLY by surviving ranks, the fleet-summed end state equals the
fixed-fleet value (:func:`expected_w_total` — the stand-in training
update is commutative, so exactly-once processing implies equality),
and the union of per-rank ``consumed`` records covers every
(shard, epoch) reader example exactly once — nothing dropped, nothing
double-consumed across resizes.  The same :func:`run_schedule` body
backs the tier-1 e2e tests (tests/test_elastic.py, tests/
test_resize.py) and the ``slow``-marked soak lane.
"""
from __future__ import annotations

import json
import os
import socket
import sys
import time
import zlib
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

SCHEDULES = ("worker_kill", "master_restart", "rpc_refuse", "combined",
             "fixed", "resize_grow", "resize_shrink", "resize_combined",
             "resize_soak")

# world-size plan per resize schedule: one entry per epoch BOUNDARY
# (requested mid-epoch, applied when the epoch drains), so a plan of
# length k needs at least k+1 epochs
RESIZE_PLANS = {
    "resize_grow": (3,),
    "resize_shrink": (1,),
    "resize_combined": (3,),
    "resize_soak": (4, 1, 3),
}

# master timing: the heartbeat reaper (worker death -> immediate
# requeue) must be what recovers leases, not the per-task timeout —
# keep the task lease LONG so a hung run proves membership worked
_LEASE_TIMEOUT = 60.0
_WORKER_TIMEOUT = 1.0
_HEARTBEAT_INTERVAL = 0.2


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _seed_where_exit_fires(prob: float, lo: int, hi: int,
                           site: str = "trainer.step") -> int:
    """Smallest chaos seed whose first ``exit`` firing at `site` lands
    in invocation window [lo, hi) — pure crc32 math (the chaos plane's
    own decision function), so the kill point is chosen deterministically
    without running anything."""
    for seed in range(10_000):
        fires = [n for n in range(hi)
                 if zlib.crc32(f"{seed}:{site}:{n}".encode())
                 / 0xFFFFFFFF < prob]
        if fires and lo <= fires[0] < hi:
            return seed
    raise RuntimeError("no seed found (unreachable for sane prob)")


def worker_cmd(endpoints: str, world: int, rank: int, out: str,
               ckpt_dir: str) -> List[str]:
    return [sys.executable, "-m", "paddle_tpu.resilience.elastic_worker",
            endpoints, str(world), str(rank), out, ckpt_dir]


def worker_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)    # one CPU device per process
    env.pop("PYTHONPATH", None)   # axon plugin quirk (tests/conftest.py)
    env["PTPU_WORKER_HEARTBEAT_INTERVAL"] = str(_HEARTBEAT_INTERVAL)
    # ride through the master-restart gap without exhausting the RPC
    # retry budget (downtime is short but nonzero)
    env["PTPU_RETRY_MAX_ATTEMPTS"] = "8"
    if extra:
        env.update(extra)
    return env


def expected_w_total(n_tasks: int, epochs: int) -> float:
    """The fixed-fleet end state for a clean run over ``n_tasks``
    shards x ``epochs``: the elastic worker's stand-in update is a
    commutative pure sum of per-(shard, epoch) contributions, so ANY
    fleet — fixed, resized, chaos-restarted — that processes each pair
    exactly once lands on this value.  Computed with the worker's OWN
    ``_apply`` (not a re-derived formula) so the oracle is
    definitionally consistent with what the fleet runs.  The resize
    schedules assert their fleet-summed end state equals it: the
    'same final loss as a fixed-fleet run' check."""
    import numpy as np

    from paddle_tpu.resilience.elastic_worker import _apply
    w = np.zeros(16, dtype="float64")
    for i in range(n_tasks):
        for ep in range(epochs):
            w = _apply(w, f"shard-{i:03d}", ep)
    return float(w.sum())


def check_consumed(workers: List[dict], n_tasks: int,
                   epochs: int) -> List[str]:
    """Reader-example exactly-once: the union of per-rank ``consumed``
    records (each rank's checkpointed multiset of applied (shard,
    epoch) pairs, reconciled against the ledger across restarts and
    resizes) must cover every pair exactly once."""
    from collections import Counter
    seen = Counter(tuple(c) for r in workers
                   for c in r.get("consumed", []))
    problems = []
    dups = sorted(k for k, v in seen.items() if v > 1)
    if dups:
        problems.append(f"reader examples double-consumed: {dups}")
    want = {(f"shard-{i:03d}", ep)
            for i in range(n_tasks) for ep in range(epochs)}
    missing = sorted(want - set(seen))
    if missing:
        problems.append(f"reader examples lost: {missing}")
    extra = sorted(set(seen) - want)
    if extra:
        problems.append(f"unexpected reader examples: {extra}")
    return problems


def check_ledger(ledger: List[dict], n_tasks: int,
                 epochs: int) -> List[str]:
    """Exactly-once: every (task, epoch) pair completed once, none
    twice, none missing.  Returns human-readable problems (empty =
    pass)."""
    problems = []
    seen: Dict[tuple, int] = {}
    for e in ledger:
        seen[(e["task_id"], e["epoch"])] = \
            seen.get((e["task_id"], e["epoch"]), 0) + 1
    dups = sorted(k for k, v in seen.items() if v > 1)
    if dups:
        problems.append(f"duplicate completions (fenced ack accepted?): "
                        f"{dups}")
    want = {(t, ep) for t in range(n_tasks) for ep in range(epochs)}
    missing = sorted(want - set(seen))
    if missing:
        problems.append(f"missing completions: {missing}")
    extra = sorted(set(seen) - want)
    if extra:
        problems.append(f"unexpected completions: {extra}")
    return problems


def run_schedule(workdir: str, name: str, seed: int = 0, world: int = 2,
                 n_tasks: int = 6, epochs: int = 2,
                 timeout: float = 120.0) -> dict:
    """One schedule end-to-end; returns a report dict with ``ok`` and
    ``problems`` (see module docstring for the assertions)."""
    from paddle_tpu.distributed.supervisor import Supervisor
    from paddle_tpu.distributed.task_queue import (TaskMaster,
                                                   serve_master)

    if name not in SCHEDULES:
        raise ValueError(f"unknown schedule {name!r} "
                         f"(expected one of {SCHEDULES})")
    resize_plan = list(RESIZE_PLANS.get(name, ()))
    if resize_plan:
        # one boundary per planned world; the final world needs an
        # epoch of its own to prove it actually trains
        epochs = max(epochs, len(resize_plan) + 1)
    os.makedirs(workdir, exist_ok=True)
    t_start = time.time()
    port = _free_port()
    endpoints = f"127.0.0.1:{port}"
    snap = os.path.join(workdir, "master.json")

    def _master() -> "TaskMaster":
        # snapshot_interval=0: every mutation durable BEFORE the RPC
        # reply — the exactly-once-across-master-restart guarantee
        # assumes the ledger survives the restart
        return TaskMaster(snapshot_path=snap,
                          lease_timeout=_LEASE_TIMEOUT,
                          snapshot_interval=0.0,
                          worker_timeout=_WORKER_TIMEOUT,
                          num_epochs=epochs,
                          world_size=world if resize_plan else 0)

    master = _master()
    master.set_dataset([f"shard-{i:03d}" for i in range(n_tasks)])
    srv, _ = serve_master(master, port=port)

    kill_rank0 = name in ("worker_kill", "combined", "resize_combined")
    restart_master = name in ("master_restart", "combined")
    refuse = name in ("rpc_refuse", "combined")

    # ranks that will ever exist: the launch fleet plus every grow
    # target — out/checkpoint paths are per-rank for the whole run
    max_world = max([world] + resize_plan)
    envs: List[Optional[Dict[str, str]]] = [None] * world
    if kill_rank0:
        # die on the 2nd or 3rd leased task (mid-epoch, at least one
        # task completed first), at a deterministically pre-computed
        # invocation — late enough to be mid-epoch, early enough that
        # rank 0 is guaranteed to reach it before the queue drains
        kseed = _seed_where_exit_fires(0.4, 1, 3)
        envs[0] = {"PTPU_CHAOS_SPEC": "trainer.step=exit:0.4:9",
                   "PTPU_CHAOS_SEED": str(kseed)}
    if refuse:
        rank = 1 if world > 1 else 0
        spec = "task_queue.rpc=refuse:0.05:0.2"
        cur = dict(envs[rank] or {})
        # refuse composes with an existing spec via ';'
        prev = cur.get("PTPU_CHAOS_SPEC", "")
        cur["PTPU_CHAOS_SPEC"] = (prev + ";" if prev else "") + spec
        cur.setdefault("PTPU_CHAOS_SEED", str(seed))
        envs[rank] = cur

    outs = [os.path.join(workdir, f"worker_{r}.json")
            for r in range(max_world)]

    def _cmd(rank: int) -> List[str]:
        return worker_cmd(endpoints, world, rank, outs[rank],
                          os.path.join(workdir, f"ckpt_r{rank}"))

    from paddle_tpu.resilience.elastic_worker import RETIRED_RC
    sup = Supervisor(
        cmds=[_cmd(r) for r in range(world)],
        env=worker_env(), envs=envs, cwd=REPO_ROOT,
        log_dir=workdir, cmd_factory=_cmd, retire_rc=RETIRED_RC)
    sup.start()

    generation_after = master.generation
    resizes_applied = 0
    try:
        if restart_master:
            # wait for real progress, then bounce the coordinator on
            # the SAME port: leases void, generation bumps, clients
            # re-dial and the fleet keeps going
            deadline = time.time() + timeout / 2
            while len(master.ledger_entries()) < world \
                    and time.time() < deadline:
                time.sleep(0.05)
            srv.shutdown()
            master = _master()       # recovers from the snapshot
            srv, _ = serve_master(master, port=port)
        # the resize driver: request each planned world, mirror it on
        # the supervisor (growth spawns now — new ranks wait out the
        # epoch; shrink is worker-side retirement), then wait for the
        # epoch boundary to flip it live before the next step
        for i, w_new in enumerate(resize_plan):
            master.request_resize(w_new)
            sup.set_world_size(w_new)
            deadline = time.time() + timeout / 2
            while master.resizes < i + 1 and time.time() < deadline:
                time.sleep(0.02)
            resizes_applied = master.resizes
        finished = sup.wait(timeout=timeout)
        generation_after = master.generation
        resizes_applied = master.resizes
        ledger = master.ledger_entries()
        stats = master.stats()
    finally:
        sup.stop()
        srv.shutdown()

    problems = []
    status = sup.status()
    if not finished:
        problems.append(f"fleet did not finish within {timeout}s: "
                        f"{status}")
    problems += check_ledger(ledger, n_tasks, epochs)
    if kill_rank0 and sup.restarts[0] < 1:
        problems.append("rank 0 was never restarted (chaos exit did "
                        "not fire or the supervisor missed the crash)")
    if restart_master and generation_after < 2:
        problems.append(f"master generation did not bump "
                        f"(still {generation_after})")
    # ranks ever in the fleet (launch set + every grow target) each
    # leave a final report; retired ranks' reports carry their share
    # of the end state
    spawned = set(range(world))
    for t in resize_plan:
        spawned |= set(range(t))
    workers = []
    for r in sorted(spawned):
        if os.path.exists(outs[r]):
            with open(outs[r]) as f:
                workers.append(json.load(f))
        else:
            problems.append(f"missing worker report {outs[r]}")
    w_total = sum(w["w_sum"] for w in workers)
    expected_total = expected_w_total(n_tasks, epochs)
    if resize_plan:
        if resizes_applied < len(resize_plan):
            problems.append(
                f"only {resizes_applied}/{len(resize_plan)} resizes "
                f"applied (epoch boundary never drained?)")
        elif stats["target_world_size"] != resize_plan[-1]:
            problems.append(
                f"fleet landed on world "
                f"{stats['target_world_size']}, plan said "
                f"{resize_plan[-1]}")
        # the master's resize_log records the FIRST epoch each new
        # world governed (epoch boundaries can outpace the driver, so
        # the plan alone doesn't pin which epoch maps to which world);
        # a completion by a rank outside its epoch's world means a
        # shrink leaked leases
        log = stats.get("resize_log", [])
        applied_worlds = [r["new"] for r in log]
        if applied_worlds != resize_plan[:len(applied_worlds)]:
            problems.append(f"resizes applied out of order: "
                            f"{log} vs plan {resize_plan}")

        def world_at(epoch):
            w_cur = world
            for r in log:
                if epoch >= r["epoch"]:
                    w_cur = r["new"]
            return w_cur

        bad = [e for e in ledger
               if e.get("worker") is not None
               and e["worker"] >= world_at(e["epoch"])]
        if bad:
            problems.append(f"completions by out-of-world ranks: "
                            f"{bad}")
    if resize_plan or name == "fixed":
        # the 'same final loss' check: commutative updates + exactly-
        # once processing => the fleet sum equals the fixed-fleet value
        if abs(w_total - expected_total) > 1e-6:
            problems.append(
                f"fleet end state {w_total!r} != fixed-fleet "
                f"{expected_total!r} (examples lost or "
                f"double-applied)")
        problems += check_consumed(workers, n_tasks, epochs)
    return {"schedule": name, "ok": not problems, "problems": problems,
            "seed": seed, "world": world, "n_tasks": n_tasks,
            "epochs": epochs, "ledger_entries": len(ledger),
            "restarts": dict(sup.restarts),
            "spawns": dict(sup.spawns),
            "resize_plan": resize_plan,
            "resizes_applied": resizes_applied,
            "generation": generation_after,
            "w_total": w_total, "expected_w_total": expected_total,
            "stats": stats, "workers": workers,
            "duration_s": round(time.time() - t_start, 2)}


def _main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.resilience.soak",
        description="Elastic-fleet chaos-matrix soak: supervisor e2e "
                    "under seeded fault schedules; nonzero exit on any "
                    "hang or exactly-once ledger violation.")
    ap.add_argument("--schedules", default=",".join(SCHEDULES),
                    help=f"comma list from {SCHEDULES} "
                         f"(default: all)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--tasks", type=int, default=6)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--workdir", default=None,
                    help="scratch root (default: a fresh tempdir)")
    ap.add_argument("--out", default=None,
                    help="write the full JSON report here")
    args = ap.parse_args(argv)
    names = [s.strip() for s in args.schedules.split(",") if s.strip()]
    bad = [n for n in names if n not in SCHEDULES]
    if bad:
        ap.error(f"unknown schedule(s) {bad}; pick from {SCHEDULES}")
    root = args.workdir
    if root is None:
        import tempfile
        root = tempfile.mkdtemp(prefix="ptpu_soak_")
    reports = []
    for name in names:
        rep = run_schedule(os.path.join(root, name), name,
                           seed=args.seed, world=args.world,
                           n_tasks=args.tasks, epochs=args.epochs,
                           timeout=args.timeout)
        reports.append(rep)
        verdict = "PASS" if rep["ok"] else "FAIL"
        resize = (f" resizes={rep['resizes_applied']}/"
                  f"{len(rep['resize_plan'])}" if rep["resize_plan"]
                  else "")
        print(f"[{verdict}] {name:<16} ledger={rep['ledger_entries']} "
              f"restarts={rep['restarts']} gen={rep['generation']}"
              f"{resize} {rep['duration_s']}s")
        for p in rep["problems"]:
            print(f"         - {p}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"reports": reports}, f, indent=2)
    failed = [r["schedule"] for r in reports if not r["ok"]]
    if failed:
        print(f"soak FAILED: {failed}")
        return 1
    print(f"soak OK: {len(reports)} schedule(s) clean under seed "
          f"{args.seed}")
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
