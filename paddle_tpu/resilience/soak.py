"""Elastic-fleet chaos-matrix soak runner.

``python -m paddle_tpu.resilience.soak`` drives the full supervisor
end-to-end — master with snapshot + membership reaper, N supervised
``elastic_worker`` processes — under seeded fault schedules, and exits
nonzero when any schedule hangs or the completion ledger is not
exactly-once:

* ``worker_kill``     — chaos ``exit`` hard-kills rank 0 mid-task; the
  supervisor restarts it and it resumes from checkpoint.
* ``master_restart``  — the master is shut down mid-run and restarted
  on the same port from its snapshot (generation bump, leases void);
  clients re-dial and the fleet drains the queue.
* ``rpc_refuse``      — chaos ``refuse`` opens connection-refused
  windows at the RPC site; clients back off / re-dial through them.
* ``combined``        — all of the above in one run.
* ``fixed``           — no faults, no resizes: the fixed-fleet baseline
  the elastic runs are compared against.
* ``resize_grow`` / ``resize_shrink`` / ``resize_combined`` — elastic
  resizes (ISSUE 14): the task master's ``request_resize`` drains the
  current epoch, then the fleet grows (2→3), shrinks (2→1), or grows
  while chaos kill-9s rank 0 (``resize_combined``); the supervisor
  spawns/retires rank processes to match.
* ``resize_soak``     — the headline: 2→4→1→3 across four epochs.
* ``controller`` / ``controller_ramp`` / ``controller_chaos`` — the
  Helmsman closed loop (ISSUE 17): an open-loop arrival trace (per-task
  service time rides in the shard name, ``shard-NNN#<seconds>``) feeds
  a STREAMING task master (``extend_dataset``, ``num_epochs=1``) while
  backlog-driven alert rules with ``action:`` clauses grow and shrink
  the fleet through the controller — ZERO human resize calls.
  ``controller`` is the tier-1 miniature (≥1 grow + ≥1 shrink);
  ``controller_ramp`` is the slow headline (two bursts/two valleys,
  ≥2 grow + ≥2 shrink, p99 task sojourn under
  ``serving_p99_budget_ms``, chip-seconds BEAT a static max-world
  baseline run of the same trace); ``controller_chaos`` additionally
  kill-9s rank 0 mid-task, bounces the coordinator mid-decision (the
  stale fence token must be REJECTED, the retry applies — no
  double-apply) and fires a drain action with no serving plane
  attached until the circuit breaker degrades the controller to
  alert-only mode.

Every schedule asserts: all workers exit 0 inside the deadline, every
(task, epoch) pair completes EXACTLY once in the master's persisted
ledger, fenced acks were rejected (never recorded), and — per
schedule — the dead worker was restarted within its backoff budget /
the generation bumped.  Resize schedules additionally assert the fleet
landed on the planned final world, epochs after a shrink were worked
ONLY by surviving ranks, the fleet-summed end state equals the
fixed-fleet value (:func:`expected_w_total` — the stand-in training
update is commutative, so exactly-once processing implies equality),
and the union of per-rank ``consumed`` records covers every
(shard, epoch) reader example exactly once — nothing dropped, nothing
double-consumed across resizes.  The same :func:`run_schedule` body
backs the tier-1 e2e tests (tests/test_elastic.py, tests/
test_resize.py) and the ``slow``-marked soak lane.
"""
from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

SCHEDULES = ("worker_kill", "master_restart", "rpc_refuse", "combined",
             "fixed", "resize_grow", "resize_shrink", "resize_combined",
             "resize_soak", "resize_soak_chaos", "controller",
             "controller_ramp", "controller_chaos")

# world-size plan per resize schedule: one entry per epoch BOUNDARY
# (requested mid-epoch, applied when the epoch drains), so a plan of
# length k needs at least k+1 epochs
RESIZE_PLANS = {
    "resize_grow": (3,),
    "resize_shrink": (1,),
    "resize_combined": (3,),
    "resize_soak": (4, 1, 3),
    # ISSUE 19: the Timecard conservation gate — the full 2->4->1->3
    # resize sweep PLUS a chaos-killed rank 0, so the goodput ledger
    # must survive restarts, parks and revives in one run
    "resize_soak_chaos": (4, 1, 3),
}

# Helmsman closed-loop profiles (ISSUE 17).  ``phases`` is the arrival
# trace: (duration_s, tasks_per_second) segments, each task carrying
# ``work_s`` of simulated service time in its shard name.  The
# controller's knobs (cooldown/hysteresis/clamps) ride the flags the
# schedule sets; ``grow_at``/``idle_for`` parameterize the backlog
# rules.  Numbers are sized so the policy outcome is structural, not a
# timing coin-flip: heavy phases oversubscribe the launch world by >2x
# (backlog must build), valleys are longer than idle_for + cooldown
# (a shrink must land), and the tier-1 miniature stays ~10s wall.
_CONTROLLER_PROFILES = {
    "controller": {
        "world": 1, "min_world": 1, "max_world": 3, "max_step": 2,
        "cooldown": 1.0, "hysteresis": 1.0, "work_s": 0.3,
        "phases": ((3.0, 4.0), (4.5, 0.0)),
        "grow_at": 2, "grow_for": 0.3, "idle_for": 0.8,
        "min_grow": 1, "min_shrink": 1, "p99_budget_ms": 0.0,
        "baseline": False, "chaos": False, "drain_at": None,
    },
    "controller_ramp": {
        "world": 1, "min_world": 1, "max_world": 4, "max_step": 2,
        "cooldown": 2.0, "hysteresis": 2.0, "work_s": 0.4,
        "phases": ((5.0, 6.0), (4.0, 0.5), (5.0, 6.0), (4.0, 0.25)),
        "grow_at": 3, "grow_for": 0.3, "idle_for": 1.2,
        "min_grow": 2, "min_shrink": 2, "p99_budget_ms": 15000.0,
        "baseline": True, "chaos": False, "drain_at": None,
    },
    "controller_chaos": {
        "world": 2, "min_world": 1, "max_world": 4, "max_step": 2,
        "cooldown": 1.0, "hysteresis": 1.0, "work_s": 0.35,
        "phases": ((4.0, 5.0), (4.0, 0.4), (4.0, 0.0)),
        "grow_at": 3, "grow_for": 0.3, "idle_for": 1.2,
        "min_grow": 1, "min_shrink": 1, "p99_budget_ms": 0.0,
        "baseline": False, "chaos": True, "drain_at": 10.0,
    },
}

# flags every controller schedule sets (and restores) around its run
_CONTROLLER_FLAGS = (
    "controller", "alert_rules_path", "alert_eval_interval",
    "controller_cooldown_s", "controller_hysteresis_s",
    "controller_min_world", "controller_max_world",
    "controller_max_step", "controller_backoff_s",
    "controller_breaker_threshold", "controller_state_path",
    "serving_p99_budget_ms", "journal_path")

# master timing: the heartbeat reaper (worker death -> immediate
# requeue) must be what recovers leases, not the per-task timeout —
# keep the task lease LONG so a hung run proves membership worked
_LEASE_TIMEOUT = 60.0
_WORKER_TIMEOUT = 1.0
_HEARTBEAT_INTERVAL = 0.2


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _seed_where_exit_fires(prob: float, lo: int, hi: int,
                           site: str = "trainer.step") -> int:
    """Smallest chaos seed whose first ``exit`` firing at `site` lands
    in invocation window [lo, hi) — pure crc32 math (the chaos plane's
    own decision function), so the kill point is chosen deterministically
    without running anything."""
    for seed in range(10_000):
        fires = [n for n in range(hi)
                 if zlib.crc32(f"{seed}:{site}:{n}".encode())
                 / 0xFFFFFFFF < prob]
        if fires and lo <= fires[0] < hi:
            return seed
    raise RuntimeError("no seed found (unreachable for sane prob)")


def worker_cmd(endpoints: str, world: int, rank: int, out: str,
               ckpt_dir: str) -> List[str]:
    return [sys.executable, "-m", "paddle_tpu.resilience.elastic_worker",
            endpoints, str(world), str(rank), out, ckpt_dir]


def worker_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)    # one CPU device per process
    env.pop("PYTHONPATH", None)   # axon plugin quirk (tests/conftest.py)
    env["PTPU_WORKER_HEARTBEAT_INTERVAL"] = str(_HEARTBEAT_INTERVAL)
    # ride through the master-restart gap without exhausting the RPC
    # retry budget (downtime is short but nonzero)
    env["PTPU_RETRY_MAX_ATTEMPTS"] = "8"
    if extra:
        env.update(extra)
    return env


def expected_w_total(n_tasks: int, epochs: int,
                     shard_names: Optional[List[str]] = None) -> float:
    """The fixed-fleet end state for a clean run over ``n_tasks``
    shards x ``epochs``: the elastic worker's stand-in update is a
    commutative pure sum of per-(shard, epoch) contributions, so ANY
    fleet — fixed, resized, chaos-restarted — that processes each pair
    exactly once lands on this value.  Computed with the worker's OWN
    ``_apply`` (not a re-derived formula) so the oracle is
    definitionally consistent with what the fleet runs.  The resize
    schedules assert their fleet-summed end state equals it: the
    'same final loss as a fixed-fleet run' check."""
    import numpy as np

    from paddle_tpu.resilience.elastic_worker import _apply
    names = shard_names if shard_names is not None \
        else [f"shard-{i:03d}" for i in range(n_tasks)]
    w = np.zeros(16, dtype="float64")
    for sh in names:
        for ep in range(epochs):
            w = _apply(w, sh, ep)
    return float(w.sum())


def check_consumed(workers: List[dict], n_tasks: int, epochs: int,
                   shard_names: Optional[List[str]] = None) -> List[str]:
    """Reader-example exactly-once: the union of per-rank ``consumed``
    records (each rank's checkpointed multiset of applied (shard,
    epoch) pairs, reconciled against the ledger across restarts and
    resizes) must cover every pair exactly once."""
    from collections import Counter
    seen = Counter(tuple(c) for r in workers
                   for c in r.get("consumed", []))
    problems = []
    dups = sorted(k for k, v in seen.items() if v > 1)
    if dups:
        problems.append(f"reader examples double-consumed: {dups}")
    names = shard_names if shard_names is not None \
        else [f"shard-{i:03d}" for i in range(n_tasks)]
    want = {(sh, ep) for sh in names for ep in range(epochs)}
    missing = sorted(want - set(seen))
    if missing:
        problems.append(f"reader examples lost: {missing}")
    extra = sorted(set(seen) - want)
    if extra:
        problems.append(f"unexpected reader examples: {extra}")
    return problems


def check_ledger(ledger: List[dict], n_tasks: int,
                 epochs: int) -> List[str]:
    """Exactly-once: every (task, epoch) pair completed once, none
    twice, none missing.  Returns human-readable problems (empty =
    pass)."""
    problems = []
    seen: Dict[tuple, int] = {}
    for e in ledger:
        seen[(e["task_id"], e["epoch"])] = \
            seen.get((e["task_id"], e["epoch"]), 0) + 1
    dups = sorted(k for k, v in seen.items() if v > 1)
    if dups:
        problems.append(f"duplicate completions (fenced ack accepted?): "
                        f"{dups}")
    want = {(t, ep) for t in range(n_tasks) for ep in range(epochs)}
    missing = sorted(want - set(seen))
    if missing:
        problems.append(f"missing completions: {missing}")
    extra = sorted(set(seen) - want)
    if extra:
        problems.append(f"unexpected completions: {extra}")
    return problems


def run_schedule(workdir: str, name: str, seed: int = 0, world: int = 2,
                 n_tasks: int = 6, epochs: int = 2,
                 timeout: float = 120.0) -> dict:
    """One schedule end-to-end; returns a report dict with ``ok`` and
    ``problems`` (see module docstring for the assertions)."""
    from paddle_tpu.distributed.supervisor import Supervisor
    from paddle_tpu.distributed.task_queue import (TaskMaster,
                                                   serve_master)

    if name not in SCHEDULES:
        raise ValueError(f"unknown schedule {name!r} "
                         f"(expected one of {SCHEDULES})")
    if name in _CONTROLLER_PROFILES:
        # the Helmsman closed loop has its own driver: a streaming
        # trace and a controller making every resize decision (the
        # batch params world/n_tasks/epochs don't apply)
        return run_controller_schedule(workdir, name, seed=seed,
                                       timeout=timeout)
    resize_plan = list(RESIZE_PLANS.get(name, ()))
    if resize_plan:
        # one boundary per planned world; the final world needs an
        # epoch of its own to prove it actually trains
        epochs = max(epochs, len(resize_plan) + 1)
    os.makedirs(workdir, exist_ok=True)
    t_start = time.time()
    port = _free_port()
    endpoints = f"127.0.0.1:{port}"
    snap = os.path.join(workdir, "master.json")

    def _master() -> "TaskMaster":
        # snapshot_interval=0: every mutation durable BEFORE the RPC
        # reply — the exactly-once-across-master-restart guarantee
        # assumes the ledger survives the restart
        return TaskMaster(snapshot_path=snap,
                          lease_timeout=_LEASE_TIMEOUT,
                          snapshot_interval=0.0,
                          worker_timeout=_WORKER_TIMEOUT,
                          num_epochs=epochs,
                          world_size=world if resize_plan else 0)

    master = _master()
    master.set_dataset([f"shard-{i:03d}" for i in range(n_tasks)])
    srv, _ = serve_master(master, port=port)

    kill_rank0 = name in ("worker_kill", "combined", "resize_combined",
                          "resize_soak_chaos")
    restart_master = name in ("master_restart", "combined")
    refuse = name in ("rpc_refuse", "combined")

    # ranks that will ever exist: the launch fleet plus every grow
    # target — out/checkpoint paths are per-rank for the whole run
    max_world = max([world] + resize_plan)
    envs: List[Optional[Dict[str, str]]] = [None] * world
    if kill_rank0:
        # die on the 2nd or 3rd leased task (mid-epoch, at least one
        # task completed first), at a deterministically pre-computed
        # invocation — late enough to be mid-epoch, early enough that
        # rank 0 is guaranteed to reach it before the queue drains
        kseed = _seed_where_exit_fires(0.4, 1, 3)
        envs[0] = {"PTPU_CHAOS_SPEC": "trainer.step=exit:0.4:9",
                   "PTPU_CHAOS_SEED": str(kseed)}
    if refuse:
        rank = 1 if world > 1 else 0
        spec = "task_queue.rpc=refuse:0.05:0.2"
        cur = dict(envs[rank] or {})
        # refuse composes with an existing spec via ';'
        prev = cur.get("PTPU_CHAOS_SPEC", "")
        cur["PTPU_CHAOS_SPEC"] = (prev + ";" if prev else "") + spec
        cur.setdefault("PTPU_CHAOS_SEED", str(seed))
        envs[rank] = cur

    outs = [os.path.join(workdir, f"worker_{r}.json")
            for r in range(max_world)]

    def _cmd(rank: int) -> List[str]:
        return worker_cmd(endpoints, world, rank, outs[rank],
                          os.path.join(workdir, f"ckpt_r{rank}"))

    from paddle_tpu.resilience.elastic_worker import RETIRED_RC
    sup = Supervisor(
        cmds=[_cmd(r) for r in range(world)],
        env=worker_env(), envs=envs, cwd=REPO_ROOT,
        log_dir=workdir, cmd_factory=_cmd, retire_rc=RETIRED_RC)
    sup.start()

    generation_after = master.generation
    resizes_applied = 0
    try:
        if restart_master:
            # wait for real progress, then bounce the coordinator on
            # the SAME port: leases void, generation bumps, clients
            # re-dial and the fleet keeps going
            deadline = time.time() + timeout / 2
            while len(master.ledger_entries()) < world \
                    and time.time() < deadline:
                time.sleep(0.05)
            srv.shutdown()
            master = _master()       # recovers from the snapshot
            srv, _ = serve_master(master, port=port)
        # the resize driver: request each planned world, mirror it on
        # the supervisor (growth spawns now — new ranks wait out the
        # epoch; shrink is worker-side retirement), then wait for the
        # epoch boundary to flip it live before the next step
        for i, w_new in enumerate(resize_plan):
            master.request_resize(w_new)
            sup.set_world_size(w_new)
            deadline = time.time() + timeout / 2
            while master.resizes < i + 1 and time.time() < deadline:
                time.sleep(0.02)
            resizes_applied = master.resizes
        finished = sup.wait(timeout=timeout)
        generation_after = master.generation
        resizes_applied = master.resizes
        ledger = master.ledger_entries()
        stats = master.stats()
    finally:
        sup.stop()
        srv.shutdown()

    problems = []
    status = sup.status()
    if not finished:
        problems.append(f"fleet did not finish within {timeout}s: "
                        f"{status}")
    problems += check_ledger(ledger, n_tasks, epochs)
    if kill_rank0 and sup.restarts[0] < 1:
        problems.append("rank 0 was never restarted (chaos exit did "
                        "not fire or the supervisor missed the crash)")
    if restart_master and generation_after < 2:
        problems.append(f"master generation did not bump "
                        f"(still {generation_after})")
    # ranks ever in the fleet (launch set + every grow target) each
    # leave a final report; retired ranks' reports carry their share
    # of the end state
    spawned = set(range(world))
    for t in resize_plan:
        spawned |= set(range(t))
    workers = []
    for r in sorted(spawned):
        if os.path.exists(outs[r]):
            with open(outs[r]) as f:
                workers.append(json.load(f))
        else:
            problems.append(f"missing worker report {outs[r]}")
    w_total = sum(w["w_sum"] for w in workers)
    expected_total = expected_w_total(n_tasks, epochs)
    if resize_plan:
        if resizes_applied < len(resize_plan):
            problems.append(
                f"only {resizes_applied}/{len(resize_plan)} resizes "
                f"applied (epoch boundary never drained?)")
        elif stats["target_world_size"] != resize_plan[-1]:
            problems.append(
                f"fleet landed on world "
                f"{stats['target_world_size']}, plan said "
                f"{resize_plan[-1]}")
        # the master's resize_log records the FIRST epoch each new
        # world governed (epoch boundaries can outpace the driver, so
        # the plan alone doesn't pin which epoch maps to which world);
        # a completion by a rank outside its epoch's world means a
        # shrink leaked leases
        log = stats.get("resize_log", [])
        applied_worlds = [r["new"] for r in log]
        if applied_worlds != resize_plan[:len(applied_worlds)]:
            problems.append(f"resizes applied out of order: "
                            f"{log} vs plan {resize_plan}")

        def world_at(epoch):
            w_cur = world
            for r in log:
                if epoch >= r["epoch"]:
                    w_cur = r["new"]
            return w_cur

        bad = [e for e in ledger
               if e.get("worker") is not None
               and e["worker"] >= world_at(e["epoch"])]
        if bad:
            problems.append(f"completions by out-of-world ranks: "
                            f"{bad}")
    if resize_plan or name == "fixed":
        # the 'same final loss' check: commutative updates + exactly-
        # once processing => the fleet sum equals the fixed-fleet value
        if abs(w_total - expected_total) > 1e-6:
            problems.append(
                f"fleet end state {w_total!r} != fixed-fleet "
                f"{expected_total!r} (examples lost or "
                f"double-applied)")
        problems += check_consumed(workers, n_tasks, epochs)
    return {"schedule": name, "ok": not problems, "problems": problems,
            "seed": seed, "world": world, "n_tasks": n_tasks,
            "epochs": epochs, "ledger_entries": len(ledger),
            "restarts": dict(sup.restarts),
            "spawns": dict(sup.spawns),
            "resize_plan": resize_plan,
            "resizes_applied": resizes_applied,
            "generation": generation_after,
            "w_total": w_total, "expected_w_total": expected_total,
            "stats": stats, "workers": workers,
            "duration_s": round(time.time() - t_start, 2)}


def _build_trace(prof: dict) -> Tuple[List[Tuple[float, str]], float]:
    """Expand a profile's ``phases`` into an arrival trace: a list of
    (offset_s, shard_name) sorted by offset, plus the trace duration.
    Each shard name carries the per-task service time in its ``#``
    suffix (elastic_worker._work_seconds) so backlog builds under real
    wall-clock load."""
    trace: List[Tuple[float, str]] = []
    base = 0.0
    idx = 0
    for dur, rate in prof["phases"]:
        if rate > 0:
            gap = 1.0 / rate
            t = 0.0
            while t < dur - 1e-9:
                trace.append((base + t,
                              f"shard-{idx:03d}#{prof['work_s']}"))
                idx += 1
                t += gap
        base += dur
    return trace, base


def _controller_rules(prof: dict) -> dict:
    """The Helmsman rules file for a controller schedule: backlog over
    target grows the fleet (critical, burn-proportional), a drained
    queue shrinks it (warning — criticals-first ordering means a real
    backlog always outranks the shrink), and the chaos lane adds an
    operator drain lever wired to a deliberately-broken actuator (the
    circuit-breaker food)."""
    rules = [
        {"name": "task_backlog", "metric": "taskmaster_tasks",
         "predicate": "threshold", "labels": {"state": "todo"},
         "op": ">", "value": prof["grow_at"], "for": prof["grow_for"],
         "severity": "critical",
         "description": "queue backlog over target: grow the fleet",
         "action": {"kind": "request_resize", "direction": "grow",
                    "step": 1, "proportional": True,
                    "immediate": True}},
        {"name": "fleet_idle", "metric": "taskmaster_tasks",
         "predicate": "threshold", "labels": {"state": "todo"},
         "op": "<", "value": 1, "for": prof["idle_for"],
         "severity": "warning",
         "description": "queue drained: shrink the fleet",
         "action": {"kind": "request_resize", "direction": "shrink",
                    "step": 1, "immediate": True}},
    ]
    if prof["chaos"]:
        rules.append(
            {"name": "drain_cmd", "metric": "helm_drain_cmd",
             "predicate": "threshold", "op": ">", "value": 0,
             "for": 0.0, "severity": "critical",
             "description": "operator lever: drain serving now",
             "action": {"kind": "drain", "cooldown": 0.3}})
    return {"rules": rules}


def _run_trace_fleet(workdir: str, prof: dict,
                     trace: List[Tuple[float, str]], trace_dur: float,
                     timeout: float, controlled: bool,
                     chaos: bool = False) -> dict:
    """Drive one open-loop arrival trace against a supervised fleet.

    ``controlled=True`` wires the Helmsman controller (caller has
    already set the flags): the controller makes EVERY fleet-size
    decision; this driver only feeds arrivals and samples chip-seconds.
    ``controlled=False`` is the static max-world baseline the elastic
    run must beat on chip-seconds.  ``chaos`` additionally kills rank 0
    mid-run and bounces the coordinator between a resize decision's
    fence cut and its actuation (the pre_actuate seam)."""
    from paddle_tpu.distributed.supervisor import Supervisor
    from paddle_tpu.distributed.task_queue import (TaskMaster,
                                                   serve_master)
    from paddle_tpu.observability import controller as obs_controller
    from paddle_tpu.observability import metrics as obs_metrics
    from paddle_tpu.resilience import retry as rretry
    from paddle_tpu.resilience.elastic_worker import RETIRED_RC

    os.makedirs(workdir, exist_ok=True)
    port = _free_port()
    endpoints = f"127.0.0.1:{port}"
    snap = os.path.join(workdir, "master.json")
    world0 = prof["world"] if controlled else prof["max_world"]

    def _master() -> "TaskMaster":
        # streaming mode: one epoch, tasks arrive via extend_dataset
        # as the trace plays; sealed only when the trace ends
        return TaskMaster(snapshot_path=snap,
                          lease_timeout=_LEASE_TIMEOUT,
                          snapshot_interval=0.0,
                          worker_timeout=_WORKER_TIMEOUT,
                          num_epochs=1, world_size=world0)

    holder = {"master": _master()}
    holder["srv"], _ = serve_master(holder["master"], port=port)

    outs = [os.path.join(workdir, f"worker_{r}.json")
            for r in range(prof["max_world"])]

    def _cmd(rank: int) -> List[str]:
        return worker_cmd(endpoints, world0, rank, outs[rank],
                          os.path.join(workdir, f"ckpt_r{rank}"))

    envs: List[Optional[Dict[str, str]]] = [None] * world0
    if chaos:
        kseed = _seed_where_exit_fires(0.4, 1, 3)
        envs[0] = {"PTPU_CHAOS_SPEC": "trainer.step=exit:0.4:9",
                   "PTPU_CHAOS_SEED": str(kseed)}
    sup = Supervisor(
        cmds=[_cmd(r) for r in range(world0)], env=worker_env(),
        envs=envs, cwd=REPO_ROOT, log_dir=workdir, cmd_factory=_cmd,
        retire_rc=RETIRED_RC, worker_timeout=_WORKER_TIMEOUT,
        # restart slower than the death-declaration window (the PR 15
        # gotcha: a chaos-killed rank must be DECLARED dead and its
        # lease requeued before the respawn rejoins) but capped so a
        # revived rank never waits out a full exponential tail
        backoff=rretry.RetryPolicy(name="supervisor_restart",
                                   max_attempts=1, base_delay=2.5,
                                   max_delay=2.5))
    sup.start()

    # bounce + feed serialize on one lock: the coordinator swap must
    # never interleave with an extend_dataset (a task landing on the
    # outgoing master AFTER the successor read the snapshot would be
    # lost — exactly the torn-write class snapshots exist to prevent)
    swap_lock = threading.Lock()
    arrivals: Dict[int, float] = {}
    fed = {"i": 0}

    def _feed_due(now_off: float):
        while fed["i"] < len(trace) and \
                trace[fed["i"]][0] <= now_off:
            with swap_lock:
                holder["master"].extend_dataset([trace[fed["i"]][1]])
            arrivals[fed["i"]] = time.time()
            fed["i"] += 1

    # the t=0 arrivals go in BEFORE the controller starts: an empty
    # pre-traffic queue reads as "idle", and the shrink rule must not
    # charge its first cooldown on launch noise
    _feed_due(0.0)

    ctrl = None
    g_drain = None
    bounced = {"n": 0}
    if controlled:
        def _bounce(dec: dict):
            # the chaos seam: the coordinator dies between the fence
            # token and the actuation — exactly once, on the first
            # resize decision.  The successor recovers from the
            # snapshot with a bumped generation, so the in-flight
            # decision's fence MUST be rejected (never double-applied);
            # the controller retries with a fresh token next tick.
            if not chaos or bounced["n"] \
                    or dec.get("action") != "request_resize":
                return
            bounced["n"] += 1
            with swap_lock:
                holder["srv"].shutdown()
                holder["master"] = _master()
                holder["srv"], _ = serve_master(holder["master"],
                                                port=port)

        def _fleet() -> dict:
            return holder["master"].stats()

        def _resize(target: int, fence, immediate: bool = False):
            reply = holder["master"].request_resize(
                target, fence=fence, immediate=immediate)
            if not reply.get("fenced"):
                # follower discipline: the supervisor mirrors what the
                # master ACCEPTED — mechanical, not a human resize
                sup.set_world_size(target)
            return reply

        def _drain():
            # no serving batcher is attached in this soak: every drain
            # raises — deliberately, to feed the circuit breaker
            from paddle_tpu import serving
            return serving.drain()

        g_drain = obs_metrics.gauge(
            "helm_drain_cmd",
            "Soak lever: nonzero arms the chaos lane's drain rule.")
        g_drain.set(0.0)
        ctrl = obs_controller.ensure_started(
            fleet_fn=_fleet,
            actuators={"request_resize": _resize,
                       "revive": sup.revive, "drain": _drain},
            pre_actuate=_bounce)

    t0 = time.time()
    last = [t0]
    chip = [0.0]
    finished = False
    drain_armed = False
    deadline = t0 + timeout

    def _tick():
        now = time.time()
        alive = sum(1 for s in sup.status().values()
                    if s["state"] == "running")
        chip[0] += alive * (now - last[0])
        last[0] = now

    try:
        while time.time() - t0 < trace_dur and time.time() < deadline:
            _feed_due(time.time() - t0)
            if controlled and chaos and not drain_armed \
                    and prof["drain_at"] is not None \
                    and time.time() - t0 >= prof["drain_at"]:
                g_drain.set(1.0)
                drain_armed = True
            _tick()
            time.sleep(0.05)
        _feed_due(float("inf"))       # anything the loop granularity missed
        with swap_lock:
            holder["master"].extend_dataset([], final=True)   # seal
        while time.time() < deadline:
            _tick()
            if sup.wait(timeout=0.25):
                finished = True
                break
        _tick()
        status_doc = ctrl.status_doc() if ctrl is not None else None
        degraded = bool(ctrl.degraded) if ctrl is not None else False
        ledger = holder["master"].ledger_entries()
        stats = holder["master"].stats()
    finally:
        sup.stop()
        try:
            holder["srv"].shutdown()
        except Exception:
            pass
        if g_drain is not None:
            g_drain.set(0.0)
    workers, missing = [], []
    for r in sorted(set(sup.spawns)):
        if sup.spawns.get(r, 0) <= 0:
            continue
        if os.path.exists(outs[r]):
            with open(outs[r]) as f:
                workers.append(json.load(f))
        else:
            missing.append(outs[r])
    return {"finished": finished, "chip_seconds": chip[0],
            "arrivals": arrivals, "ledger": ledger, "stats": stats,
            "workers": workers, "missing_reports": missing,
            "restarts": dict(sup.restarts), "spawns": dict(sup.spawns),
            "controller": status_doc, "degraded": degraded,
            "duration_s": time.time() - t0}


def run_controller_schedule(workdir: str, name: str, seed: int = 0,
                            timeout: float = 120.0) -> dict:
    """One Helmsman closed-loop schedule (ISSUE 17): the fleet
    grows/shrinks ITSELF off the alert rules with zero human resizes.
    Asserts the exactly-once invariants of every other schedule PLUS
    the control-plane gates: enough applied grow and shrink decisions,
    a 1:1 map between applied decisions and the master's resize_log,
    cooldown-bounded decision rate (no flapping), and per-lane
    headline checks — p99 sojourn under the serving budget and
    chip-seconds below the static max-world baseline (ramp), fence
    rejection + breaker degradation under coordinator/rank-0 chaos
    (chaos)."""
    from paddle_tpu.core import flags
    from paddle_tpu.observability import alerts as obs_alerts
    from paddle_tpu.observability import controller as obs_controller
    from paddle_tpu.observability import journal as obs_journal

    prof = _CONTROLLER_PROFILES[name]
    os.makedirs(workdir, exist_ok=True)
    t_start = time.time()
    trace, trace_dur = _build_trace(prof)
    n_tasks = len(trace)
    shard_names = [s for _, s in trace]

    # the static max-world baseline runs FIRST, controller plane
    # untouched: the chip-seconds bar the elastic run must beat
    base = None
    if prof["baseline"]:
        base = _run_trace_fleet(os.path.join(workdir, "baseline"),
                                prof, trace, trace_dur, timeout,
                                controlled=False)

    rules_path = os.path.join(workdir, "rules.json")
    with open(rules_path, "w") as f:
        json.dump(_controller_rules(prof), f, indent=1)
    saved = {k: flags.get_flag(k) for k in _CONTROLLER_FLAGS}
    try:
        flags.set_flag("controller", True)
        flags.set_flag("alert_rules_path", rules_path)
        flags.set_flag("alert_eval_interval", 0.1)
        flags.set_flag("controller_cooldown_s", prof["cooldown"])
        flags.set_flag("controller_hysteresis_s", prof["hysteresis"])
        flags.set_flag("controller_min_world", prof["min_world"])
        flags.set_flag("controller_max_world", prof["max_world"])
        flags.set_flag("controller_max_step", prof["max_step"])
        flags.set_flag("controller_backoff_s", 0.2)
        flags.set_flag("controller_breaker_threshold", 3)
        flags.set_flag("controller_state_path",
                       os.path.join(workdir, "controller_state.json"))
        flags.set_flag("serving_p99_budget_ms", prof["p99_budget_ms"])
        flags.set_flag("journal_path",
                       os.path.join(workdir, "journal.jsonl"))
        run = _run_trace_fleet(os.path.join(workdir, "elastic"), prof,
                               trace, trace_dur, timeout,
                               controlled=True, chaos=prof["chaos"])
    finally:
        obs_controller.reset()
        obs_alerts.reset()
        obs_journal.reset()
        for k, v in saved.items():
            flags.set_flag(k, v)

    problems = []
    if not run["finished"]:
        problems.append(f"fleet did not finish within {timeout}s")
    problems += [f"missing worker report {p}"
                 for p in run["missing_reports"]]
    problems += check_ledger(run["ledger"], n_tasks, 1)
    w_total = sum(w["w_sum"] for w in run["workers"])
    expected_total = expected_w_total(n_tasks, 1,
                                      shard_names=shard_names)
    if abs(w_total - expected_total) > 1e-6:
        problems.append(f"fleet end state {w_total!r} != fixed-fleet "
                        f"{expected_total!r} (examples lost or "
                        f"double-applied across controller resizes)")
    problems += check_consumed(run["workers"], n_tasks, 1,
                               shard_names=shard_names)

    decisions = list((run["controller"] or {}).get("decisions", []))
    applied = [d for d in decisions if d["action"] == "request_resize"
               and d["outcome"] == "applied"]
    grows = [d for d in applied if d.get("direction") == "grow"]
    shrinks = [d for d in applied if d.get("direction") == "shrink"]
    fenced = [d for d in decisions if d["outcome"] == "fenced"]
    if len(grows) < prof["min_grow"]:
        problems.append(f"only {len(grows)} grow decisions applied "
                        f"(need >= {prof['min_grow']}): the fleet "
                        f"never scaled itself up under backlog")
    if len(shrinks) < prof["min_shrink"]:
        problems.append(f"only {len(shrinks)} shrink decisions applied "
                        f"(need >= {prof['min_shrink']}): the fleet "
                        f"never scaled itself down when idle")
    # ZERO human resizes + exactly-once actuation: every entry in the
    # master's resize_log maps 1:1 to an applied controller decision
    # (a fenced decision adds NO entry — that's the no-double-apply
    # guarantee under the coordinator bounce)
    log = run["stats"].get("resize_log", [])
    if len(log) != len(applied):
        problems.append(f"resize_log has {len(log)} entries but "
                        f"{len(applied)} controller decisions applied "
                        f"(double-apply, or a resize the controller "
                        f"did not make)")
    for a, b in zip(log, log[1:]):
        if b["old"] != a["new"]:
            problems.append(f"resize_log does not chain: {log}")
            break
    # anti-flap: cooldown bounds the decision rate per action class
    charged = [d for d in decisions if d["action"] == "request_resize"
               and d["outcome"] in ("applied", "dry_run", "clamped",
                                    "no_actuator")]
    bound = int(run["duration_s"] / prof["cooldown"]) + 2
    if len(charged) > bound:
        problems.append(f"{len(charged)} cooldown-charging resize "
                        f"decisions in {run['duration_s']:.1f}s "
                        f"(cooldown {prof['cooldown']}s allows "
                        f"{bound}): the controller is flapping")
    p99_ms = None
    if prof["p99_budget_ms"] > 0:
        soj = sorted(
            (e["time_unix"] - run["arrivals"][e["task_id"]]) * 1000.0
            for e in run["ledger"]
            if "time_unix" in e and e["task_id"] in run["arrivals"])
        if soj:
            p99_ms = soj[min(len(soj) - 1,
                             int(round(0.99 * (len(soj) - 1))))]
            if p99_ms > prof["p99_budget_ms"]:
                problems.append(
                    f"p99 task sojourn {p99_ms:.0f}ms blew the "
                    f"{prof['p99_budget_ms']:.0f}ms budget (the "
                    f"controller grew too little or too late)")
        else:
            problems.append("no sojourn samples (empty ledger?)")
    chip_base = base["chip_seconds"] if base else None
    if base is not None:
        if not base["finished"]:
            problems.append("static baseline run did not finish")
        if run["chip_seconds"] >= base["chip_seconds"]:
            problems.append(
                f"elastic chip-seconds {run['chip_seconds']:.1f} did "
                f"not beat the static world={prof['max_world']} "
                f"baseline {base['chip_seconds']:.1f}")
    if prof["chaos"]:
        if not fenced:
            problems.append("coordinator bounce mid-decision produced "
                            "no fence rejection (a stale decision was "
                            "silently applied?)")
        if run["restarts"].get(0, 0) < 1:
            problems.append("rank 0 was never chaos-killed/restarted")
        if int(run["stats"].get("generation", 1)) < 2:
            problems.append("master generation never bumped (the "
                            "mid-decision bounce did not happen)")
        failed = [d for d in decisions if d["outcome"] == "failed"]
        if len(failed) < 3:
            problems.append(f"expected >= 3 failed drain decisions "
                            f"before the breaker trips, saw "
                            f"{len(failed)}")
        if not run["degraded"]:
            problems.append("drain actuator failures never tripped "
                            "the circuit breaker (controller should "
                            "be degraded to alert-only)")
    return {"schedule": name, "ok": not problems, "problems": problems,
            "seed": seed, "world": prof["world"], "n_tasks": n_tasks,
            "epochs": 1, "ledger_entries": len(run["ledger"]),
            "restarts": run["restarts"], "spawns": run["spawns"],
            "resize_plan": [], "resizes_applied": len(log),
            "generation": run["stats"].get("generation"),
            "w_total": w_total, "expected_w_total": expected_total,
            "stats": run["stats"], "workers": run["workers"],
            "decisions": decisions,
            "grows": len(grows), "shrinks": len(shrinks),
            "fence_rejections": len(fenced),
            "degraded": run["degraded"],
            "chip_seconds": round(run["chip_seconds"], 2),
            "chip_seconds_baseline": (round(chip_base, 2)
                                      if chip_base is not None
                                      else None),
            "p99_sojourn_ms": (round(p99_ms, 1)
                               if p99_ms is not None else None),
            "duration_s": round(time.time() - t_start, 2)}


def _main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.resilience.soak",
        description="Elastic-fleet chaos-matrix soak: supervisor e2e "
                    "under seeded fault schedules; nonzero exit on any "
                    "hang or exactly-once ledger violation.")
    ap.add_argument("--schedules", default=",".join(SCHEDULES),
                    help=f"comma list from {SCHEDULES} "
                         f"(default: all)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--tasks", type=int, default=6)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--workdir", default=None,
                    help="scratch root (default: a fresh tempdir)")
    ap.add_argument("--out", default=None,
                    help="write the full JSON report here")
    args = ap.parse_args(argv)
    names = [s.strip() for s in args.schedules.split(",") if s.strip()]
    bad = [n for n in names if n not in SCHEDULES]
    if bad:
        ap.error(f"unknown schedule(s) {bad}; pick from {SCHEDULES}")
    root = args.workdir
    if root is None:
        import tempfile
        root = tempfile.mkdtemp(prefix="ptpu_soak_")
    reports = []
    for name in names:
        rep = run_schedule(os.path.join(root, name), name,
                           seed=args.seed, world=args.world,
                           n_tasks=args.tasks, epochs=args.epochs,
                           timeout=args.timeout)
        reports.append(rep)
        verdict = "PASS" if rep["ok"] else "FAIL"
        resize = (f" resizes={rep['resizes_applied']}/"
                  f"{len(rep['resize_plan'])}" if rep["resize_plan"]
                  else "")
        print(f"[{verdict}] {name:<16} ledger={rep['ledger_entries']} "
              f"restarts={rep['restarts']} gen={rep['generation']}"
              f"{resize} {rep['duration_s']}s")
        for p in rep["problems"]:
            print(f"         - {p}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"reports": reports}, f, indent=2)
    failed = [r["schedule"] for r in reports if not r["ok"]]
    if failed:
        print(f"soak FAILED: {failed}")
        return 1
    print(f"soak OK: {len(reports)} schedule(s) clean under seed "
          f"{args.seed}")
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
