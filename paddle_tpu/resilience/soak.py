"""Elastic-fleet chaos-matrix soak runner.

``python -m paddle_tpu.resilience.soak`` drives the full supervisor
end-to-end — master with snapshot + membership reaper, N supervised
``elastic_worker`` processes — under seeded fault schedules, and exits
nonzero when any schedule hangs or the completion ledger is not
exactly-once:

* ``worker_kill``     — chaos ``exit`` hard-kills rank 0 mid-task; the
  supervisor restarts it and it resumes from checkpoint.
* ``master_restart``  — the master is shut down mid-run and restarted
  on the same port from its snapshot (generation bump, leases void);
  clients re-dial and the fleet drains the queue.
* ``rpc_refuse``      — chaos ``refuse`` opens connection-refused
  windows at the RPC site; clients back off / re-dial through them.
* ``combined``        — all of the above in one run.

Every schedule asserts: all workers exit 0 inside the deadline, every
(task, epoch) pair completes EXACTLY once in the master's persisted
ledger, fenced acks were rejected (never recorded), and — per
schedule — the dead worker was restarted within its backoff budget /
the generation bumped.  The same :func:`run_schedule` body backs the
tier-1 e2e test (tests/test_elastic.py) and the ``slow``-marked soak
lane.
"""
from __future__ import annotations

import json
import os
import socket
import sys
import time
import zlib
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

SCHEDULES = ("worker_kill", "master_restart", "rpc_refuse", "combined")

# master timing: the heartbeat reaper (worker death -> immediate
# requeue) must be what recovers leases, not the per-task timeout —
# keep the task lease LONG so a hung run proves membership worked
_LEASE_TIMEOUT = 60.0
_WORKER_TIMEOUT = 1.0
_HEARTBEAT_INTERVAL = 0.2


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _seed_where_exit_fires(prob: float, lo: int, hi: int,
                           site: str = "trainer.step") -> int:
    """Smallest chaos seed whose first ``exit`` firing at `site` lands
    in invocation window [lo, hi) — pure crc32 math (the chaos plane's
    own decision function), so the kill point is chosen deterministically
    without running anything."""
    for seed in range(10_000):
        fires = [n for n in range(hi)
                 if zlib.crc32(f"{seed}:{site}:{n}".encode())
                 / 0xFFFFFFFF < prob]
        if fires and lo <= fires[0] < hi:
            return seed
    raise RuntimeError("no seed found (unreachable for sane prob)")


def worker_cmd(endpoints: str, world: int, rank: int, out: str,
               ckpt_dir: str) -> List[str]:
    return [sys.executable, "-m", "paddle_tpu.resilience.elastic_worker",
            endpoints, str(world), str(rank), out, ckpt_dir]


def worker_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)    # one CPU device per process
    env.pop("PYTHONPATH", None)   # axon plugin quirk (tests/conftest.py)
    env["PTPU_WORKER_HEARTBEAT_INTERVAL"] = str(_HEARTBEAT_INTERVAL)
    # ride through the master-restart gap without exhausting the RPC
    # retry budget (downtime is short but nonzero)
    env["PTPU_RETRY_MAX_ATTEMPTS"] = "8"
    if extra:
        env.update(extra)
    return env


def check_ledger(ledger: List[dict], n_tasks: int,
                 epochs: int) -> List[str]:
    """Exactly-once: every (task, epoch) pair completed once, none
    twice, none missing.  Returns human-readable problems (empty =
    pass)."""
    problems = []
    seen: Dict[tuple, int] = {}
    for e in ledger:
        seen[(e["task_id"], e["epoch"])] = \
            seen.get((e["task_id"], e["epoch"]), 0) + 1
    dups = sorted(k for k, v in seen.items() if v > 1)
    if dups:
        problems.append(f"duplicate completions (fenced ack accepted?): "
                        f"{dups}")
    want = {(t, ep) for t in range(n_tasks) for ep in range(epochs)}
    missing = sorted(want - set(seen))
    if missing:
        problems.append(f"missing completions: {missing}")
    extra = sorted(set(seen) - want)
    if extra:
        problems.append(f"unexpected completions: {extra}")
    return problems


def run_schedule(workdir: str, name: str, seed: int = 0, world: int = 2,
                 n_tasks: int = 6, epochs: int = 2,
                 timeout: float = 120.0) -> dict:
    """One schedule end-to-end; returns a report dict with ``ok`` and
    ``problems`` (see module docstring for the assertions)."""
    from paddle_tpu.distributed.supervisor import Supervisor
    from paddle_tpu.distributed.task_queue import (TaskMaster,
                                                   serve_master)

    if name not in SCHEDULES:
        raise ValueError(f"unknown schedule {name!r} "
                         f"(expected one of {SCHEDULES})")
    os.makedirs(workdir, exist_ok=True)
    t_start = time.time()
    port = _free_port()
    endpoints = f"127.0.0.1:{port}"
    snap = os.path.join(workdir, "master.json")

    def _master() -> "TaskMaster":
        # snapshot_interval=0: every mutation durable BEFORE the RPC
        # reply — the exactly-once-across-master-restart guarantee
        # assumes the ledger survives the restart
        return TaskMaster(snapshot_path=snap,
                          lease_timeout=_LEASE_TIMEOUT,
                          snapshot_interval=0.0,
                          worker_timeout=_WORKER_TIMEOUT,
                          num_epochs=epochs)

    master = _master()
    master.set_dataset([f"shard-{i:03d}" for i in range(n_tasks)])
    srv, _ = serve_master(master, port=port)

    kill_rank0 = name in ("worker_kill", "combined")
    restart_master = name in ("master_restart", "combined")
    refuse = name in ("rpc_refuse", "combined")

    envs: List[Optional[Dict[str, str]]] = [None] * world
    if kill_rank0:
        # die on the 2nd or 3rd leased task (mid-epoch, at least one
        # task completed first), at a deterministically pre-computed
        # invocation — late enough to be mid-epoch, early enough that
        # rank 0 is guaranteed to reach it before the queue drains
        kseed = _seed_where_exit_fires(0.4, 1, 3)
        envs[0] = {"PTPU_CHAOS_SPEC": "trainer.step=exit:0.4:9",
                   "PTPU_CHAOS_SEED": str(kseed)}
    if refuse:
        rank = 1 if world > 1 else 0
        spec = "task_queue.rpc=refuse:0.05:0.2"
        cur = dict(envs[rank] or {})
        # refuse composes with an existing spec via ';'
        prev = cur.get("PTPU_CHAOS_SPEC", "")
        cur["PTPU_CHAOS_SPEC"] = (prev + ";" if prev else "") + spec
        cur.setdefault("PTPU_CHAOS_SEED", str(seed))
        envs[rank] = cur

    outs = [os.path.join(workdir, f"worker_{r}.json")
            for r in range(world)]
    sup = Supervisor(
        cmds=[worker_cmd(endpoints, world, r, outs[r],
                         os.path.join(workdir, f"ckpt_r{r}"))
              for r in range(world)],
        env=worker_env(), envs=envs, cwd=REPO_ROOT,
        log_dir=workdir)
    sup.start()

    generation_after = master.generation
    try:
        if restart_master:
            # wait for real progress, then bounce the coordinator on
            # the SAME port: leases void, generation bumps, clients
            # re-dial and the fleet keeps going
            deadline = time.time() + timeout / 2
            while len(master.ledger_entries()) < world \
                    and time.time() < deadline:
                time.sleep(0.05)
            srv.shutdown()
            master = _master()       # recovers from the snapshot
            srv, _ = serve_master(master, port=port)
        finished = sup.wait(timeout=timeout)
        generation_after = master.generation
        ledger = master.ledger_entries()
        stats = master.stats()
    finally:
        sup.stop()
        srv.shutdown()

    problems = []
    status = sup.status()
    if not finished:
        problems.append(f"fleet did not finish within {timeout}s: "
                        f"{status}")
    problems += check_ledger(ledger, n_tasks, epochs)
    if kill_rank0 and sup.restarts[0] < 1:
        problems.append("rank 0 was never restarted (chaos exit did "
                        "not fire or the supervisor missed the crash)")
    if restart_master and generation_after < 2:
        problems.append(f"master generation did not bump "
                        f"(still {generation_after})")
    workers = []
    for out in outs:
        if os.path.exists(out):
            with open(out) as f:
                workers.append(json.load(f))
        else:
            problems.append(f"missing worker report {out}")
    return {"schedule": name, "ok": not problems, "problems": problems,
            "seed": seed, "world": world, "n_tasks": n_tasks,
            "epochs": epochs, "ledger_entries": len(ledger),
            "restarts": dict(sup.restarts),
            "generation": generation_after,
            "stats": stats, "workers": workers,
            "duration_s": round(time.time() - t_start, 2)}


def _main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.resilience.soak",
        description="Elastic-fleet chaos-matrix soak: supervisor e2e "
                    "under seeded fault schedules; nonzero exit on any "
                    "hang or exactly-once ledger violation.")
    ap.add_argument("--schedules", default=",".join(SCHEDULES),
                    help=f"comma list from {SCHEDULES} "
                         f"(default: all)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--tasks", type=int, default=6)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--workdir", default=None,
                    help="scratch root (default: a fresh tempdir)")
    ap.add_argument("--out", default=None,
                    help="write the full JSON report here")
    args = ap.parse_args(argv)
    names = [s.strip() for s in args.schedules.split(",") if s.strip()]
    bad = [n for n in names if n not in SCHEDULES]
    if bad:
        ap.error(f"unknown schedule(s) {bad}; pick from {SCHEDULES}")
    root = args.workdir
    if root is None:
        import tempfile
        root = tempfile.mkdtemp(prefix="ptpu_soak_")
    reports = []
    for name in names:
        rep = run_schedule(os.path.join(root, name), name,
                           seed=args.seed, world=args.world,
                           n_tasks=args.tasks, epochs=args.epochs,
                           timeout=args.timeout)
        reports.append(rep)
        verdict = "PASS" if rep["ok"] else "FAIL"
        print(f"[{verdict}] {name:<16} ledger={rep['ledger_entries']} "
              f"restarts={rep['restarts']} gen={rep['generation']} "
              f"{rep['duration_s']}s")
        for p in rep["problems"]:
            print(f"         - {p}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"reports": reports}, f, indent=2)
    failed = [r["schedule"] for r in reports if not r["ok"]]
    if failed:
        print(f"soak FAILED: {failed}")
        return 1
    print(f"soak OK: {len(reports)} schedule(s) clean under seed "
          f"{args.seed}")
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
