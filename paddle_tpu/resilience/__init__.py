"""Resilience plane: the runtime half of the reference's fault story.

The storage half of the reference's fault tolerance was replicated
earlier (incubate/checkpoint.py CRC-and-rename, distributed/
task_queue.py lease/requeue); this package adds the machinery that
*recovers at runtime* and the machinery that *proves it*:

  * :mod:`.chaos` — deterministic, seeded fault injection.  Named fault
    points on the executor, checkpoint writer, collective dispatch,
    task-queue RPC, and trainer step; armed via ``PTPU_CHAOS_SPEC``,
    replayable exactly from (spec, seed).
  * :mod:`.guard` — NaN/Inf + EMA loss-spike detection with a
    raise / skip_step / rollback policy and a consecutive-bad-step
    circuit breaker.
  * :mod:`.retry` — exponential-backoff-with-jitter retry applied to
    ``TaskMasterClient`` calls (reconnect on socket error) and
    transient checkpoint-save OSErrors.

Preemption tolerance (SIGTERM/SIGINT -> stop at step boundary ->
emergency checkpoint -> clean exit, plus step-accurate resume) lives in
``trainer.py``.  The elastic-fleet plane (fenced leases, membership,
master generations/failover, the crash-restarting supervisor) lives in
``distributed/``; this package carries its worker body
(:mod:`.elastic_worker`, run via ``python -m``) and the chaos-matrix
soak lane (:mod:`.soak` — ``python -m paddle_tpu.resilience.soak``),
both imported lazily.  Recovery actions emit ``resilience_*`` /
``trainer_*`` / ``retry_*`` / ``fenced_*`` counters through the
observability registry.  Catalog and semantics: docs/RESILIENCE.md.
"""
from __future__ import annotations

from . import chaos, guard, retry                              # noqa: F401
from .chaos import InjectedFault, fault_point                  # noqa: F401
from .guard import (BadStepError, CircuitBreakerOpen,          # noqa: F401
                    NumericGuard)
from .retry import RetryPolicy, call_with_retry                # noqa: F401
from .retry import retry as retry_call                         # noqa: F401
