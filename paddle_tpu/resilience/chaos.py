"""Deterministic, seeded fault injection.

The runtime half of the reference stack's fault story is only provable
if faults can be *produced* on demand: the Go master requeues leased
tasks on failure (go/master/service.go:455) and the pserver survives
torn checkpoint writes (go/pserver/service.go:346), and both are tested
by killing things.  This module is the killing machinery — named fault
points planted on the hot paths (executor compile/run, checkpoint shard
write, collective dispatch, task-queue RPC, trainer step) that stay
zero-overhead no-ops until armed via the ``chaos_spec`` flag
(``PTPU_CHAOS_SPEC`` env or ``set_flag``).

Spec grammar (one directive per site, ';'-separated)::

    site=kind[:prob[:arg]]

    trainer.step=nan:0.1              poison fetched loss with NaN, p=0.1
    checkpoint.shard_write=truncate:0.5   torn-write half the shard file
    task_queue.rpc=raise:0.2          raise at the RPC boundary, p=0.2
    executor.run=delay:1.0:0.05       sleep 0.05s before dispatch, p=1.0

Kinds: ``raise`` (the planted site's exception class — ConnectionError
at RPC sites, OSError at filesystem sites), ``nan`` / ``inf`` (poison a
value), ``delay`` (sleep ``arg`` seconds, default 0.01), ``truncate``
(cut a file to ``arg`` fraction of its bytes, default 0.5), ``exit``
(hard process death via ``os._exit(arg)`` — the ``kill -9`` a
supervisor must survive; default code 9), and ``refuse`` (a
connection-refused WINDOW: the first firing opens ``arg`` seconds —
default 0.25 — during which every pass of the site raises
``ConnectionRefusedError``, modelling a master that is down for a
stretch, driving client re-dial/failover).

Determinism: every fault point keeps a per-site invocation counter, and
the fire/skip decision hashes (seed, site, counter) through crc32 — no
process-randomized ``hash()``, no global RNG state — so the same
(spec, seed) replays the identical fault schedule in any process, and a
failure seen in CI reproduces locally from the two flag values.  The
fired schedule is recorded and exposed via :func:`schedule` for tests
to assert exact replay.
"""
from __future__ import annotations

import functools
import os
import sys
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..core import flags
from ..observability import flight as obs_flight
from ..observability import journal as obs_journal
from ..observability import metrics as obs_metrics

_m_injected = obs_metrics.counter(
    "resilience_faults_injected_total",
    "Faults fired by the chaos plane, by site and kind.",
    ("site", "kind"))


class InjectedFault(Exception):
    """Default exception class for ``raise``-kind faults."""


class Fault:
    __slots__ = ("site", "kind", "prob", "arg")

    KINDS = ("raise", "nan", "inf", "delay", "truncate", "exit",
             "refuse")

    # per-kind default for the optional third field
    DEFAULT_ARGS = {"delay": 0.01, "truncate": 0.5, "exit": 9.0,
                    "refuse": 0.25}

    def __init__(self, site: str, kind: str, prob: float, arg: float):
        self.site = site
        self.kind = kind
        self.prob = prob
        self.arg = arg


def parse_spec(spec: str) -> Dict[str, Fault]:
    """Parse the chaos grammar; raises ValueError naming the bad
    directive (the same courtesy core/flags.py extends to env values)."""
    out: Dict[str, Fault] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"chaos_spec directive {part!r} has no '=': expected "
                f"site=kind[:prob[:arg]]")
        site, rhs = part.split("=", 1)
        fields = rhs.split(":")
        kind = fields[0].strip()
        if kind not in Fault.KINDS:
            raise ValueError(
                f"chaos_spec site {site!r}: unknown fault kind {kind!r} "
                f"(expected one of {Fault.KINDS})")
        try:
            prob = float(fields[1]) if len(fields) > 1 else 1.0
            arg = float(fields[2]) if len(fields) > 2 else \
                Fault.DEFAULT_ARGS.get(kind, 0.5)
        except ValueError:
            raise ValueError(
                f"chaos_spec site {site!r}: non-numeric prob/arg in "
                f"{rhs!r}")
        if not 0.0 <= prob <= 1.0:
            raise ValueError(
                f"chaos_spec site {site!r}: prob {prob} not in [0, 1]")
        out[site.strip()] = Fault(site.strip(), kind, prob, arg)
    return out


# Parsed-spec cache + per-site counters + fired-schedule record.  One
# lock: fault points are on hot paths but the unarmed fast path below
# never takes it.
_lock = threading.Lock()
_EMPTY: Dict[str, Fault] = {}
_parsed: Tuple[str, Dict[str, Fault]] = ("", {})
_counters: Dict[str, int] = {}
_fired: List[Tuple[str, int, str]] = []
# open connection-refused windows per site (kind "refuse"): passes of
# the site inside the window raise without burning schedule slots
_refuse_until: Dict[str, float] = {}


def _active() -> Dict[str, Fault]:
    """Current armed spec (cached parse; re-parses when the flag text
    changes, so set_flag re-arms without a reset)."""
    global _parsed
    spec = flags.get_flag("chaos_spec")
    if not spec:
        return _EMPTY
    if _parsed[0] != spec:
        with _lock:
            if _parsed[0] != spec:
                _parsed = (spec, parse_spec(spec))
    return _parsed[1]


def reset():
    """Zero the per-site counters and the fired-schedule record, so the
    next armed run replays the schedule from the top."""
    global _parsed, _counters, _fired
    with _lock:
        _parsed = ("", {})
        _counters = {}
        _fired = []
        _refuse_until.clear()


def schedule() -> List[Tuple[str, int, str]]:
    """The (site, invocation_index, kind) tuples fired since reset() —
    two runs with the same (spec, seed) produce identical lists."""
    return list(_fired)


def _decide(fault: Fault) -> Optional[int]:
    """Advance the site counter; return the invocation index if this
    invocation fires, else None.  crc32 keyed on (seed, site, counter)
    is the whole RNG: stable across processes and replayable."""
    with _lock:
        n = _counters.get(fault.site, 0)
        _counters[fault.site] = n + 1
    seed = flags.get_flag("chaos_seed")
    h = zlib.crc32(f"{seed}:{fault.site}:{n}".encode()) / 0xFFFFFFFF
    if h >= fault.prob:
        return None
    with _lock:
        _fired.append((fault.site, n, fault.kind))
    _m_injected.labels(site=fault.site, kind=fault.kind).inc()
    obs_flight.record("chaos", fault.site, fault_kind=fault.kind, n=n)
    # journaled BEFORE the fault acts (the write flushes per line), so
    # even an `exit`-kind hard kill leaves "chaos killed me HERE" in
    # the victim's journal for the incident timeline
    obs_journal.emit("chaos", "injected", site=fault.site,
                     fault_kind=fault.kind, n=n)
    return n


def trigger(site: str, exc: type = InjectedFault):
    """Fire side-effect faults (raise/delay/exit/refuse) armed at
    `site`.  The unarmed path is one flag read + dict miss."""
    fault = _active().get(site)
    if fault is None:
        return
    if fault.kind == "refuse":
        now = time.time()
        with _lock:
            until = _refuse_until.get(site, 0.0)
        if until > now:
            # inside an open window: refuse without consuming a new
            # schedule slot (one decision opened the whole window)
            raise ConnectionRefusedError(
                f"chaos: refuse window at {site} "
                f"({until - now:.2f}s left)")
        n = _decide(fault)
        if n is None:
            return
        with _lock:
            _refuse_until[site] = now + fault.arg
        raise ConnectionRefusedError(
            f"chaos: injected refuse window at {site}#{n} "
            f"for {fault.arg}s")
    if fault.kind in ("raise", "delay", "exit"):
        n = _decide(fault)
        if n is None:
            return
        if fault.kind == "delay":
            time.sleep(fault.arg)
        elif fault.kind == "exit":
            # kill -9 semantics: no atexit, no finally, no flushes —
            # the process is simply gone mid-step (the supervisor's
            # problem now).  One stderr line so the operator can tell
            # an injected death from a real one.
            print(f"chaos: injected hard exit at {site}#{n} "
                  f"(code {int(fault.arg)})", file=sys.stderr,
                  flush=True)
            os._exit(int(fault.arg))
        else:
            raise exc(f"chaos: injected fault at {site}#{n}")


class fault_point:
    """``with fault_point("site"): ...`` or ``@fault_point("site")`` —
    fires raise/delay faults on entry.  Value/file faults use
    :func:`poison` / :func:`corrupt_file` at the site instead."""

    __slots__ = ("site", "exc")

    def __init__(self, site: str, exc: type = InjectedFault):
        self.site = site
        self.exc = exc

    def __enter__(self):
        trigger(self.site, self.exc)
        return self

    def __exit__(self, *e):
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            trigger(self.site, self.exc)
            return fn(*args, **kwargs)
        return wrapped


def poison(site: str, value: Any) -> Any:
    """NaN/Inf-poison `value` (a scalar, array, or list whose first
    element is the loss) when a nan/inf fault fires at `site`; returns
    the value unchanged otherwise."""
    fault = _active().get(site)
    if fault is None or fault.kind not in ("nan", "inf"):
        return value
    if isinstance(value, (list, tuple)) and not value:
        return value    # nothing to poison: don't burn a schedule slot
    if _decide(fault) is None:
        return value
    bad = float("nan") if fault.kind == "nan" else float("inf")

    def _poison_one(v):
        import numpy as np
        arr = np.asarray(v, dtype="float64") if not hasattr(v, "dtype") \
            else np.array(v, copy=True)
        if not np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype("float64")
        arr[...] = bad
        return arr

    if isinstance(value, (list, tuple)) and value:
        out = list(value)
        out[0] = _poison_one(out[0])
        return type(value)(out) if isinstance(value, tuple) else out
    return _poison_one(value)


# prefix of the named-variable poison family planted in the executor's
# lowering loop: `executor.var.<var_name>=nan:1.0` NaN-poisons that
# variable's value INSIDE the step — the deterministic "this layer went
# bad" injection tensorstats' first-bad-layer attribution is tested
# against (docs/RESILIENCE.md catalog).
VAR_SITE_PREFIX = "executor.var."


def var_sites_armed() -> bool:
    """True when any executor.var.* directive is armed — the executor's
    per-op guard, so the unarmed hot path pays one empty-dict check."""
    spec = _active()
    if not spec:
        return False
    return any(s.startswith(VAR_SITE_PREFIX) for s in spec)


def poison_value(site: str, value: Any) -> Any:
    """NaN/Inf-poison a (possibly traced) floating tensor when a
    nan/inf fault fires at `site`; returns it unchanged otherwise.
    Unlike :func:`poison` (host-side numpy), this composes with jax
    tracing: inside a jitted step the fire decision lands at TRACE time
    and the poison is baked into that executable — so use prob 1.0 (or
    expect the probability to apply per compile, not per step; eager
    modes decide per step as usual)."""
    fault = _active().get(site)
    if fault is None or fault.kind not in ("nan", "inf"):
        return value
    try:
        import jax.numpy as jnp
        if not jnp.issubdtype(getattr(value, "dtype", None),
                              jnp.floating):
            return value
    except Exception:
        return value
    if _decide(fault) is None:
        return value
    bad = float("nan") if fault.kind == "nan" else float("inf")
    return jnp.full_like(value, bad)


def corrupt_file(site: str, path: str):
    """Torn-write simulation: truncate `path` to the armed fraction of
    its bytes when a truncate fault fires at `site` (the partial flush a
    crash leaves behind — detected downstream by CRC, exactly the
    go/pserver:346 failure mode)."""
    fault = _active().get(site)
    if fault is None or fault.kind != "truncate":
        return
    if _decide(fault) is None:
        return
    try:
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(0, int(size * fault.arg)))
    except OSError:
        pass        # the file vanished mid-injection; nothing to tear
