"""One rank of an elastic training fleet — the supervised worker body.

Run:  python -m paddle_tpu.resilience.elastic_worker \\
          <endpoints> <world> <rank> <out.json> <ckpt_dir>

``endpoints`` is a comma-separated ``host:port[,host:port]`` list of
master endpoints (failover order).  The worker

* registers under its rank and heartbeats (``task_queue.Heartbeater``
  re-registers automatically after a master restart or a declared
  death — the supervisor-restarted incarnation rejoins under the SAME
  rank);
* leases dataset tasks and applies one deterministic parameter update
  per shard (a stand-in training step whose final value is a pure
  function of the multiset of (shard, epoch) pairs applied — so tests
  can verify exactly-once end state, not just the ledger);
* checkpoints after every task through ``incubate/checkpoint.py`` (CRC
  + atomic rename, PR 2 machinery) — a ``kill -9`` mid-task costs at
  most that task, and the restarted incarnation resumes from the
  newest VALID serial and fast-forwards (already-applied work is in
  the checkpoint, the half-done task's lease is fenced/requeued);
* passes the ``trainer.step`` chaos fault point once per leased task,
  which is where a ``PTPU_CHAOS_SPEC=trainer.step=exit:...`` schedule
  hard-kills it;
* presents its lease on every ack: a ``fenced`` reply (the task was
  re-leased while we were dead/slow) is counted, never treated as a
  completion;
* honours the master's elastic directives (ISSUE 14): a ``retire``
  reply (the fleet shrank past this rank at an epoch boundary) makes
  it say goodbye and exit :data:`RETIRED_RC` with its cumulative state
  reported — a later grow revives the rank from its checkpoint;
  ``wait_resize``
  (joined under a pending grow) just keeps polling until the boundary.
  ``PTPU_FLEET_WORLD_SIZE`` (threaded by the supervisor) overrides the
  launch-argv world so a respawned incarnation joins the CURRENT
  fleet, not the original one.

Exit code 0 = this rank saw the job through to ``complete``;
:data:`RETIRED_RC` = it was retired by a shrink (the supervisor parks
the rank instead of counting it done — the exit-code convention is
what lets a later grow revive it race-free).
"""
from __future__ import annotations

import json
import os
import sys
import time

# distinct from 0 (job complete) and from crash codes: tells the
# supervisor this rank retired on the master's shrink directive
RETIRED_RC = 3


def _apply(w, shard: str, epoch: int):
    """One deterministic 'training' update; commutative (pure sum of
    per-(shard, epoch) contributions) so any interleaving of the fleet
    reaches the same global end state when each pair is applied exactly
    once."""
    import zlib
    h = zlib.crc32(f"{shard}:{epoch}".encode()) % 1000
    w[h % w.size] += 1.0 + h / 1000.0
    return w


def _work_seconds(shards) -> float:
    """Open-loop trace hook (ISSUE 17): a shard named
    ``shard-007#0.25`` carries 0.25s of simulated per-shard work — the
    controller soak encodes its arrival trace's service times in the
    shard names so backlog builds under real wall-clock load.  Shards
    without the ``#`` suffix (every pre-existing test) cost nothing.
    The FULL name, suffix included, stays the exactly-once ledger key."""
    total = 0.0
    for sh in shards:
        _, sep, tail = str(sh).rpartition("#")
        if sep:
            try:
                total += max(0.0, float(tail))
            except ValueError:
                pass
    return total


def _unapply(w, shards, epoch: int):
    import numpy as np
    for sh in shards:
        w -= _apply(np.zeros_like(w), sh, epoch)
    return w


def reconcile_in_flight(w, applied: int, meta: dict, ledger_entries):
    """Resolve a resumed checkpoint's applied-but-not-yet-acked task
    against the master's ledger (the exactly-once source of truth):

    * the completion LANDED (crash fell between the ack and the next
      checkpoint) — keep the update;
    * the lease never committed (crash fell between the checkpoint and
      the ack; the task was requeued and re-runs elsewhere) — subtract
      it, or the fleet-summed end state counts the pair twice.

    Returns (w, applied)."""
    inf = meta.get("in_flight")
    if not inf:
        return w, applied
    landed = any(e.get("task_id") == inf["task_id"]
                 and e.get("lease") == inf["lease"]
                 for e in ledger_entries)
    if not landed:
        w = _unapply(w, inf["shards"], inf["epoch"])
        applied -= len(inf["shards"])
    return w, applied


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 5:
        print(__doc__, file=sys.stderr)
        return 2
    endpoints, world, rank, out_path, ckpt_dir = argv
    world, rank = int(world), int(rank)
    # the supervisor threads the LIVE fleet target through the env
    # (ISSUE 14 bugfix): a worker respawned after a resize must join
    # the current world, not the launch-time one baked into its argv
    world = int(os.environ.get("PTPU_FLEET_WORLD_SIZE", world))
    restart_count = int(os.environ.get("PTPU_WORKER_RESTART_COUNT", "0"))

    import numpy as np

    from paddle_tpu.core import flags
    from paddle_tpu.distributed.task_queue import (Heartbeater,
                                                   TaskMasterClient)
    from paddle_tpu.incubate import checkpoint as ckpt
    from paddle_tpu.observability import goodput as obs_goodput
    from paddle_tpu.observability import journal as obs_journal
    from paddle_tpu.resilience import chaos

    # fleet identity on every journal event this rank emits (chaos
    # fires, checkpoint commits) — the incident timeline's rank column
    obs_journal.set_rank(rank)

    hb = Heartbeater(endpoints, rank)
    hb.start()
    client = TaskMasterClient(endpoints=endpoints)

    # resume (PR 2 machinery): the newest VALID serial wins; a torn
    # write from the previous incarnation's death fails CRC and is
    # skipped by latest_checkpoint.  An in-flight (applied-but-unacked)
    # task recorded in the meta reconciles against the master's ledger.
    w = np.zeros(16, dtype="float64")
    applied = 0
    # every (shard, epoch) pair this rank's state currently counts —
    # the reader-example ledger: the soak sums these across the fleet's
    # final reports and asserts each pair appears EXACTLY once, i.e. no
    # example was dropped or double-consumed across resizes/restarts
    consumed = []
    resumed = False
    retired = False
    serial = ckpt.latest_checkpoint(ckpt_dir) if os.path.isdir(ckpt_dir) \
        else -1
    if serial >= 0:
        t_restore = time.perf_counter()
        state, meta, _ = ckpt.load_checkpoint(ckpt_dir, serial)
        w = np.asarray(state["w"], dtype="float64")
        applied = int(meta.get("applied", 0))
        consumed = [list(c) for c in meta.get("consumed", [])]
        before = applied
        w, applied = reconcile_in_flight(w, applied, meta,
                                         client.ledger())
        if applied != before:
            # the in-flight task never committed: its pairs re-run
            # elsewhere, so they leave this rank's consumed record too
            inf = meta["in_flight"]
            for sh in inf["shards"]:
                try:
                    consumed.remove([sh, inf["epoch"]])
                except ValueError:
                    pass
        resumed = True
        # the resume itself is chip-time (load + ledger reconcile) —
        # the load's existing boundary feeds checkpoint_restore
        obs_goodput.note_span("checkpoint_restore",
                              time.perf_counter() - t_restore)
    completed, fenced_acks, failed_acks = [], 0, 0
    generations = set()
    try:
        while True:
            t = client.get_task(worker=rank)
            # the lease RPC is this rank's input pipeline — everything
            # since the last boundary was waiting on the master
            obs_goodput.note_wait("input_wait")
            if client.master_generation is not None:
                generations.add(client.master_generation)
            if t is None:
                if client.job_complete:
                    break
                if client.retire:
                    # the fleet shrank past this rank (ISSUE 14): say
                    # goodbye and exit RETIRED_RC (the supervisor
                    # parks, not restarts) — the checkpoint stays so a
                    # later grow revives this rank with its state
                    retired = True
                    break
                # all work leased elsewhere, or waiting out a pending
                # grow (client.wait_resize): spin
                time.sleep(0.05)
                obs_goodput.note_wait(
                    "resize_barrier" if client.wait_resize else "idle")
                continue
            # the hard-death fault point: an armed exit schedule kills
            # this process HERE, mid-task, lease held — the master's
            # membership reaper requeues it and the supervisor respawns
            # this rank
            chaos.trigger("trainer.step")
            work_s = _work_seconds(t.shards)
            if work_s > 0.0:
                time.sleep(work_s)
            for sh in t.shards:
                w = _apply(w, sh, t.epoch)
                consumed.append([sh, t.epoch])
            applied += len(t.shards)
            # chaos point + simulated work + parameter update = the
            # training step body
            obs_goodput.note_wait("compute")
            # the meta carries the not-yet-acked task: a crash between
            # this save and the ack is resolved at resume by
            # reconcile_in_flight (ledger truth), never double-applied
            ckpt.save_checkpoint(ckpt_dir, {"w": w},
                                 {"applied": applied, "rank": rank,
                                  "consumed": consumed,
                                  "in_flight": {
                                      "task_id": t.task_id,
                                      "epoch": t.epoch,
                                      "lease": t.lease,
                                      "shards": list(t.shards)}},
                                 max_keep=2)
            obs_goodput.note_wait("checkpoint_save")
            status = client.task_finished(t.task_id, lease=t.lease,
                                          worker=rank)
            # the ack RPC waits on the master, like the lease
            obs_goodput.note_wait("input_wait")
            if status == "ok":
                completed.append([t.task_id, t.epoch])
            elif status == "fenced":
                # our lease was voided while we worked (declared dead /
                # master restart): the task belongs to someone else now
                # — roll the local update back so the fleet-sum end
                # state still counts each (shard, epoch) exactly once
                fenced_acks += 1
                w = _unapply(w, t.shards, t.epoch)
                applied -= len(t.shards)
                for sh in t.shards:
                    try:
                        consumed.remove([sh, t.epoch])
                    except ValueError:
                        pass
                # the pre-rollback state is already on disk: overwrite
                # it so a later resume can't resurrect the fenced update
                ckpt.save_checkpoint(ckpt_dir, {"w": w},
                                     {"applied": applied, "rank": rank,
                                      "consumed": consumed},
                                     max_keep=2)
                obs_goodput.note_wait("checkpoint_save")
            else:
                failed_acks += 1
    finally:
        hb.stop(goodbye=True)
        client.close()

    # complete the Timecard: close the open segment, journal the final
    # per-state totals, and carry the snapshot in the worker report so
    # the soak's conservation check reads live accounting directly
    obs_goodput.flush()
    obs_goodput.emit_final()
    with open(out_path, "w") as f:
        json.dump({"rank": rank, "world": world,
                   "restart_count": restart_count,
                   "resumed": resumed, "retired": retired,
                   "completed": completed,
                   "consumed": consumed,
                   "fenced_acks": fenced_acks,
                   "failed_acks": failed_acks,
                   "hb_re_registrations": hb.re_registrations,
                   "generations": sorted(generations),
                   "w_sum": float(w.sum()),
                   "goodput": (obs_goodput.snapshot()
                               if obs_goodput.enabled() else None),
                   "chaos_spec": flags.get_flag("chaos_spec")}, f)
    print(f"ELASTIC_WORKER_OK rank={rank} completed={len(completed)} "
          f"fenced={fenced_acks} restarts={restart_count} "
          f"retired={retired}")
    return RETIRED_RC if retired else 0


if __name__ == "__main__":
    raise SystemExit(main())
