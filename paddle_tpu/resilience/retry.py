"""Exponential backoff with jitter for transient failures.

The reference's Go client retries master RPCs until the lease plane
recovers (go/master/client.go re-dials on error; the pserver client
retries checkpoint RPCs); here one policy object serves every transient
boundary: ``TaskMasterClient`` socket errors (reconnect between
attempts) and checkpoint-save ``OSError``s.  Jitter draws from the same
crc32 hash the chaos plane uses — keyed on (chaos_seed, policy name,
attempt) — so a chaos run's full timeline, faults AND backoff sleeps,
replays exactly.
"""
from __future__ import annotations

import functools
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

from ..core import flags
from ..observability import flight as obs_flight
from ..observability import metrics as obs_metrics

_m_attempts = obs_metrics.counter(
    "retry_attempts_total",
    "Retries performed (attempts beyond the first), by policy name.",
    ("name",))
_m_exhausted = obs_metrics.counter(
    "retry_exhausted_total",
    "Retry budgets exhausted (the last error propagated), by policy "
    "name.", ("name",))


@dataclass
class RetryPolicy:
    """max_attempts=None reads the ``retry_max_attempts`` flag at call
    time, so one env knob tunes every boundary at once."""

    name: str = "default"
    max_attempts: Optional[int] = None
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.5          # fraction of the delay added, in [0, j)
    retry_on: Tuple[Type[BaseException], ...] = (ConnectionError, OSError)

    def attempts(self) -> int:
        n = self.max_attempts
        if n is None:
            n = int(flags.get_flag("retry_max_attempts"))
        return max(1, n)

    def delay(self, attempt: int) -> float:
        """Backoff before retry `attempt` (1-based): exponential, capped,
        plus deterministic jitter."""
        d = min(self.max_delay, self.base_delay * (2.0 ** (attempt - 1)))
        if self.jitter > 0:
            seed = flags.get_flag("chaos_seed")
            h = zlib.crc32(
                f"{seed}:retry:{self.name}:{attempt}".encode()) / 0xFFFFFFFF
            d *= 1.0 + self.jitter * h
        return d


def call_with_retry(fn: Callable, policy: RetryPolicy, *args,
                    on_retry: Optional[Callable[[BaseException], None]] = None,
                    **kwargs):
    """Run fn(*args, **kwargs) under `policy`; `on_retry(exc)` runs
    between attempts (the reconnect hook).  The final failure re-raises
    the underlying exception — callers keep their native error types."""
    last: Optional[BaseException] = None
    for attempt in range(1, policy.attempts() + 1):
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            last = e
            if attempt >= policy.attempts():
                break
            _m_attempts.labels(name=policy.name).inc()
            obs_flight.record("retry", policy.name, attempt=attempt,
                              error=repr(e)[:200])
            time.sleep(policy.delay(attempt))
            if on_retry is not None:
                try:
                    on_retry(e)
                except policy.retry_on:
                    pass    # a failed reconnect: let the next attempt try
    _m_exhausted.labels(name=policy.name).inc()
    assert last is not None
    obs_flight.dump("retry_exhausted",
                    extra={"policy": policy.name,
                           "attempts": policy.attempts(),
                           "error": repr(last)[:500]})
    raise last


def retry(policy: RetryPolicy,
          on_retry: Optional[Callable[[BaseException], None]] = None):
    """Decorator form of :func:`call_with_retry`."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call_with_retry(fn, policy, *args,
                                   on_retry=on_retry, **kwargs)
        return wrapped
    return deco
