"""Numeric health guard: NaN/Inf + EMA loss-spike detection with a
policy and a circuit breaker.

The reference only ever *detects* (FLAGS_check_nan_inf raises,
operator.cc:829); this guard adds the recovery policy the distributed
era needs: ``raise`` (the reference's behavior), ``skip_step`` (drop
the step from the health statistics and keep going — right when the
corruption is transient, e.g. a poisoned fetch), or ``rollback``
(restore the newest valid checkpoint via incubate.checkpoint and
continue — right when the parameters themselves may be poisoned).
Whatever the policy, K consecutive bad steps open the circuit breaker
and training stops: a persistently-diverging run must not silently
rollback-loop forever.

The guard is pure bookkeeping — the *actions* (rollback, raise) are the
Trainer's; ``observe()`` returns a verdict and raises only for the
breaker.  Policy/limit default from the ``nan_policy`` /
``bad_step_limit`` flags so one env var flips a fleet.
"""
from __future__ import annotations

import math
from typing import Optional

from ..core import flags
from ..observability import flight as obs_flight
from ..observability import journal as obs_journal
from ..observability import metrics as obs_metrics
from ..observability import tensorstats as obs_tensorstats

_m_bad_steps = obs_metrics.counter(
    "trainer_bad_steps_total",
    "Steps whose fetched loss failed the numeric guard, by reason "
    "(nan = NaN/Inf, spike = EMA loss-spike) and first-bad-variable "
    "attribution (the earliest var with NaN/Inf in the last "
    "tensorstats sample; bounded: top offender only, 'unattributed' "
    "when tensor_stats sampling has no answer).",
    ("reason", "first_var"))

POLICIES = ("raise", "skip_step", "rollback")

OK = "ok"
NAN = "nan"
SPIKE = "spike"


class BadStepError(RuntimeError):
    """A guarded step failed and the policy is 'raise' (or recovery was
    impossible)."""


class CircuitBreakerOpen(RuntimeError):
    """bad_step_limit consecutive bad steps: recovery is not converging;
    stop instead of rollback-looping forever."""


class NumericGuard:
    """Feed every fetched loss through observe(); it returns OK / NAN /
    SPIKE and trips CircuitBreakerOpen after `bad_step_limit`
    consecutive non-OK verdicts."""

    def __init__(self, policy: Optional[str] = None,
                 bad_step_limit: Optional[int] = None,
                 ema_decay: float = 0.9,
                 spike_factor: float = 10.0,
                 warmup_steps: int = 5):
        self.policy = policy if policy is not None \
            else str(flags.get_flag("nan_policy"))
        if self.policy not in POLICIES:
            raise ValueError(
                f"nan_policy {self.policy!r} not one of {POLICIES}")
        self.bad_step_limit = bad_step_limit if bad_step_limit is not None \
            else int(flags.get_flag("bad_step_limit"))
        self.ema_decay = ema_decay
        # spike_factor <= 0 disables spike detection (NaN/Inf always on);
        # warmup_steps healthy observations must seed the EMA first, so
        # the noisy first losses of a fresh model aren't "spikes"
        self.spike_factor = spike_factor
        self.warmup_steps = warmup_steps
        self.ema: Optional[float] = None
        self.healthy_steps = 0
        self.consecutive_bad = 0
        # last non-OK verdict + its first-bad-var attribution detail —
        # the Trainer names these in its raise/skip/rollback log lines
        self.last_verdict: str = OK
        self.last_attribution: str = ""

    def observe(self, loss: float) -> str:
        loss = float(loss)
        verdict = OK
        if not math.isfinite(loss):
            verdict = NAN
        elif (self.spike_factor > 0 and self.ema is not None
                and self.healthy_steps >= self.warmup_steps
                and abs(loss) > self.spike_factor * (abs(self.ema) + 1e-12)):
            verdict = SPIKE
        if verdict == OK:
            self.consecutive_bad = 0
            self.healthy_steps += 1
            self.last_verdict = OK
            self.last_attribution = ""
            self.ema = loss if self.ema is None else (
                self.ema_decay * self.ema + (1 - self.ema_decay) * loss)
            return verdict
        self.consecutive_bad += 1
        # first-bad-layer attribution: the earliest variable (in final-
        # write order) whose NaN/Inf count went nonzero in the last
        # tensorstats sample.  Always answers — when sampling is off or
        # the last sample was clean, the label is 'unattributed' and the
        # detail says what to enable (satellite: the metric/log carry
        # the attribution string even with tensor_stats off).  NaN
        # verdicts only: a finite-loss spike has no NaN to attribute,
        # and a stale NaN sample from an earlier bad step would pin the
        # spike on an unrelated layer.
        if verdict == NAN:
            label, detail = obs_tensorstats.attribution()
        else:
            label, detail = "unattributed", \
                "unattributed(finite loss spike, no NaN to attribute)"
        self.last_verdict = verdict
        self.last_attribution = detail
        _m_bad_steps.labels(reason=verdict, first_var=label).inc()
        obs_flight.record("guard", verdict, loss=loss,
                          consecutive_bad=self.consecutive_bad,
                          policy=self.policy, first_var=label,
                          attribution=detail)
        # the fleet journal carries the trip WITH its first-bad-var:
        # "what corrupted, where, under which policy" joins the
        # incident timeline next to chaos/supervisor/master events
        obs_journal.emit("guard", verdict, loss=loss,
                         consecutive_bad=self.consecutive_bad,
                         policy=self.policy, first_var=label,
                         attribution=detail)
        if 0 < self.bad_step_limit <= self.consecutive_bad:
            obs_flight.dump("circuit_breaker",
                            extra={"verdict": verdict, "loss": loss,
                                   "consecutive_bad": self.consecutive_bad,
                                   "bad_step_limit": self.bad_step_limit,
                                   "attribution": detail})
            obs_journal.emit("guard", "circuit_breaker", loss=loss,
                             consecutive_bad=self.consecutive_bad,
                             bad_step_limit=self.bad_step_limit,
                             attribution=detail)
            raise CircuitBreakerOpen(
                f"{self.consecutive_bad} consecutive bad steps (last: "
                f"{verdict}, loss={loss!r}, {detail}) >= bad_step_limit "
                f"{self.bad_step_limit}; training is not recovering")
        return verdict
