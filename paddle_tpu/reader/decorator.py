"""Composable reader decorators.

Capability parity with /root/reference/python/paddle/reader/decorator.py
(map_readers:36, shuffle:58, chain:93, compose:125, buffered:172,
xmap_readers:243, multiprocess_reader:338, PipeReader:438) and
python/paddle/batch.py.  A *reader* is a zero-arg callable returning an
iterator of samples; a *reader creator* builds readers.  The buffered/xmap
decorators are the host-side async input pipeline (the reference's C++
double_buffer / py_reader role is played by `buffered` + the executor's
async dispatch; the native-code path is paddle_tpu/fast/ when built).
"""
from __future__ import annotations

import itertools
import multiprocessing
import queue
import random as _random
import subprocess
import threading
from typing import Callable, Iterable, List, Sequence

from ..observability import metrics as obs_metrics

# input-pipeline headroom: sampled at every buffered() consume.  Depth
# pinned at 0 while the device waits = the producer can't keep up
# (pairs with the trainer's trainer_data_wait_seconds anatomy).
# Labeled per buffered() so composed pipelines — e.g.
# buffered(batch(buffered(raw, 64)), 8) — stay attributable instead of
# two queues racing one series.
_m_buffer_depth = obs_metrics.gauge(
    "reader_buffer_depth",
    "Items queued in a reader.buffered() prefetch queue at its last "
    "consume, labeled per buffered() decorator (name= arg, or "
    "buffered<N> in creation order).",
    ("reader",))
_buffered_seq = itertools.count()
# anonymous buffered() labels recycle modulo this bound: a pipeline
# rebuilt every epoch must not grow one permanent gauge series per
# epoch (registry series are never reclaimed).  Pass name= for stable
# attribution.
_MAX_ANON_BUFFERED_LABELS = 64

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "xmap_readers", "multiprocess_reader", "batch",
           "cache", "PipeReader", "DeviceBatch", "device_prefetch",
           "elastic_shard", "elastic_watermark"]


def elastic_shard(reader, world: int, rank: int, start: int = 0):
    """Partition one GLOBAL example stream across an elastic fleet
    (ISSUE 14): fast-forward past the first ``start`` examples (the
    watermark everything already consumed before a resize), then yield
    the round-robin share of the remainder — global index ``i`` goes to
    ``(i - start) % world == rank``.

    The elastic-resize discipline: resizes land at epoch (or other
    all-ranks-agree) boundaries, where every rank has consumed the same
    number of ROUNDS ``r`` — so the fleet-wide watermark is
    ``start + r * world``.  Re-partitioning the stream from that
    watermark under a new world size hands every remaining example to
    exactly one rank and repeats none: N→M resizes drop nothing and
    double-consume nothing (regression-tested in
    tests/test_reader_trainer.py).

    A checkpoint records the watermark, not per-rank offsets: compute
    it with the companion :func:`elastic_watermark` from the per-rank
    rounds consumed."""
    world, rank, start = int(world), int(rank), int(start)
    if not (0 <= rank < world):
        raise ValueError(f"elastic_shard: rank {rank} outside world "
                         f"{world}")
    if start < 0:
        raise ValueError(f"elastic_shard: negative start {start}")

    def data_reader():
        for i, item in enumerate(reader()):
            if i < start:
                continue                       # fast-forward
            if (i - start) % world == rank:
                yield item
    return data_reader


def elastic_watermark(start: int, rounds: int, world: int) -> int:
    """The global consumed-through watermark after ``rounds`` per-rank
    items under ``world`` ranks from ``start`` — the value to feed the
    next :func:`elastic_shard` as its ``start`` after a resize at a
    rank-aligned boundary."""
    return int(start) + int(rounds) * int(world)


class DeviceBatch:
    """One prefetched step's feed, already staged on DEVICE by
    ``device_prefetch``: ``feed`` is a {name: jax.Array} dict ready to
    hand to Executor.run, ``size`` the raw batch size (the trainer's
    examples/s denominator).  The consumer must treat the buffers as
    single-use — the trainer donates them to the step."""

    __slots__ = ("feed", "size")

    def __init__(self, feed, size):
        self.feed = feed
        self.size = size


def device_prefetch(reader, size: int = 2, feeder=None, device=None,
                    name: str = "device_prefetch"):
    """Async DEVICE prefetch: a background thread builds each step's
    feed (via ``feeder.feed`` when given, else the reader must yield
    {name: array} dicts) and stages it on device with jax.device_put
    while the consumer's CURRENT step runs on the accelerator — the
    double-buffered input pipeline (size=2) that takes the reader wait
    AND the host->device copy out of the training step entirely.  Yields
    DeviceBatch items; queue depth rides the ``reader_buffer_depth``
    gauge under `name`.  Producer exceptions re-raise in the consumer.
    """
    import jax

    depth_gauge = _m_buffer_depth.labels(reader=name)

    class _End:
        pass

    class _Error:
        def __init__(self, exc):
            self.exc = exc

    def _stage(batch):
        if feeder is not None:
            feed = feeder.feed(batch)
            n = len(batch)
        else:
            if not isinstance(batch, dict):
                raise TypeError(
                    "device_prefetch without a feeder needs the reader "
                    "to yield {name: array} feed dicts; got "
                    f"{type(batch).__name__}")
            feed = batch
            first = next(iter(batch.values()))
            n = int(getattr(first, "shape", (1,))[0] or 1)
        feed = {k: jax.device_put(v, device) for k, v in feed.items()}
        return DeviceBatch(feed, n)

    def data_reader():
        q: queue.Queue = queue.Queue(maxsize=max(1, int(size)))

        def producer():
            try:
                for d in reader():
                    q.put(_stage(d))
            except BaseException as exc:   # propagate to consumer
                q.put(_Error(exc))
            else:
                q.put(_End)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            e = q.get()
            depth_gauge.set(q.qsize())
            if e is _End:
                break
            if isinstance(e, _Error):
                raise e.exc
            yield e
    return data_reader


def map_readers(func, *readers):
    """Apply func to the items of several readers zipped together."""
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


def shuffle(reader, buf_size: int, seed=None):
    """Pool-shuffle within a sliding buffer (ref decorator.py:58)."""
    def data_reader():
        rng = _random.Random(seed)
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf
    return data_reader


def chain(*readers):
    """Concatenate readers back to back (ref decorator.py:93)."""
    def reader():
        for r in readers:
            yield from r()
    return reader


def compose(*readers, check_alignment: bool = True):
    """Zip readers into tuple samples (ref decorator.py:125)."""
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        iters = itertools.zip_longest(*rs) if not check_alignment else zip(*rs)
        for outputs in iters:
            if check_alignment and any(o is None for o in outputs):
                raise RuntimeError("readers not aligned")
            yield sum((make_tuple(o) for o in outputs), ())
    return reader


def buffered(reader, size: int, name: str = None):
    """Background-thread prefetch into a bounded queue (ref :172) —
    overlaps host input work with device steps.  Producer exceptions are
    re-raised in the consumer (not swallowed as end-of-data).  `name`
    labels this queue's reader_buffer_depth gauge series (auto
    buffered<N> otherwise)."""
    depth_gauge = _m_buffer_depth.labels(
        reader=name or "buffered%d" % (
            next(_buffered_seq) % _MAX_ANON_BUFFERED_LABELS))

    class _End:
        pass

    class _Error:
        def __init__(self, exc):
            self.exc = exc

    def data_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def producer():
            try:
                for d in reader():
                    q.put(d)
            except BaseException as exc:   # propagate to consumer
                q.put(_Error(exc))
            else:
                q.put(_End)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            e = q.get()
            depth_gauge.set(q.qsize())
            if e is _End:
                break
            if isinstance(e, _Error):
                raise e.exc
            yield e
    return data_reader


def firstn(reader, n: int):
    def data_reader():
        return itertools.islice(reader(), n)
    return data_reader


def cache(reader):
    """Materialise once, then replay from memory.  A failed first pass
    leaves nothing cached (no partial/duplicated data on retry)."""
    state = {"data": None}

    def data_reader():
        if state["data"] is None:
            state["data"] = list(reader())   # atomic: assign only on success
        return iter(state["data"])
    return data_reader


def xmap_readers(mapper, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map over samples with worker threads (ref :243)."""
    def data_reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        END = object()
        errors: list = []

        def feeder():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
            except BaseException as exc:
                errors.append(exc)
            finally:
                for _ in range(process_num):
                    in_q.put(END)

        def worker():
            try:
                while True:
                    item = in_q.get()
                    if item is END:
                        return
                    i, d = item
                    out_q.put((i, mapper(d)))
            except BaseException as exc:
                errors.append(exc)
            finally:
                out_q.put(END)   # always signal, even on mapper failure

        threading.Thread(target=feeder, daemon=True).start()
        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is END:
                    finished += 1
                    continue
                i, d = item
                pending[i] = d
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is END:
                    finished += 1
                    continue
                yield item[1]
        if errors:
            raise errors[0]
    return data_reader


def multiprocess_reader(readers, use_pipe: bool = True, queue_size: int = 1000):
    """Fan-in several readers, each in its own process (ref :338).
    Samples must be picklable."""
    def data_reader():
        q: multiprocessing.Queue = multiprocessing.Queue(queue_size)

        def worker(r):
            try:
                for d in r():
                    q.put(d)
            finally:
                q.put(None)    # always signal, even on failure

        procs = [multiprocessing.Process(target=worker, args=(r,),
                                         daemon=True) for r in readers]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            # bounded wait so an OOM-killed worker (which can't reach its
            # finally) doesn't hang the consumer forever
            try:
                d = q.get(timeout=1.0)
            except queue.Empty:
                dead = [p for p in procs if not p.is_alive()]
                if len(dead) == len(procs):
                    break
                continue
            if d is None:
                finished += 1
            else:
                yield d
        failed = []
        for p in procs:
            p.join()
            if p.exitcode not in (0, None):
                failed.append(p.exitcode)
        if failed:
            raise RuntimeError(
                f"multiprocess_reader: {len(failed)} worker(s) died with "
                f"exit codes {failed}")
    return data_reader


def batch(reader, batch_size: int, drop_last: bool = False):
    """Group samples into lists of batch_size (ref python/paddle/batch.py)."""
    def batch_reader():
        b = []
        for d in reader():
            b.append(d)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


class PipeReader:
    """Stream records from a shell command's stdout (ref decorator.py:438)."""

    def __init__(self, command: str, bufsize: int = 8192):
        self.command = command
        self.bufsize = bufsize

    def get_line(self, cut_lines: bool = True, line_break: bytes = b"\n"):
        proc = subprocess.Popen(self.command.split(),
                                stdout=subprocess.PIPE, bufsize=self.bufsize)
        remained = b""
        assert proc.stdout is not None
        while True:
            buf = proc.stdout.read(self.bufsize)
            if not buf:
                break
            if cut_lines:
                lines = (remained + buf).split(line_break)
                remained = lines.pop(-1)
                yield from lines
            else:
                yield buf
        if remained:
            yield remained
