"""Reader pipeline (ref python/paddle/reader/)."""
from .decorator import (DeviceBatch, PipeReader, batch, buffered, cache,
                        chain, compose, device_prefetch, elastic_shard,
                        elastic_watermark, firstn, map_readers,
                        multiprocess_reader, shuffle, xmap_readers)
