"""Reader pipeline (ref python/paddle/reader/)."""
from .decorator import (PipeReader, batch, buffered, cache, chain, compose,
                        firstn, map_readers, multiprocess_reader, shuffle,
                        xmap_readers)
