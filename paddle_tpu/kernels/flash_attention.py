"""Pallas TPU flash attention (forward kernel + memory-efficient VJP).

This is the fused replacement for the matmul-softmax-matmul attention the
reference computes through cuDNN/cuBLAS kernels (its closest analogues:
/root/reference/paddle/fluid/operators/math/softmax.cu + matmul ops; the
reference has no fused attention at all — 2018 codebase).  TPU-first
design per /opt/skills/guides/pallas_guide.md:

  * grid = (batch*heads, Tq/BLOCK_Q, Tk/BLOCK_K): K/V enter VMEM one block
    per grid step (streaming — VMEM holds O(BLOCK) not O(T)), the Q block
    and the FlashAttention running (max, sum, acc) stay resident in VMEM
    scratch across the inner K dimension.  O(T) HBM memory, no [T, T]
    score tensor.
  * matmuls in the input dtype (bf16 MXU pass) with f32 accumulation
    (preferred_element_type), softmax statistics in f32.
  * causal: blocks fully above the diagonal skip their compute via
    pl.when.
  * backward: two Pallas kernels (dk/dv with the Q dimension innermost,
    dq with the K dimension innermost) recomputing probabilities from the
    saved (o, lse) — the FlashAttention-2 recurrence, also without [T, T]
    HBM tensors.  The delta term rowsum(do*o) is precomputed in XLA.
    Causal blocks above the diagonal skip compute in both kernels.

On non-TPU platforms the kernel runs in interpret mode (tests), so the op
surface is identical everywhere.  Measured on v5e (bf16, d=64, causal,
chained-invocation timing — see _drive_flash_ab.py): 1.7x the forward
throughput of jax.experimental.pallas.ops.tpu.flash_attention at T=2048
(2.74 vs 4.65 ms) and 3.3x at T=8192 (2.82 vs 9.28 ms); 1.5x / 80x vs the
unfused XLA matmul-softmax-matmul composition at those lengths.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 spells CompilerParams TPUCompilerParams; the alias keeps
# the kernels importable (and interpret-mode runnable) on older builds
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = -1e30
_LANES = 128
# one-pass multi-K-block HDT backward (vs the two-kernel fallback)
_FUSED_BWD_MULTI_K = True


def _pick_block(t: int, target: int) -> int:
    """Largest power-of-two block <= target that divides t."""
    b = 1
    while b < target and t % (b * 2) == 0:
        b *= 2
    return b


def _bmm(a, b, contract, batch=((0,), (0,))):
    """Batched matmul over leading g dim with f32 accumulation."""
    return jax.lax.dot_general(a, b, (contract, batch),
                               preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_scr, m_scr, l_scr,
                *, block_q, block_k, nk, scale, causal, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # causal: skip K blocks strictly above the diagonal
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        # scale folded into q: one [g, bq, d] multiply instead of a
        # [g, bq, bk] one on the scores (the VPU is the bottleneck here)
        q = q_ref[...] * jnp.asarray(scale, q_ref.dtype)
        k = k_ref[...]                             # [g, block_k, d]
        v = v_ref[...]
        s = _bmm(q, k, ((2,), (2,)))               # [g, block_q, block_k]
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where((q_pos >= k_pos)[None], s, NEG_INF)
        if kv_len is not None:
            # sequence was padded up to a tile multiple: mask padded keys
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where((k_pos < kv_len)[None], s, NEG_INF)
        m_prev = m_scr[:, :, :1]                   # [g, block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, :, :1] = l_scr[:, :, :1] * corr + jnp.sum(
            p, axis=2, keepdims=True)
        m_scr[:, :, :1] = m_new
        acc_scr[...] = acc_scr[...] * corr + _bmm(
            p.astype(v.dtype), v, ((2,), (1,)))

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :, :1], 1e-30)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[...] = (m_scr[:, :, :1] + jnp.log(l)).astype(jnp.float32)


def _pick_group(BH: int, block_q: int, block_k: int,
                cap: int = 1024 * 1024) -> int:
    """Batch-heads processed per grid step.  Folding several [T, d] heads
    into one step amortises per-step overhead (DMA issue + scalar
    prologue) while keeping the f32 score intermediates g*block_q*block_k
    under `cap` elements so everything stays in the 16M scoped VMEM
    (fwd holds 2 score-sized arrays -> cap 1M; bwd holds ~4 -> cap 512K,
    both halved again for f32 inputs whose blocks are twice the bytes;
    the caps sit just under limits measured to OOM on v5e).  g is
    capped at 4: the v5e sweep (_drive_flash_tune.py) showed no gain
    beyond 4 and g=8+ OOMs scoped VMEM at common block sizes."""
    g = 1
    while (g < 4 and BH % (g * 2) == 0
           and (g * 2) * block_q * block_k <= cap):
        g *= 2
    return g


def _flash_fwd(q, k, v, scale, causal, interpret, block_q, block_k,
               kv_len=None, block_bh=None):
    """q,k,v: [BH, T, d] -> (o [BH, T, d], lse [BH, T]).  kv_len: actual
    key length when T includes tile padding (mask keys >= kv_len)."""
    BH, T, d = q.shape
    # NOTE: 256-blocks "so causal pairs can skip" were measured SLOWER
    # on v5e (skipped blocks still pay their DMA + grid-step cost);
    # large blocks win
    block_q = block_q or _pick_block(T, 512)
    block_k = block_k or _pick_block(T, 1024)
    if T % block_q or T % block_k:
        raise ValueError(f"seq len {T} not divisible by blocks "
                         f"({block_q}, {block_k})")
    cap = 1024 * 1024 if q.dtype == jnp.bfloat16 else 512 * 1024
    g = block_bh or _pick_group(BH, block_q, block_k, cap=cap)
    if BH % g:
        raise ValueError(f"block_bh {g} must divide batch*heads {BH}")
    nk = T // block_k
    grid = (BH // g, T // block_q, nk)
    kernel = functools.partial(_fwd_kernel, block_q=block_q,
                               block_k=block_k, nk=nk, scale=scale,
                               causal=causal, kv_len=kv_len)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((g, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((g, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((g, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((g, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((g, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, d), q.dtype),
            jax.ShapeDtypeStruct((BH, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, block_q, d), jnp.float32),       # acc
            pltpu.VMEM((g, block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((g, block_q, _LANES), jnp.float32),  # running sum
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return o, lse[..., 0]


def _recompute_p_ds(qs, k, v, do, lse, delta, qi, ki, block_q, block_k,
                    causal, kv_len):
    """Shared bwd-block math: recompute p [g, block_q, block_k] from the
    PRE-SCALED q' = q*scale and (k, lse), and the cotangent
    ds' = p*(dp-delta) (wrt s' = q'@k^T — the scale is folded into the
    operands so no [g, bq, bk]-wide multiply is spent on it; this path is
    VPU-bound at small head dims).  f32 softmax math, input-dtype matmul
    operands."""
    s = _bmm(qs, k, ((2,), (2,)))
    k_pos = ki * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    if causal:
        q_pos = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        s = jnp.where((q_pos >= k_pos)[None], s, NEG_INF)
    if kv_len is not None:
        s = jnp.where((k_pos < kv_len)[None], s, NEG_INF)
    p = jnp.exp(s - lse)                           # [g, block_q, block_k]
    dp = _bmm(do, v, ((2,), (2,)))
    ds = p * (dp - delta)
    return p, ds


def _bwd_dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, block_q, block_k,
                    nq, scale, causal, kv_len):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # causal: Q blocks strictly above this K block contribute nothing
    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _compute():
        qs = q_ref[...] * jnp.asarray(scale, q_ref.dtype)  # [g, bq, d]
        do = do_ref[...]
        k = k_ref[...]                                 # [g, block_k, d]
        v = v_ref[...]
        p, ds = _recompute_p_ds(
            qs, k, v, do, lse_ref[...], delta_ref[...], qi, ki,
            block_q, block_k, causal, kv_len)
        dv_scr[...] = dv_scr[...] + _bmm(
            p.astype(do.dtype), do, ((1,), (1,)))
        # dk = ds'^T @ (q*scale): the pre-scaled q' already carries scale
        dk_scr[...] = dk_scr[...] + _bmm(
            ds.astype(qs.dtype), qs, ((1,), (1,)))

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                   dq_ref, dq_scr, *, block_q, block_k, nk, scale, causal,
                   kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        qs = q_ref[...] * jnp.asarray(scale, q_ref.dtype)
        k = k_ref[...]
        _, ds = _recompute_p_ds(
            qs, k, v_ref[...], do_ref[...], lse_ref[...], delta_ref[...],
            qi, ki, block_q, block_k, causal, kv_len)
        dq_scr[...] = dq_scr[...] + _bmm(ds.astype(k.dtype), k,
                                         ((2,), (1,)))

    @pl.when(ki == nk - 1)
    def _finish():
        # dq = (ds' @ k) * scale — one [g, bq, d]-wide multiply at the end
        dq_ref[...] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_fused1_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                       dq_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                       block_q, block_k, nq, scale, causal, kv_len):
    """One-pass backward for nk == 1 (the whole K/V fits one block, the
    common short-T case): p is recomputed ONCE per q block and feeds all
    three grads.  Valid only because each dq block is visited exactly
    once (no output revisit across an inner K sweep), which the general
    two-kernel path cannot assume."""
    qi = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    qs = q_ref[...] * jnp.asarray(scale, q_ref.dtype)
    do = do_ref[...]
    k = k_ref[...]
    p, ds = _recompute_p_ds(
        qs, k, v_ref[...], do, lse_ref[...], delta_ref[...], qi, 0,
        block_q, block_k, causal, kv_len)
    dv_scr[...] = dv_scr[...] + _bmm(p.astype(do.dtype), do,
                                     ((1,), (1,)))
    dk_scr[...] = dk_scr[...] + _bmm(ds.astype(qs.dtype), qs,
                                     ((1,), (1,)))
    dq_ref[...] = (scale * _bmm(ds.astype(k.dtype), k,
                                ((2,), (1,)))).astype(dq_ref.dtype)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(scale, causal, kv_len, interpret, res, do,
               block_q=None, block_k=None, block_bh=None):
    """Pallas backward: dk/dv kernel (Q innermost) + dq kernel (K
    innermost), FlashAttention-2 recurrence recomputing p from the saved
    (o, lse).  No [T,T] HBM tensor; matmuls run in the INPUT dtype (bf16
    under AMP — full MXU rate) with f32 accumulation; softmax recompute
    and the (dp - delta) correction stay f32."""
    q, k, v, o, lse = res
    BH, T, d = q.shape
    block_q = block_q or _pick_block(T, 256)
    block_k = block_k or _pick_block(T, 512)
    nq, nk = T // block_q, T // block_k
    cap = 512 * 1024 if q.dtype == jnp.bfloat16 else 256 * 1024
    g = block_bh or _pick_group(BH, block_q, block_k, cap=cap)
    if BH % g:
        raise ValueError(f"block_bh {g} must divide batch*heads {BH}")
    # delta from the UNconverted (f32) cotangent, then downcast do for
    # the matmul operands — downcasting first would round the correction
    # term delta = rowsum(do*o) under AMP
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)             # [BH, T, 1]
    do = do.astype(q.dtype)
    lse3 = lse[..., None]                               # [BH, T, 1]

    if nk == 1:
        # single K/V block: the one-pass kernel recomputes p once and
        # feeds all three grads (~45% less bwd VPU + DMA at short T)
        def qspec1(w):
            return pl.BlockSpec((g, block_q, w), lambda b, i: (b, i, 0),
                                memory_space=pltpu.VMEM)

        kv1 = pl.BlockSpec((g, block_k, d), lambda b, i: (b, 0, 0),
                           memory_space=pltpu.VMEM)
        fused1 = functools.partial(
            _bwd_fused1_kernel, block_q=block_q, block_k=block_k, nq=nq,
            scale=scale, causal=causal, kv_len=kv_len)
        dq, dk, dv = pl.pallas_call(
            fused1,
            grid=(BH // g, nq),
            in_specs=[qspec1(d), qspec1(d), qspec1(1), qspec1(1),
                      kv1, kv1],
            out_specs=[qspec1(d), kv1, kv1],
            out_shape=[jax.ShapeDtypeStruct((BH, T, d), q.dtype),
                       jax.ShapeDtypeStruct((BH, T, d), k.dtype),
                       jax.ShapeDtypeStruct((BH, T, d), v.dtype)],
            scratch_shapes=[pltpu.VMEM((g, block_k, d), jnp.float32),
                            pltpu.VMEM((g, block_k, d), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(q, do, lse3, delta, k, v)
        return dq, dk, dv

    def q_side(ix):         # q/do/lse/delta blocks, width w, q index = ix
        def spec(w):
            return pl.BlockSpec((g, block_q, w),
                                lambda b, i, j: (b, ix(i, j), 0),
                                memory_space=pltpu.VMEM)
        return spec

    def kv_side(ix):
        return pl.BlockSpec((g, block_k, d),
                            lambda b, i, j: (b, ix(i, j), 0),
                            memory_space=pltpu.VMEM)

    qs, ks = q_side(lambda i, j: j), kv_side(lambda i, j: i)
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, block_q=block_q, block_k=block_k, nq=nq,
        scale=scale, causal=causal, kv_len=kv_len)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH // g, nk, nq),
        in_specs=[qs(d), qs(d), qs(1), qs(1), ks, ks],
        out_specs=[ks, ks],
        out_shape=[jax.ShapeDtypeStruct((BH, T, d), k.dtype),
                   jax.ShapeDtypeStruct((BH, T, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((g, block_k, d), jnp.float32),
                        pltpu.VMEM((g, block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, do, lse3, delta, k, v)

    qs2, ks2 = q_side(lambda i, j: i), kv_side(lambda i, j: j)
    dq_kernel = functools.partial(
        _bwd_dq_kernel, block_q=block_q, block_k=block_k, nk=nk,
        scale=scale, causal=causal, kv_len=kv_len)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH // g, nq, nk),
        in_specs=[qs2(d), qs2(d), qs2(1), qs2(1), ks2, ks2],
        out_specs=pl.BlockSpec((g, block_q, d), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, T, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((g, block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, do, lse3, delta, k, v)
    return dq, dk, dv


@functools.lru_cache(maxsize=64)
def _make_flash(scale, causal, interpret, block_q, block_k, kv_len=None,
                block_bh=None):
    @jax.custom_vjp
    def f(q, k, v):
        o, _ = _flash_fwd(q, k, v, scale, causal, interpret, block_q,
                          block_k, kv_len, block_bh)
        return o

    def fwd(q, k, v):
        o, lse = _flash_fwd(q, k, v, scale, causal, interpret, block_q,
                            block_k, kv_len, block_bh)
        return o, (q, k, v, o, lse)

    def bwd(res, g):
        return _flash_bwd(scale, causal, kv_len, interpret, res, g,
                          block_q, block_k, block_bh)

    f.defvjp(fwd, bwd)
    return f


# Sequence lengths are padded up to a multiple of this before entering the
# kernel: it guarantees every auto-picked block is >= 128, satisfying the
# TPU (8, 128) VMEM tile constraints for both the [block_q, d] blocks and
# the [block_q, block_k] score intermediates (pallas_guide.md).
_SEQ_GRANULE = 128


def flash_attention(q, k, v, causal: bool = False, scale: float = None,
                    interpret: bool = None, block_q: int = None,
                    block_k: int = None, block_bh: int = None):
    """q,k,v: [B, H, T, d] (or [BH, T, d]).  Returns same shape.

    Any T works: sequences not divisible by 128 are internally padded to
    the next multiple (padded keys are masked out, padded query rows are
    sliced off), so the kernel always sees MXU-tileable blocks; d should
    be <= 128.
    """
    squeeze = False
    if q.ndim == 3:
        q, k, v = q[:, None], k[:, None], v[:, None]
        squeeze = True
    B, H, T, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    Tp = -(-T // _SEQ_GRANULE) * _SEQ_GRANULE
    kv_len = T if Tp != T else None
    if block_q is not None and Tp % block_q:
        raise ValueError(f"block_q {block_q} must divide padded seq {Tp}")
    if block_k is not None and Tp % block_k:
        raise ValueError(f"block_k {block_k} must divide padded seq {Tp}")
    q = q.reshape(B * H, T, d)
    k = k.reshape(B * H, T, d)
    v = v.reshape(B * H, T, d)
    if kv_len is not None:
        pad = ((0, 0), (0, Tp - T), (0, 0))
        q, k, v = (jnp.pad(a, pad) for a in (q, k, v))
    f = _make_flash(float(scale), bool(causal), bool(interpret),
                    block_q, block_k, kv_len, block_bh)
    out = f(q, k, v)
    if kv_len is not None:
        out = out[:, :T]
    out = out.reshape(B, H, T, d)
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# HDT layout: transpose-free attention for the fused-projection op.
#
# q, k: [H, d, B*T]; v: [H, dv, B*T]; o: [H, dv, B*T].  This is the layout a
# dot_general(W, x) projection produces NATURALLY (weights as lhs: output
# dims = [heads*d_head, tokens]) — so the model runs attention with ZERO
# XLA transposes, forward or backward (the [B,T,H,d]<->[B,H,T,d] layout
# churn around the bhtd kernels cost ~24% of the flagship step,
# docs/profile_r03).  In-kernel, scores are computed TRANSPOSED
# (s_T [g, block_k, block_q] with k as the lhs) so the softmax running
# stats are lane-major [g, 1, block_q] and broadcast over the [g, d,
# block_q] accumulator without any sublane<->lane relayout.  Every matmul
# is a Mosaic-supported rank-3 batch-0 dot_general, and every VMEM block
# is fully packed (d=64 sits in sublanes: no half-empty 128-lane tiles,
# unlike the [.., T, d] layout).  The three bwd kernels follow the same
# FlashAttention-2 recurrence as the bhtd path.
# ---------------------------------------------------------------------------


def _mask_hdt(s, qi, ki, block_q, block_k, causal, kv_len):
    """Mask transposed scores s [g, block_k, block_q]: keys in SUBLANES
    (dim 1), queries in LANES (dim 2)."""
    k_pos = ki * block_k + lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 0)
    if causal:
        q_pos = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1)
        s = jnp.where((q_pos >= k_pos)[None], s, NEG_INF)
    if kv_len is not None:
        s = jnp.where((k_pos < kv_len)[None], s, NEG_INF)
    return s


def _fwd_kernel_hdt(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_scr, m_scr,
                    l_scr, *, block_q, block_k, nk, scale, causal, kv_len):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[...] * jnp.asarray(scale, q_ref.dtype)   # [g, d, bq]
        k = k_ref[...]                                     # [g, d, bk]
        v = v_ref[...]                                     # [g, dv, bk]
        s = _bmm(k, q, ((1,), (1,)))       # [g, bk, bq] transposed scores
        s = _mask_hdt(s, qi, ki, block_q, block_k, causal, kv_len)
        m_prev = m_scr[:, :1, :]                           # [g, 1, bq]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)                     # [g, 1, bq]
        l_scr[:, :1, :] = l_scr[:, :1, :] * corr + jnp.sum(
            p, axis=1, keepdims=True)
        m_scr[:, :1, :] = m_new
        acc_scr[...] = acc_scr[...] * corr + _bmm(
            v, p.astype(v.dtype), ((2,), (1,)))            # [g, dv, bq]

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1, :], 1e-30)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[...] = (m_scr[:, :1, :] + jnp.log(l)).astype(jnp.float32)


def _recompute_p_ds_hdt(qs, k, v, do, lse, delta, qi, ki, block_q,
                        block_k, causal, kv_len):
    """Transposed-score bwd block math: p_T, ds_T [g, block_k, block_q]
    from pre-scaled q' and (k, lse); do [g, dv, bq]."""
    s = _bmm(k, qs, ((1,), (1,)))
    s = _mask_hdt(s, qi, ki, block_q, block_k, causal, kv_len)
    p = jnp.exp(s - lse)                   # lse [g, 1, bq] broadcasts
    dp = _bmm(v, do, ((1,), (1,)))         # [g, bk, bq]
    ds = p * (dp - delta)
    return p, ds


def _bwd_dkv_kernel_hdt(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                        dk_ref, dv_ref, dk_scr, dv_scr, *, block_q,
                        block_k, nq, scale, causal, kv_len):
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _compute():
        qs = q_ref[...] * jnp.asarray(scale, q_ref.dtype)  # [g, d, bq]
        do = do_ref[...]                                   # [g, dv, bq]
        k = k_ref[...]
        v = v_ref[...]
        p, ds = _recompute_p_ds_hdt(
            qs, k, v, do, lse_ref[...], delta_ref[...], qi, ki,
            block_q, block_k, causal, kv_len)
        dv_scr[...] = dv_scr[...] + _bmm(
            do, p.astype(do.dtype), ((2,), (2,)))          # [g, dv, bk]
        dk_scr[...] = dk_scr[...] + _bmm(
            qs, ds.astype(qs.dtype), ((2,), (2,)))         # [g, d, bk]

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel_hdt(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                       dq_ref, dq_scr, *, block_q, block_k, nk, scale,
                       causal, kv_len):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        qs = q_ref[...] * jnp.asarray(scale, q_ref.dtype)
        k = k_ref[...]
        _, ds = _recompute_p_ds_hdt(
            qs, k, v_ref[...], do_ref[...], lse_ref[...], delta_ref[...],
            qi, ki, block_q, block_k, causal, kv_len)
        dq_scr[...] = dq_scr[...] + _bmm(k, ds.astype(k.dtype),
                                         ((2,), (1,)))     # [g, d, bq]

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[...] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_fused1_kernel_hdt(q_ref, do_ref, lse_ref, delta_ref, k_ref,
                           v_ref, dq_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                           *, block_q, block_k, nq, scale, causal,
                           kv_len):
    """One-pass backward for nk == 1: p/ds recomputed once feed all three
    grads (each dq block visited exactly once)."""
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    qs = q_ref[...] * jnp.asarray(scale, q_ref.dtype)
    do = do_ref[...]
    k = k_ref[...]
    p, ds = _recompute_p_ds_hdt(
        qs, k, v_ref[...], do, lse_ref[...], delta_ref[...], qi, 0,
        block_q, block_k, causal, kv_len)
    dv_scr[...] = dv_scr[...] + _bmm(do, p.astype(do.dtype),
                                     ((2,), (2,)))
    dk_scr[...] = dk_scr[...] + _bmm(qs, ds.astype(qs.dtype),
                                     ((2,), (2,)))
    dq_ref[...] = (scale * _bmm(k, ds.astype(k.dtype),
                                ((2,), (1,)))).astype(dq_ref.dtype)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _flash_fwd_hdt(q, k, v, B, scale, causal, interpret, block_q,
                   block_k, kv_len=None, block_g=None):
    H, d, Nq = q.shape
    dv = v.shape[1]
    Tq, Tk = Nq // B, k.shape[2] // B
    block_q = block_q or _pick_block(Tq, 512)
    block_k = block_k or _pick_block(Tk, 1024)
    if Tq % block_q or Tk % block_k:
        raise ValueError(f"seq lens ({Tq}, {Tk}) not divisible by blocks "
                         f"({block_q}, {block_k})")
    cap = 1024 * 1024 if q.dtype == jnp.bfloat16 else 512 * 1024
    g = block_g or _pick_group(H, block_q, block_k, cap=cap)
    if H % g:
        raise ValueError(f"block_g {g} must divide heads {H}")
    nq, nk = Tq // block_q, Tk // block_k
    grid = (H // g, B, nq, nk)
    kernel = functools.partial(_fwd_kernel_hdt, block_q=block_q,
                               block_k=block_k, nk=nk, scale=scale,
                               causal=causal, kv_len=kv_len)

    def qsp(w):
        return pl.BlockSpec((g, w, block_q),
                            lambda h, b, i, j: (h, 0, b * nq + i),
                            memory_space=pltpu.VMEM)

    def ksp(w):
        return pl.BlockSpec((g, w, block_k),
                            lambda h, b, i, j: (h, 0, b * nk + j),
                            memory_space=pltpu.VMEM)

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qsp(d), ksp(d), ksp(dv)],
        out_specs=[qsp(dv), qsp(1)],
        out_shape=[
            jax.ShapeDtypeStruct((H, dv, Nq), q.dtype),
            jax.ShapeDtypeStruct((H, 1, Nq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, dv, block_q), jnp.float32),   # acc
            pltpu.VMEM((g, 8, block_q), jnp.float32),    # running max
            pltpu.VMEM((g, 8, block_q), jnp.float32),    # running sum
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _bwd_fused_kernel_hdt(q_ref, do_ref, lse_ref, delta_ref, k_ref,
                          v_ref, dq_part_ref, dk_ref, dv_ref, *,
                          block_q, block_k, nq, scale, causal, kv_len):
    """General one-pass backward (any nk): p/ds recomputed ONCE per
    (q, k) block pair and feed all three grads — the nk == 1 fused
    kernel's 5-matmul-unit plan extended past one K block (the
    two-kernel path costs 7 units).

    Grid is (h, b, ki, qi) with qi INNERMOST: dk/dv accumulate directly
    in their (VMEM-resident, constant-index-across-the-sweep) OUTPUT
    blocks — no HBM round trips, no aliasing; each (ki, qi) pair writes
    its dq contribution to a DISTINCT slot of a [nk, ...] partials
    array (never revisited), which the caller sums in XLA.  Fully
    deterministic: an earlier HBM-aliased accumulator variant raced its
    own write-back at small nk (2/10 trials corrupted at nk=2 on v5e).
    Causal-skipped pairs write zero partials / keep the accumulators."""
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _compute():
        qs = q_ref[...] * jnp.asarray(scale, q_ref.dtype)
        do = do_ref[...]
        k = k_ref[...]
        p, ds = _recompute_p_ds_hdt(
            qs, k, v_ref[...], do, lse_ref[...], delta_ref[...], qi, ki,
            block_q, block_k, causal, kv_len)
        dv_new = _bmm(do, p.astype(do.dtype), ((2,), (2,)))
        dk_new = _bmm(qs, ds.astype(qs.dtype), ((2,), (2,)))
        first = qi == 0 if not causal else qi == (ki * block_k) // block_q
        dv_ref[...] = jnp.where(first, dv_new, dv_ref[...] + dv_new)
        dk_ref[...] = jnp.where(first, dk_new, dk_ref[...] + dk_new)
        dq_part_ref[...] = (scale * _bmm(k, ds.astype(k.dtype),
                                         ((2,), (1,))))

    @pl.when(jnp.logical_not(run))
    def _skip():
        # the partial slot is written exactly once per (ki, qi): zero it
        dq_part_ref[...] = jnp.zeros_like(dq_part_ref)


def _flash_bwd_hdt(B, scale, causal, kv_len, interpret, res, do,
                   block_q=None, block_k=None, block_g=None):
    q, k, v, o, lse = res                   # lse [H, 1, Nq]
    H, d, Nq = q.shape
    dv = v.shape[1]
    Tq, Tk = Nq // B, k.shape[2] // B
    block_q = block_q or _pick_block(Tq, 256)
    block_k = block_k or _pick_block(Tk, 512)
    nq, nk = Tq // block_q, Tk // block_k
    cap = 512 * 1024 if q.dtype == jnp.bfloat16 else 256 * 1024
    g = block_g or _pick_group(H, block_q, block_k, cap=cap)
    if H % g:
        raise ValueError(f"block_g {g} must divide heads {H}")
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=1, keepdims=True)  # [H, 1, Nq] (f32 cotangent)
    do = do.astype(q.dtype)
    out_shapes = [jax.ShapeDtypeStruct((H, d, Nq), q.dtype),
                  jax.ShapeDtypeStruct(k.shape, k.dtype),
                  jax.ShapeDtypeStruct(v.shape, v.dtype)]

    def qsp(w, ix):
        return pl.BlockSpec((g, w, block_q),
                            lambda h, b, i, j: (h, 0, b * nq + ix(i, j)),
                            memory_space=pltpu.VMEM)

    def ksp(w, ix):
        return pl.BlockSpec((g, w, block_k),
                            lambda h, b, i, j: (h, 0, b * nk + ix(i, j)),
                            memory_space=pltpu.VMEM)

    if nk == 1:
        def qsp1(w):
            return pl.BlockSpec((g, w, block_q),
                                lambda h, b, i: (h, 0, b * nq + i),
                                memory_space=pltpu.VMEM)

        def ksp1(w):
            return pl.BlockSpec((g, w, block_k),
                                lambda h, b, i: (h, 0, b),
                                memory_space=pltpu.VMEM)

        fused1 = functools.partial(
            _bwd_fused1_kernel_hdt, block_q=block_q, block_k=block_k,
            nq=nq, scale=scale, causal=causal, kv_len=kv_len)
        dq, dk, dv_ = pl.pallas_call(
            fused1,
            grid=(H // g, B, nq),
            in_specs=[qsp1(d), qsp1(dv), qsp1(1), qsp1(1),
                      ksp1(d), ksp1(dv)],
            out_specs=[qsp1(d), ksp1(d), ksp1(dv)],
            out_shape=out_shapes,
            scratch_shapes=[pltpu.VMEM((g, d, block_k), jnp.float32),
                            pltpu.VMEM((g, dv, block_k), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(q, do, lse, delta, k, v)
        return dq, dk, dv_

    if _FUSED_BWD_MULTI_K and 1 < nk <= 16:
        # general one-pass kernel: 5 matmul units vs the two-kernel
        # path's 7 (kept below for A/B and for very long T, where the
        # [nk, ...] dq-partials traffic overtakes the recompute savings
        # — v5e: T=8k +15%, longer T loses).  dk/dv accumulate in their
        # VMEM-resident out blocks; dq partials occupy distinct slots —
        # no aliasing, bit-deterministic, works in interpret mode too.
        H_, _, Nq_ = q.shape
        Nk_ = k.shape[2]
        # the shared qsp/ksp helpers: grid here is (h, b, ki, qi), so
        # the q side indexes by the 4th grid dim and k/v by the 3rd
        qsp4 = lambda w: qsp(w, lambda i, j: j)
        ksp4 = lambda w: ksp(w, lambda i, j: i)

        dq_part_spec = pl.BlockSpec(
            (None, g, d, block_q),
            lambda h, b, j, i: (j, h, 0, b * nq + i),
            memory_space=pltpu.VMEM)
        kern = functools.partial(
            _bwd_fused_kernel_hdt, block_q=block_q, block_k=block_k,
            nq=nq, scale=scale, causal=causal, kv_len=kv_len)
        dq_parts, dkf, dvf = pl.pallas_call(
            kern,
            grid=(H_ // g, B, nk, nq),
            in_specs=[qsp4(d), qsp4(dv), qsp4(1), qsp4(1), ksp4(d),
                      ksp4(dv)],
            out_specs=[dq_part_spec, ksp4(d), ksp4(dv)],
            out_shape=[jax.ShapeDtypeStruct((nk, H_, d, Nq_),
                                            jnp.float32),
                       jax.ShapeDtypeStruct((H_, d, Nk_), jnp.float32),
                       jax.ShapeDtypeStruct((H_, dv, Nk_), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary", "arbitrary")),
            interpret=interpret,
        )(q, do, lse, delta, k, v)
        return (dq_parts.sum(axis=0).astype(q.dtype),
                dkf.astype(k.dtype), dvf.astype(v.dtype))

    iq, ik = lambda i, j: j, lambda i, j: i
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel_hdt, block_q=block_q, block_k=block_k, nq=nq,
        scale=scale, causal=causal, kv_len=kv_len)
    dk, dv_ = pl.pallas_call(
        dkv_kernel,
        grid=(H // g, B, nk, nq),
        in_specs=[qsp(d, iq), qsp(dv, iq), qsp(1, iq), qsp(1, iq),
                  ksp(d, ik), ksp(dv, ik)],
        out_specs=[ksp(d, ik), ksp(dv, ik)],
        out_shape=out_shapes[1:],
        scratch_shapes=[pltpu.VMEM((g, d, block_k), jnp.float32),
                        pltpu.VMEM((g, dv, block_k), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, do, lse, delta, k, v)

    iq2, ik2 = lambda i, j: i, lambda i, j: j
    dq_kernel = functools.partial(
        _bwd_dq_kernel_hdt, block_q=block_q, block_k=block_k, nk=nk,
        scale=scale, causal=causal, kv_len=kv_len)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(H // g, B, nq, nk),
        in_specs=[qsp(d, iq2), qsp(dv, iq2), qsp(1, iq2), qsp(1, iq2),
                  ksp(d, ik2), ksp(dv, ik2)],
        out_specs=qsp(d, iq2),
        out_shape=out_shapes[0],
        scratch_shapes=[pltpu.VMEM((g, d, block_q), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, do, lse, delta, k, v)
    return dq, dk, dv_


@functools.lru_cache(maxsize=64)
def _make_flash_hdt(B, scale, causal, interpret, block_q, block_k,
                    kv_len=None, block_g=None):
    @jax.custom_vjp
    def f(q, k, v):
        o, _ = _flash_fwd_hdt(q, k, v, B, scale, causal, interpret,
                              block_q, block_k, kv_len, block_g)
        return o

    def fwd(q, k, v):
        o, lse = _flash_fwd_hdt(q, k, v, B, scale, causal, interpret,
                                block_q, block_k, kv_len, block_g)
        return o, (q, k, v, o, lse)

    def bwd(res, g):
        return _flash_bwd_hdt(B, scale, causal, kv_len, interpret, res,
                              g, block_q, block_k, block_g)

    f.defvjp(fwd, bwd)
    return f


def flash_attention_hdt(q, k, v, batch, causal: bool = False,
                        scale: float = None, interpret: bool = None,
                        kv_len: int = None, block_q: int = None,
                        block_k: int = None, block_g: int = None):
    """Flash attention in the transpose-free head-major layout.

    q, k: [H, d, batch*Tq] / [H, d, batch*Tk]; v: [H, dv, batch*Tk].
    Returns o [H, dv, batch*Tq].  Tq/Tk must be multiples of 128 (the
    caller pads tokens BEFORE the projections and passes kv_len to mask
    the padded keys).  causal requires Tq == Tk.
    """
    H, d, Nq = q.shape
    if Nq % batch or k.shape[2] % batch:
        raise ValueError(f"token counts {Nq}/{k.shape[2]} not divisible "
                         f"by batch {batch}")
    if causal and Nq != k.shape[2]:
        raise ValueError("causal attention requires Tq == Tk")
    if kv_len is not None and not 1 <= kv_len <= k.shape[2] // batch:
        # kv_len <= 0 would fully mask the first K block and the online
        # softmax would silently return a uniform average
        # (exp(NEG_INF - NEG_INF) = 1) instead of erroring
        raise ValueError(f"kv_len={kv_len} out of range [1, "
                         f"{k.shape[2] // batch}]")
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    f = _make_flash_hdt(int(batch), float(scale), bool(causal),
                        bool(interpret), block_q, block_k, kv_len,
                        block_g)
    return f(q, k, v)
