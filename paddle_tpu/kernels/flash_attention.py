"""Pallas TPU flash attention (forward kernel + memory-efficient VJP).

This is the fused replacement for the matmul-softmax-matmul attention the
reference computes through cuDNN/cuBLAS kernels (its closest analogues:
/root/reference/paddle/fluid/operators/math/softmax.cu + matmul ops; the
reference has no fused attention at all — 2018 codebase).  TPU-first
design per /opt/skills/guides/pallas_guide.md:

  * grid = (batch*heads, Tq/BLOCK_Q, Tk/BLOCK_K): K/V enter VMEM one block
    per grid step (streaming — VMEM holds O(BLOCK) not O(T)), the Q block
    and the FlashAttention running (max, sum, acc) stay resident in VMEM
    scratch across the inner K dimension.  O(T) HBM memory, no [T, T]
    score tensor.
  * matmuls in the input dtype (bf16 MXU pass) with f32 accumulation
    (preferred_element_type), softmax statistics in f32.
  * causal: blocks fully above the diagonal skip their compute via
    pl.when.
  * backward: custom_vjp recomputes blockwise under lax.scan (XLA fuses
    it) from the saved (o, lse) — FlashAttention-2 recurrence, also
    without [T, T] HBM tensors.

On non-TPU platforms the kernel runs in interpret mode (tests), so the op
surface is identical everywhere.  Measured on v5e (bf16, d=64, causal,
chained-invocation timing — see _drive_flash_ab.py): 1.7x the forward
throughput of jax.experimental.pallas.ops.tpu.flash_attention at T=2048
(2.74 vs 4.65 ms) and 3.3x at T=8192 (2.82 vs 9.28 ms); 1.5x / 80x vs the
unfused XLA matmul-softmax-matmul composition at those lengths.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def _pick_block(t: int, target: int) -> int:
    """Largest power-of-two block <= target that divides t."""
    b = 1
    while b < target and t % (b * 2) == 0:
        b *= 2
    return b


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_scr, m_scr, l_scr,
                *, block_q, block_k, nk, scale, causal, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # causal: skip K blocks strictly above the diagonal
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]                               # [block_q, d]
        k = k_ref[0]                               # [block_k, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if kv_len is not None:
            # sequence was padded up to a tile multiple: mask padded keys
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos < kv_len, s, NEG_INF)
        m_prev = m_scr[:, :1]                      # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, :1] = l_scr[:, :1] * corr + jnp.sum(p, axis=1,
                                                     keepdims=True)
        m_scr[:, :1] = m_new
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:, :1] + jnp.log(l)).astype(jnp.float32)


def _flash_fwd(q, k, v, scale, causal, interpret, block_q, block_k,
               kv_len=None):
    """q,k,v: [BH, T, d] -> (o [BH, T, d], lse [BH, T]).  kv_len: actual
    key length when T includes tile padding (mask keys >= kv_len)."""
    BH, T, d = q.shape
    block_q = block_q or _pick_block(T, 512)
    block_k = block_k or _pick_block(T, 1024)
    if T % block_q or T % block_k:
        raise ValueError(f"seq len {T} not divisible by blocks "
                         f"({block_q}, {block_k})")
    nk = T // block_k
    grid = (BH, T // block_q, nk)
    kernel = functools.partial(_fwd_kernel, block_q=block_q,
                               block_k=block_k, nk=nk, scale=scale,
                               causal=causal, kv_len=kv_len)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, d), q.dtype),
            jax.ShapeDtypeStruct((BH, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),       # acc
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return o, lse[..., 0]


def _flash_bwd(scale, causal, kv_len, res, do):
    """Blockwise recompute backward (FlashAttention-2 recurrence) — pure
    XLA lax.scan, no [T,T] HBM tensor.  Matmuls run in the INPUT dtype
    (bf16 under AMP — full MXU rate) with f32 accumulation; the softmax
    recompute (exp, the (dp - D) correction) stays f32."""
    q, k, v, o, lse = res
    BH, T, d = q.shape
    blk = _pick_block(T, 128)
    nb = T // blk
    mm = q.dtype                          # matmul operand dtype
    dom = do.astype(mm)
    D = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1)                                    # [BH, T]
    q_idx = jnp.arange(T)

    def kv_block(carry, bi):
        dq = carry
        ks = lax.dynamic_slice_in_dim(k, bi * blk, blk, axis=1)
        vs = lax.dynamic_slice_in_dim(v, bi * blk, blk, axis=1)
        s = jnp.einsum("bqd,bkd->bqk", q, ks,
                       preferred_element_type=jnp.float32) * scale
        k_pos = bi * blk + jnp.arange(blk)
        if causal:
            mask = q_idx[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None], s, NEG_INF)
        if kv_len is not None:
            s = jnp.where((k_pos < kv_len)[None, None, :], s, NEG_INF)
        p = jnp.exp(s - lse[:, :, None])                    # [BH, T, blk]
        pm = p.astype(mm)
        dv = jnp.einsum("bqk,bqd->bkd", pm, dom,
                        preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqd,bkd->bqk", dom, vs,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - D[:, :, None]) * scale
        dsm = ds.astype(mm)
        dk = jnp.einsum("bqk,bqd->bkd", dsm, q,
                        preferred_element_type=jnp.float32)
        dq = dq + jnp.einsum("bqk,bkd->bqd", dsm, ks,
                             preferred_element_type=jnp.float32)
        return dq, (dk, dv)

    dq0 = jnp.zeros((BH, T, d), jnp.float32)
    dq, (dks, dvs) = lax.scan(kv_block, dq0, jnp.arange(nb))
    dk = jnp.moveaxis(dks, 0, 1).reshape(BH, T, d)
    dv = jnp.moveaxis(dvs, 0, 1).reshape(BH, T, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.lru_cache(maxsize=64)
def _make_flash(scale, causal, interpret, block_q, block_k, kv_len=None):
    @jax.custom_vjp
    def f(q, k, v):
        o, _ = _flash_fwd(q, k, v, scale, causal, interpret, block_q,
                          block_k, kv_len)
        return o

    def fwd(q, k, v):
        o, lse = _flash_fwd(q, k, v, scale, causal, interpret, block_q,
                            block_k, kv_len)
        return o, (q, k, v, o, lse)

    def bwd(res, g):
        return _flash_bwd(scale, causal, kv_len, res, g)

    f.defvjp(fwd, bwd)
    return f


# Sequence lengths are padded up to a multiple of this before entering the
# kernel: it guarantees every auto-picked block is >= 128, satisfying the
# TPU (8, 128) VMEM tile constraints for both the [block_q, d] blocks and
# the [block_q, block_k] score intermediates (pallas_guide.md).
_SEQ_GRANULE = 128


def flash_attention(q, k, v, causal: bool = False, scale: float = None,
                    interpret: bool = None, block_q: int = None,
                    block_k: int = None):
    """q,k,v: [B, H, T, d] (or [BH, T, d]).  Returns same shape.

    Any T works: sequences not divisible by 128 are internally padded to
    the next multiple (padded keys are masked out, padded query rows are
    sliced off), so the kernel always sees MXU-tileable blocks; d should
    be <= 128.
    """
    squeeze = False
    if q.ndim == 3:
        q, k, v = q[:, None], k[:, None], v[:, None]
        squeeze = True
    B, H, T, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    Tp = -(-T // _SEQ_GRANULE) * _SEQ_GRANULE
    kv_len = T if Tp != T else None
    if block_q is not None and Tp % block_q:
        raise ValueError(f"block_q {block_q} must divide padded seq {Tp}")
    if block_k is not None and Tp % block_k:
        raise ValueError(f"block_k {block_k} must divide padded seq {Tp}")
    q = q.reshape(B * H, T, d)
    k = k.reshape(B * H, T, d)
    v = v.reshape(B * H, T, d)
    if kv_len is not None:
        pad = ((0, 0), (0, Tp - T), (0, 0))
        q, k, v = (jnp.pad(a, pad) for a in (q, k, v))
    f = _make_flash(float(scale), bool(causal), bool(interpret),
                    block_q, block_k, kv_len)
    out = f(q, k, v)
    if kv_len is not None:
        out = out[:, :T]
    out = out.reshape(B, H, T, d)
    return out[:, 0] if squeeze else out
