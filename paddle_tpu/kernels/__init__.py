"""Pallas TPU kernels for the hot ops (SURVEY.md §7: the load-bearing
cuDNN/cuBLAS/xbyak kernels' TPU-native replacements)."""
from .flash_attention import flash_attention
from .layer_norm import fused_layer_norm
from .lm_head import lm_head_xent
