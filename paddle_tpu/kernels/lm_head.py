"""Pallas TPU fused LM-head + softmax cross-entropy.

The reference computes this as fc -> softmax_with_cross_entropy
(/root/reference/paddle/fluid/operators/softmax_with_cross_entropy_op.cc)
materializing the full [N, V] logits; at V=32k that makes the LM head
HBM-bound: the f32 logits round-trip HBM once in forward and 2-3 more
times in backward (measured ~19 ms of a 57 ms d512/L6 train step on v5e
— docs/profile_r03/breakdown.md).

TPU-first design: stream the vocabulary.  Logits NEVER exist in HBM —
only one [block_n, block_v] f32 tile lives in VMEM while a running
(max, sumexp, label-logit) triple is carried across vocab blocks
(online softmax, same recurrence as flash attention):

  forward : grid (N/bn, V/bv), vocab innermost; out = per-token loss
            + lse residual.  One matmul pass over W.
  backward: python loop over token chunks; per chunk ONE kernel with
            grid (V/bv,) recomputing the logits tile, forming
            dlogits = (softmax - onehot) * g and feeding BOTH matmuls:
            dx (VMEM accumulator across vocab blocks) and dW (HBM
            accumulator via input_output_aliases, one visit per vocab
            block per chunk).  3 matmul passes total — the minimum for
            a rematerialized head — with all softmax arithmetic fused
            into them.

Numerics: matmuls run in the input dtype (bf16 under AMP) with f32
accumulation; softmax statistics, loss and the dW accumulator are f32.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 spells CompilerParams TPUCompilerParams; the alias keeps
# the kernels importable (and interpret-mode runnable) on older builds
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = -1e30
_LANES = 128

# overridable defaults (None = auto) — the tuning knobs the v5e sweeps
# exercise; lm_head_xent args take precedence
DEFAULT_BLOCK_N = None
DEFAULT_BLOCK_V = None


def _pick_block_v(V: int) -> int:
    """Largest multiple of 128 that divides V, capped at 1280.  Bigger
    vocab blocks amortise per-grid-step overhead in the forward (v5e
    sweep: bv 640 -> 1280 took the flagship 0.427 -> 0.436 MFU; 2560+
    OOMs scoped VMEM at block_n 1024).  0 if none divides — caller pads
    V.  The backward shrinks bv separately to fit its chunk."""
    for bv in (1280, 640, 512, 384, 256, 128):
        if V % bv == 0:
            return bv
    return 0


def _fwd_kernel(x_ref, w_ref, y_ref, loss_ref, lse_ref, m_scr, l_scr,
                g_scr, *, block_v, nv, valid_v):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        g_scr[:] = jnp.zeros_like(g_scr)

    logits = lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)        # [bn, bv]
    cols = vi * block_v + lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    if valid_v is not None:                        # padded vocab tail
        logits = jnp.where(cols < valid_v, logits, NEG_INF)
    y = y_ref[...]                                 # [bn, 1] int32
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    l_scr[:, :1] = l_scr[:, :1] * corr + jnp.sum(
        jnp.exp(logits - m_new), axis=1, keepdims=True)
    m_scr[:, :1] = m_new
    # the gold logit lives in exactly one vocab block: masked row-sum
    g_scr[:, :1] = g_scr[:, :1] + jnp.sum(
        jnp.where(cols == y, logits, 0.0), axis=1, keepdims=True)

    @pl.when(vi == nv - 1)
    def _finish():
        lse = m_scr[:, :1] + jnp.log(jnp.maximum(l_scr[:, :1], 1e-30))
        valid = (y_ref[...] >= 0).astype(jnp.float32)
        loss_ref[...] = (lse - g_scr[:, :1]) * valid
        lse_ref[...] = lse


def _bwd_kernel(x_ref, w_ref, stats_ref, dw_in_ref,
                dx_ref, dw_out_ref, *, block_v, nv, valid_v):
    """stats packs (lse, g, label-as-f32) in one [C, 128] f32 block —
    three separate [C, 1] inputs would each pad to 128 lanes in VMEM.
    dx accumulates directly in its (revisited, constant-index) f32
    output block instead of a scratch copy."""
    vi = pl.program_id(0)

    @pl.when(vi == 0)
    def _init():
        dx_ref[:] = jnp.zeros_like(dx_ref)

    x = x_ref[...]                                 # [C, D]
    w = w_ref[...]                                 # [D, bv]
    logits = lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    cols = vi * block_v + lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    if valid_v is not None:
        logits = jnp.where(cols < valid_v, logits, NEG_INF)
    lse = stats_ref[:, 0:1]
    g = stats_ref[:, 1:2]
    y = stats_ref[:, 2:3].astype(jnp.int32)
    p = jnp.exp(logits - lse)                      # [C, bv]
    onehot = (cols == y).astype(jnp.float32)
    dlogits = ((p - onehot) * g).astype(x.dtype)
    dx_ref[...] = dx_ref[...] + lax.dot_general(
        dlogits, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dw_out_ref[...] = dw_in_ref[...] + lax.dot_general(
        x, dlogits, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fwd(x, w, y2d, interpret, block_n, block_v, valid_v):
    N, D = x.shape
    _, Vp = w.shape
    nv = Vp // block_v
    nt = N // block_n
    kernel = functools.partial(_fwd_kernel, block_v=block_v, nv=nv,
                               valid_v=valid_v)
    loss, lse = pl.pallas_call(
        kernel,
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((block_n, D), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((D, block_v), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_n, _LANES), jnp.float32)] * 3,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x, w, y2d)
    return loss[:, 0], lse


def _bwd(x, w, y2d, lse, g, interpret, chunk, block_v, valid_v):
    N, D = x.shape
    _, Vp = w.shape
    chunk = min(chunk, N)
    # bigger chunks halve the dw HBM-accumulator churn (the [D, Vp] f32
    # buffer is read+written once per chunk), but the kernel's resident
    # set (x block + f32 dx accumulator + logits tile) must fit the 16M
    # scoped VMEM.  Measured on v5e at D=512: chunk 4096 compiles and is
    # faster on the f32 path but OOMs scoped VMEM (20.8M) with 2-byte
    # operands — Mosaic's buffering differs by dtype — so cap bf16 at
    # 2048
    cap_chunk = 2048 if jnp.dtype(x.dtype).itemsize == 2 else 4096
    while chunk > cap_chunk and chunk % 2 == 0:
        chunk //= 2        # [chunk, *] f32 tiles must fit scoped VMEM
    # the bwd kernel holds ~3 [chunk, bv] f32 intermediates plus the
    # [chunk, D] accumulator; shrink bv until the logits tile is <= 2MB
    # (bv must still divide the padded vocab and keep 128 lanes)
    bv = block_v
    while chunk * bv * 4 > 2 * 1024 * 1024:
        for cand in range(bv - 128, 0, -128):
            if Vp % cand == 0:
                bv = cand
                break
        else:
            break
    block_v = bv
    nv = Vp // block_v
    n_chunks = N // chunk
    kernel = functools.partial(_bwd_kernel, block_v=block_v, nv=nv,
                               valid_v=valid_v)
    dw = jnp.zeros((D, Vp), jnp.float32)
    dxs = []
    stats = jnp.zeros((N, _LANES), jnp.float32)
    stats = stats.at[:, 0].set(lse[:, 0])
    # ignored (negative-label) tokens have zero loss -> zero cotangent;
    # mask g here so the kernel's (p - onehot)*g emits no gradient for
    # them (the forward multiplies by the same valid mask)
    valid = (y2d[:, 0] >= 0).astype(jnp.float32)
    stats = stats.at[:, 1].set(g.reshape(N).astype(jnp.float32) * valid)
    stats = stats.at[:, 2].set(y2d[:, 0].astype(jnp.float32))
    for ci in range(n_chunks):
        sl = slice(ci * chunk, (ci + 1) * chunk)
        dx_c, dw = pl.pallas_call(
            kernel,
            grid=(nv,),
            in_specs=[
                pl.BlockSpec((chunk, D), lambda j: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((D, block_v), lambda j: (0, j),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((chunk, _LANES), lambda j: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((D, block_v), lambda j: (0, j),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((chunk, D), lambda j: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((D, block_v), lambda j: (0, j),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((chunk, D), jnp.float32),
                jax.ShapeDtypeStruct((D, Vp), jnp.float32),
            ],
            input_output_aliases={3: 1},   # dw accumulates in place
            compiler_params=_CompilerParams(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(x[sl], w, stats[sl], dw)
        dxs.append(dx_c.astype(x.dtype))
    return jnp.concatenate(dxs, 0), dw.astype(w.dtype)


@functools.lru_cache(maxsize=32)
def _make_head(interpret, block_n, block_v, chunk, valid_v):
    @jax.custom_vjp
    def f(x, w, y2d):
        loss, _ = _fwd(x, w, y2d, interpret, block_n, block_v, valid_v)
        return loss

    def fwd(x, w, y2d):
        loss, lse = _fwd(x, w, y2d, interpret, block_n, block_v, valid_v)
        return loss, (x, w, y2d, lse)

    def bwd(res, g):
        x, w, y2d, lse = res
        dx, dw = _bwd(x, w, y2d, lse, g, interpret, chunk, block_v,
                      valid_v)
        dy = np.zeros(y2d.shape, jax.dtypes.float0)
        return dx, dw, dy

    f.defvjp(fwd, bwd)
    return f


def lm_head_xent(x, w, labels, interpret: bool = None,
                 block_n: int = None, block_v: int = None,
                 chunk: int = 2048):
    """Per-token softmax cross-entropy through a streamed LM head.

    x [N, D], w [D, V], labels [N] int (negative = ignored) ->
    loss [N] f32 (0 at ignored positions).  N must be a multiple of 256
    (the framework pads batches); V is padded internally to a tile
    multiple.  Differentiable wrt x and w.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if block_n is None:
        block_n = DEFAULT_BLOCK_N
    if block_v is None:
        block_v = DEFAULT_BLOCK_V
    N, D = x.shape
    V = w.shape[1]
    bv = block_v or _pick_block_v(V) or 512
    valid_v = None
    if V % bv:
        Vp = -(-V // bv) * bv
        w = jnp.pad(w, ((0, 0), (0, Vp - V)))
        valid_v = V
    bn = block_n
    if bn is None:
        bn = min(1024, N)
        while N % bn:
            bn //= 2
    if N % bn or bn < 8:
        raise ValueError(f"token count {N} not divisible by block {bn}")
    chunk = min(chunk, N)
    while N % chunk:
        chunk //= 2
    y2d = labels.reshape(N, 1).astype(jnp.int32)
    f = _make_head(bool(interpret), int(bn), int(bv), int(chunk),
                   valid_v)
    return f(x, w, y2d)
