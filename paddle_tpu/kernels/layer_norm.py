"""Pallas fused layer norm (forward kernel + analytic VJP).

Replaces the reference's layer_norm CUDA kernel
(/root/reference/paddle/fluid/operators/layer_norm_op.cu and the xbyak JIT
CPU path operators/math/jit_kernel_layer_norm.cc) with a single VMEM-
resident row kernel: one pass computes mean/var/normalize/affine, so the
activation never round-trips HBM between the statistics and the scale —
the fusion those hand-written kernels existed for.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _ln_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)                 # [rows, D]
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) + b_ref[:].astype(
        jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    # stats laid out [N, 1]: trailing singleton satisfies the TPU tile rule
    mean_ref[:] = mu
    rstd_ref[:] = rstd


from .flash_attention import _pick_block


def _pick_rows(n: int, target: int = 128) -> int:
    return _pick_block(n, target)


def _ln_fwd(x2d, gamma, beta, eps, interpret):
    N, D = x2d.shape
    rows = _pick_rows(N)
    kernel = functools.partial(_ln_kernel, eps=eps)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(N // rows,),
        in_specs=[
            pl.BlockSpec((rows, D), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((D,), lambda i: (0,), memory_space=pltpu.VMEM),
            pl.BlockSpec((D,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rows, D), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D), x2d.dtype),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, gamma, beta)
    return y, mean[:, 0], rstd[:, 0]


@functools.lru_cache(maxsize=32)
def _make_ln(eps, interpret):
    @jax.custom_vjp
    def f(x2d, gamma, beta):
        return _ln_fwd(x2d, gamma, beta, eps, interpret)

    def fwd(x2d, gamma, beta):
        y, mean, rstd = _ln_fwd(x2d, gamma, beta, eps, interpret)
        return (y, mean, rstd), (x2d, gamma, mean, rstd)

    def bwd(res, g):
        gy, gmean, grstd = g
        x, gamma, mean, rstd = res
        xf = x.astype(jnp.float32)
        gyf = gy.astype(jnp.float32)
        xhat = (xf - mean[:, None]) * rstd[:, None]
        gf = gamma.astype(jnp.float32)
        dgamma = jnp.sum(gyf * xhat, axis=0).astype(gamma.dtype)
        dbeta = jnp.sum(gyf, axis=0).astype(gamma.dtype)
        wg = gyf * gf
        D = x.shape[1]
        dx = (wg - jnp.mean(wg, axis=1, keepdims=True)
              - xhat * jnp.mean(wg * xhat, axis=1, keepdims=True))
        dx = dx * rstd[:, None]
        # cotangents through the auxiliary stats outputs:
        #   dmean/dx_j = 1/D;  drstd/dx_j = -rstd^3 * (x_j - mu) / D
        dx = dx + gmean.astype(jnp.float32)[:, None] / D
        dx = dx - (grstd.astype(jnp.float32) * rstd ** 3)[:, None] \
            * (xf - mean[:, None]) / D
        return dx.astype(x.dtype), dgamma, dbeta

    f.defvjp(fwd, bwd)
    return f


def fused_layer_norm(x, gamma, beta, eps: float = 1e-5,
                     interpret: bool = None, return_stats: bool = False):
    """Normalize over the last axis; gamma/beta shape [D].
    return_stats=True additionally returns (mean, variance) with shape
    x.shape[:-1] — computed by the same kernel pass, no extra HBM reads."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    shape = x.shape
    D = shape[-1]
    f = _make_ln(float(eps), bool(interpret))
    y, mean, rstd = f(x.reshape(-1, D), gamma, beta)
    y = y.reshape(shape)
    if not return_stats:
        return y
    mean = mean.reshape(shape[:-1])
    var = (1.0 / jnp.square(rstd) - eps).reshape(shape[:-1])
    return y, mean, var
