"""Pallas fused transformer block: LN -> attention -> residual -> LN ->
MLP -> residual in ONE kernel.

The unfused pre-norm block (models/transformer.py encoder_layer) writes
every intermediate — LN output, q/k/v, attention context, residual sum,
second LN, the [N, F] MLP hidden — to HBM and reads it back.  This
kernel keeps ALL of them in VMEM for a block of queries: the only HBM
traffic is the input x block (read twice: as queries and as keys), the
weights (VMEM-resident across the K sweep) and the output block.

Design (pallas_guide.md):

  * grid = (B, Tq/block_q, Tk/block_k), K innermost ("arbitrary"): the
    flash-attention online-softmax recurrence runs per head over the K
    sweep while q, the softmax stats and the attention accumulator stay
    in VMEM scratch.
  * LN1 of the KEY block is recomputed per (q, k) pair — O(T^2/block)
    extra VPU work, which is what buys zero HBM round trips for the LN
    output (the flash-style remat trade).
  * at the last K step the epilogue runs entirely in VMEM: output
    projection + residual + LN2 + MLP + residual, then ONE output
    store.
  * matmuls take input-dtype operands (bf16 under AMP) with f32
    accumulation via preferred_element_type; LN statistics, softmax
    stats and the MLP hidden stay f32.
  * causal blocks strictly above the diagonal skip compute (pl.when);
    ragged sequence tails are padded to the 128 granule and the padded
    KEYS masked via kv_len (padded query rows are sliced off outside).

Backward: custom VJP — the block recomputes from (x, params) through
`block_reference`, the numerically-matching XLA composition (which
itself routes attention through the Pallas flash kernel on TPU), so
training memory stays O(block) for the fused forward while gradients
are exact for the reference math.  Off-TPU the op lowering uses
`block_reference` directly (no Pallas), keeping CPU tier-1 green; the
kernel itself also runs under interpret=True for numerics tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _pick_block

NEG_INF = -1e30
_LANES = 128
_SEQ_GRANULE = 128

# jax < 0.5 spells CompilerParams TPUCompilerParams; the alias keeps
# interpret-mode tests runnable on older builds
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))


def _ln_affine(x, g, b, eps):
    """f32 layer norm over the last axis with affine params."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    return (xc * lax.rsqrt(var + eps) * g.astype(jnp.float32)
            + b.astype(jnp.float32))


def block_reference(x, p, n_head, causal, eps1=1e-5, eps2=1e-5,
                    use_flash=False, interpret=None):
    """The XLA composition the kernel must match (and the source of its
    gradients): pre-norm attention + MLP with residuals, input-dtype
    matmul operands, f32 accumulation/statistics.  use_flash routes the
    attention through kernels/flash_attention.py (the TPU backward
    path)."""
    ln1g, ln1b, wq, wk, wv, wo, ln2g, ln2b, w1, b1, w2, b2 = p
    cd = x.dtype
    B, T, D = x.shape
    E = wq.shape[1]
    dh = E // n_head

    def mm(a, b_):
        return lax.dot_general(a, b_, (((a.ndim - 1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)

    a = _ln_affine(x, ln1g, ln1b, eps1).astype(cd)
    q = mm(a, wq).astype(cd)
    k = mm(a, wk).astype(cd)
    v = mm(a, wv).astype(cd)

    def split(t):
        return t.reshape(B, T, n_head, dh).transpose(0, 2, 1, 3)

    if use_flash:
        from .flash_attention import flash_attention
        o = flash_attention(split(q), split(k), split(v), causal=causal,
                            interpret=interpret)
    else:
        qh, kh, vh = split(q), split(k), split(v)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                       preferred_element_type=jnp.float32)
        s = s * (dh ** -0.5)
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, NEG_INF)
        w_att = jax.nn.softmax(s, -1).astype(cd)
        o = jnp.einsum("bhqk,bhkd->bhqd", w_att, vh)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, E)
    h = (x.astype(jnp.float32) + mm(o.astype(cd), wo)).astype(cd)
    f = _ln_affine(h, ln2g, ln2b, eps2).astype(cd)
    u = jnp.maximum(mm(f, w1) + b1.astype(jnp.float32), 0.0).astype(cd)
    y = mm(u, w2) + b2.astype(jnp.float32)
    return (h.astype(jnp.float32) + y).astype(cd)


def _block_kernel(xq_ref, xk_ref, ln1g_ref, ln1b_ref, wq_ref, wk_ref,
                  wv_ref, wo_ref, ln2g_ref, ln2b_ref, w1_ref, b1_ref,
                  w2_ref, b2_ref, out_ref, q_scr, acc_scr, m_scr, l_scr,
                  *, block_q, block_k, nk, n_head, dh, scale, causal,
                  kv_len, eps1, eps2):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    cd = xq_ref.dtype

    def ln(xb, g_ref, b_ref, eps):
        return _ln_affine(xb, g_ref[...], b_ref[...], eps)

    @pl.when(ki == 0)
    def _init():
        a_q = ln(xq_ref[0], ln1g_ref, ln1b_ref, eps1).astype(cd)
        q = lax.dot_general(a_q, wq_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        # scale folded into the stored q: one [bq, E] multiply instead
        # of a per-(head, k-block) one on the scores
        q_scr[...] = q * scale
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _attend():
        a_k = ln(xk_ref[0], ln1g_ref, ln1b_ref, eps1).astype(cd)
        k = lax.dot_general(a_k, wk_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        v = lax.dot_general(a_k, wv_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        k_pos = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = None
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = q_pos >= k_pos
        if kv_len is not None:
            live = k_pos < kv_len
            mask = live if mask is None else (mask & live)
        for h in range(n_head):
            sl = slice(h * dh, (h + 1) * dh)
            s = lax.dot_general(
                q_scr[:, sl].astype(cd), k[:, sl].astype(cd),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if mask is not None:
                s = jnp.where(mask, s, NEG_INF)
            m_prev = m_scr[:, h:h + 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_scr[:, h:h + 1] = (l_scr[:, h:h + 1] * corr
                                 + jnp.sum(p, axis=1, keepdims=True))
            m_scr[:, h:h + 1] = m_new
            acc_scr[:, sl] = acc_scr[:, sl] * corr + lax.dot_general(
                p.astype(cd), v[:, sl].astype(cd),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _epilogue():
        o = jnp.concatenate(
            [acc_scr[:, h * dh:(h + 1) * dh]
             / jnp.maximum(l_scr[:, h:h + 1], 1e-30)
             for h in range(n_head)], axis=1).astype(cd)
        attn = lax.dot_general(o, wo_ref[...], (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
        hres = (xq_ref[0].astype(jnp.float32) + attn).astype(cd)
        f = ln(hres, ln2g_ref, ln2b_ref, eps2).astype(cd)
        u = jnp.maximum(
            lax.dot_general(f, w1_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
            + b1_ref[...].astype(jnp.float32), 0.0).astype(cd)
        y = lax.dot_general(u, w2_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) \
            + b2_ref[...].astype(jnp.float32)
        out_ref[0] = (hres.astype(jnp.float32) + y).astype(out_ref.dtype)


def _block_fwd_pallas(x, p, n_head, causal, eps1, eps2, interpret,
                      block_q, block_k):
    """Pad the token dim to the 128 granule, run the fused kernel, slice
    the pad back off.  x: [B, T, D]."""
    ln1g, ln1b, wq, wk, wv, wo, ln2g, ln2b, w1, b1, w2, b2 = p
    B, T, D = x.shape
    E = wq.shape[1]
    F = w1.shape[1]
    if E % n_head:
        raise ValueError(f"model width {E} not divisible by "
                         f"n_head {n_head}")
    if n_head > _LANES:
        raise ValueError(f"fused block kernel tracks per-head softmax "
                         f"stats in one {_LANES}-lane row; n_head "
                         f"{n_head} > {_LANES}")
    dh = E // n_head
    Tp = -(-T // _SEQ_GRANULE) * _SEQ_GRANULE
    kv_len = T if Tp != T else None
    xp = jnp.pad(x, ((0, 0), (0, Tp - T), (0, 0))) if kv_len else x
    bq = block_q or _pick_block(Tp, 256)
    bk = block_k or _pick_block(Tp, 256)
    nq, nk = Tp // bq, Tp // bk
    kernel = functools.partial(
        _block_kernel, block_q=bq, block_k=bk, nk=nk, n_head=n_head,
        dh=dh, scale=float(dh) ** -0.5, causal=causal, kv_len=kv_len,
        eps1=eps1, eps2=eps2)

    def vec(n):
        return pl.BlockSpec((n,), lambda b, i, j: (0,),
                            memory_space=pltpu.VMEM)

    def mat(r, c):
        return pl.BlockSpec((r, c), lambda b, i, j: (0, 0),
                            memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        kernel,
        grid=(B, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            vec(D), vec(D), mat(D, E), mat(D, E), mat(D, E), mat(E, D),
            vec(D), vec(D), mat(D, F), vec(F), mat(F, D), vec(D),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, Tp, D), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, E), jnp.float32),       # scaled q
            pltpu.VMEM((bq, E), jnp.float32),       # attention acc
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running max / head
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running sum / head
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(xp, xp, ln1g, ln1b, wq, wk, wv, wo, ln2g, ln2b, w1, b1, w2, b2)
    return out[:, :T] if kv_len else out


@functools.lru_cache(maxsize=32)
def _make_block(n_head, causal, eps1, eps2, interpret, block_q, block_k):
    @jax.custom_vjp
    def f(x, *p):
        return _block_fwd_pallas(x, p, n_head, causal, eps1, eps2,
                                 interpret, block_q, block_k)

    def fwd(x, *p):
        return f(x, *p), (x, p)

    def bwd(res, g):
        x, p = res
        # exact gradients of the matching composition, rematerialized
        # from (x, params); attention goes through the flash kernels on
        # TPU (interpret mode keeps the pure-XLA path)
        _, vjp_fn = jax.vjp(
            lambda x_, *p_: block_reference(
                x_, p_, n_head, causal, eps1, eps2,
                use_flash=not interpret, interpret=interpret),
            x, *p)
        return vjp_fn(g.astype(x.dtype))

    f.defvjp(fwd, bwd)
    return f


def transformer_block(x, params, n_head, causal=False, eps1=1e-5,
                      eps2=1e-5, interpret=None, use_pallas=None,
                      block_q=None, block_k=None):
    """One fused pre-norm transformer block.

    x [B, T, D]; params = (ln1_scale, ln1_bias, wq, wk, wv, wo,
    ln2_scale, ln2_bias, w1, b1, w2, b2) with wq/wk/wv [D, E], wo
    [E, D], w1 [D, F], w2 [F, D].  Any T works (ragged tails are padded
    to the 128 granule and the padded keys masked).  Differentiable wrt
    x and every param.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if use_pallas is None:
        use_pallas = not interpret
    if not use_pallas:
        return block_reference(x, tuple(params), n_head, causal,
                               eps1, eps2)
    f = _make_block(int(n_head), bool(causal), float(eps1), float(eps2),
                    bool(interpret), block_q, block_k)
    return f(x, *params)
