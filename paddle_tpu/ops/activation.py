"""Pointwise activation / unary math functors.

Parity: the ~30 functors in /root/reference/paddle/fluid/operators/
activation_op.h (relu, sigmoid, tanh, exp, sqrt, rsqrt, abs, ceil, floor,
cos, sin, round, reciprocal, log, square, softplus, softsign, brelu,
leaky_relu, soft_relu, elu, relu6, pow, stanh, hard_shrink, hard_sigmoid,
swish, thresholded_relu) + gelu, selu, prelu, maxout, hard_swish, mish.

All are trivially fused by XLA into neighbouring matmuls/convs — exactly the
fusion the reference needed handwritten fused_ops/ and xbyak JIT kernels for
(operators/math/jit_kernel*.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op, single_input


def _unary(name, fn):
    @register_op(name)
    def _lower(ctx, ins, attrs, _fn=fn):
        return {"Out": [_fn(single_input(ins), attrs)]}
    return _lower


_unary("relu", lambda x, a: jax.nn.relu(x))
_unary("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_unary("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_unary("tanh", lambda x, a: jnp.tanh(x))
_unary("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_unary("exp", lambda x, a: jnp.exp(x))
_unary("sqrt", lambda x, a: jnp.sqrt(x))
_unary("rsqrt", lambda x, a: jax.lax.rsqrt(x))
_unary("abs", lambda x, a: jnp.abs(x))
_unary("ceil", lambda x, a: jnp.ceil(x))
_unary("floor", lambda x, a: jnp.floor(x))
_unary("cos", lambda x, a: jnp.cos(x))
_unary("sin", lambda x, a: jnp.sin(x))
_unary("round", lambda x, a: jnp.round(x))
_unary("reciprocal", lambda x, a: 1.0 / x)
_unary("log", lambda x, a: jnp.log(x))
_unary("square", lambda x, a: jnp.square(x))
_unary("softplus", lambda x, a: jax.nn.softplus(x))
_unary("softsign", lambda x, a: jax.nn.soft_sign(x))
_unary("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0),
                                      a.get("t_max", 24.0)))
_unary("leaky_relu", lambda x, a: jax.nn.leaky_relu(x, a.get("alpha", 0.02)))
_unary("soft_relu",
       lambda x, a: jnp.log1p(jnp.exp(jnp.clip(
           x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))))
_unary("elu", lambda x, a: jax.nn.elu(x, a.get("alpha", 1.0)))
_unary("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_unary("pow", lambda x, a: jnp.power(x, a.get("factor", 1.0)))
_unary("stanh", lambda x, a: a.get("scale_b", 1.7159) *
       jnp.tanh(a.get("scale_a", 2.0 / 3.0) * x))
_unary("hard_shrink",
       lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0))
_unary("softshrink",
       lambda x, a: jnp.sign(x) * jax.nn.relu(jnp.abs(x) -
                                              a.get("lambda", 0.5)))
_unary("hard_sigmoid",
       lambda x, a: jnp.clip(a.get("slope", 0.2) * x +
                             a.get("offset", 0.5), 0.0, 1.0))
_unary("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
_unary("hard_swish",
       lambda x, a: x * jnp.clip(x + a.get("offset", 3.0), 0.0,
                                 a.get("threshold", 6.0)) /
       a.get("scale", 6.0))
_unary("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))
_unary("thresholded_relu",
       lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0))
_unary("gelu",
       lambda x, a: jax.nn.gelu(x, approximate=bool(a.get("approximate",
                                                          False))))
_unary("erf", lambda x, a: jax.lax.erf(x))
_unary("selu", lambda x, a: a.get("scale", 1.0507009873554805) * jnp.where(
    x > 0, x, a.get("alpha", 1.6732632423543772) * (jnp.exp(x) - 1)))
_unary("sign", lambda x, a: jnp.sign(x))


@register_op("prelu")
def _prelu(ctx, ins, attrs):
    """ref operators/prelu_op.cc — modes: all | channel | element."""
    x = single_input(ins)
    alpha = single_input(ins, "Alpha")
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "element":
        alpha = alpha.reshape((1,) + x.shape[1:])
    return {"Out": [jnp.where(x > 0, x, alpha * x)]}


@register_op("maxout")
def _maxout(ctx, ins, attrs):
    """ref operators/maxout_op.cc (NCHW, groups along C)."""
    x = single_input(ins)
    groups = int(attrs["groups"])
    n, c = x.shape[0], x.shape[1]
    rest = x.shape[2:]
    x = x.reshape((n, c // groups, groups) + rest)
    return {"Out": [x.max(axis=2)]}
