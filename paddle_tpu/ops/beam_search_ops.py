"""Beam search ops.

Capability parity with /root/reference/paddle/fluid/operators/
beam_search_op.cc and beam_search_decode_op.cc, redesigned TPU-first:
the reference walks LoD-structured candidate lists per source sentence;
here everything is dense [batch, beam] tensors + lax.top_k, so one step
is a couple of MXU/VPU-friendly ops and the whole decode loop lives
inside a single lax.scan (layers.StaticRNN) under jit — no host control
flow, no dynamic shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op, single_input

NEG_INF = -1e9


@register_op("beam_search", stop_gradient=True)
def _beam_search(ctx, ins, attrs):
    """One expansion step.

    Inputs:
      PreScores [B, K] cumulative log-probs (init row = [0, -inf, ...]);
      PreIds    [B, K] previous token per beam (to detect finished beams);
      LogProbs  [B, K, V] next-token log-probs.
    attrs: beam_size K (= input K), end_id.
    Outputs: Scores [B, K], Ids [B, K], Parents [B, K] int32.

    Finished beams (PreIds == end_id) are frozen: they can only emit
    end_id again at zero cost, so their cumulative score is carried
    unchanged (ref beam_search_op.cc end-token handling)."""
    pre_scores = single_input(ins, "PreScores")
    pre_ids = single_input(ins, "PreIds")
    log_probs = single_input(ins, "LogProbs")
    B, K, V = log_probs.shape
    end_id = int(attrs.get("end_id", 1))

    finished = (pre_ids.astype(jnp.int32) == end_id)           # [B, K]
    # finished beams: only end_id continuation, at zero added cost
    only_end = jnp.full((V,), NEG_INF, log_probs.dtype).at[end_id].set(0.0)
    step_lp = jnp.where(finished[..., None], only_end[None, None, :],
                        log_probs)
    total = pre_scores[..., None] + step_lp                    # [B, K, V]
    flat = total.reshape(B, K * V)
    scores, idx = lax.top_k(flat, K)                           # [B, K]
    parents = (idx // V).astype(jnp.int32)
    ids = (idx % V).astype(jnp.int32)
    outs = {"Scores": [scores], "Ids": [ids], "Parents": [parents]}
    if ins.get("State"):
        # fused beam reorder: State [B, K, ...] gathered by parent, so
        # the decode loop needs no separate flat-index gather plumbing
        state = ins["State"][0]
        binc = jnp.arange(B)[:, None]
        outs["StateOut"] = [state[binc, parents]]
    return outs


@register_op("beam_search_decode", stop_gradient=True)
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack stored (ids, parents) steps into full hypotheses.

    Inputs: Ids [T, B, K], Parents [T, B, K] (the per-step outputs of
    `beam_search`, stacked by the scan), Scores [B, K] final cumulative.
    Outputs: SentenceIds [B, K, T] int32, SentenceScores [B, K]
    (ref beam_search_decode_op.cc, dense instead of LoD trees)."""
    ids = single_input(ins, "Ids").astype(jnp.int32)
    parents = single_input(ins, "Parents").astype(jnp.int32)
    scores = single_input(ins, "Scores")
    T, B, K = ids.shape
    binc = jnp.arange(B)[:, None]                              # [B, 1]

    def back(beam, t):
        tok = ids[t][binc, beam]                               # [B, K]
        par = parents[t][binc, beam]
        return par, tok

    init = jnp.tile(jnp.arange(K)[None, :], (B, 1))            # [B, K]
    _, toks = lax.scan(back, init, jnp.arange(T - 1, -1, -1))
    sent = jnp.flip(jnp.transpose(toks, (1, 2, 0)), axis=-1)   # [B, K, T]
    return {"SentenceIds": [sent], "SentenceScores": [scores]}
