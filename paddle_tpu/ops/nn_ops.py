"""Neural-net structural ops: conv, pool, normalization, softmax, dropout,
embedding, interpolation.

Parity: conv2d/conv3d/depthwise/conv2d_transpose (operators/conv_op.cc,
conv_cudnn_op.cu.cc), pool2d/pool3d/pool_with_index (pool_op.cc), batch_norm
(batch_norm_op.cc), layer_norm, group_norm, lrn, softmax (softmax_op.cc),
dropout (dropout_op.cc), lookup_table (lookup_table_op.cc), interpolate
(interpolate_op.cc), im2sequence, affine_channel, grid_sampler.

TPU-first notes:
 * Layout is NCHW at the API (reference contract); lowering passes explicit
   dimension_numbers to lax.conv_general_dilated and XLA's TPU layout
   assignment picks the efficient internal layout — no manual transposes.
 * Matmuls surface f32 accumulation (preferred_element_type); convs
   compute in the input dtype with the matmul-style AMP output policy
   (bf16 activation plane — math_ops.amp_result); the MXU still
   accumulates f32 internally — see math_ops.amp_inputs for why convs
   cannot use preferred_element_type.
 * batch_norm's running-stat update is the reference's MeanOut/VarianceOut
   in-place contract: outputs write back to the same var names.
 * softmax/layer_norm have Pallas fast paths (kernels/) selected by flag.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..core.dtypes import index_dtype
from ..framework.registry import register_op, single_input


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _conv_padding(padding, ksize, strides, dilations, spatial):
    """Reference uses explicit symmetric int padding; also accept SAME/VALID."""
    if isinstance(padding, str):
        return padding.upper()
    p = _pair(padding, len(spatial))
    return [(pi, pi) for pi in p]



@register_op("conv2d")
def _conv2d(ctx, ins, attrs):
    """NCHW x OIHW -> NCHW (ref operators/conv_op.cc)."""
    x = single_input(ins, "Input")
    w = single_input(ins, "Filter")
    strides = _pair(attrs.get("strides", 1))
    dilations = _pair(attrs.get("dilations", 1))
    groups = int(attrs.get("groups", 1))
    padding = _conv_padding(attrs.get("paddings", 0), w.shape[2:], strides,
                            dilations, x.shape[2:])
    from .math_ops import amp_inputs, amp_result
    orig_dtype = x.dtype
    xc, wc = amp_inputs(x, w)
    # NOTE: no preferred_element_type here — jax's conv transpose rule
    # feeds the cotangent straight back into conv_general_dilated, which
    # requires matching operand dtypes; the MXU accumulates bf16 convs
    # in f32 internally regardless, so compute (and stay) in bf16.
    out = jax.lax.conv_general_dilated(
        xc, wc, window_strides=strides, padding=padding,
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if ins.get("Bias"):    # optional fused bias (inference transpiler fold)
        bias, = amp_inputs(ins["Bias"][0])   # keep the bf16 plane intact
        out = out + bias.reshape(1, -1, 1, 1)
    # matmul-style AMP output policy (see math_ops.amp_result): staying
    # bf16 also keeps cotangents in the dtype the conv transpose rule
    # needs against bf16 operands
    return {"Output": [amp_result(out, orig_dtype)]}


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ctx, ins, attrs):
    x = single_input(ins, "Input")
    attrs = dict(attrs)
    attrs["groups"] = x.shape[1]
    return _conv2d(ctx, ins, attrs)


@register_op("conv3d")
def _conv3d(ctx, ins, attrs):
    x = single_input(ins, "Input")
    w = single_input(ins, "Filter")
    strides = _pair(attrs.get("strides", 1), 3)
    dilations = _pair(attrs.get("dilations", 1), 3)
    groups = int(attrs.get("groups", 1))
    padding = _conv_padding(attrs.get("paddings", 0), w.shape[2:], strides,
                            dilations, x.shape[2:])
    from .math_ops import amp_inputs, amp_result
    orig_dtype = x.dtype
    xc, wc = amp_inputs(x, w)
    out = jax.lax.conv_general_dilated(
        xc, wc, strides, padding, rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    return {"Output": [amp_result(out, orig_dtype)]}


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    """ref conv_transpose_op.cc.  Filter layout is IOHW (in, out, kh, kw)."""
    x = single_input(ins, "Input")
    w = single_input(ins, "Filter")
    strides = _pair(attrs.get("strides", 1))
    dilations = _pair(attrs.get("dilations", 1))
    p = _pair(attrs.get("paddings", 0))
    groups = int(attrs.get("groups", 1))
    # gradient-of-conv formulation: lhs_dilation implements the stride
    kh = (w.shape[2] - 1) * dilations[0] + 1
    kw = (w.shape[3] - 1) * dilations[1] + 1
    pad = [(kh - 1 - p[0], kh - 1 - p[0]), (kw - 1 - p[1], kw - 1 - p[1])]
    w_flip = jnp.flip(w, axis=(2, 3))          # IOHW
    w_t = jnp.swapaxes(w_flip, 0, 1)           # -> OIHW
    if groups > 1:
        i, o = w.shape[0], w.shape[1]
        wg = w_flip.reshape(groups, i // groups, o, *w.shape[2:])
        w_t = jnp.swapaxes(wg, 1, 2).reshape(groups * o, i // groups,
                                             *w.shape[2:])
    from .math_ops import amp_inputs, amp_result
    orig_dtype = x.dtype
    xc, wc = amp_inputs(x, w_t)
    out = jax.lax.conv_general_dilated(
        xc, wc, window_strides=(1, 1), padding=pad,
        lhs_dilation=strides, rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return {"Output": [amp_result(out, orig_dtype)]}


def _pool_nd(x, attrs, nd):
    """Shared N-D pooling (ref pool_op.cc): max|avg, global_pooling,
    ceil_mode, exclusive avg — serves pool2d (NCHW) and pool3d (NCDHW)."""
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        ksize = x.shape[2:]
        pads = [(0, 0)] * nd
        strides = (1,) * nd
    else:
        ksize = _pair(attrs["ksize"], nd)
        strides = _pair(attrs.get("strides", 1), nd)
        p = _pair(attrs.get("paddings", 0), nd)
        pads = [(pi, pi) for pi in p]
    if attrs.get("ceil_mode", False):
        new_pads = []
        for i, (lo, hi) in enumerate(pads):
            size = x.shape[2 + i] + lo + hi - ksize[i]
            rem = size % strides[i]
            new_pads.append((lo, hi + (strides[i] - rem) % strides[i]))
        pads = new_pads
    window = (1, 1) + tuple(ksize)
    strides_full = (1, 1) + tuple(strides)
    pads_full = [(0, 0), (0, 0)] + pads
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else (
            jnp.iinfo(x.dtype).min)
        out = jax.lax.reduce_window(x, init, jax.lax.max, window,
                                    strides_full, pads_full)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window,
                                       strides_full, pads_full)
        if attrs.get("exclusive", True) and any(p != (0, 0) for p in pads):
            ones = jnp.ones(x.shape[2:], x.dtype)[None, None]
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                           strides_full, pads_full)
            out = summed / counts
        else:
            out = summed / float(np.prod(ksize))
    return out.astype(x.dtype)


@register_op("pool2d")
def _pool2d(ctx, ins, attrs):
    """ref pool_op.cc: max|avg, global_pooling, exclusive avg, NCHW."""
    return {"Out": [_pool_nd(single_input(ins), attrs, 2)]}


def _max_pool_with_index(x, attrs, nd):
    """Shared rank-parameterized max pool that also carries the flat
    spatial argmax index through a (value, index) reduce_window
    (ref pool_with_index_op.cc; serves the 2-D and 3-D registrations)."""
    ksize = _pair(attrs["ksize"], nd)
    strides = _pair(attrs.get("strides", 1), nd)
    p = _pair(attrs.get("paddings", 0), nd)
    spatial = x.shape[2:]
    flat_idx = jnp.arange(int(np.prod(spatial)),
                          dtype=jnp.float32).reshape((1, 1) + spatial)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)
    window = (1, 1) + tuple(ksize)
    strides_full = (1, 1) + tuple(strides)
    pads_full = [(0, 0), (0, 0)] + [(pi, pi) for pi in p]

    def select(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    vals, idxs = jax.lax.reduce_window(
        (x, flat_idx), (-jnp.inf, 0.0),
        lambda a, b: select(a, b), window, strides_full, pads_full)
    return {"Out": [vals.astype(x.dtype)], "Mask": [idxs.astype(index_dtype())]}


@register_op("pool2d_with_index")
def _pool2d_with_index(ctx, ins, attrs):
    """max_pool2d_with_index (ref pool_with_index_op.cc): also returns the
    flat spatial argmax index per window."""
    return _max_pool_with_index(single_input(ins), attrs, 2)


@register_op("batch_norm")
def _batch_norm(ctx, ins, attrs):
    """ref batch_norm_op.cc.  In-place running stats: MeanOut/VarianceOut
    write the same var names as Mean/Variance inputs."""
    x = single_input(ins)
    scale = single_input(ins, "Scale")
    bias = single_input(ins, "Bias")
    mean = single_input(ins, "Mean")
    var = single_input(ins, "Variance")
    eps = float(attrs.get("epsilon", 1e-5))
    momentum = float(attrs.get("momentum", 0.9))
    is_test = bool(attrs.get("is_test", False))
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = tuple(x.shape[c_axis] if i == c_axis else 1
                   for i in range(x.ndim))

    xf = x.astype(jnp.float32)
    if is_test or bool(attrs.get("use_global_stats", False)):
        use_mean, use_var = mean, var
        saved_mean, saved_var = mean, var
        mean_out, var_out = mean, var
    else:
        bmean = jnp.mean(xf, axis=red_axes)
        bvar = jnp.mean(jnp.square(xf), axis=red_axes) - jnp.square(bmean)
        use_mean, use_var = bmean, bvar
        saved_mean = bmean
        saved_var = 1.0 / jnp.sqrt(bvar + eps)
        mean_out = jax.lax.stop_gradient(
            momentum * mean + (1 - momentum) * bmean).astype(mean.dtype)
        var_out = jax.lax.stop_gradient(
            momentum * var + (1 - momentum) * bvar).astype(var.dtype)
    inv = jax.lax.rsqrt(use_var.astype(jnp.float32) + eps)
    y = (xf - use_mean.reshape(bshape)) * inv.reshape(bshape)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    return {"Y": [y.astype(x.dtype)],
            "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [saved_mean], "SavedVariance": [saved_var]}


@register_op("layer_norm")
def _layer_norm(ctx, ins, attrs):
    """ref layer_norm_op.cc: normalise over dims >= begin_norm_axis.
    Fast path: the fused Pallas kernel (kernels/layer_norm.py) when
    normalising a single trailing axis with affine params."""
    x = single_input(ins)
    eps = float(attrs.get("epsilon", 1e-5))
    axis = int(attrs.get("begin_norm_axis", 1))
    from ..core import flags as _flags
    if (_flags.get_flag("use_pallas_kernels") and axis == x.ndim - 1
            and ins.get("Scale") and ins.get("Bias")):
        from ..kernels.layer_norm import fused_layer_norm
        y, mean, var = fused_layer_norm(x, ins["Scale"][0], ins["Bias"][0],
                                        eps=eps, return_stats=True,
                                        interpret=ctx.pallas_interpret())
        return {"Y": [y], "Mean": [mean], "Variance": [var]}
    axes = tuple(range(axis, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    norm_shape = x.shape[axis:]
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(norm_shape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(norm_shape)
    return {"Y": [y.astype(x.dtype)],
            "Mean": [mean.reshape(x.shape[:axis])],
            "Variance": [var.reshape(x.shape[:axis])]}


@register_op("group_norm")
def _group_norm(ctx, ins, attrs):
    """ref group_norm_op.cc (NCHW)."""
    x = single_input(ins)
    g = int(attrs["groups"])
    eps = float(attrs.get("epsilon", 1e-5))
    n, c = x.shape[0], x.shape[1]
    rest = x.shape[2:]
    xf = x.astype(jnp.float32).reshape((n, g, c // g) + rest)
    axes = tuple(range(2, xf.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = ((xf - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * len(rest)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(bshape)
    return {"Y": [y.astype(x.dtype)],
            "Mean": [mean.reshape(n, g)], "Variance": [var.reshape(n, g)]}


@register_op("instance_norm")
def _instance_norm(ctx, ins, attrs):
    x = single_input(ins)
    eps = float(attrs.get("epsilon", 1e-5))
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(bshape)
    return {"Y": [y.astype(x.dtype)]}


@register_op("lrn")
def _lrn(ctx, ins, attrs):
    """Local response norm across channels (ref lrn_op.cc)."""
    x = single_input(ins)
    n = int(attrs.get("n", 5))
    k = float(attrs.get("k", 2.0))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    sq = jnp.square(x)
    half = n // 2
    pads = [(0, 0), (half, n - 1 - half), (0, 0), (0, 0)]
    window = (1, n, 1, 1)
    mid = jax.lax.reduce_window(sq, 0.0, jax.lax.add, window, (1, 1, 1, 1),
                                pads)
    mid = k + alpha * mid
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


@register_op("softmax")
def _softmax(ctx, ins, attrs):
    x = single_input(ins)
    axis = int(attrs.get("axis", -1))
    return {"Out": [jax.nn.softmax(x, axis=axis)]}


@register_op("log_softmax")
def _log_softmax(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jax.nn.log_softmax(x, axis=int(attrs.get("axis", -1)))]}


@register_op("dropout")
def _dropout(ctx, ins, attrs):
    """ref dropout_op.cc: implementations downgrade_in_infer (default) and
    upscale_in_train."""
    x = single_input(ins)
    p = float(attrs.get("dropout_prob", 0.5))
    is_test = bool(attrs.get("is_test", False))
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x * (1.0 - p) if impl == "downgrade_in_infer" else x
        return {"Out": [out], "Mask": [jnp.ones_like(x, dtype=jnp.uint8)]}
    seed = int(attrs.get("seed", 0) or 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.rng()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / max(1.0 - p, 1e-8), 0.0)
    else:
        out = jnp.where(keep, x, 0.0)
    return {"Out": [out.astype(x.dtype)],
            "Mask": [keep.astype(jnp.uint8)]}


@register_op("lookup_table")
def _lookup_table(ctx, ins, attrs):
    """Embedding lookup (ref lookup_table_op.cc).  Ids trailing dim of 1 is
    squeezed; padding_idx rows produce zeros.  Sparse-grad/SelectedRows is a
    representation detail the reference needed for pserver traffic — here
    XLA's gather/scatter-add handles the grad natively."""
    w = single_input(ins, "W")
    ids = single_input(ins, "Ids")
    padding_idx = int(attrs.get("padding_idx", -1))
    squeeze = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze:
        ids = ids.squeeze(-1)
    idsi = ids.astype(jnp.int32)
    out = jnp.take(w, idsi, axis=0)
    if padding_idx != -1:
        pad = (idsi == padding_idx)[..., None]
        out = jnp.where(pad, 0.0, out)
    return {"Out": [out]}


@register_op("lookup_table_v2")
def _lookup_table_v2(ctx, ins, attrs):
    return _lookup_table(ctx, ins, attrs)


def _hash_mix_u32(ids_u32):
    """xor-shift/multiply avalanche — MUST stay bit-identical to
    sparse/table.py hash_bucket (host plane) so an id buckets to the
    same row whether folded in the reader or in the graph."""
    c = jnp.uint32(0x45D9F3B)
    x = ids_u32
    x = x ^ (x >> jnp.uint32(16))
    x = x * c
    x = x ^ (x >> jnp.uint32(16))
    x = x * c
    x = x ^ (x >> jnp.uint32(16))
    return x


@register_op("sparse_embedding_lookup")
def _sparse_embedding_lookup(ctx, ins, attrs):
    """Sparse-plane table lookup (paddle_tpu/sparse; ref
    lookup_sparse_table_op.cc + the CTR pipelines' id folding).  Like
    lookup_table, plus ``hash_bucket``: raw ids of ANY magnitude fold
    into [0, vocab) with the sparse plane's avalanche hash, so the
    table never needs the raw id space's extent.  Differentiable: jax
    AD turns the gather's cotangent into a scatter-add over the
    looked-up rows only (duplicate ids accumulate — the SelectedRows
    merge contract)."""
    w = single_input(ins, "W")
    ids = single_input(ins, "Ids")
    squeeze = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze:
        ids = ids.squeeze(-1)
    if bool(attrs.get("hash_bucket", False)):
        mixed = _hash_mix_u32(ids.astype(jnp.uint32))
        idsi = (mixed % jnp.uint32(w.shape[0])).astype(jnp.int32)
    else:
        idsi = ids.astype(jnp.int32)
    return {"Out": [jnp.take(w, idsi, axis=0)]}


@register_op("sparse_scatter_update")
def _sparse_scatter_update(ctx, ins, attrs):
    """SelectedRows-style sparse SGD application: Out = W with
    ``W[Ids] -= lr * Grad`` scatter-ADDED per occurrence (duplicate ids
    accumulate, the scatter-add-vs-overwrite bug class the sparse
    plane's tests pin).  Ids [N] int, Grad [N, dim]; rows not named in
    Ids pass through untouched — the dense [vocab, dim] gradient never
    exists."""
    w = single_input(ins, "W")
    ids = single_input(ins, "Ids").reshape(-1).astype(jnp.int32)
    grad = single_input(ins, "Grad")
    grad = grad.reshape(ids.shape[0], w.shape[1])
    lr = float(attrs.get("learning_rate", 1.0))
    return {"Out": [w.at[ids].add(-lr * grad.astype(w.dtype))]}


@register_op("interpolate")
def _interpolate(ctx, ins, attrs):
    """bilinear/nearest resize, NCHW (ref interpolate_op.cc)."""
    x = single_input(ins)
    method = attrs.get("interp_method", "bilinear")
    out_h = int(attrs.get("out_h", 0))
    out_w = int(attrs.get("out_w", 0))
    scale = attrs.get("scale", 0)
    if (not out_h or not out_w) and scale:
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    align = bool(attrs.get("align_corners", True))
    jmethod = {"bilinear": "linear", "nearest": "nearest",
               "trilinear": "linear", "bicubic": "cubic"}[method]
    if align and jmethod == "linear":
        # jax.image.resize has no align_corners; emulate with explicit
        # gather-based bilinear for exact reference parity.
        h, w = x.shape[2], x.shape[3]
        ys = jnp.linspace(0, h - 1, out_h)
        xs = jnp.linspace(0, w - 1, out_w)
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[None, None, :, None]
        wx = (xs - x0)[None, None, None, :]
        g = lambda yy, xx: x[:, :, yy][:, :, :, xx]
        out = ((1 - wy) * (1 - wx) * g(y0, x0) + (1 - wy) * wx * g(y0, x1)
               + wy * (1 - wx) * g(y1, x0) + wy * wx * g(y1, x1))
        return {"Out": [out.astype(x.dtype)]}
    out = jax.image.resize(x, (x.shape[0], x.shape[1], out_h, out_w),
                           method=jmethod)
    return {"Out": [out.astype(x.dtype)]}


@register_op("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    return _interpolate(ctx, ins, dict(attrs, interp_method="bilinear"))


@register_op("nearest_interp")
def _nearest_interp(ctx, ins, attrs):
    return _interpolate(ctx, ins, dict(attrs, interp_method="nearest"))


@register_op("affine_channel")
def _affine_channel(ctx, ins, attrs):
    x = single_input(ins)
    c = x.shape[1]
    scale = ins["Scale"][0].reshape(1, c, *([1] * (x.ndim - 2)))
    bias = ins["Bias"][0].reshape(1, c, *([1] * (x.ndim - 2)))
    return {"Out": [x * scale + bias]}


@register_op("im2sequence")
def _im2sequence(ctx, ins, attrs):
    """Sliding-window patches -> rows (ref im2sequence_op.cc).  Dense
    output: (N * out_h * out_w, C*kh*kw)."""
    x = single_input(ins)
    kh, kw = _pair(attrs["kernels"])
    sh, sw = _pair(attrs.get("strides", 1))
    p = attrs.get("paddings", [0, 0, 0, 0])
    x = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])])
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: (N, C*kh*kw, oh, ow) -> (N*oh*ow, C*kh*kw), or keep the
    # batch dim ((N, oh*ow, C*kh*kw)) when per_example is set — the
    # dense-plane spelling of "one patch subsequence per image"
    out = patches.transpose(0, 2, 3, 1)
    if attrs.get("per_example"):
        return {"Out": [out.reshape(n, oh * ow, c * kh * kw)]}
    return {"Out": [out.reshape(n * oh * ow, c * kh * kw)]}


@register_op("grid_sampler")
def _grid_sampler(ctx, ins, attrs):
    """Bilinear sampling at normalized grid coords (ref grid_sampler_op.cc)."""
    x = single_input(ins)
    grid = single_input(ins, "Grid")  # (N, H, W, 2) in [-1, 1]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx1, wy1 = gx - x0, gy - y0
    wx0, wy0 = 1 - wx1, 1 - wy1

    def sample(yy, xx):
        yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) &
                 (xx <= w - 1))[:, None]
        batch = jnp.arange(n)[:, None, None]
        v = x[batch, :, yi, xi]          # (N, H, W, C) gather
        v = jnp.moveaxis(v, -1, 1)       # -> (N, C, H, W)
        return v * valid.astype(x.dtype)

    out = (sample(y0, x0) * (wy0 * wx0)[:, None]
           + sample(y0, x1) * (wy0 * wx1)[:, None]
           + sample(y1, x0) * (wy1 * wx0)[:, None]
           + sample(y1, x1) * (wy1 * wx1)[:, None])
    return {"Output": [out.astype(x.dtype)]}


@register_op("pad3d")
def _pad3d(ctx, ins, attrs):
    x = single_input(ins)
    p = attrs["paddings"]  # [l, r, t, b, f, bk] for NCDHW
    pads = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
    return {"Out": [jnp.pad(x, pads,
                            constant_values=attrs.get("value", 0.0))]}


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, ins, attrs):
    """Reference-registered name for pool2d_with_index
    (ref pool_with_index_op.cc registers max_pool2d_with_index)."""
    return _pool2d_with_index(ctx, ins, attrs)


@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ctx, ins, attrs):
    """ref pool_with_index_op.cc (3-D): max pool over NCDHW windows plus
    the flat spatial argmax index per window."""
    return _max_pool_with_index(single_input(ins), attrs, 3)
