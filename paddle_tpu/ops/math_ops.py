"""Matmul-family and misc math ops — the MXU path.

Parity: mul (operators/mul_op.cc — flattening matmul used by fc), matmul
(operators/matmul_op.cc — batched, transpose flags, alpha), scale, sum
(operators/sum_op.cc — N-ary add used by grad accumulation), mean, minus,
clip, clip_by_norm, cumsum, increment, isfinite, dot,
bilinear_tensor_product.

Matmuls lower to jax.lax.dot_general in the program dtype; on TPU these hit
the MXU directly.  bf16 inputs accumulate in f32 (preferred_element_type),
matching MXU native accumulation.
"""
from __future__ import annotations

from functools import reduce as _reduce

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..framework.registry import register_op, single_input


def _acc_type(x):
    return jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else None


def amp_inputs(*xs):
    """Under FLAGS_amp_bf16, f32 MXU-op inputs are cast to bfloat16 right
    before the dot (XLA fuses the convert); dot-style ops keep
    preferred_element_type=f32 so accumulation is surfaced in f32 and
    cast back — params/activations remain f32 master copies.
    EXCEPTION: the conv family omits preferred_element_type (jax's conv
    transpose rule needs matching operand dtypes), so convs compute in
    bf16 and keep the amp_result bf16 output policy; the MXU still
    accumulates f32 internally."""
    if flags.get_flag("amp_bf16"):
        xs = tuple(x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x
                   for x in xs)
    return xs


def amp_result(out, orig_dtype):
    """Matmul-style output dtype: under AMP a f32-origin result STAYS
    bf16 so the activation plane (and every residual the vjp saves) is
    bf16 in HBM — f32 outputs double the activation traffic (measured
    ~2ms/step on the flagship; docs/profile_r03).  Accumulation is still
    f32 inside the MXU via preferred_element_type."""
    if (flags.get_flag("amp_bf16")
            and jnp.dtype(orig_dtype) == jnp.float32):
        return out.astype(jnp.bfloat16)
    return out.astype(orig_dtype)


def amp_matmul(x, y, orig_dtype=None):
    """jnp.matmul with the AMP dtype policy applied in ONE step: when
    the (possibly amp-cast) operands are 2-byte, ask XLA for the 2-byte
    result DIRECTLY — the MXU still accumulates f32 internally, but an
    f32 surface (preferred_element_type) followed by astype(bf16) left
    an unfused convert_element_type pass over the [N, F] activations
    (~1 ms/step on the flagship; docs/profile_r04 math_ops.py rows).
    f32 operands keep the old path: f32 accumulation surfaced, then
    amp_result decides the output plane.

    Under FLAGS_quantize_dtype the matmul leaves the bf16 plane
    entirely: real int8/fp8 operands with dynamic scales and a
    straight-through bf16 backward (ops/quantize_ops.py
    low_precision_matmul)."""
    orig = x.dtype if orig_dtype is None else orig_dtype
    qd = flags.get_flag("quantize_dtype")
    if (qd and jnp.issubdtype(x.dtype, jnp.floating)
            and jnp.issubdtype(y.dtype, jnp.floating)):
        from .quantize_ops import low_precision_matmul
        return low_precision_matmul(x, y, str(qd), orig)
    x, y = amp_inputs(x, y)
    if jnp.dtype(x.dtype).itemsize == 2:
        out = jnp.matmul(x, y)          # 2-byte in -> 2-byte out
        want = (jnp.bfloat16 if jnp.dtype(orig) == jnp.float32
                else orig)              # amp_result's output policy
        return out if out.dtype == jnp.dtype(want) else out.astype(want)
    out = jnp.matmul(x, y, preferred_element_type=_acc_type(x))
    return amp_result(out, orig)


def _flatten2(x, num_col_dims):
    lead = int(np.prod(x.shape[:num_col_dims])) if num_col_dims else 1
    return x.reshape(lead, -1)


@register_op("mul")
def _mul(ctx, ins, attrs):
    """fc's matmul: flatten X to 2-D by x_num_col_dims, Y by y_num_col_dims
    (ref operators/mul_op.cc)."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = int(attrs.get("x_num_col_dims", 1))
    yn = int(attrs.get("y_num_col_dims", 1))
    x2 = _flatten2(x, xn)
    y2 = _flatten2(y, yn)
    out = amp_matmul(x2, y2, x.dtype)
    out_shape = x.shape[:xn] + y.shape[yn:]
    return {"Out": [out.reshape(out_shape)]}


@register_op("matmul")
def _matmul(ctx, ins, attrs):
    """Batched matmul with transpose flags + alpha (ref matmul_op.cc)."""
    x, y = ins["X"][0], ins["Y"][0]
    tx, ty = bool(attrs.get("transpose_X", False)), bool(
        attrs.get("transpose_Y", False))
    alpha = float(attrs.get("alpha", 1.0))
    squeeze_out = []
    if x.ndim == 1:
        x, squeeze_out = x[None, :], [-2]
    if y.ndim == 1:
        y = y[:, None]
        squeeze_out = squeeze_out + [-1]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = amp_matmul(x, y)
    for ax in squeeze_out:
        out = jnp.squeeze(out, axis=ax)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register_op("bmm")
def _bmm(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [amp_matmul(x, y)]}


@register_op("dot")
def _dot(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.sum(x * y, axis=-1, keepdims=True)]}


@register_op("scale")
def _scale(ctx, ins, attrs):
    x = single_input(ins)
    scale = attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * scale + bias]}
    return {"Out": [(x + bias) * scale]}


@register_op("sum")
def _sum(ctx, ins, attrs):
    """N-ary elementwise add (ref sum_op.cc) — grad accumulation's op."""
    return {"Out": [_reduce(jnp.add, ins["X"])]}


@register_op("mean")
def _mean(ctx, ins, attrs):
    return {"Out": [jnp.mean(single_input(ins))]}


@register_op("minus")
def _minus(ctx, ins, attrs):
    return {"Out": [ins["X"][0] - ins["Y"][0]]}


@register_op("clip")
def _clip(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.clip(x, attrs.get("min"), attrs.get("max"))]}


@register_op("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    x = single_input(ins)
    max_norm = float(attrs["max_norm"])
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": [jnp.where(norm > max_norm, x * (max_norm / norm), x)]}


@register_op("cumsum")
def _cumsum(ctx, ins, attrs):
    x = single_input(ins)
    axis = int(attrs.get("axis", -1))
    if attrs.get("flatten", False):
        x, axis = x.reshape(-1), 0
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    else:
        out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    return {"Out": [out]}


@register_op("increment")
def _increment(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [x + attrs.get("step", 1.0)]}


@register_op("isfinite", stop_gradient=True)
def _isfinite(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.isfinite(x).all()]}


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.sum(jnp.square(x)).reshape(())]}


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    return {"Out": [jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)))],
            "sub_result": [sub]}


@register_op("l1_norm")
def _l1_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.abs(single_input(ins))).reshape(())]}


@register_op("norm")
def _norm(ctx, ins, attrs):
    """L2-normalise along axis (ref operators/norm_op.cc)."""
    x = single_input(ins)
    axis = int(attrs.get("axis", 1))
    eps = float(attrs.get("epsilon", 1e-10))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


@register_op("cos_sim")
def _cos_sim(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@register_op("bilinear_tensor_product")
def _bilinear(ctx, ins, attrs):
    """out[:, i] = x @ W[i] @ y^T diag (ref bilinear_tensor_product_op.cc)."""
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    out = jnp.einsum("bi,oij,bj->bo", x, w, y)
    if "Bias" in ins and ins["Bias"]:
        out = out + ins["Bias"][0]
    return {"Out": [out]}
