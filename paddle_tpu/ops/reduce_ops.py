"""Reductions, argmax/sort/topk.

Parity: operators/reduce_ops/ (reduce_sum/mean/max/min/prod/all/any),
arg_max/arg_min (operators/arg_min_max_op_base.h), argsort, top_k, cumsum.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtypes import index_dtype
from ..framework.registry import register_op, single_input


def _axes(attrs, ndim):
    if attrs.get("reduce_all", False):
        return None
    dim = attrs.get("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % ndim for d in dim)


def _reduce(name, fn):
    @register_op(name)
    def _lower(ctx, ins, attrs, _fn=fn):
        x = single_input(ins)
        return {"Out": [_fn(x, axis=_axes(attrs, x.ndim),
                            keepdims=bool(attrs.get("keep_dim", False)))]}
    return _lower


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_all", jnp.all)
_reduce("reduce_any", jnp.any)


@register_op("arg_max", stop_gradient=True)
def _arg_max(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.argmax(x, axis=int(attrs.get("axis", -1)))
                    .astype(index_dtype())]}


@register_op("arg_min", stop_gradient=True)
def _arg_min(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.argmin(x, axis=int(attrs.get("axis", -1)))
                    .astype(index_dtype())]}


@register_op("argsort", stop_gradient=True)
def _argsort(ctx, ins, attrs):
    x = single_input(ins)
    axis = int(attrs.get("axis", -1))
    descending = bool(attrs.get("descending", False))
    key = -x if descending else x
    idx = jnp.argsort(key, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx.astype(index_dtype())]}


@register_op("top_k", stop_gradient=True)
def _top_k(ctx, ins, attrs):
    x = single_input(ins)
    k = int(attrs["k"])
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(index_dtype())]}
