"""Optimizer update ops — updates stay *in the program* like the reference.

Parity: operators/optimizers/ (sgd, momentum, lars_momentum, adam, adamax,
adagrad, decayed_adagrad, adadelta, rmsprop, ftrl, proximal_gd,
proximal_adagrad) — each op reads Param/Grad/LearningRate/moments and writes
ParamOut/moment-outs to the SAME var names (functional in-place: the
executor's env rebinds the name, XLA aliases the donated buffer).

The reference's dense + SelectedRows dual paths collapse to one dense path:
sparse embedding grads arrive as dense arrays produced by XLA scatter-add
(see ops/nn_ops.py lookup_table note).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


def _p(ins, slot):
    return ins[slot][0]


@register_op("sgd")
def _sgd(ctx, ins, attrs):
    param, grad, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    return {"ParamOut": [param - lr.reshape(()) * grad]}


@register_op("momentum")
def _momentum(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    vel, lr = _p(ins, "Velocity"), _p(ins, "LearningRate").reshape(())
    mu = float(attrs["mu"])
    v = mu * vel + grad
    if attrs.get("use_nesterov", False):
        p = param - (grad + mu * v) * lr
    else:
        p = param - lr * v
    return {"ParamOut": [p], "VelocityOut": [v]}


@register_op("lars_momentum")
def _lars_momentum(ctx, ins, attrs):
    """Layer-wise adaptive rate scaling (ref lars_momentum_op.cc)."""
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    vel, lr = _p(ins, "Velocity"), _p(ins, "LearningRate").reshape(())
    mu = float(attrs["mu"])
    lars_coeff = float(attrs.get("lars_coeff", 1e-3))
    lars_wd = float(attrs.get("lars_weight_decay", 5e-4))
    pn = jnp.sqrt(jnp.sum(jnp.square(param)))
    gn = jnp.sqrt(jnp.sum(jnp.square(grad)))
    local_lr = jnp.where(
        (pn > 0) & (gn > 0),
        lr * lars_coeff * pn / (gn + lars_wd * pn + 1e-12), lr)
    v = mu * vel + local_lr * (grad + lars_wd * param)
    return {"ParamOut": [param - v], "VelocityOut": [v]}


@register_op("adam")
def _adam(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    m1, m2 = _p(ins, "Moment1"), _p(ins, "Moment2")
    b1p_in, b2p_in = _p(ins, "Beta1Pow"), _p(ins, "Beta2Pow")
    b1p = b1p_in.reshape(())
    b2p = b2p_in.reshape(())
    lr = _p(ins, "LearningRate").reshape(())
    b1 = float(attrs.get("beta1", 0.9))
    b2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    m1n = b1 * m1 + (1 - b1) * grad
    m2n = b2 * m2 + (1 - b2) * jnp.square(grad)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p = param - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    # pow accumulators keep their incoming shape: a state var that changes
    # shape across runs invalidates the executor's jit cache (recompile)
    return {"ParamOut": [p], "Moment1Out": [m1n], "Moment2Out": [m2n],
            "Beta1PowOut": [(b1p * b1).reshape(b1p_in.shape)],
            "Beta2PowOut": [(b2p * b2).reshape(b2p_in.shape)]}


@register_op("adamw")
def _adamw(ctx, ins, attrs):
    """Decoupled weight decay Adam (post-reference but standard now)."""
    param = _p(ins, "Param")
    wd = float(attrs.get("coeff", 0.01))
    lr = _p(ins, "LearningRate").reshape(())
    outs = _adam(ctx, ins, attrs)
    outs["ParamOut"] = [outs["ParamOut"][0] - lr * wd * param]
    return outs


@register_op("adamax")
def _adamax(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    m, inf = _p(ins, "Moment"), _p(ins, "InfNorm")
    b1p_in = _p(ins, "Beta1Pow")
    b1p = b1p_in.reshape(())
    lr = _p(ins, "LearningRate").reshape(())
    b1 = float(attrs.get("beta1", 0.9))
    b2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    mn = b1 * m + (1 - b1) * grad
    infn = jnp.maximum(b2 * inf, jnp.abs(grad) + eps)
    p = param - (lr / (1 - b1p)) * (mn / infn)
    return {"ParamOut": [p], "MomentOut": [mn], "InfNormOut": [infn],
            "Beta1PowOut": [(b1p * b1).reshape(b1p_in.shape)]}


@register_op("adagrad")
def _adagrad(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    moment = _p(ins, "Moment")
    lr = _p(ins, "LearningRate").reshape(())
    eps = float(attrs.get("epsilon", 1e-6))
    mn = moment + jnp.square(grad)
    return {"ParamOut": [param - lr * grad / (jnp.sqrt(mn) + eps)],
            "MomentOut": [mn]}


@register_op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    moment = _p(ins, "Moment")
    lr = _p(ins, "LearningRate").reshape(())
    decay = float(attrs.get("decay", 0.95))
    eps = float(attrs.get("epsilon", 1e-6))
    mn = decay * moment + (1 - decay) * jnp.square(grad)
    return {"ParamOut": [param - lr * grad / (jnp.sqrt(mn) + eps)],
            "MomentOut": [mn]}


@register_op("adadelta")
def _adadelta(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    avg_sq_g = _p(ins, "AvgSquaredGrad")
    avg_sq_u = _p(ins, "AvgSquaredUpdate")
    rho = float(attrs.get("rho", 0.95))
    eps = float(attrs.get("epsilon", 1e-6))
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(grad)
    upd = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * grad
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(upd)
    return {"ParamOut": [param + upd], "AvgSquaredGradOut": [g2],
            "AvgSquaredUpdateOut": [u2]}


@register_op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    ms, mom = _p(ins, "MeanSquare"), _p(ins, "Moment")
    lr = _p(ins, "LearningRate").reshape(())
    rho = float(attrs.get("decay", 0.9))
    eps = float(attrs.get("epsilon", 1e-10))
    mu = float(attrs.get("momentum", 0.0))
    centered = bool(attrs.get("centered", False))
    msn = rho * ms + (1 - rho) * jnp.square(grad)
    if centered:
        mg = _p(ins, "MeanGrad")
        mgn = rho * mg + (1 - rho) * grad
        denom = jnp.sqrt(msn - jnp.square(mgn) + eps)
        momn = mu * mom + lr * grad / denom
        return {"ParamOut": [param - momn], "MeanSquareOut": [msn],
                "MomentOut": [momn], "MeanGradOut": [mgn]}
    momn = mu * mom + lr * grad / jnp.sqrt(msn + eps)
    return {"ParamOut": [param - momn], "MeanSquareOut": [msn],
            "MomentOut": [momn]}


@register_op("ftrl")
def _ftrl(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    sq_acc, lin_acc = _p(ins, "SquaredAccumulator"), _p(
        ins, "LinearAccumulator")
    lr = _p(ins, "LearningRate").reshape(())
    l1 = float(attrs.get("l1", 0.0))
    l2 = float(attrs.get("l2", 0.0))
    power = float(attrs.get("lr_power", -0.5))
    new_sq = sq_acc + jnp.square(grad)
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq_acc)) / lr
    else:
        sigma = (jnp.power(new_sq, -power) - jnp.power(sq_acc, -power)) / lr
    new_lin = lin_acc + grad - sigma * param
    if power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -power) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    p = pre / denom
    return {"ParamOut": [p], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [new_lin]}


@register_op("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    lr = _p(ins, "LearningRate").reshape(())
    l1 = float(attrs.get("l1", 0.0))
    l2 = float(attrs.get("l2", 0.0))
    prox = param - lr * grad
    p = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
         / (1.0 + lr * l2))
    return {"ParamOut": [p]}


@register_op("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    moment = _p(ins, "Moment")
    lr = _p(ins, "LearningRate").reshape(())
    l1 = float(attrs.get("l1", 0.0))
    l2 = float(attrs.get("l2", 0.0))
    mn = moment + jnp.square(grad)
    alr = lr / (jnp.sqrt(mn) + 1e-12)
    prox = param - alr * grad
    p = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - alr * l1, 0.0)
         / (1.0 + alr * l2))
    return {"ParamOut": [p], "MomentOut": [mn]}


@register_op("lamb")
def _lamb(ctx, ins, attrs):
    """LAMB (post-reference; needed for BERT-scale large-batch training)."""
    param, grad = _p(ins, "Param"), _p(ins, "Grad")
    m1, m2 = _p(ins, "Moment1"), _p(ins, "Moment2")
    b1p_in, b2p_in = _p(ins, "Beta1Pow"), _p(ins, "Beta2Pow")
    b1p = b1p_in.reshape(())
    b2p = b2p_in.reshape(())
    lr = _p(ins, "LearningRate").reshape(())
    b1 = float(attrs.get("beta1", 0.9))
    b2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-6))
    wd = float(attrs.get("weight_decay", 0.01))
    m1n = b1 * m1 + (1 - b1) * grad
    m2n = b2 * m2 + (1 - b2) * jnp.square(grad)
    mhat = m1n / (1 - b1p)
    vhat = m2n / (1 - b2p)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * param
    pn = jnp.sqrt(jnp.sum(jnp.square(param)))
    rn = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((pn > 0) & (rn > 0), pn / rn, 1.0)
    return {"ParamOut": [param - lr * trust * r],
            "Moment1Out": [m1n], "Moment2Out": [m2n],
            "Beta1PowOut": [(b1p * b1).reshape(b1p_in.shape)],
            "Beta2PowOut": [(b2p * b2).reshape(b2p_in.shape)]}


@register_op("average_accumulates")
def _average_accumulates(ctx, ins, attrs):
    """ModelAverage support (ref average_accumulates_op.cc), simplified to
    the sum accumulators actually consumed by optimizer.ModelAverage.
    Within max_average_window steps this is the exact running sum; past
    the cap it becomes a sliding-window approximation
    (sum <- sum * (w-1)/w + param) so the count stays bounded — an
    unbounded fp32 count would saturate at 2^24 and freeze, and the
    reference's bucket rotation bounds its window the same way."""
    param = _p(ins, "param")
    s1 = _p(ins, "in_sum_1")
    num = _p(ins, "in_num_accumulates").reshape(())
    w = float(attrs.get("max_average_window", 10000))
    in_window = (num < w).astype(s1.dtype)
    s1_out = jnp.where(in_window > 0, s1 + param,
                       s1 * (w - 1.0) / w + param)
    return {"out_sum_1": [s1_out],
            "out_num_accumulates": [jnp.minimum(num + 1, w)]}
