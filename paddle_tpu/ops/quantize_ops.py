"""Fake quantization ops for QAT.

Parity with /root/reference/paddle/fluid/operators/fake_quantize_op.cc
(abs-max and moving-average-abs-max variants) and fake_dequantize_op.cc.
Quantize-dequantize in one op (straight-through estimator): rounding is
a zero-gradient op, so the executor's whole-program vjp sees identity —
exactly the reference's QAT training semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op, single_input


def _ste_round(x):
    """round with straight-through gradient."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


@register_op("fake_quantize_abs_max")
def _fake_quantize_abs_max(ctx, ins, attrs):
    x = single_input(ins)
    bit_length = int(attrs.get("bit_length", 8))
    qmax = float(2 ** (bit_length - 1) - 1)
    scale = jnp.max(jnp.abs(x)).astype(jnp.float32)
    scale = jnp.maximum(scale, 1e-8)
    q = _ste_round(jnp.clip(x / scale * qmax, -qmax, qmax))
    return {"Out": [(q * scale / qmax).astype(x.dtype)],
            "OutScale": [scale]}


@register_op("fake_quantize_moving_average_abs_max")
def _fake_quantize_ma(ctx, ins, attrs):
    x = single_input(ins)
    in_scale = ins["InScale"][0]
    bit_length = int(attrs.get("bit_length", 8))
    rate = float(attrs.get("moving_rate", 0.9))
    is_test = bool(attrs.get("is_test", False))
    qmax = float(2 ** (bit_length - 1) - 1)
    cur = jnp.max(jnp.abs(x)).astype(jnp.float32)
    scale = jnp.where(is_test, in_scale,
                      rate * in_scale + (1 - rate) * cur)
    scale = jnp.maximum(scale, 1e-8)
    q = _ste_round(jnp.clip(x / scale * qmax, -qmax, qmax))
    return {"Out": [(q * scale / qmax).astype(x.dtype)],
            "OutScale": [scale]}


@register_op("fake_channel_wise_quantize_abs_max")
def _fake_cw_quant(ctx, ins, attrs):
    """Per-channel weight quantization.  quant_axis: 0 for conv2d (OIHW
    output channels), 1 for mul/matmul ([in, out]) and conv2d_transpose
    (IOHW) — ref quantization pass semantics."""
    x = single_input(ins)
    bit_length = int(attrs.get("bit_length", 8))
    axis = int(attrs.get("quant_axis", 0))
    qmax = float(2 ** (bit_length - 1) - 1)
    axes = tuple(a for a in range(x.ndim) if a != axis)
    scale = jnp.max(jnp.abs(x), axis=axes).astype(jnp.float32)
    scale = jnp.maximum(scale, 1e-8)
    shape = [1] * x.ndim
    shape[axis] = -1
    s = scale.reshape(shape)
    q = _ste_round(jnp.clip(x / s * qmax, -qmax, qmax))
    return {"Out": [(q * s / qmax).astype(x.dtype)],
            "OutScale": [scale]}


@register_op("fake_dequantize_max_abs")
def _fake_dequantize(ctx, ins, attrs):
    x = single_input(ins)
    scale = ins["Scale"][0]
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": [(x * scale / max_range).astype(x.dtype)]}
