"""Fake quantization ops for QAT — and REAL low-precision execution.

Parity with /root/reference/paddle/fluid/operators/fake_quantize_op.cc
(abs-max and moving-average-abs-max variants) and fake_dequantize_op.cc.
Quantize-dequantize in one op (straight-through estimator): rounding is
a zero-gradient op, so the executor's whole-program vjp sees identity —
exactly the reference's QAT training semantics.

The reference only ever SIMULATED int8 (its quantize_transpiler folds
scales at freeze time and hopes a downstream engine has an int8 kernel).
On TPU we execute it: the second half of this module is the real thing —

  * ``low_precision_matmul``: dynamic-scale int8 x int8 -> int32 (or fp8
    -> f32) dot_general with straight-through bf16 gradients, routed
    under every mul/matmul/bmm by the ``quantize_dtype`` flag
    (training-side path);
  * ``quantized_matmul`` / ``quantized_conv2d`` ops: consume weights
    ALREADY quantized at freeze time (int8/fp8 values + per-channel f32
    scales in the scope), quantize the activation on the fly (frozen
    moving-average scale when recorded, dynamic abs-max otherwise) and
    contract on the low-precision units — what
    QuantizeTranspiler.freeze_program emits.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..core.enforce import EnforceNotMet
from ..framework.registry import register_op, single_input


def _ste_round(x):
    """round with straight-through gradient."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


@register_op("fake_quantize_abs_max")
def _fake_quantize_abs_max(ctx, ins, attrs):
    x = single_input(ins)
    bit_length = int(attrs.get("bit_length", 8))
    qmax = float(2 ** (bit_length - 1) - 1)
    scale = jnp.max(jnp.abs(x)).astype(jnp.float32)
    scale = jnp.maximum(scale, 1e-8)
    q = _ste_round(jnp.clip(x / scale * qmax, -qmax, qmax))
    return {"Out": [(q * scale / qmax).astype(x.dtype)],
            "OutScale": [scale]}


@register_op("fake_quantize_moving_average_abs_max")
def _fake_quantize_ma(ctx, ins, attrs):
    x = single_input(ins)
    in_scale = ins["InScale"][0]
    bit_length = int(attrs.get("bit_length", 8))
    rate = float(attrs.get("moving_rate", 0.9))
    is_test = bool(attrs.get("is_test", False))
    qmax = float(2 ** (bit_length - 1) - 1)
    cur = jnp.max(jnp.abs(x)).astype(jnp.float32)
    scale = jnp.where(is_test, in_scale,
                      rate * in_scale + (1 - rate) * cur)
    scale = jnp.maximum(scale, 1e-8)
    q = _ste_round(jnp.clip(x / scale * qmax, -qmax, qmax))
    return {"Out": [(q * scale / qmax).astype(x.dtype)],
            "OutScale": [scale]}


@register_op("fake_channel_wise_quantize_abs_max")
def _fake_cw_quant(ctx, ins, attrs):
    """Per-channel weight quantization.  quant_axis: 0 for conv2d (OIHW
    output channels), 1 for mul/matmul ([in, out]) and conv2d_transpose
    (IOHW) — ref quantization pass semantics."""
    x = single_input(ins)
    bit_length = int(attrs.get("bit_length", 8))
    axis = int(attrs.get("quant_axis", 0))
    qmax = float(2 ** (bit_length - 1) - 1)
    axes = tuple(a for a in range(x.ndim) if a != axis)
    scale = jnp.max(jnp.abs(x), axis=axes).astype(jnp.float32)
    scale = jnp.maximum(scale, 1e-8)
    shape = [1] * x.ndim
    shape[axis] = -1
    s = scale.reshape(shape)
    q = _ste_round(jnp.clip(x / s * qmax, -qmax, qmax))
    return {"Out": [(q * s / qmax).astype(x.dtype)],
            "OutScale": [scale]}


@register_op("fake_dequantize_max_abs")
def _fake_dequantize(ctx, ins, attrs):
    x = single_input(ins)
    scale = ins["Scale"][0]
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": [(x * scale / max_range).astype(x.dtype)]}


# ---------------------------------------------------------------------------
# Real low-precision execution.
# ---------------------------------------------------------------------------

# storage dtype and representable max per quantize_dtype spelling
_QSPECS = {
    "int8": (jnp.int8, 127.0),
    "e4m3": ("float8_e4m3fn", 448.0),
    "e5m2": ("float8_e5m2", 57344.0),
}


def qspec(quantize_dtype: str):
    """(storage jnp dtype, qmax) for a quantize_dtype spelling; raises
    with the valid vocabulary on an unknown one."""
    if quantize_dtype not in _QSPECS:
        raise EnforceNotMet(
            f"unknown quantize_dtype {quantize_dtype!r}: expected one of "
            f"{sorted(_QSPECS)} (or '' = disabled)")
    dt, qmax = _QSPECS[quantize_dtype]
    if isinstance(dt, str):
        dt = getattr(jnp, dt, None)
        if dt is None:
            raise EnforceNotMet(
                f"quantize_dtype {quantize_dtype!r} needs jax fp8 dtype "
                f"support, which this jax build lacks; use 'int8'")
    return dt, qmax


def quantize_array(x, scale, quantize_dtype: str):
    """x / scale mapped onto the storage grid: int8 rounds+clips, fp8
    casts (the cast saturates).  `scale` is the absmax the grid's qmax
    should land on; broadcastable against x."""
    dt, qmax = qspec(quantize_dtype)
    s = jnp.maximum(scale.astype(jnp.float32), 1e-8)
    # clip BOTH grids: int8 rounds, fp8 saturates — but a frozen scale
    # smaller than the live absmax would otherwise overflow fp8 to inf
    y = jnp.clip(x.astype(jnp.float32) / s * qmax, -qmax, qmax)
    if quantize_dtype == "int8":
        return jnp.round(y).astype(dt)
    return y.astype(dt)


def channel_scales(w: np.ndarray, axis: int) -> np.ndarray:
    """Per-channel absmax scales along `axis` (host-side, freeze time)."""
    axes = tuple(a for a in range(w.ndim) if a != axis)
    return np.maximum(np.abs(w).max(axis=axes), 1e-8).astype("float32")


def _dequant_spec(scale, quantize_dtype):
    _, qmax = qspec(quantize_dtype)
    return jnp.maximum(scale.astype(jnp.float32), 1e-8) / qmax


def _acc_dtype(quantize_dtype):
    return jnp.int32 if quantize_dtype == "int8" else jnp.float32


@functools.lru_cache(maxsize=8)
def _make_lp_matmul(quantize_dtype: str, out_dtype_name: str):
    """Low-precision matmul with straight-through gradients: forward
    quantizes BOTH operands with dynamic scales (per-tensor x, last-axis
    per-channel y — the weight layout of mul/fc) and contracts on the
    int8/fp8 units; backward treats quantization as identity and runs
    the plain (amp-policy) matmul vjp — the STE contract of the fake
    ops, now with a real low-precision forward."""
    out_dtype = jnp.dtype(out_dtype_name)

    def lp_forward(x, y):
        _, qmax = qspec(quantize_dtype)
        sx = jnp.max(jnp.abs(x)).astype(jnp.float32)
        # per-channel over y's LAST axis (output features); reduce all
        # other axes so batched matmuls get one scale row
        red = tuple(range(y.ndim - 1))
        sy = jnp.max(jnp.abs(y), axis=red).astype(jnp.float32)
        xq = quantize_array(x, sx, quantize_dtype)
        yq = quantize_array(y, sy.reshape((1,) * (y.ndim - 1) + (-1,)),
                            quantize_dtype)
        acc = jnp.matmul(xq, yq,
                         preferred_element_type=_acc_dtype(quantize_dtype))
        out = (acc.astype(jnp.float32)
               * _dequant_spec(sx, quantize_dtype)
               * _dequant_spec(sy, quantize_dtype))
        return out.astype(out_dtype)

    def surrogate(x, y):
        # the identity the STE backward differentiates: the amp-policy
        # matmul (bf16 operands under FLAGS_amp_bf16, f32 accumulation)
        from .math_ops import _acc_type, amp_inputs
        xa, ya = amp_inputs(x, y)
        out = jnp.matmul(xa, ya, preferred_element_type=_acc_type(xa))
        return out.astype(out_dtype)

    @jax.custom_vjp
    def f(x, y):
        return lp_forward(x, y)

    def fwd(x, y):
        return lp_forward(x, y), (x, y)

    def bwd(res, g):
        x, y = res
        _, vjp_fn = jax.vjp(surrogate, x, y)
        return vjp_fn(g.astype(out_dtype))

    f.defvjp(fwd, bwd)
    return f


def low_precision_matmul(x, y, quantize_dtype: str, orig_dtype):
    """The quantize_dtype-flag path used by math_ops.amp_matmul: real
    int8/fp8 forward, STE backward.  Output dtype follows the amp
    policy (bf16 surface under FLAGS_amp_bf16, else orig)."""
    want = (jnp.bfloat16
            if (flags.get_flag("amp_bf16")
                and jnp.dtype(orig_dtype) == jnp.float32)
            else jnp.dtype(orig_dtype))
    return _make_lp_matmul(quantize_dtype, jnp.dtype(want).name)(x, y)


@register_op("quantized_matmul")
def _quantized_matmul(ctx, ins, attrs):
    """Frozen-program matmul on genuinely quantized weights (what
    QuantizeTranspiler.freeze_program emits in place of fc's mul).

    X [..., K] float activation; W int8/fp8 [K, N] quantized at freeze
    time; WScale [N] f32 per-channel absmax of the original weight;
    optional InScale [] f32 = the trained moving-average activation
    scale (absent -> dynamic abs-max quantization per dispatch).
    attrs: quantize_dtype, x_num_col_dims (mul flattening contract).

    int8 x int8 contracts to int32 via preferred_element_type (the MXU
    int path); scales apply POST-accumulation:
        out = acc * (sx/qmax) * (sw[N]/qmax)
    """
    x = single_input(ins, "X")
    w = ins["W"][0]
    w_scale = ins["WScale"][0]
    qd = str(attrs.get("quantize_dtype", "int8"))
    xn = int(attrs.get("x_num_col_dims", 1))
    lead = int(np.prod(x.shape[:xn])) if xn else 1
    x2 = x.reshape(lead, -1)
    if ins.get("InScale"):
        sx = ins["InScale"][0].astype(jnp.float32).reshape(())
    else:
        sx = jnp.max(jnp.abs(x2)).astype(jnp.float32)
    xq = quantize_array(x2, sx, qd)
    acc = jax.lax.dot_general(xq, w, (((1,), (0,)), ((), ())),
                              preferred_element_type=_acc_dtype(qd))
    out = (acc.astype(jnp.float32)
           * _dequant_spec(sx, qd)
           * _dequant_spec(w_scale, qd))
    out_shape = x.shape[:xn] + (w.shape[1],)
    want = (jnp.bfloat16 if (flags.get_flag("amp_bf16")
                             and jnp.dtype(x.dtype) == jnp.float32)
            else x.dtype)
    return {"Out": [out.reshape(out_shape).astype(want)]}


@register_op("quantized_conv2d")
def _quantized_conv2d(ctx, ins, attrs):
    """Frozen int8 conv2d (NCHW x OIHW): Filter quantized per output
    channel at freeze time, activation quantized per-tensor on the fly,
    int8 x int8 -> int32 accumulation, scales applied post-accumulation
    over the output-channel dim."""
    x = ins["Input"][0]
    w = ins["Filter"][0]
    f_scale = ins["FilterScale"][0]
    qd = str(attrs.get("quantize_dtype", "int8"))
    strides = tuple(attrs.get("strides", (1, 1)))
    pads = [(int(p), int(p)) for p in attrs.get("paddings", (0, 0))]
    dils = tuple(attrs.get("dilations", (1, 1)))
    groups = int(attrs.get("groups", 1))
    if ins.get("InScale"):
        sx = ins["InScale"][0].astype(jnp.float32).reshape(())
    else:
        sx = jnp.max(jnp.abs(x)).astype(jnp.float32)
    xq = quantize_array(x, sx, qd)
    acc = jax.lax.conv_general_dilated(
        xq, w, window_strides=strides, padding=pads,
        rhs_dilation=dils, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=_acc_dtype(qd))
    out = (acc.astype(jnp.float32)
           * _dequant_spec(sx, qd)
           * _dequant_spec(f_scale, qd).reshape(1, -1, 1, 1))
    want = (jnp.bfloat16 if (flags.get_flag("amp_bf16")
                             and jnp.dtype(x.dtype) == jnp.float32)
            else x.dtype)
    return {"Output": [out.astype(want)]}
