"""Tensor creation / fill / cast ops.

Parity: fill_constant, fill_constant_batch_size_like, fill_zeros_like,
fill_any_like, uniform_random, gaussian_random, truncated_gaussian_random,
assign, assign_value, cast, shape, one_hot, range, eye, linspace
(/root/reference/paddle/fluid/operators/fill_constant_op.cc,
uniform_random_op.cc, gaussian_random_op.cc, one_hot_op.cc, ...).

Random ops draw from the functional RNG plane (ctx.rng()); the per-op `seed`
attr (reference semantics: 0 = use global generator) is honoured by folding a
nonzero seed into a fixed key.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import index_dtype, to_jnp_dtype
from ..framework.registry import register_op, single_input


def _op_key(ctx, attrs):
    seed = int(attrs.get("seed", 0) or 0)
    if seed:
        return jax.random.PRNGKey(seed)
    return ctx.rng()


def _replicated_draw(ctx, value):
    """Pin a random draw REPLICATED under the implicit-SPMD mesh plane
    (executor sets ctx.spmd_mesh there, and only there).  The legacy
    threefry lowering yields different bits when GSPMD partitions the
    generation, so a sharded Parameter's init would silently diverge
    from the single-device run; generating replicated and letting the
    partitioner reshard the RESULT keeps the stream identical under
    any layout.  No-op single-device and inside shard_map (manual
    axes; per-device draws there are deliberate)."""
    mesh = getattr(ctx, "spmd_mesh", None)
    if mesh is None:
        return value
    return jax.lax.with_sharding_constraint(
        value, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()))


@register_op("fill_constant")
def _fill_constant(ctx, ins, attrs):
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0), dtype=dtype)]}


@register_op("fill_constant_batch_size_like")
def _fill_cbsl(ctx, ins, attrs):
    """Shape copied from Input's batch dim (ref
    fill_constant_batch_size_like_op.cc)."""
    x = single_input(ins, "Input")
    shape = list(attrs["shape"])
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = x.shape[in_idx]
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(tuple(shape), attrs.get("value", 0.0), dtype)]}


@register_op("uniform_random_batch_size_like")
def _uniform_random_bsl(ctx, ins, attrs):
    """Shape copied from Input's batch dim (ref
    uniform_random_batch_size_like_op.cc); trace-time static."""
    x = single_input(ins, "Input")
    shape = list(attrs["shape"])
    shape[int(attrs.get("output_dim_idx", 0))] = x.shape[
        int(attrs.get("input_dim_idx", 0))]
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    lo, hi = float(attrs.get("min", -1.0)), float(attrs.get("max", 1.0))
    u = _replicated_draw(ctx, jax.random.uniform(
        _op_key(ctx, attrs), tuple(shape), jnp.float32, lo, hi))
    return {"Out": [u.astype(dtype)]}


@register_op("gaussian_random_batch_size_like")
def _gaussian_random_bsl(ctx, ins, attrs):
    x = single_input(ins, "Input")
    shape = list(attrs["shape"])
    shape[int(attrs.get("output_dim_idx", 0))] = x.shape[
        int(attrs.get("input_dim_idx", 0))]
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    g = _replicated_draw(ctx, jax.random.normal(
        _op_key(ctx, attrs), tuple(shape), jnp.float32))
    return {"Out": [(g * float(attrs.get("std", 1.0))
                     + float(attrs.get("mean", 0.0))).astype(dtype)]}


@register_op("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    return {"Out": [jnp.zeros_like(single_input(ins))]}


@register_op("fill_any_like")
def _fill_any_like(ctx, ins, attrs):
    x = single_input(ins)
    dtype = attrs.get("dtype")
    dtype = to_jnp_dtype(dtype) if dtype else x.dtype
    return {"Out": [jnp.full_like(x, attrs.get("value", 0.0), dtype=dtype)]}


@register_op("uniform_random")
def _uniform_random(ctx, ins, attrs):
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    lo, hi = float(attrs.get("min", -1.0)), float(attrs.get("max", 1.0))
    u = _replicated_draw(ctx, jax.random.uniform(
        _op_key(ctx, attrs), shape, jnp.float32, lo, hi))
    return {"Out": [u.astype(dtype)]}


@register_op("gaussian_random")
def _gaussian_random(ctx, ins, attrs):
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    mean, std = float(attrs.get("mean", 0.0)), float(attrs.get("std", 1.0))
    g = _replicated_draw(ctx, jax.random.normal(
        _op_key(ctx, attrs), shape, jnp.float32))
    return {"Out": [(g * std + mean).astype(dtype)]}


@register_op("truncated_gaussian_random")
def _trunc_gaussian(ctx, ins, attrs):
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    mean, std = float(attrs.get("mean", 0.0)), float(attrs.get("std", 1.0))
    g = _replicated_draw(ctx, jax.random.truncated_normal(
        _op_key(ctx, attrs), -2.0, 2.0, shape, jnp.float32))
    return {"Out": [(g * std + mean).astype(dtype)]}


@register_op("assign")
def _assign(ctx, ins, attrs):
    return {"Out": [single_input(ins)]}


@register_op("pipeline_boundary")
def _pipeline_boundary(ctx, ins, attrs):
    """Identity marker: layers.pipeline_boundary cuts go here.  Inert in
    un-transpiled programs; transpiler/pipeline.py partitions the op
    list at these markers and the executor's shard_map plane runs the
    stages as a GPipe schedule over the pipe axis.  The payload may be
    a tuple of tensors (pytree boundary)."""
    return {"Out": list(ins["X"])}


@register_op("assign_value")
def _assign_value(ctx, ins, attrs):
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    vals = np.asarray(attrs["values"]).reshape(attrs["shape"])
    return {"Out": [jnp.asarray(vals, dtype=dtype)]}


@register_op("cast")
def _cast(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [x.astype(to_jnp_dtype(attrs["out_dtype"]))]}


@register_op("shape", stop_gradient=True)
def _shape(ctx, ins, attrs):
    x = single_input(ins, "Input")
    return {"Out": [jnp.asarray(x.shape, dtype=index_dtype())]}


@register_op("one_hot", stop_gradient=True)
def _one_hot(ctx, ins, attrs):
    x = single_input(ins)
    depth = int(attrs["depth"])
    if x.shape and x.shape[-1] == 1:
        x = x.squeeze(-1)
    return {"Out": [jax.nn.one_hot(x.astype(jnp.int32), depth,
                                   dtype=jnp.float32)]}


@register_op("range", stop_gradient=True)
def _range(ctx, ins, attrs):
    start = single_input(ins, "Start")
    end = single_input(ins, "End")
    step = single_input(ins, "Step")
    # shapes must be static under jit: require python scalars via attrs when
    # used inside programs; this op is mainly used at build time.
    n = int(attrs["len"]) if "len" in attrs else None
    if n is None:
        raise ValueError("range op inside a program needs a static 'len' attr")
    return {"Out": [(start + step * jnp.arange(n, dtype=start.dtype))]}


@register_op("eye", stop_gradient=True)
def _eye(ctx, ins, attrs):
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.eye(int(attrs["num_rows"]),
                            int(attrs.get("num_columns",
                                          attrs["num_rows"])), dtype=dtype)]}


@register_op("linspace", stop_gradient=True)
def _linspace(ctx, ins, attrs):
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.linspace(float(attrs["start"]), float(attrs["stop"]),
                                 int(attrs["num"]), dtype=dtype)]}


@register_op("sampling_id", stop_gradient=True)
def _sampling_id(ctx, ins, attrs):
    """Sample one category id per row from a probability matrix
    (ref operators/sampling_id_op.cc)."""
    x = single_input(ins)
    ids = jax.random.categorical(_op_key(ctx, attrs), jnp.log(x + 1e-20),
                                 axis=-1)
    return {"Out": [ids.astype(index_dtype())]}
