"""Op-census breadth: the remaining reference operators.

Parity targets (all under /root/reference/paddle/fluid/operators/):
sequence_conv_op.cc, shuffle_channel (era: shuffle_channel_op.cc),
unique_op.cc (+unique_with_counts), hash_op.cc, similarity_focus_op.cc,
conv_shift_op.cc, spp_op.cc, random_crop_op.cc, lstmp_op.cc,
cudnn_lstm_op.cc, pool_op.cc (pool3d), conv_transpose_op.cc
(conv3d_transpose), lod_rank_table_op.cc, and the SelectedRows plumbing
family (split_ids_op.cc, merge_ids_op.cc, merge_selected_rows_op.cc,
split_selected_rows_op.cc, get_tensor_from_selected_rows_op.cc,
lookup_sparse_table_op.cc).

TPU-first redesigns worth noting:
  * anything with variable-length outputs (unique, the SelectedRows
    family) keeps STATIC shapes: outputs are input-sized with -1/0 pads
    plus explicit counts — the dense idiom this framework uses instead
    of LoD/SelectedRows dynamic shapes;
  * SelectedRows {rows, values} is represented as an (Ids, Values) pair
    of dense tensors; sharding ops preserve original positions so a
    merge is a sum — no host-side row bookkeeping.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.dtypes import index_dtype
from ..framework.registry import register_op, single_input


def _context_columns(x, ctx_len, ctx_start):
    """[B,T,D] -> [B,T,ctx_len*D]: timestep t's row concatenates
    x[t+ctx_start .. t+ctx_start+ctx_len), zero beyond the ends (the
    ContextProjection semantics, ref projections ContextProjection /
    sequence_conv_op.cc's im2col)."""
    B, T, D = x.shape
    cols = []
    for k in range(ctx_len):
        off = ctx_start + k
        sh = jnp.roll(x, -off, axis=1)
        idx = jnp.arange(T) + off
        valid = ((idx >= 0) & (idx < T))[None, :, None]
        cols.append(jnp.where(valid, sh, 0.0))
    return jnp.concatenate(cols, axis=-1)


@register_op("sequence_context")
def _sequence_context(ctx, ins, attrs):
    """The raw sliding-window concat (v2 context_projection without
    weights — ref trainer_config_helpers/layers.py:738)."""
    x = single_input(ins, "X")
    ctx_len = int(attrs["context_length"])
    # default matches the reference's Py2 floor: -(len-1)/2 -> -2 at len 4
    ctx_start = int(attrs.get("context_start", (-(ctx_len - 1)) // 2))
    return {"Out": [_context_columns(x, ctx_len, ctx_start)]}


@register_op("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """Context-window conv over time (ref sequence_conv_op.cc):
    X [B,T,D], Filter [ctx_len*D, M]; zero-padded context."""
    x = single_input(ins, "X")
    w = single_input(ins, "Filter")
    ctx_len = int(attrs.get("contextLength", attrs.get("context_length", 3)))
    ctx_start = int(attrs.get("contextStart",
                              attrs.get("context_start", -(ctx_len // 2))))
    col = _context_columns(x, ctx_len, ctx_start)   # [B,T,ctx_len*D]
    out = jnp.einsum("btk,km->btm", col, w.astype(col.dtype))
    return {"Out": [out.astype(x.dtype)]}


@register_op("shuffle_channel")
def _shuffle_channel(ctx, ins, attrs):
    """ref shuffle_channel_op.cc: [N, g*c, H, W] -> interleave groups."""
    x = single_input(ins, "X")
    g = int(attrs.get("group", 1))
    n, c, h, w = x.shape
    y = x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4)
    return {"Out": [y.reshape(n, c, h, w)]}


def _unique_static(x):
    """(first_occurrence_mask, compacted values (-1 pad), index map,
    counts) with static shapes."""
    n = x.shape[0]
    order = jnp.argsort(x, stable=True)
    xs = x[order]
    first = jnp.concatenate([jnp.ones((1,), bool), xs[1:] != xs[:-1]])
    uniq_rank = jnp.cumsum(first.astype(jnp.int32)) - 1     # per sorted pos
    n_uniq = uniq_rank[-1] + 1
    uniq_vals = jnp.full((n,), -1, x.dtype).at[uniq_rank].set(xs)
    # index: original position -> unique rank
    index = jnp.zeros((n,), jnp.int32).at[order].set(uniq_rank)
    counts = jnp.zeros((n,), jnp.int32).at[uniq_rank].add(1)
    return uniq_vals, index, counts, n_uniq


@register_op("unique", stop_gradient=True)
def _unique(ctx, ins, attrs):
    """ref unique_op.cc — static-shape redesign: Out is input-sized,
    -1-padded beyond the unique count (returned in `Count`)."""
    x = single_input(ins, "X").reshape(-1)
    vals, index, _, n_uniq = _unique_static(x)
    return {"Out": [vals], "Index": [index],
            "Count": [n_uniq.reshape(1)]}


@register_op("unique_with_counts", stop_gradient=True)
def _unique_with_counts(ctx, ins, attrs):
    x = single_input(ins, "X").reshape(-1)
    vals, index, counts, n_uniq = _unique_static(x)
    return {"Out": [vals], "Index": [index], "Count": [counts],
            "UniqueCount": [n_uniq.reshape(1)]}


@register_op("hash", stop_gradient=True)
def _hash(ctx, ins, attrs):
    """ref hash_op.cc: num_hash independent hashes of int rows, each
    modulo mod_by.  X [N, k] int -> Out [N, num_hash, 1] int64."""
    x = single_input(ins, "X").astype(jnp.uint32)
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 100000))
    # xor-multiply rows with per-hash odd constants (splitmix-style)
    seeds = (np.arange(1, num_hash + 1, dtype=np.uint32)
             * np.uint32(0x9E3779B1)) | np.uint32(1)
    h = jnp.zeros((x.shape[0], num_hash), jnp.uint32)
    for j in range(x.shape[1]):
        col = x[:, j][:, None]
        h = (h ^ (col * seeds[None, :])) * np.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
    out = (h % jnp.uint32(mod_by)).astype(jnp.int32)
    return {"Out": [out[:, :, None]]}


@register_op("similarity_focus", stop_gradient=True)
def _similarity_focus(ctx, ins, attrs):
    """ref similarity_focus_op.cc: for each selected channel, mark the
    per-row and per-column argmax cells of the [A, B] map; the union
    mask broadcasts over all channels."""
    x = single_input(ins, "X")
    axis = int(attrs.get("axis", 1))
    indexes = list(attrs.get("indexes", [0]))
    if axis != 1:
        x = jnp.moveaxis(x, axis, 1)
    n, c, a, b = x.shape
    mask = jnp.zeros((n, a, b), x.dtype)
    for idx in indexes:
        m = x[:, idx]                                 # [N, A, B]
        row_max = m == jnp.max(m, axis=2, keepdims=True)
        col_max = m == jnp.max(m, axis=1, keepdims=True)
        mask = jnp.maximum(mask, (row_max | col_max).astype(x.dtype))
    out = jnp.broadcast_to(mask[:, None], x.shape)
    if axis != 1:
        out = jnp.moveaxis(out, 1, axis)
    return {"Out": [out]}


@register_op("conv_shift")
def _conv_shift(ctx, ins, attrs):
    """ref conv_shift_op.cc: circular correlation
    out[b, i] = sum_j x[b, (i + j - M//2) mod N] * y[b, j]."""
    x = single_input(ins, "X")
    y = single_input(ins, "Y")
    B, N = x.shape
    M = y.shape[1]
    half = M // 2
    terms = []
    for j in range(M):
        terms.append(jnp.roll(x, half - j, axis=1) * y[:, j:j + 1])
    return {"Out": [sum(terms)]}


@register_op("spp")
def _spp(ctx, ins, attrs):
    """Spatial pyramid pooling (ref spp_op.cc): levels 0..H-1 with
    2^l x 2^l adaptive bins, concatenated -> [N, C*sum(4^l)]."""
    x = single_input(ins, "X")
    levels = int(attrs.get("pyramid_height", 1))
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for l in range(levels):
        bins = 2 ** l
        # adaptive pooling: split H/W into `bins` nearly-equal pieces
        ys = np.linspace(0, h, bins + 1).astype(int)
        xs = np.linspace(0, w, bins + 1).astype(int)
        for i in range(bins):
            for j in range(bins):
                patch = x[:, :, ys[i]:max(ys[i + 1], ys[i] + 1),
                          xs[j]:max(xs[j + 1], xs[j] + 1)]
                red = (jnp.max if ptype == "max" else jnp.mean)
                outs.append(red(patch, axis=(2, 3)))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register_op("random_crop", stop_gradient=True)
def _random_crop(ctx, ins, attrs):
    """ref random_crop_op.cc: crop `shape` from the trailing dims at a
    random offset (functional RNG)."""
    x = single_input(ins, "X")
    shape = list(attrs["shape"])
    nd = len(shape)
    lead = x.ndim - nd
    key = ctx.rng()
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[lead + i] - s
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, max(limit, 0) + 1))
    idx = [0] * lead + list(starts)
    sizes = list(x.shape[:lead]) + shape
    return {"Out": [lax.dynamic_slice(x, idx, sizes)]}


# -- fused / projected RNN tier -------------------------------------------

def _lstm_scan(x_seq, wh, h0, c0, proj=None):
    """x_seq [T,B,4H] pre-projected; wh [P or H, 4H]; optional proj
    [H, P] (LSTMP, ref lstmp_op.cc)."""

    def step(carry, xt):
        h, c = carry
        gates = xt + h @ wh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        hid = jax.nn.sigmoid(o) * jnp.tanh(c)
        h = hid @ proj if proj is not None else hid
        return (h, c), (h, c)

    return lax.scan(step, (h0, c0), x_seq)


@register_op("lstmp")
def _lstmp(ctx, ins, attrs):
    """LSTM with recurrent projection (ref lstmp_op.cc): Input [B,T,4H]
    pre-projected, Weight [P,4H], ProjWeight [H,P]."""
    x = single_input(ins, "Input")
    w = single_input(ins, "Weight")
    pw = single_input(ins, "ProjWeight")
    B, T, H4 = x.shape
    H = H4 // 4
    P = pw.shape[1]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, P), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, H), x.dtype)
    (h, c), (hs, cs) = _lstm_scan(jnp.swapaxes(x, 0, 1), w, h0, c0, proj=pw)
    return {"Projection": [jnp.swapaxes(hs, 0, 1)],
            "Cell": [jnp.swapaxes(cs, 0, 1)],
            "LastH": [h], "LastC": [c]}


@register_op("cudnn_lstm")
def _cudnn_lstm(ctx, ins, attrs):
    """Multi-layer (optionally bidirectional) fused LSTM (ref
    cudnn_lstm_op.cc).  Input [B,T,D]; W: ONE flat packed weight param
    (cudnn convention), sliced per (layer, direction) into
    wx [in,4H] | wh [H,4H] | b [4H].  attrs: hidden_size, num_layers,
    is_bidirec."""
    x = single_input(ins, "Input")
    w = single_input(ins, "W").reshape(-1)
    H = int(attrs["hidden_size"])
    L = int(attrs.get("num_layers", 1))
    bidi = bool(attrs.get("is_bidirec", False))
    ndir = 2 if bidi else 1
    B, T, D = x.shape
    # optional initial states, cudnn convention [L*ndir, B, H]
    init_h = (ins["InitH"][0] if ins.get("InitH") else None)
    init_c = (ins["InitC"][0] if ins.get("InitC") else None)
    off = 0

    def take(n, shape):
        nonlocal off
        v = w[off:off + n].reshape(shape)
        off += n
        return v

    seq = jnp.swapaxes(x, 0, 1)                     # [T,B,·]
    last_hs, last_cs = [], []
    for l in range(L):
        din = D if l == 0 else H * ndir
        outs = []
        for d in range(ndir):
            wx = take(din * 4 * H, (din, 4 * H))
            wh = take(H * 4 * H, (H, 4 * H))
            b = take(4 * H, (4 * H,))
            s = seq[::-1] if d == 1 else seq
            xp = s @ wx + b
            li = l * ndir + d
            zero = jnp.zeros((B, H), x.dtype)
            h0 = (zero if init_h is None
                  else (init_h[li] if init_h.ndim == 3 else init_h))
            c0 = (zero if init_c is None
                  else (init_c[li] if init_c.ndim == 3 else init_c))
            (h_T, c_T), (hs, _) = _lstm_scan(xp, wh, h0, c0)
            outs.append(hs[::-1] if d == 1 else hs)
            last_hs.append(h_T)
            last_cs.append(c_T)
        seq = jnp.concatenate(outs, axis=-1) if bidi else outs[0]
    out = jnp.swapaxes(seq, 0, 1)
    # cudnn convention: [num_layers*ndir, B, H]
    return {"Out": [out], "LastH": [jnp.stack(last_hs)],
            "LastC": [jnp.stack(last_cs)]}


# -- pooling / conv 3d -----------------------------------------------------

@register_op("pool3d")
def _pool3d(ctx, ins, attrs):
    """ref pool_op.cc 3-D: NCDHW max/avg (shared _pool_nd machinery —
    global_pooling / ceil_mode / exclusive avg all supported)."""
    from .nn_ops import _pool_nd
    attrs = dict(attrs)
    if "ksize" not in attrs and not attrs.get("global_pooling", False):
        attrs["ksize"] = 2
    if "strides" not in attrs:
        attrs["strides"] = attrs.get("ksize", 2)
    return {"Out": [_pool_nd(single_input(ins, "X"), attrs, 3)]}


@register_op("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    """ref conv_transpose_op.cc 3-D; filter IODHW, gradient-of-conv
    formulation via lhs_dilation."""
    x = single_input(ins, "Input")
    w = single_input(ins, "Filter")
    st = attrs.get("strides", 1)
    st = tuple(st) if isinstance(st, (list, tuple)) else (st,) * 3
    p = attrs.get("paddings", 0)
    p = tuple(p) if isinstance(p, (list, tuple)) else (p,) * 3
    kd, kh, kw = w.shape[2], w.shape[3], w.shape[4]
    pad = [(kd - 1 - p[0], kd - 1 - p[0]),
           (kh - 1 - p[1], kh - 1 - p[1]),
           (kw - 1 - p[2], kw - 1 - p[2])]
    w_t = jnp.swapaxes(jnp.flip(w, axis=(2, 3, 4)), 0, 1)
    out = lax.conv_general_dilated(
        x, w_t, window_strides=(1, 1, 1), padding=pad,
        lhs_dilation=st,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    return {"Output": [out.astype(x.dtype)]}


# -- LoD / SelectedRows plumbing (dense redesigns) -------------------------

@register_op("lod_rank_table", stop_gradient=True)
def _lod_rank_table(ctx, ins, attrs):
    """ref lod_rank_table_op.cc: sort sequences by length desc.  Dense
    input: Mask [B,T] (1=token) or Lengths [B]; outputs the sorted
    indices + lengths (what DynamicRNN used the table for)."""
    x = single_input(ins, "X")
    lens = (jnp.sum(x, axis=1) if x.ndim > 1 else x).astype(jnp.int32)
    order = jnp.argsort(-lens, stable=True).astype(jnp.int32)
    return {"Out": [order], "Lengths": [lens[order]]}


@register_op("lookup_sparse_table")
def _lookup_sparse_table(ctx, ins, attrs):
    """ref lookup_sparse_table_op.cc: pserver-side auto-growing table
    lookup; on TPU the table is a dense (sharded) param — same math as
    lookup_table."""
    w = single_input(ins, "W")
    ids = single_input(ins, "Ids").reshape(-1).astype(jnp.int32)
    return {"Out": [jnp.take(w, ids, axis=0)]}


@register_op("split_ids", stop_gradient=True)
def _split_ids(ctx, ins, attrs):
    """ref split_ids_op.cc: route ids to N shards by id % N.  Dense:
    each output keeps the input length with -1 where the id is not
    owned, so positions are preserved and a later merge is a sum."""
    ids = single_input(ins, "Ids").reshape(-1).astype(jnp.int32)
    n = int(attrs.get("num_shards", 1))
    outs = [jnp.where(ids % n == i, ids, -1) for i in range(n)]
    return {"Out": outs}


@register_op("merge_ids", stop_gradient=True)
def _merge_ids(ctx, ins, attrs):
    """ref merge_ids_op.cc: merge per-shard row tensors back to the
    original order.  With split_ids' position-preserving -1 padding the
    merge is an elementwise sum of the shard outputs (rows for unowned
    positions are zero)."""
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register_op("merge_selected_rows", stop_gradient=True)
def _merge_selected_rows(ctx, ins, attrs):
    """ref merge_selected_rows_op.cc: sum duplicate rows.  (Ids, Values)
    pair, static shapes: output ids are -1 beyond the unique count and
    values are segment-summed."""
    ids = single_input(ins, "Ids").reshape(-1).astype(jnp.int32)
    vals = single_input(ins, "Values")
    uniq, index, _, n_uniq = _unique_static(ids)
    summed = jnp.zeros((ids.shape[0],) + vals.shape[1:],
                       vals.dtype).at[index].add(vals)
    return {"OutIds": [uniq], "Out": [summed]}


@register_op("split_selected_rows", stop_gradient=True)
def _split_selected_rows(ctx, ins, attrs):
    """ref split_selected_rows_op.cc: split rows into height sections
    (pserver param blocks).  Dense: per-section local ids (-1 pad) +
    zeroed values for unowned rows."""
    ids = single_input(ins, "Ids").reshape(-1).astype(jnp.int32)
    vals = single_input(ins, "Values")
    sections = list(attrs["height_sections"])
    outs_ids, outs_vals = [], []
    off = 0
    for h in sections:
        own = (ids >= off) & (ids < off + h)
        outs_ids.append(jnp.where(own, ids - off, -1))
        outs_vals.append(jnp.where(own[:, None], vals, 0))
        off += h
    return {"OutIds": outs_ids, "Out": outs_vals}


@register_op("get_tensor_from_selected_rows", stop_gradient=True)
def _get_tensor_from_selected_rows(ctx, ins, attrs):
    """ref get_tensor_from_selected_rows_op.cc: scatter (Ids, Values)
    into a dense [height, D] tensor."""
    ids = single_input(ins, "Ids").reshape(-1).astype(jnp.int32)
    vals = single_input(ins, "Values")
    height = int(attrs["height"])
    valid = ids >= 0
    idx = jnp.where(valid, ids, 0)
    out = jnp.zeros((height,) + vals.shape[1:], vals.dtype)
    out = out.at[idx].add(jnp.where(valid[:, None], vals, 0))
    return {"Out": [out]}


@register_op("label_smooth")
def _label_smooth(ctx, ins, attrs):
    """ref label_smooth_op.cc: (1-eps)*y + eps*prior (uniform default)."""
    x = single_input(ins, "X")
    eps = float(attrs.get("epsilon", 0.1))
    if ins.get("PriorDist"):
        prior = ins["PriorDist"][0]
    else:
        prior = 1.0 / x.shape[-1]
    return {"Out": [(1.0 - eps) * x + eps * prior]}


@register_op("fill")
def _fill(ctx, ins, attrs):
    """ref fill_op.cc: constant data baked into attrs."""
    from ..core.dtypes import to_jnp_dtype
    value = np.asarray(attrs["value"],
                       dtype=to_jnp_dtype(attrs.get("dtype", "float32")))
    return {"Out": [jnp.asarray(value).reshape(attrs["shape"])]}


@register_op("print", stop_gradient=True)
def _print(ctx, ins, attrs):
    """ref print_op.cc: passthrough + host-side print (jax.debug)."""
    x = single_input(ins, "In" if ins.get("In") else "X")
    msg = attrs.get("message", "")
    jax.debug.print(msg + "{x}", x=x)
    return {"Out": [x]}


@register_op("delete_var", stop_gradient=True)
def _delete_var(ctx, ins, attrs):
    """ref delete_var_op.cc — liveness is XLA's job; accepted no-op."""
    return {}


@register_op("max_sequence_len", stop_gradient=True)
def _max_sequence_len(ctx, ins, attrs):
    """ref max_sequence_len_op.cc over the dense mask idiom."""
    x = single_input(ins, "RankTable" if ins.get("RankTable") else "X")
    lens = jnp.sum(x, axis=1) if x.ndim > 1 else x
    return {"Out": [jnp.max(lens).astype(jnp.int32).reshape(1)]}


@register_op("reorder_lod_tensor_by_rank", stop_gradient=True)
def _reorder_by_rank(ctx, ins, attrs):
    """ref reorder_lod_tensor_by_rank_op.cc: permute batch rows by the
    rank-table order (dense: RankTable = the order indices)."""
    x = single_input(ins, "X")
    order = single_input(ins, "RankTable").reshape(-1).astype(jnp.int32)
    return {"Out": [jnp.take(x, order, axis=0)]}


@register_op("tensor_array_to_tensor")
def _tensor_array_to_tensor(ctx, ins, attrs):
    """ref tensor_array_to_tensor_op.cc: stack/concat the array entries
    (dense: the 'array' is the op's X input list)."""
    xs = ins["X"]
    axis = int(attrs.get("axis", 0))
    if attrs.get("use_stack", False):
        out = jnp.stack(xs, axis=axis)
    else:
        out = jnp.concatenate(xs, axis=axis)
    return {"Out": [out],
            "OutIndex": [jnp.asarray([x.shape[axis] for x in xs],
                                     jnp.int32)]}


@register_op("split_lod_tensor", stop_gradient=True)
def _split_lod_tensor(ctx, ins, attrs):
    """ref split_lod_tensor_op.cc: route rows by a boolean mask into the
    true/false branches.  Dense: both outputs keep the input size with
    rows zeroed where not selected (positions preserved for the merge)."""
    x = single_input(ins, "X")
    mask = single_input(ins, "Mask").reshape(-1).astype(bool)
    shape = (slice(None),) + (None,) * (x.ndim - 1)
    m = mask[shape]
    return {"OutTrue": [jnp.where(m, x, 0)],
            "OutFalse": [jnp.where(m, 0, x)]}


@register_op("merge_lod_tensor", stop_gradient=True)
def _merge_lod_tensor(ctx, ins, attrs):
    """ref merge_lod_tensor_op.cc: inverse of split_lod_tensor under the
    position-preserving dense contract."""
    in_true = single_input(ins, "InTrue")
    in_false = single_input(ins, "InFalse")
    mask = single_input(ins, "Mask").reshape(-1).astype(bool)
    m = mask[(slice(None),) + (None,) * (in_true.ndim - 1)]
    return {"Out": [jnp.where(m, in_true, in_false)]}


@register_op("unpool")
def _unpool(ctx, ins, attrs):
    """ref unpool_op.cc: max-unpooling by indices from
    pool2d_with_index.  X [N,C,h,w], Indices flat positions into the
    unpooled [H,W]."""
    x = single_input(ins, "X")
    idx = single_input(ins, "Indices").astype(jnp.int32)
    uh, uw = attrs["unpooled_height"], attrs["unpooled_width"]
    n, c, h, w = x.shape
    flat = jnp.zeros((n, c, uh * uw), x.dtype)
    out = flat.at[
        jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1)].add(x.reshape(n, c, -1))
    return {"Out": [out.reshape(n, c, uh, uw)]}


@register_op("lod_array_length", stop_gradient=True)
def _lod_array_length(ctx, ins, attrs):
    """ref lod_array_length_op.cc: number of entries in a tensor array.
    Dense: the 'array' is the op's X input list, so the length is static."""
    return {"Out": [jnp.asarray([len(ins["X"])], index_dtype())]}


@register_op("lod_tensor_to_array", stop_gradient=True)
def _lod_tensor_to_array(ctx, ins, attrs):
    """ref lod_tensor_to_array_op.cc: slice a batch into per-timestep
    entries ordered by the rank table.  Dense redesign: X is [B, T, ...];
    rows are permuted into rank order (longest first) and each timestep
    becomes one output entry [B, ...].  The inverse is array_to_lod_tensor."""
    x = single_input(ins, "X")
    order = single_input(ins, "RankTable").reshape(-1).astype(jnp.int32)
    xs = jnp.take(x, order, axis=0)
    return {"Out": [xs[:, t] for t in range(x.shape[1])]}


@register_op("array_to_lod_tensor", stop_gradient=True)
def _array_to_lod_tensor(ctx, ins, attrs):
    """ref array_to_lod_tensor_op.cc: stack per-timestep entries back to a
    [B, T, ...] batch and undo the rank-table permutation (inverse of
    lod_tensor_to_array under the dense contract)."""
    xs = ins["X"]
    order = single_input(ins, "RankTable").reshape(-1).astype(jnp.int32)
    stacked = jnp.stack(xs, axis=1)
    inv = jnp.zeros_like(order).at[order].set(
        jnp.arange(order.shape[0], dtype=jnp.int32))
    return {"Out": [jnp.take(stacked, inv, axis=0)]}


@register_op("shrink_rnn_memory")
def _shrink_rnn_memory(ctx, ins, attrs):
    """ref shrink_rnn_memory_op.cc: at step I keep only the rows whose
    sequence is still active.  The reference slices to the first k rows of
    the rank-sorted batch; the dense static-shape redesign keeps [B, ...]
    and zero-masks finished rows (RankTable = lengths sorted desc, i.e.
    the Lengths output of lod_rank_table)."""
    x = single_input(ins, "X")
    lens = single_input(ins, "RankTable").reshape(-1).astype(jnp.int32)
    step = single_input(ins, "I").reshape(()).astype(jnp.int32)
    active = (lens > step).astype(x.dtype)
    return {"Out": [x * active[(slice(None),) + (None,) * (x.ndim - 1)]]}


@register_op("scale_sub_region")
def _scale_sub_region(ctx, ins, attrs):
    """Multiply `value` into a per-instance CHW sub-box (ref
    scale_sub_region_layer / scale_sub_region_op): X [B, C, H, W],
    Indices [B, 6] = 1-based inclusive (C0, C1, H0, H1, W0, W1)."""
    x = single_input(ins, "X")
    idx = single_input(ins, "Indices").astype(jnp.int32)
    value = float(attrs.get("value", 1.0))
    B, C, H, W = x.shape

    def dim_mask(lo, hi, n):            # [B] 1-based inclusive -> [B, n]
        r = jnp.arange(n)[None, :]
        return (r >= lo[:, None] - 1) & (r <= hi[:, None] - 1)

    m = (dim_mask(idx[:, 0], idx[:, 1], C)[:, :, None, None]
         & dim_mask(idx[:, 2], idx[:, 3], H)[:, None, :, None]
         & dim_mask(idx[:, 4], idx[:, 5], W)[:, None, None, :])
    return {"Out": [jnp.where(m, x * jnp.asarray(value, x.dtype), x)]}
