"""In-graph metric ops.

Parity: accuracy (operators/metrics/accuracy_op.cc), auc (auc_op.cc —
stat-accumulating), precision_recall, mean_iou (mean_iou_op.cc),
edit_distance, positive/negative pair.  Like the reference, auc carries its
histogram state through persistable in/out vars.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtypes import index_dtype
from ..framework.registry import register_op, single_input


@register_op("accuracy", stop_gradient=True)
def _accuracy(ctx, ins, attrs):
    """Inputs follow the reference: Out (topk values), Indices (topk ids),
    Label.  Accuracy = fraction of rows whose label is among indices."""
    idx = single_input(ins, "Indices")
    label = single_input(ins, "Label")
    if label.ndim >= 2 and label.shape[-1] == 1:
        label = label.squeeze(-1)
    hit = jnp.any(idx == label[..., None].astype(idx.dtype), axis=-1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.asarray(hit.size, jnp.int32)
    acc = correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": [acc], "Correct": [correct], "Total": [total]}


@register_op("auc", stop_gradient=True)
def _auc(ctx, ins, attrs):
    """Histogram-bucketed streaming AUC (ref metrics/auc_op.cc): state lives
    in StatPos/StatNeg vars, updated each batch."""
    preds = single_input(ins, "Predict")
    label = single_input(ins, "Label")
    stat_pos = single_input(ins, "StatPos")
    stat_neg = single_input(ins, "StatNeg")
    num_thresholds = int(attrs.get("num_thresholds", 4095))
    if label.ndim >= 2 and label.shape[-1] == 1:
        label = label.squeeze(-1)
    p1 = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 else (
        preds.reshape(-1))
    bucket = jnp.clip((p1 * num_thresholds).astype(jnp.int32), 0,
                      num_thresholds)
    is_pos = (label.reshape(-1) > 0)
    pos_hist = jnp.zeros_like(stat_pos).at[bucket].add(
        is_pos.astype(stat_pos.dtype))
    neg_hist = jnp.zeros_like(stat_neg).at[bucket].add(
        (~is_pos).astype(stat_neg.dtype))
    new_pos = stat_pos + pos_hist
    new_neg = stat_neg + neg_hist
    # AUC from histograms (trapezoid over descending-threshold ROC)
    tp = jnp.cumsum(new_pos[::-1])[::-1]
    fp = jnp.cumsum(new_neg[::-1])[::-1]
    tot_pos = tp[0]
    tot_neg = fp[0]
    tp_next = jnp.concatenate([tp[1:], jnp.zeros((1,), tp.dtype)])
    fp_next = jnp.concatenate([fp[1:], jnp.zeros((1,), fp.dtype)])
    area = jnp.sum((fp - fp_next) * (tp + tp_next) / 2.0)
    auc = jnp.where(tot_pos * tot_neg > 0,
                    area / (tot_pos * tot_neg + 1e-12), 0.0)
    return {"AUC": [auc.astype(jnp.float32)],
            "StatPosOut": [new_pos], "StatNegOut": [new_neg]}


@register_op("mean_iou", stop_gradient=True)
def _mean_iou(ctx, ins, attrs):
    pred = single_input(ins, "Predictions").astype(jnp.int32).reshape(-1)
    label = single_input(ins, "Labels").astype(jnp.int32).reshape(-1)
    n = int(attrs["num_classes"])
    inter = jnp.zeros((n,), jnp.float32).at[pred].add(
        (pred == label).astype(jnp.float32))
    pred_cnt = jnp.zeros((n,), jnp.float32).at[pred].add(1.0)
    lab_cnt = jnp.zeros((n,), jnp.float32).at[label].add(1.0)
    union = pred_cnt + lab_cnt - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1e-12), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)),
                                      1.0)
    return {"OutMeanIou": [miou], "OutWrong": [(union - inter)],
            "OutCorrect": [inter]}


@register_op("precision_recall", stop_gradient=True)
def _precision_recall(ctx, ins, attrs):
    """Multi-class micro/macro P/R/F1 with running state
    (ref metrics/precision_recall_op.cc, simplified state layout:
    per-class [tp, fp, fn])."""
    pred_ids = single_input(ins, "MaxProbs") if "MaxProbs" in ins else None
    idx = single_input(ins, "Indices").astype(jnp.int32).reshape(-1)
    label = single_input(ins, "Labels").astype(jnp.int32).reshape(-1)
    states = single_input(ins, "StatesInfo")
    n = states.shape[0]
    tp = jnp.zeros((n,), jnp.float32).at[idx].add(
        (idx == label).astype(jnp.float32))
    fp = jnp.zeros((n,), jnp.float32).at[idx].add(
        (idx != label).astype(jnp.float32))
    fn = jnp.zeros((n,), jnp.float32).at[label].add(
        (idx != label).astype(jnp.float32))
    new_states = states + jnp.stack([tp, fp, fn], axis=1)
    ctp, cfp, cfn = new_states[:, 0], new_states[:, 1], new_states[:, 2]
    prec = ctp / jnp.maximum(ctp + cfp, 1.0)
    rec = ctp / jnp.maximum(ctp + cfn, 1.0)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
    macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
    stp, sfp, sfn = jnp.sum(ctp), jnp.sum(cfp), jnp.sum(cfn)
    mp = stp / jnp.maximum(stp + sfp, 1.0)
    mr = stp / jnp.maximum(stp + sfn, 1.0)
    mf = 2 * mp * mr / jnp.maximum(mp + mr, 1e-12)
    metrics = jnp.concatenate([macro, jnp.stack([mp, mr, mf])])
    return {"BatchMetrics": [metrics], "AccumMetrics": [metrics],
            "AccumStatesInfo": [new_states]}


@register_op("edit_distance", stop_gradient=True)
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance per row over dense padded sequences
    (ref edit_distance_op.cc; LoD inputs become dense + length vectors)."""
    hyp = single_input(ins, "Hyps").astype(jnp.int32)
    ref = single_input(ins, "Refs").astype(jnp.int32)
    if hyp.ndim == 1:
        hyp, ref = hyp[None], ref[None]
    m, n = hyp.shape[1], ref.shape[1]

    def row_dist(h, r):
        init = jnp.arange(n + 1, dtype=jnp.float32)

        def outer(i, prev):
            def inner(j, carry):
                cur, diag = carry
                cost = jnp.where(h[i] == r[j], 0.0, 1.0)
                val = jnp.minimum(jnp.minimum(cur[j] + 1, prev[j + 1] + 1),
                                  diag + cost)
                return cur.at[j + 1].set(val), prev[j + 1]
            start = jnp.zeros(n + 1).at[0].set(i + 1.0)
            cur, _ = jax.lax.fori_loop(0, n, inner, (start, prev[0]))
            return cur
        final = jax.lax.fori_loop(0, m, outer, init)
        return final[n]

    d = jax.vmap(row_dist)(hyp, ref)
    if attrs.get("normalized", False):
        d = d / jnp.maximum(jnp.asarray(n, jnp.float32), 1.0)
    return {"Out": [d[:, None]],
            "SequenceNum": [jnp.asarray(hyp.shape[0], index_dtype())]}
