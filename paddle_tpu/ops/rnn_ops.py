"""Recurrent ops: LSTM / GRU via lax.scan.

Capability parity with the reference's RNN op family
(/root/reference/paddle/fluid/operators/lstm_op.cc "dynamic_lstm",
gru_op.cc "dynamic_gru", lstm_unit_op.cc, gru_unit_op.cc, cudnn_lstm_op —
plus the math in operators/math/lstm_compute.cc / gru_compute.cc and the
xbyak JIT lstm kernels).  TPU-first differences:

  * sequences are dense [B, T, ...] with an optional float mask [B, T]
    (1=token) instead of LoD ragged batches — masked steps carry the
    previous state through, which reproduces LoD semantics for
    right-padded batches (SURVEY.md hard part (a));
  * the recurrence is ONE lax.scan over time: XLA keeps h/c in registers
    /VMEM across steps and fuses the gate math into the per-step matmul;
  * gate order is i, f, c(candidate), o for LSTM and u(update), r(reset),
    c for GRU (documented — we do not chase the reference's weight memory
    layout, only its function).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op, single_input

_ACT = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh, "relu": jax.nn.relu,
        "identity": lambda x: x}


def _acc(x):
    return jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else None


@register_op("lstm")
def _lstm(ctx, ins, attrs):
    """Input [B,T,4H] (pre-projected x@Wx+b, ref dynamic_lstm contract),
    Weight [H,4H] recurrent, optional H0/C0 [B,H], Mask [B,T].
    Outputs: Hidden [B,T,H], LastH [B,H], LastC [B,H]."""
    x = single_input(ins, "Input")
    w = single_input(ins, "Weight")
    B, T, H4 = x.shape
    H = H4 // 4
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]
    reverse = bool(attrs.get("is_reverse", False))
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, H), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, H), x.dtype)
    mask = ins["Mask"][0] if ins.get("Mask") else None

    xt_seq = jnp.swapaxes(x, 0, 1)                      # [T,B,4H]
    if reverse:
        xt_seq = xt_seq[::-1]
    mask_seq = None
    if mask is not None:
        # cast to the activation dtype: a f32 mask would promote the
        # bf16 blend under AMP and flip the scan carry dtype (a scan
        # type error at trace time)
        mask_seq = jnp.swapaxes(mask, 0, 1)[..., None].astype(x.dtype)
        if reverse:
            mask_seq = mask_seq[::-1]

    def step(carry, inp):
        h, c = carry
        if mask_seq is None:
            xt = inp
        else:
            xt, m = inp
        gates = xt + jnp.matmul(h, w, preferred_element_type=_acc(x))\
            .astype(x.dtype)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = gate_act(i), gate_act(f), gate_act(o)
        g = cand_act(g)
        c_new = f * c + i * g
        h_new = o * cell_act(c_new)
        if mask_seq is not None:
            h_new = m * h_new + (1 - m) * h
            c_new = m * c_new + (1 - m) * c
        return (h_new, c_new), (h_new, c_new)

    (h_last, c_last), (hs, cs) = lax.scan(
        step, (h0, c0),
        xt_seq if mask_seq is None else (xt_seq, mask_seq))
    if reverse:
        hs = hs[::-1]
        cs = cs[::-1]
    hidden = jnp.swapaxes(hs, 0, 1)                     # [B,T,H]
    cell = jnp.swapaxes(cs, 0, 1)                       # [B,T,H]
    return {"Hidden": [hidden], "Cell": [cell],
            "LastH": [h_last], "LastC": [c_last]}


@register_op("gru")
def _gru(ctx, ins, attrs):
    """Input [B,T,3H] pre-projected, Weight [H,3H] (u|r|c blocks),
    optional H0 [B,H], Mask [B,T].  Outputs Hidden [B,T,H], LastH."""
    x = single_input(ins, "Input")
    w = single_input(ins, "Weight")
    B, T, H3 = x.shape
    H = H3 // 3
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    reverse = bool(attrs.get("is_reverse", False))
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, H), x.dtype)
    mask = ins["Mask"][0] if ins.get("Mask") else None
    w_g = w[:, :2 * H]                                  # update|reset
    w_c = w[:, 2 * H:]

    xt_seq = jnp.swapaxes(x, 0, 1)
    if reverse:
        xt_seq = xt_seq[::-1]
    mask_seq = None
    if mask is not None:
        # see dynamic_lstm: keep the mask in the activation dtype
        mask_seq = jnp.swapaxes(mask, 0, 1)[..., None].astype(x.dtype)
        if reverse:
            mask_seq = mask_seq[::-1]

    def step(h, inp):
        if mask_seq is None:
            xt = inp
        else:
            xt, m = inp
        xg, xc = xt[:, :2 * H], xt[:, 2 * H:]
        ur = gate_act(xg + jnp.matmul(h, w_g,
                                      preferred_element_type=_acc(x))
                      .astype(x.dtype))
        u, r = ur[:, :H], ur[:, H:]
        c = cand_act(xc + jnp.matmul(r * h, w_c,
                                     preferred_element_type=_acc(x))
                     .astype(x.dtype))
        h_new = u * h + (1 - u) * c
        if mask_seq is not None:
            h_new = m * h_new + (1 - m) * h
        return h_new, h_new

    h_last, hs = lax.scan(step, h0,
                          xt_seq if mask_seq is None else (xt_seq, mask_seq))
    if reverse:
        hs = hs[::-1]
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)], "LastH": [h_last]}


@register_op("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    """One step (ref lstm_unit_op.cc): X [B,4H] pre-activation, C_prev."""
    x = single_input(ins, "X")
    c_prev = single_input(ins, "C_prev")
    forget_bias = float(attrs.get("forget_bias", 0.0))
    i, f, g, o = jnp.split(x, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + jax.nn.sigmoid(
        i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return {"C": [c], "H": [h]}


@register_op("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """One step (ref gru_unit_op.cc): Input [B,3H] pre-projected,
    HiddenPrev [B,H], Weight [H,3H]."""
    x = single_input(ins, "Input")
    h = single_input(ins, "HiddenPrev")
    w = single_input(ins, "Weight")
    H = h.shape[-1]
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    xg, xc = x[:, :2 * H], x[:, 2 * H:]
    ur = gate_act(xg + jnp.matmul(h, w[:, :2 * H],
                                  preferred_element_type=_acc(x))
                  .astype(x.dtype))
    u, r = ur[:, :H], ur[:, H:]
    c = cand_act(xc + jnp.matmul(r * h, w[:, 2 * H:],
                                 preferred_element_type=_acc(x))
                 .astype(x.dtype))
    h_new = u * h + (1 - u) * c
    return {"Hidden": [h_new], "Gate": [jnp.concatenate([u, r], -1)],
            "ResetHiddenPrev": [r * h]}
